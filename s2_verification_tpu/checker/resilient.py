"""Crash-resilient driver for long on-chip runs.

The axon TPU worker dies (rather than raising RESOURCE_EXHAUSTED) on HBM
exhaustion, and a dead tunnel makes backend *init* hang instead of error.
A long measurement therefore needs three bounds the reference never did
(its CPU engine can't take the machine down —
/root/reference/rust/s2-verification has no analog):

1. the measurement runs in a **bounded child** (crash -> nonzero rc,
   hang -> timeout + process-group kill);
2. between attempts the backend is **probed** in its own bounded child
   until the tunnel answers again (init hangs are unkillable from inside
   the process — SIGALRM cannot interrupt the blocking C init);
3. each relaunch **resumes from the search checkpoint**
   (``check_device(checkpoint_path=...)``, checker/checkpoint.py), so a
   worker crash costs one segment, not the run.

``drive()`` is the generic loop; adv_bench.py --resilient and
scripts/onchip_runbook.sh use it so the measurement matrix survives
worker death without a human.
"""

from __future__ import annotations

import contextlib
import dataclasses
import os
import signal
import subprocess
import sys
import time
from typing import Callable, Sequence

from ..obs.trace import NULL_TRACER, Tracer

__all__ = ["DriveOutcome", "drive", "default_probe_cmd"]

#: Probe child source: init the backend honoring an explicit JAX_PLATFORMS
#: pin through the config API (the axon sitecustomize hook overrides the
#: env var), run one tiny computation, and — when unpinned — assert a TPU
#: platform is actually present: a CPU-fallback init also exits 0, so rc
#: alone would lie.
_PROBE_CODE = """\
import os, jax
p = os.environ.get('JAX_PLATFORMS')
if p: jax.config.update('jax_platforms', p)
ds = jax.devices()
if not p:
    assert any(d.platform == 'tpu' for d in ds), ds
import jax.numpy as jnp
print(jnp.arange(8).sum())
"""


def default_probe_cmd() -> list[str]:
    return [sys.executable, "-c", _PROBE_CODE]


@dataclasses.dataclass
class DriveOutcome:
    ok: bool
    attempts: int
    last_rc: int | None  #: None when the last attempt was killed on timeout
    note: str


def _kill_tree(child: subprocess.Popen) -> None:
    with contextlib.suppress(ProcessLookupError):
        os.killpg(child.pid, signal.SIGKILL)
    with contextlib.suppress(Exception):
        child.wait(timeout=30)


def drive(
    cmd: Sequence[str],
    *,
    done: Callable[[], bool],
    attempt_timeout_s: float = 3600.0,
    max_restarts: int = 8,
    probe_cmd: Sequence[str] | None = None,
    probe_timeout_s: float = 150.0,
    probe_interval_s: float = 180.0,
    max_probes: int = 120,
    log: Callable[[str], None] | None = None,
    tracer: Tracer = NULL_TRACER,
    trace_tid: int = 0,
    cancel: Callable[[], str | None] | None = None,
    grace_s: float = 5.0,
) -> DriveOutcome:
    """Run ``cmd`` in a bounded child until ``done()`` reports a conclusive
    result, restarting through crashes and hangs.

    ``cmd`` must be idempotent-with-progress: each invocation resumes from
    whatever persistent state (checkpoint) the previous attempt left.
    ``done()`` is the only success signal — a zero exit without ``done()``
    counts as a failed attempt (the child died before writing its result).
    ``probe_cmd`` (``None`` = no probing, e.g. host-backend tests) gates
    each relaunch on the backend answering again; the probe child is
    bounded too, because a dead tunnel hangs init.

    ``tracer``/``trace_tid``: record one span per attempt (and one per
    backend-probe wait) on the caller's trace track — verifyd passes its
    job track here so supervised device escalations show their restart
    structure in the trace export.

    ``cancel`` (cooperative cancellation): polled every quarter second
    while the child runs; a non-None reason SIGTERMs the child's process
    group, waits ``grace_s`` for a clean exit, SIGKILLs it otherwise,
    and returns a failed outcome noting the reason — no relaunch.
    """
    say = log or (lambda s: print(f"# resilient: {s}", file=sys.stderr, flush=True))
    attempts = 0
    last_rc: int | None = None
    current: list[subprocess.Popen | None] = [None]

    # The child runs in its own session (so a kill reaches its whole tree),
    # which also detaches it from an outer `timeout` bounding THIS process:
    # forward SIGTERM so the step's outer bound never strands an orphan
    # holding the device.
    def _on_term(signum, frame):
        if current[0] is not None:
            _kill_tree(current[0])
        raise SystemExit(128 + signum)

    try:
        prev = signal.signal(signal.SIGTERM, _on_term)
    except ValueError:  # non-main thread (tests): no handler, no orphankill
        prev = None
    try:
        while attempts <= max_restarts:
            attempts += 1
            if cancel is not None:
                reason = cancel()
                if reason:
                    return DriveOutcome(
                        False, attempts - 1, last_rc, f"cancelled ({reason})"
                    )
            say(f"attempt {attempts}: {' '.join(cmd)}")
            t_att = tracer.now()
            child = subprocess.Popen(list(cmd), start_new_session=True)
            current[0] = child
            cancelled_reason: str | None = None
            deadline = time.monotonic() + attempt_timeout_s
            try:
                # Chunked wait so the cancel flag is polled while the
                # child runs; the plain timeout path is the chunk sum.
                while True:
                    try:
                        last_rc = child.wait(
                            timeout=min(
                                0.25, max(0.0, deadline - time.monotonic())
                            )
                        )
                        break
                    except subprocess.TimeoutExpired:
                        if cancel is not None:
                            cancelled_reason = cancel()
                            if cancelled_reason:
                                # SIGTERM → grace → SIGKILL: give the
                                # child a chance to flush its checkpoint.
                                with contextlib.suppress(ProcessLookupError):
                                    os.killpg(child.pid, signal.SIGTERM)
                                try:
                                    last_rc = child.wait(timeout=grace_s)
                                except subprocess.TimeoutExpired:
                                    _kill_tree(child)
                                    last_rc = None
                                say(
                                    f"attempt {attempts} cancelled "
                                    f"({cancelled_reason}); child stopped"
                                )
                                break
                        if time.monotonic() >= deadline:
                            _kill_tree(child)
                            last_rc = None
                            say(
                                f"attempt {attempts} hung "
                                f">{attempt_timeout_s:.0f}s; killed"
                            )
                            break
            finally:
                current[0] = None
            finished = done()
            tracer.add_span(
                f"attempt {attempts}",
                t_att,
                tracer.now(),
                tid=trace_tid,
                cat="resilient",
                args={"rc": last_rc, "conclusive": finished},
            )
            if finished:
                return DriveOutcome(True, attempts, last_rc, "conclusive")
            if cancelled_reason:
                return DriveOutcome(
                    False, attempts, last_rc, f"cancelled ({cancelled_reason})"
                )
            if last_rc is not None:
                say(f"attempt {attempts} exited rc={last_rc} without a result")
            if attempts > max_restarts:
                break
            if probe_cmd is not None:
                t_probe = tracer.now()
                alive = _wait_for_backend(
                    probe_cmd, probe_timeout_s, probe_interval_s, max_probes, say
                )
                tracer.add_span(
                    "backend_probe",
                    t_probe,
                    tracer.now(),
                    tid=trace_tid,
                    cat="resilient",
                    args={"answered": alive},
                )
                if not alive:
                    return DriveOutcome(
                        False,
                        attempts,
                        last_rc,
                        "backend never answered between attempts",
                    )
        return DriveOutcome(False, attempts, last_rc, "restart budget exhausted")
    finally:
        if prev is not None:
            signal.signal(signal.SIGTERM, prev)


def _wait_for_backend(
    probe_cmd: Sequence[str],
    probe_timeout_s: float,
    probe_interval_s: float,
    max_probes: int,
    say: Callable[[str], None],
) -> bool:
    for i in range(1, max_probes + 1):
        probe = subprocess.Popen(
            list(probe_cmd),
            start_new_session=True,
            stdout=subprocess.DEVNULL,
            stderr=subprocess.DEVNULL,
        )
        try:
            rc = probe.wait(timeout=probe_timeout_s)
        except subprocess.TimeoutExpired:
            _kill_tree(probe)
            rc = None
        if rc == 0:
            say(f"backend answered on probe {i}")
            return True
        if i < max_probes:
            time.sleep(probe_interval_s)
    say(f"backend dead after {max_probes} probes")
    return False
