"""Failure diagnostics: which ops *refuse* to linearize at the deepest
prefix.

The reference hands ``porcupine.Visualize`` per-op partial-linearization
info that lets a reader explore where a failed check got stuck
(golang/s2-porcupine/main.go:606,627).  The engines here record the deepest
linearized prefix (``CheckResult.deepest``); this module turns that prefix
into an actionable report — the concrete ops whose outputs are inconsistent
with every state reachable at the deepest configuration — so the HTML
artifact can point at the culprit instead of only outlining how far the
search got.

The deepest set of a sequential client is always a per-chain prefix, so it
is equivalent to a counts vector; a bounded single-state DFS (same shape as
the device engine's witness recovery) re-derives one concrete path to that
configuration, and the path's end state is then tested against every
window-open candidate op.  Refusal is reported per reached state — exactly
the device engine's per-row semantics.
"""

from __future__ import annotations

import logging

from ..models.stream import INIT_STATE, step_set
from .entries import History

__all__ = ["deepest_refusals", "derive_path"]

log = logging.getLogger("s2_verification_tpu.diagnostics")


def _counts_of_deepest(history: History, deepest: list[int]) -> list[int] | None:
    """Deepest op set → per-chain prefix lengths; None if not a prefix
    (malformed input, never expected from an engine)."""
    ds = set(deepest)
    counts = []
    for members in history.chains:
        k = 0
        for i, op_idx in enumerate(members):
            if op_idx in ds:
                if i != k:
                    return None
                k = i + 1
        counts.append(k)
    if sum(counts) != len(ds):
        return None
    return counts


def _next_cands(history: History, counts) -> tuple[dict[int, int], list[int]]:
    """Window-open candidate chains at a configuration (the host mirror of
    the engines' candidate rule): each chain's next op, filtered to those
    whose call precedes every unlinearized op's earliest return."""
    nxt: dict[int, int] = {}
    m = None
    for c, members in enumerate(history.chains):
        if counts[c] < len(members):
            j = members[counts[c]]
            nxt[c] = j
            r = history.ops[j].ret
            m = r if m is None else min(m, r)
    cand = [c for c, j in nxt.items() if m is None or history.ops[j].call < m]
    return nxt, cand


def derive_path(
    history: History,
    deepest: list[int],
    node_budget: int = 200_000,
):
    """Re-derive one concrete linearization ORDER reaching the deepest
    configuration (a per-chain prefix set), plus its end state.

    Returns ``(order, goal_state)`` — ``order`` is the op-index sequence of
    a valid path, the per-op ordinals the HTML artifact annotates a failed
    check with (porcupine's partial-linearization info, main.go:606,627) —
    or ``(None, None)`` when the set is not a prefix or the DFS exhausts
    ``node_budget`` nodes."""
    target = _counts_of_deepest(history, deepest)
    if target is None:
        log.warning("deepest set is not a per-chain prefix; no diagnostics")
        return None, None
    return _derive_from_counts(history, tuple(target), node_budget)


def _derive_from_counts(history: History, tt: tuple, node_budget: int):
    start = (0,) * len(history.chains)

    init_key = (
        start,
        (INIT_STATE.tail, INIT_STATE.stream_hash, INIT_STATE.fencing_token),
    )
    # Parent pointers (key -> (parent key, op index)) reconstruct the path
    # at the goal without carrying per-node op lists.
    parent: dict = {init_key: None}
    stack = [(init_key, INIT_STATE)]
    budget = node_budget
    goal = None
    while stack:
        key, state = stack.pop()
        counts_t = key[0]
        if counts_t == tt:
            goal = (key, state)
            break
        nxt, cand = _next_cands(history, counts_t)
        for c in cand:
            if counts_t[c] >= tt[c]:
                continue
            j = nxt[c]
            op = history.ops[j]
            nct = counts_t[:c] + (counts_t[c] + 1,) + counts_t[c + 1 :]
            for ns in step_set([state], op.inp, op.out):
                nkey = (nct, (ns.tail, ns.stream_hash, ns.fencing_token))
                if nkey in parent:
                    continue
                budget -= 1
                if budget <= 0:
                    log.warning(
                        "refusal diagnostics exhausted the %d-node budget",
                        node_budget,
                    )
                    return None, None
                parent[nkey] = (key, j)
                stack.append((nkey, ns))
    if goal is None:
        log.warning("deepest configuration not re-derivable; no diagnostics")
        return None, None
    order: list[int] = []
    key = goal[0]
    while parent[key] is not None:
        key, j = parent[key]
        order.append(j)
    order.reverse()
    return order, goal[1]


def deepest_refusals(
    history: History,
    deepest: list[int],
    node_budget: int = 200_000,
) -> tuple[list[int], list[int]] | None:
    """(deepest prefix ops in one valid linearization order, ops refusing
    to linearize there), or None when the prefix cannot be re-derived
    inside ``node_budget`` DFS nodes."""
    target = _counts_of_deepest(history, deepest)
    if target is None:
        log.warning("deepest set is not a per-chain prefix; no diagnostics")
        return None
    tt = tuple(target)
    order, goal_state = _derive_from_counts(history, tt, node_budget)
    if order is None:
        return None
    nxt, cand = _next_cands(history, tt)
    refused = [
        nxt[c]
        for c in cand
        if not step_set([goal_state], history.ops[nxt[c]].inp, history.ops[nxt[c]].out)
    ]
    return order, sorted(refused)
