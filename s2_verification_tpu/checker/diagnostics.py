"""Failure diagnostics: which ops *refuse* to linearize at the deepest
prefix.

The reference hands ``porcupine.Visualize`` per-op partial-linearization
info that lets a reader explore where a failed check got stuck
(golang/s2-porcupine/main.go:606,627).  The engines here record the deepest
linearized prefix (``CheckResult.deepest``); this module turns that prefix
into an actionable report — the concrete ops whose outputs are inconsistent
with every state reachable at the deepest configuration — so the HTML
artifact can point at the culprit instead of only outlining how far the
search got.

The deepest set of a sequential client is always a per-chain prefix, so it
is equivalent to a counts vector; a bounded single-state DFS (same shape as
the device engine's witness recovery) re-derives one concrete path to that
configuration, and the path's end state is then tested against every
window-open candidate op.  Refusal is reported per reached state — exactly
the device engine's per-row semantics.
"""

from __future__ import annotations

import logging

from ..models.stream import INIT_STATE, step_set
from .entries import History

__all__ = ["deepest_refusals"]

log = logging.getLogger("s2_verification_tpu.diagnostics")


def _counts_of_deepest(history: History, deepest: list[int]) -> list[int] | None:
    """Deepest op set → per-chain prefix lengths; None if not a prefix
    (malformed input, never expected from an engine)."""
    ds = set(deepest)
    counts = []
    for members in history.chains:
        k = 0
        for i, op_idx in enumerate(members):
            if op_idx in ds:
                if i != k:
                    return None
                k = i + 1
        counts.append(k)
    if sum(counts) != len(ds):
        return None
    return counts


def _next_cands(history: History, counts) -> tuple[dict[int, int], list[int]]:
    """Window-open candidate chains at a configuration (the host mirror of
    the engines' candidate rule): each chain's next op, filtered to those
    whose call precedes every unlinearized op's earliest return."""
    nxt: dict[int, int] = {}
    m = None
    for c, members in enumerate(history.chains):
        if counts[c] < len(members):
            j = members[counts[c]]
            nxt[c] = j
            r = history.ops[j].ret
            m = r if m is None else min(m, r)
    cand = [c for c, j in nxt.items() if m is None or history.ops[j].call < m]
    return nxt, cand


def deepest_refusals(
    history: History,
    deepest: list[int],
    node_budget: int = 200_000,
) -> tuple[list[int], list[int]] | None:
    """(deepest prefix ops, ops refusing to linearize there), or None when
    the prefix cannot be re-derived inside ``node_budget`` DFS nodes."""
    target = _counts_of_deepest(history, deepest)
    if target is None:
        log.warning("deepest set is not a per-chain prefix; no diagnostics")
        return None
    tt = tuple(target)
    start = (0,) * len(history.chains)

    seen = {(start, (INIT_STATE.tail, INIT_STATE.stream_hash, INIT_STATE.fencing_token))}
    stack = [(start, INIT_STATE)]
    budget = node_budget
    goal_state = None
    while stack:
        counts_t, state = stack.pop()
        if counts_t == tt:
            goal_state = state
            break
        nxt, cand = _next_cands(history, counts_t)
        for c in cand:
            if counts_t[c] >= tt[c]:
                continue
            op = history.ops[nxt[c]]
            nct = counts_t[:c] + (counts_t[c] + 1,) + counts_t[c + 1 :]
            for ns in step_set([state], op.inp, op.out):
                key = (nct, (ns.tail, ns.stream_hash, ns.fencing_token))
                if key in seen:
                    continue
                budget -= 1
                if budget <= 0:
                    log.warning(
                        "refusal diagnostics exhausted the %d-node budget",
                        node_budget,
                    )
                    return None
                seen.add(key)
                stack.append((nct, ns))
    if goal_state is None:
        log.warning("deepest configuration not re-derivable; no diagnostics")
        return None

    nxt, cand = _next_cands(history, tt)
    refused = [
        nxt[c]
        for c in cand
        if not step_set([goal_state], history.ops[nxt[c]].inp, history.ops[nxt[c]].out)
    ]
    return sorted(deepest), sorted(refused)
