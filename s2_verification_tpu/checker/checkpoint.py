"""Checkpoint/resume for long device searches.

The reference has no checkpointing — its persistent artifact is the JSONL
history and checking is one-shot in-memory (SURVEY.md §5).  Long frontier
searches on device deserve better: the whole search state is one dense
:class:`~.device.Frontier` plus a few counters, so a snapshot is a single
``.npz`` write, and resuming is exactly the capacity-escalation path the
driver already exercises.

A checkpoint is bound to its history by a fingerprint over the encoded
arrays; resuming against a different history raises.  Writes are atomic
(tmp + rename) so a crash mid-write never corrupts the previous snapshot.
"""

from __future__ import annotations

import contextlib
import hashlib
import json
import os
import zipfile
from dataclasses import dataclass

import numpy as np

from ..models.encode import EncodedHistory

__all__ = [
    "history_fingerprint",
    "save_checkpoint",
    "load_checkpoint",
    "Checkpoint",
    "CheckpointError",
]

_FORMAT = 2  # v2: per-state frontier rows (no svalid / state-slot axis)


class CheckpointError(ValueError):
    """A snapshot is unreadable or does not belong to this search."""


#: Bumped whenever the encoder's array layout changes (e.g. the r3
#: shape-bucketing): a checkpoint from another format must fail with an
#: accurate message, not "different history".
ENCODING_FORMAT = "v3-bucketed"


def history_fingerprint(enc: EncodedHistory) -> str:
    """Stable digest of everything the search semantics depend on,
    prefixed with the encoding-format tag so stale-format snapshots are
    distinguishable from different-history ones."""
    h = hashlib.sha256()
    h.update(ENCODING_FORMAT.encode())
    for name in (
        "op_type",
        "has_set_token",
        "set_token",
        "has_batch_token",
        "batch_token",
        "has_match",
        "match_seq",
        "num_records",
        "rh_row",
        "rh_len",
        "out_failure",
        "out_definite",
        "out_tail",
        "out_has_hash",
        "out_hash_hi",
        "out_hash_lo",
        "call",
        "ret",
        "chain_of",
        "rh_hi",
        "rh_lo",
        "chain_ops",
        "chain_len",
        "chain_start",
    ):
        arr = np.ascontiguousarray(getattr(enc, name))
        h.update(name.encode())
        h.update(str(arr.dtype).encode())
        h.update(str(arr.shape).encode())
        h.update(arr.tobytes())
    for s in sorted(enc.init_states):
        h.update(repr(s).encode())
    return f"{ENCODING_FORMAT}:{h.hexdigest()}"


def fingerprint_mismatch_reason(saved: str, current: str) -> str:
    """Human-accurate diagnosis of a fingerprint mismatch: a snapshot from
    an older encoding format (pre-bucketing checkpoints carry a bare hex
    digest) is stale, not 'a different history'."""
    saved_fmt = saved.split(":", 1)[0] if ":" in saved else "<pre-v2>"
    cur_fmt = current.split(":", 1)[0]
    if saved_fmt != cur_fmt:
        return (
            f"was written by encoding format {saved_fmt} (current "
            f"{cur_fmt}) and cannot seed the new program shapes; delete "
            "it to restart the search"
        )
    return "belongs to a different history (fingerprint mismatch)"


@dataclass
class Checkpoint:
    fingerprint: str
    #: frontier arrays, host-side
    counts: np.ndarray
    tail: np.ndarray
    hi: np.ndarray
    lo: np.ndarray
    tok: np.ndarray
    valid: np.ndarray
    #: driver state
    f: int
    beam: bool
    layers_done: int
    stats: dict


def save_checkpoint(path: str, ckpt: Checkpoint) -> None:
    meta = {
        "format": _FORMAT,
        "fingerprint": ckpt.fingerprint,
        "f": int(ckpt.f),
        "beam": bool(ckpt.beam),
        "layers_done": int(ckpt.layers_done),
        "stats": ckpt.stats,
    }
    tmp = f"{path}.tmp.{os.getpid()}"
    try:
        with open(tmp, "wb") as fh:
            np.savez_compressed(
                fh,
                meta=np.frombuffer(json.dumps(meta).encode(), np.uint8),
                counts=ckpt.counts,
                tail=ckpt.tail,
                hi=ckpt.hi,
                lo=ckpt.lo,
                tok=ckpt.tok,
                valid=ckpt.valid,
            )
        os.replace(tmp, path)
    except BaseException:
        with contextlib.suppress(OSError):
            os.remove(tmp)
        raise


def load_checkpoint(path: str) -> Checkpoint:
    try:
        with np.load(path) as z:
            meta = json.loads(bytes(z["meta"]).decode())
            if meta.get("format") != _FORMAT:
                raise CheckpointError(
                    f"checkpoint {path} has format {meta.get('format')}, "
                    f"want {_FORMAT}"
                )
            return Checkpoint(
                fingerprint=meta["fingerprint"],
                counts=z["counts"],
                tail=z["tail"],
                hi=z["hi"],
                lo=z["lo"],
                tok=z["tok"],
                valid=z["valid"],
                f=int(meta["f"]),
                beam=bool(meta["beam"]),
                layers_done=int(meta["layers_done"]),
                stats=dict(meta["stats"]),
            )
    except CheckpointError:
        raise
    except (OSError, ValueError, KeyError, zipfile.BadZipFile) as e:
        # Truncated/corrupt archives surface as zipfile/pickle/KeyError
        # noise; normalize so callers can handle one exception type.
        raise CheckpointError(f"cannot read checkpoint {path}: {e}") from e
