"""Commutativity pruning: static interleaving analysis over one history.

The Wing–Gong search explores every admissible interleaving of concurrent
ops, but under the S2 Step kernel (models/stream.py) large families of
those interleavings are provably equivalent or provably dead, and both
facts are visible *statically* — from the observed outputs alone, before
any state is materialized.  This module derives three sound artifacts
(the DPOR move, specialized to the S2 model's monotone-tail structure):

**1. Append rank order** (``app_rank`` / ``minrank_tab``).  A successful
append with ``num_records >= 1`` moves the tail from ``out_tail - n`` to
``out_tail``; tails are monotone along every linearization, so two such
appends with distinct ``out_tail`` linearize in ``out_tail`` order in
*every* accepting interleaving — the pair commutes in the DPOR sense that
only one order ever needs exploring.  The search gates a ranked candidate
out of the window unless its rank is the minimum remaining rank: the
gated branches provably never accept, so OK *and* ILLEGAL verdicts are
both preserved (this is an exact prune, unlike the beam).  Appends
sharing an ``out_tail`` (never both acceptable, but order unprovable) and
zero-record appends are conservatively left unranked.

**2. Eager commit** (``inert`` / ``filter_succ``).  Reads and check_tails
never mutate state — ``step`` either returns ``{s}`` or ``{}`` — so a
candidate filter that *passes* the current state is an identity op there,
and any accepting continuation that linearizes it later can be reordered
to linearize it now (every other op sees the same states; the candidate
window only loosens).  The engines fold such ops into the auto-close
sweep: committed immediately, per single-state row on device, and only
when they pass **all** states of a configuration's set on the host (a
partial pass filters the set and is not an identity).  Inert ops
(definite failures — normally elided at prepare, but present under
``elide_trivial=False`` — and failed filters) commit unconditionally.

**3. Tail pins** (``pintail_tab``).  A successful filter observing
``out_tail = t`` can only linearize at a state whose tail *is* ``t``, and
a successful append with ``out_tail = t`` only at tail ``t - n``.  Tails
never decrease, so a configuration whose tail has passed the smallest
such pin among its remaining ops can never linearize that op — the row is
dead forever and is dropped.  On the adversarial k-family this collapses
the frontier from all ordered subsets to those at or below the pinning
read's tail (~99.7% of rows at k=10).

All three prunes are **verdict-exact**: they only remove interleavings
with no accepting extension (rank, pins) or with an equivalent retained
representative (eager commit), so OK, ILLEGAL and UNKNOWN all match the
un-pruned engines — the property `scripts/prune_check.py` enforces
differentially on every campaign history.  They assume tails do not wrap
u32 mid-history, the same monotonicity assumption the auto-close rules
already make (checker/device.py `_auto_close_row`).

The pairwise facts are also exposed directly (:func:`classify_pair`,
:func:`order_mask`) for unit coverage and for the canonical-order mask
the encoded tables summarize.
"""

from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np

from ..models.stream import APPEND

__all__ = [
    "FREE",
    "ORDERED",
    "CONFLICT",
    "PruneTables",
    "HostPrunePlan",
    "classify_pair",
    "commutes",
    "order_mask",
    "analyze_encoded",
    "analyze_history",
    "neutral_tables",
]

#: pair classes: FREE — order irrelevant (both orders reach identical
#: state sets); ORDERED — order statically forced (the canonical-order
#: mask fixes it; only one order can ever appear in an accepting
#: linearization); CONFLICT — no static fact, both orders explored.
FREE, ORDERED, CONFLICT = "free", "ordered", "conflict"

#: rank sentinel for unranked ops in the int32 tables
RANK_INF = np.int32(2**31 - 1)
#: pin sentinel (no pin) in the uint32 tables
PIN_INF = np.uint32(0xFFFFFFFF)


# ---------------------------------------------------------------------------
# Op classification (History-level; the encoded path mirrors these rules
# on the packed arrays)
# ---------------------------------------------------------------------------


def _is_inert(op) -> bool:
    """Identity on every state: definite failures (any type) and failed
    filters (read/check_tail failures are definite and guard nothing)."""
    if op.out.failure and op.out.definite_failure:
        return True
    return op.inp.input_type != APPEND and op.out.failure


def _is_filter_success(op) -> bool:
    return op.inp.input_type != APPEND and not op.out.failure


def _is_append_success(op) -> bool:
    return op.inp.input_type == APPEND and not op.out.failure


def _pin_of(op) -> int | None:
    """The unique tail this op can linearize at, if statically known."""
    if _is_filter_success(op):
        return int(op.out.tail) & 0xFFFFFFFF
    if _is_append_success(op):
        n = int(op.inp.num_records or 0)
        t = int(op.out.tail) & 0xFFFFFFFF
        if t >= n:  # no-wrap guard; wrapped tails stay unpinned
            return t - n
    return None


def classify_pair(op_i, op_j) -> str:
    """Statically classify the interleaving freedom of two ops.

    Returns :data:`FREE` when both orders provably reach identical state
    sets, :data:`ORDERED` when monotone tails force one order in every
    accepting linearization, :data:`CONFLICT` otherwise.  Used for tests
    and the explicit :func:`order_mask`; the engines consume the O(N)
    rank/pin summaries instead.
    """
    # Identity ops commute with everything: they always pass and never
    # move state, so both orders compose to the other op's step.
    if _is_inert(op_i) or _is_inert(op_j):
        return FREE

    fi, fj = _is_filter_success(op_i), _is_filter_success(op_j)
    ai, aj = _is_append_success(op_i), _is_append_success(op_j)

    if fi and fj:
        ti = int(op_i.out.tail) & 0xFFFFFFFF
        tj = int(op_j.out.tail) & 0xFFFFFFFF
        hi, hj = op_i.out.stream_hash, op_j.out.stream_hash
        if ti == tj:
            # Same committed prefix observed: both pass exactly at states
            # with that tail (and matching hash); each is identity there.
            if hi is None or hj is None or hi == hj:
                return FREE
            # Overlapping reads: same range, conflicting contents — they
            # can never both pass on one path, and neither order is
            # statically preferable.
            return CONFLICT
        # Disjoint committed ranges: the lower observation must precede
        # the higher one (tails are monotone), so the order is forced.
        return ORDERED

    if ai and aj:
        ni = int(op_i.inp.num_records or 0)
        nj = int(op_j.inp.num_records or 0)
        ti = int(op_i.out.tail) & 0xFFFFFFFF
        tj = int(op_j.out.tail) & 0xFFFFFFFF
        if ni >= 1 and nj >= 1 and ti != tj:
            return ORDERED
        return CONFLICT

    if (fi and aj) or (fj and ai):
        # Filter observing t vs append covering (a-n, a]: the filter
        # linearizes strictly outside the append's record range on every
        # accepting path, which fixes the order; an observation *inside*
        # the range can never linearize at all (the tail jumps across it),
        # which is a history-level inconsistency, not a static order.
        f_op, a_op = (op_i, op_j) if fi else (op_j, op_i)
        t = int(f_op.out.tail) & 0xFFFFFFFF
        a = int(a_op.out.tail) & 0xFFFFFFFF
        n = int(a_op.inp.num_records or 0)
        if n >= 1:
            if t <= a - n:
                return ORDERED  # filter strictly before the append
            if t >= a:
                return ORDERED  # append strictly before the filter
        return CONFLICT

    # At least one indefinite append / token mutator: fencing ops never
    # commute statically (their effect branch depends on the path).
    return CONFLICT


def commutes(op_i, op_j) -> bool:
    """True iff only one interleaving order of the pair needs exploring
    (the pair is FREE or statically ORDERED)."""
    return classify_pair(op_i, op_j) is not CONFLICT


def order_mask(history) -> np.ndarray:
    """Canonical-order mask: ``mask[i, j]`` iff op ``i`` must precede op
    ``j`` in every accepting linearization (the ORDERED pairs, oriented).

    O(N^2); meant for tests and small-history introspection — the engines
    consume the O(N) rank/pin tables, which summarize exactly this
    relation's append chain.  The mask is canonical: antisymmetric and
    transitively closed over the static order (both properties are what
    tests/test_prune.py asserts).
    """
    ops = history.ops
    n = len(ops)
    mask = np.zeros((n, n), bool)

    def sort_key(op):
        # Position of the op on the tail axis: filters sit AT their
        # observed tail, appends END at theirs (and so sort after a
        # filter observing their start).
        t = int(op.out.tail) & 0xFFFFFFFF
        return (t, 0 if _is_filter_success(op) else 1)

    for i in range(n):
        for j in range(n):
            if i == j:
                continue
            if classify_pair(ops[i], ops[j]) is ORDERED:
                if sort_key(ops[i]) < sort_key(ops[j]):
                    mask[i, j] = True
    return mask


# ---------------------------------------------------------------------------
# Host plan (check_frontier)
# ---------------------------------------------------------------------------


@dataclass
class HostPrunePlan:
    """Prune artifacts for the host frontier search, op-index keyed."""

    #: op index -> rank in the forced append order (dense from 0)
    rank: dict[int, int] = field(default_factory=dict)
    #: minrank[c][k]: min rank among chain c ops at positions >= k
    minrank: list[list[int]] = field(default_factory=list)
    #: pin[c][k]: min tail pin among chain c ops at positions >= k
    pin: list[list[int]] = field(default_factory=list)
    #: ops that are identity on every state
    inert: set[int] = field(default_factory=set)
    #: successful filters: op index -> (out_tail, out stream_hash | None)
    filter_guard: dict[int, tuple[int, object]] = field(default_factory=dict)

    @property
    def n_ranked(self) -> int:
        return len(self.rank)

    def min_remaining_rank(self, counts) -> int:
        return min(
            (self.minrank[c][counts[c]] for c in range(len(counts))),
            default=int(RANK_INF),
        )

    def min_pin(self, counts) -> int:
        return min(
            (self.pin[c][counts[c]] for c in range(len(counts))),
            default=int(PIN_INF),
        )


def _rank_appends(ops, indices) -> dict[int, int]:
    """Dense out_tail ranks over the ranked-append subset of ``indices``.

    Duplicated out_tails disqualify the whole duplicate group (the order
    within it is not statically provable), matching the conservative
    exclusions documented in the module header.
    """
    ranked = [
        j
        for j in indices
        if _is_append_success(ops[j]) and int(ops[j].inp.num_records or 0) >= 1
    ]
    tails: dict[int, list[int]] = {}
    for j in ranked:
        tails.setdefault(int(ops[j].out.tail) & 0xFFFFFFFF, []).append(j)
    unique = sorted(t for t, js in tails.items() if len(js) == 1)
    return {tails[t][0]: r for r, t in enumerate(unique)}


def analyze_history(history) -> HostPrunePlan:
    """Build the host prune plan from a prepared History."""
    ops = history.ops
    chains = history.chains
    plan = HostPrunePlan()
    plan.rank = _rank_appends(ops, range(len(ops)))
    for c, chain in enumerate(chains):
        ln = len(chain)
        mr = [int(RANK_INF)] * (ln + 1)
        pn = [int(PIN_INF)] * (ln + 1)
        for k in range(ln - 1, -1, -1):
            j = chain[k]
            r = plan.rank.get(j, int(RANK_INF))
            p = _pin_of(ops[j])
            mr[k] = min(mr[k + 1], r)
            pn[k] = min(pn[k + 1], p if p is not None else int(PIN_INF))
        plan.minrank.append(mr)
        plan.pin.append(pn)
    for j, op in enumerate(ops):
        if _is_inert(op):
            plan.inert.add(j)
        elif _is_filter_success(op):
            plan.filter_guard[j] = (
                int(op.out.tail) & 0xFFFFFFFF,
                op.out.stream_hash,
            )
    return plan


# ---------------------------------------------------------------------------
# Encoded tables (device + native engines)
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class PruneTables:
    """Numpy prune tables over an EncodedHistory (device/native layout).

    Neutral values (RANK_INF ranks, PIN_INF pins, all-false masks) make
    every consumer a no-op with an identical compiled graph — pruning
    on/off is a table-content change, not a retrace.
    """

    app_rank: np.ndarray  # [N] int32; RANK_INF = unranked
    minrank_tab: np.ndarray  # [C, Lc+1] int32 suffix-min rank
    pintail_tab: np.ndarray  # [C, Lc+1] uint32 suffix-min tail pin
    inert: np.ndarray  # [N] bool
    filter_succ: np.ndarray  # [N] bool (successful read/check_tail)
    n_ranked: int


def neutral_tables(n_ops: int, chain_shape: tuple[int, int]) -> PruneTables:
    c, lc = chain_shape
    return PruneTables(
        app_rank=np.full(n_ops, RANK_INF, np.int32),
        minrank_tab=np.full((c, lc + 1), RANK_INF, np.int32),
        pintail_tab=np.full((c, lc + 1), PIN_INF, np.uint32),
        inert=np.zeros(n_ops, bool),
        filter_succ=np.zeros(n_ops, bool),
        n_ranked=0,
    )


def analyze_encoded(enc) -> PruneTables:
    """Build the encoded prune tables.  Only ops reachable through the
    chain tables are classified — padded rows (which masquerade as
    zero-record appends) never receive ranks, pins, or eager masks."""
    n = int(enc.op_type.shape[0])
    c, lc = enc.chain_ops.shape
    t = neutral_tables(n, (c, lc))
    app_rank = t.app_rank.copy()
    minrank_tab = t.minrank_tab.copy()
    pintail_tab = t.pintail_tab.copy()
    inert = t.inert.copy()
    filter_succ = t.filter_succ.copy()

    live = [
        int(enc.chain_ops[ci, k])
        for ci in range(c)
        for k in range(int(enc.chain_len[ci]))
    ]

    from ..models.encode import op_class_masks

    masks = op_class_masks(enc)
    app_succ = masks["app_succ"]
    filt_succ = masks["filter_succ"]
    is_inert = masks["inert"]

    tails: dict[int, list[int]] = {}
    for j in live:
        if app_succ[j] and int(enc.num_records[j]) >= 1:
            tails.setdefault(int(enc.out_tail[j]), []).append(j)
        inert[j] = bool(is_inert[j])
        filter_succ[j] = bool(filt_succ[j])
    unique = sorted(tl for tl, js in tails.items() if len(js) == 1)
    for r, tl in enumerate(unique):
        app_rank[tails[tl][0]] = r

    def pin_of(j: int) -> int:
        if filt_succ[j]:
            return int(enc.out_tail[j])
        if app_succ[j]:
            nr = int(enc.num_records[j])
            tl = int(enc.out_tail[j])
            if tl >= nr:
                return tl - nr
        return int(PIN_INF)

    for ci in range(c):
        ln = int(enc.chain_len[ci])
        for k in range(ln - 1, -1, -1):
            j = int(enc.chain_ops[ci, k])
            minrank_tab[ci, k] = min(minrank_tab[ci, k + 1], app_rank[j])
            pintail_tab[ci, k] = min(int(pintail_tab[ci, k + 1]), pin_of(j))

    return PruneTables(
        app_rank=app_rank,
        minrank_tab=minrank_tab,
        pintail_tab=pintail_tab,
        inert=inert,
        filter_succ=filter_succ,
        n_ranked=len(unique),
    )
