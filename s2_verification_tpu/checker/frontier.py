"""Frontier (breadth-first) linearizability search — host reference.

This is the algorithm the TPU backend executes, in plain python, used both as
its differential oracle and as the place the invariants are documented.  It
is DFS-equivalent in verdict (explores the same reachable configuration
graph as the Wing–Gong search in checker/oracle.py) but executes layer by
layer so every step is a dense, batched map over a *frontier* — the shape
that vmaps onto a TPU and shards over a mesh.

Key structural facts it exploits (see checker/entries.py):

- Ops within a chain (client id) are sequential, so a configuration's
  linearized set is one prefix counter per chain — no op bitset.
- A configuration is ``(counts, state-set)``; two configurations with equal
  counts and equal state sets have identical futures, so layers dedup on
  exactly that pair (the frontier twin of Lowe's memoization).
- Candidate rule: chain c's next op j can linearize iff ``call[j] < m`` where
  ``m`` is the minimum return time over *all* unlinearized ops — and since
  returns are increasing within a chain, ``m`` is the min over chains of the
  next op's return.
- BFS layers are exhaustive: every linearization has length N, success iff
  some configuration completes all chains, failure iff a layer is empty.

**Prefix resume & snapshot cuts** (the incremental-verification engine,
see checker/prefix.py): because ops are call-ordered, a boundary after op
K with ``max(ret of ops[:K]) < min(call of ops[K:])`` is *prefix-closed* —
the candidate rule forces every linearization to commit exactly
``ops[:K]`` before any later op.  ``step_set`` distributes over unions of
state sets and the candidate/acceptance rules depend only on counts, so
the *union* of every reachable state set at that cut is a single carried
configuration that is verdict-equivalent to restarting from op 0:
resume-OK iff cold-OK, provided the union is exact.  ``check_frontier``
therefore accepts ``init_counts``/``init_states`` (resume from a carried
cut) and ``snapshot_cuts`` (collect those unions during the search); a
cut's union is only *complete* — and only then emitted on
``res.snapshots`` — once every configuration in a layer has linearized
past it, and any beam prune invalidates cuts not yet complete (a pruned
branch could have contributed states; a subset union can produce a false
ILLEGAL on resume, which is exactly the unsoundness the completeness rule
exists to prevent).

**Auto-close** (an optimization the reference's Porcupine search lacks):
an indefinite-failure append whose effect branch is *dead forever* — its
``match_seq_num`` is below every candidate state's tail (tails are
monotone), or its fencing token can no longer match (no remaining op sets
it) — steps every state to itself.  Linearizing it immediately, without
forking a child, is sound (nothing is lost: its only surviving branch
changes no state) and complete (it must be linearized eventually and the
position no longer matters).  Without this, the open ops left behind by
client rotation multiply candidate positions combinatorially — this is
precisely what makes adversarial histories CPU-intractable for Porcupine.
"""

from __future__ import annotations

import time
import zlib
from bisect import bisect_left
from dataclasses import dataclass, field
from typing import Iterable

from ..models.stream import APPEND, INIT_STATE, StreamState, step_set
from .entries import History, Op
from .oracle import CheckOutcome, CheckResult

__all__ = ["check_frontier", "check_frontier_auto", "FrontierStats", "state_digest"]


def _state_canon(s: StreamState) -> str:
    """The canonical text of one stream state — the digest's sole input."""
    return f"{s.tail}:{s.stream_hash}:{s.fencing_token!r}"


def state_digest(s: StreamState) -> int:
    """Deterministic 32-bit digest of a single stream state.

    The same canon the beam tie-break digest folds per state; exposed so
    service/distsearch.py can partition a frontier union into disjoint
    digest ranges that both ends of the wire compute identically
    (PYTHONHASHSEED-independent, like every digest in this module).
    """
    return zlib.crc32(_state_canon(s).encode())


def _cfg_digest(cfg) -> int:
    """Deterministic (PYTHONHASHSEED-independent) beam tie-break digest."""
    counts, states = cfg
    parts = [",".join(map(str, counts))]
    for s in sorted(states):
        parts.append(_state_canon(s))
    return zlib.crc32("|".join(parts).encode())


@dataclass
class FrontierStats:
    layers: int = 0
    max_frontier: int = 0
    max_state_set: int = 0
    auto_closed: int = 0
    expanded: int = 0
    pruned: bool = False
    #: verdict-exact commutativity-prune counters (``prune=True`` runs;
    #: see checker/prune.py — these never imply ``pruned``/UNKNOWN):
    #: candidates eagerly committed (inert or passing-filter), rows or
    #: configurations killed by the tail-pin bound, and candidate
    #: expansions skipped by the append rank gate (host engine only).
    prune_commits: int = 0
    prune_dead: int = 0
    prune_ranked: int = 0
    #: speculative-dive counters (device engine, ``speculate_depth > 0``):
    #: launches with a dive armed, total speculated layers, dives that
    #: conclusively accepted, dives discarded (rolled back).
    spec_launches: int = 0
    spec_layers: int = 0
    spec_accepts: int = 0
    spec_rollbacks: int = 0
    #: per-layer profile entries (``profile=True`` runs only): each is
    #: ``{"layer", "frontier", "states", "auto_closed", "elapsed_s"}`` —
    #: host search appends one per BFS layer, the device search one per
    #: compiled segment.  Plain dicts so ``dataclasses.asdict`` keeps the
    #: whole object JSON/checkpoint-serializable.
    timeline: list = field(default_factory=list)
    #: per-shard summary (mesh-sharded device runs with stats collection
    #: only): one dict per mesh device — ``{"shard", "device",
    #: "peak_occupancy", "occupancy_sum", "segments", "collective_wall_s",
    #: "skew"}`` — the raw material for verifyd's per-shard metric
    #: families and the viz shard panel.  Plain dicts, same
    #: serializability contract as ``timeline``.
    shards: list = field(default_factory=list)


def _op_dead_forever(
    op: Op, states: frozenset[StreamState], settable_tokens: frozenset[str]
) -> bool:
    """True if an indefinite append's effect branch can never fire again."""
    if not op.is_indefinite_append:
        return False
    inp = op.inp
    if inp.match_seq_num is not None:
        # Tails are monotone along every path; once every candidate state's
        # tail has passed the guard, the effect can never apply.
        if all(s.tail > inp.match_seq_num for s in states):
            return True
    if inp.batch_fencing_token is not None:
        token = inp.batch_fencing_token
        if all(s.fencing_token != token for s in states) and token not in settable_tokens:
            return True
    return False


def check_frontier(
    history: History,
    auto_close: bool = True,
    max_frontier: int | None = None,
    beam: bool = False,
    collect_stats: bool = False,
    witness: bool = True,
    profile: bool = False,
    init_counts: tuple[int, ...] | None = None,
    init_states: Iterable[StreamState] | None = None,
    snapshot_cuts: Iterable[int] | None = None,
    complete_cuts: bool = False,
    time_budget_s: float | None = None,
    progress=None,
    prune: bool = False,
) -> CheckResult:
    """Decide linearizability by frontier BFS.  Verdict matches the DFS.

    With ``beam=True``, layers exceeding ``max_frontier`` are *pruned* to the
    best configurations (fewest linearized indefinite appends — the lazy
    order — then deterministic hash) instead of aborting.  An OK under
    pruning is still sound (any accepting path proves linearizability); a
    dead end after pruning is inconclusive and reported UNKNOWN — callers
    escalate to an exhaustive pass (see :func:`check_frontier_auto`).

    ``witness=True`` keeps parent links for every configuration generated so
    an accepting path can be walked back into a concrete linearization —
    O(visited configs) extra memory (comparable to the DFS memo cache);
    pass ``witness=False`` for verdict-only runs.

    ``profile=True`` (implies ``collect_stats``) additionally records a
    per-layer timeline — frontier width, layer-max state-set size, ops
    auto-closed in the layer, and elapsed wall seconds — on
    ``stats.timeline``, the raw material for the viz frontier panel and
    the daemon's per-job ``profile`` field.

    ``init_counts``/``init_states`` resume the search from a carried cut:
    the caller asserts the boundary was prefix-closed and the states are
    the exact reachable-state union there (checker/prefix.py produces
    both).  A resumed OK linearization covers only the ops searched here.

    ``snapshot_cuts`` is a set of op boundaries K (each prefix-closed, as
    computed by :func:`..checker.prefix.closed_boundaries`); on an OK
    verdict the result carries ``res.snapshots`` — ``{K: sorted state
    union}`` for every cut whose union completed before any prune.

    ``complete_cuts=True`` holds an accept until every requested cut's
    union is complete: the relaxed acceptance (all remaining ops are
    indefinite appends) normally ends the search without materializing
    those layers, which leaves a requested cut below the frontier floor
    incomplete — an OK *without* its end union.  Distributed partition
    searches (service/distsearch.py) need the union itself, so they pay
    for the held layers; the verdict is unchanged, only the return is
    deferred until the unions are exact.

    ``time_budget_s`` bounds the search wall clock (checked per layer);
    expiry returns UNKNOWN, matching the other engines' budget semantics.

    ``progress`` is an optional :class:`.progress.ProgressSink`: each
    layer offers ``(ops committed, total ops, frontier width, states
    expanded)`` and the sink time-gates what actually leaves — one clock
    read per layer on the fast path.

    ``prune=True`` activates the verdict-exact commutativity prunes
    (checker/prune.py): eager commit of inert / passing-filter candidates
    inside the auto-close sweep, the append rank gate, and tail-pin
    dead-configuration elimination.  All three preserve OK, ILLEGAL *and*
    UNKNOWN (they never set ``stats.pruned``).  While ``snapshot_cuts``
    are collecting, the rank gate and pin kill stand down — gated branches
    never accept but can still contribute states to a cut union, and the
    snapshot contract promises the *exact* reachable union (see
    checker/prefix.py); eager commit reaches identical unions (filters are
    identity where they commit) and stays active.
    """
    collect_stats = collect_stats or profile
    ops = history.ops
    chains = history.chains
    n_chains = len(chains)
    stats = FrontierStats()

    if not ops:
        start = sorted(init_states) if init_states else [INIT_STATE]
        return CheckResult(CheckOutcome.OK, linearization=[], final_states=start)

    settable_tokens = frozenset(
        op.inp.set_fencing_token
        for op in ops
        if op.inp.input_type == APPEND and op.inp.set_fencing_token is not None
    )

    if init_counts is None:
        init_counts = tuple(0 for _ in range(n_chains))
    else:
        init_counts = tuple(init_counts)
    start_states = (
        frozenset(init_states) if init_states is not None else frozenset([INIT_STATE])
    )
    init_cfg = (init_counts, start_states)
    frontier: dict[tuple[tuple[int, ...], frozenset[StreamState]], None] = {
        init_cfg: None
    }
    base_sum = sum(init_counts)

    # Snapshot-cut table: K -> [expected counts at the cut, state union,
    # complete?].  The counts at a closed cut are forced (every
    # linearization of K ops commits exactly ops[:K]), so they are derived
    # from chain membership, and noting a config is a sum lookup plus an
    # equality check that doubles as a self-test of closedness.
    cuts: dict[int, list] = {}
    for K in sorted(set(snapshot_cuts or ())):
        if base_sum < K <= len(ops):
            counts_k = tuple(bisect_left(chain, K) for chain in chains)
            cuts[K] = [counts_k, set(), False]

    def note_cut(counts, states) -> None:
        cut = cuts.get(sum(counts))
        if cut is not None and not cut[2] and counts == cut[0]:
            cut[1].update(states)

    plan = None
    if prune:
        from .prune import analyze_history

        plan = analyze_history(history)
    # Rank gate + pin kill vs snapshot cuts: see the docstring — both
    # stand down while cuts are collecting so unions stay exact.
    order_prunes = plan is not None and not cuts
    # Witness links: cfg -> (parent cfg, ops auto-closed at the parent's
    # layer, the expanded op) — walked backwards on accept to recover a
    # concrete linearization (same role as the device engine's witness log).
    parents: dict = {init_cfg: None} if witness else {}
    target = tuple(len(c) for c in chains)
    # Deepest committed prefix across the whole search (diagnostics parity
    # with the oracle's global best, oracle.py:130).
    deep_counts = init_counts
    deep_sum = sum(init_counts)

    def walk(cfg) -> list[int]:
        rev: list[int] = []
        while parents[cfg] is not None:
            cfg, closed_ops, op_index = parents[cfg]
            rev.append(op_index)
            rev.extend(reversed(closed_ops))
        rev.reverse()
        return rev

    def completion(counts) -> list[int]:
        # Remaining ops are all indefinite appends: call order respects both
        # chain order and real time, and each no-effect step is valid.
        rest = [
            chains[c][k]
            for c in range(n_chains)
            for k in range(counts[c], len(chains[c]))
        ]
        rest.sort(key=lambda j: ops[j].call)
        return rest

    def deepest_of(counts) -> list[int]:
        return [chains[c][k] for c in range(n_chains) for k in range(counts[c])]

    # Per-chain prefix counts of indefinite appends, for the relaxed
    # acceptance test and the lazy beam ranking.
    open_prefix = [
        [0] * (len(chain) + 1) for chain in chains
    ]
    for c, chain in enumerate(chains):
        for k, op_index in enumerate(chain):
            open_prefix[c][k + 1] = open_prefix[c][k] + int(
                ops[op_index].is_indefinite_append
            )

    def accepting(counts) -> bool:
        """All remaining ops are indefinite appends.

        Such ops step every non-empty state set to a non-empty superset-or-
        self, and once only they remain every one of them is a candidate, so
        they can be linearized in any order — the configuration is accepted
        without materializing those 2^(remaining) layers.
        """
        for c in range(n_chains):
            remaining = len(chains[c]) - counts[c]
            if remaining and (
                open_prefix[c][-1] - open_prefix[c][counts[c]] != remaining
            ):
                return False
        return True

    def opens_taken(counts) -> int:
        return sum(open_prefix[c][counts[c]] for c in range(n_chains))

    def next_op(counts, c) -> Op | None:
        if counts[c] >= len(chains[c]):
            return None
        return ops[chains[c][counts[c]]]

    def window(counts) -> tuple[int, list[int]]:
        """(m, candidate chains) for a configuration."""
        m = None
        for c in range(n_chains):
            op = next_op(counts, c)
            if op is not None and (m is None or op.ret < m):
                m = op.ret
        cands = []
        for c in range(n_chains):
            op = next_op(counts, c)
            if op is not None and op.call < m:
                cands.append(c)
        return m, cands

    def auto_close_config(counts, states):
        closed_ops: list[int] = []
        if not auto_close:
            return counts, states, closed_ops
        counts = list(counts)
        changed = True
        while changed:
            changed = False
            _, cands = window(tuple(counts))
            for c in cands:
                op = next_op(tuple(counts), c)
                eager = False
                if plan is not None:
                    j = chains[c][counts[c]]
                    if j in plan.inert:
                        eager = True
                    else:
                        guard = plan.filter_guard.get(j)
                        if guard is not None:
                            t, hsh = guard
                            # Identity only where it passes EVERY state —
                            # a partial pass filters the set.
                            eager = all(
                                s.tail == t
                                and (hsh is None or s.stream_hash == hsh)
                                for s in states
                            )
                if eager or _op_dead_forever(op, states, settable_tokens):
                    closed_ops.append(chains[c][counts[c]])
                    counts[c] += 1
                    if eager:
                        stats.prune_commits += 1
                    else:
                        stats.auto_closed += 1
                    if cuts:
                        # Auto-close leaves states untouched, so each
                        # intermediate position is a reachable cut config.
                        note_cut(tuple(counts), states)
                    changed = True
        return tuple(counts), states, closed_ops

    # Per-layer profiling state: `entry` is the timeline dict under
    # construction; _finish_layer() seals it at every layer exit point.
    t_search = time.monotonic()
    entry: dict | None = None
    layer_states = 0
    auto_before = 0

    def _finish_layer() -> None:
        if entry is not None:
            entry["states"] = layer_states
            entry["auto_closed"] = stats.auto_closed - auto_before
            entry["elapsed_s"] = round(time.monotonic() - t_search, 6)

    deadline = None if time_budget_s is None else t_search + time_budget_s

    def _ok_result(order, final_states) -> CheckResult:
        res = CheckResult(
            CheckOutcome.OK,
            linearization=order,
            deepest=order or [],
            final_states=final_states,
        )
        if cuts:
            snaps = {
                K: sorted(cut[1])
                for K, cut in cuts.items()
                if cut[2] and cut[1]
            }
            if snaps:
                res.snapshots = snaps  # type: ignore[attr-defined]
        if collect_stats:
            res.stats = stats  # type: ignore[attr-defined]
        return res

    #: first accepting configuration seen while ``complete_cuts`` holds
    #: the return open (the verdict; only the unions are still cooking)
    held: tuple | None = None

    layer = 0
    while True:
        layer += 1
        stats.layers = layer
        if progress is not None:
            progress.update(
                ops_committed=deep_sum,
                total_ops=len(ops),
                frontier_width=len(frontier),
                states_expanded=stats.expanded,
                layer=layer,
                engine="frontier",
            )
        if deadline is not None and time.monotonic() > deadline:
            _finish_layer()
            res = CheckResult(CheckOutcome.UNKNOWN, deepest=deepest_of(deep_counts))
            if collect_stats:
                res.stats = stats  # type: ignore[attr-defined]
            return res
        stats.max_frontier = max(stats.max_frontier, len(frontier))
        layer_states = 0
        if profile:
            auto_before = stats.auto_closed
            entry = {
                "layer": layer,
                "frontier": len(frontier),
                "states": 0,
                "auto_closed": 0,
                "elapsed_s": 0.0,
            }
            stats.timeline.append(entry)

        closed: dict[tuple[tuple[int, ...], frozenset[StreamState]], None] = {}
        #: post-close cfg -> (pre-close cfg, ops closed getting there)
        close_link: dict = {}
        for counts, states in frontier:
            pre = (counts, states)
            if cuts:
                note_cut(counts, states)
            counts, states, closed_ops = auto_close_config(counts, states)
            key = (counts, states)
            if key not in closed:
                closed[key] = None
                close_link[key] = (pre, closed_ops)

        if cuts:
            # A cut is complete once no configuration can reach it again:
            # children of this layer sit strictly above the layer's minimum
            # post-close sum, so every cut at or below that floor is final.
            floor = min(sum(counts) for counts, _ in closed)
            for K, cut in cuts.items():
                if not cut[2] and K <= floor:
                    cut[2] = True
            if held is not None and all(cut[2] for cut in cuts.values()):
                _finish_layer()
                return _ok_result(*held)

        for counts, states in closed:
            csum = sum(counts)
            if csum > deep_sum:
                deep_sum, deep_counts = csum, counts
            if accepting(counts):
                stats.max_state_set = max(stats.max_state_set, len(states))
                layer_states = max(layer_states, len(states))
                if witness:
                    pre, closed_ops = close_link[(counts, states)]
                    order = walk(pre) + closed_ops + completion(counts)
                else:
                    order = None
                if complete_cuts and any(
                    not cut[2] for cut in cuts.values()
                ):
                    # The verdict is decided, but a requested union is
                    # still collecting below this configuration — hold
                    # the return and keep expanding until it is exact.
                    if held is None:
                        held = (order, sorted(states))
                    continue
                _finish_layer()
                return _ok_result(order, sorted(states))

        children: dict[tuple[tuple[int, ...], frozenset[StreamState]], None] = {}
        for counts, states in closed:
            if order_prunes and min(s.tail for s in states) > plan.min_pin(counts):
                # Every state's tail has passed the smallest pin among the
                # remaining ops: that op can never linearize from here, so
                # no accepting extension exists — exact, unlike the beam.
                stats.prune_dead += 1
                continue
            pre, closed_ops = close_link[(counts, states)]
            _, cands = window(counts)
            minrank = plan.min_remaining_rank(counts) if order_prunes else None
            for c in cands:
                if order_prunes:
                    r = plan.rank.get(chains[c][counts[c]])
                    if r is not None and r > minrank:
                        # A later-ranked append before an earlier-ranked
                        # one cannot appear in any accepting
                        # linearization (tails are monotone).
                        stats.prune_ranked += 1
                        continue
                op = next_op(counts, c)
                new_states = step_set(sorted(states), op.inp, op.out)
                stats.expanded += 1
                if not new_states:
                    continue
                stats.max_state_set = max(stats.max_state_set, len(new_states))
                layer_states = max(layer_states, len(new_states))
                child_counts = counts[:c] + (counts[c] + 1,) + counts[c + 1 :]
                child = (child_counts, frozenset(new_states))
                if child not in children:
                    children[child] = None
                    if witness and child not in parents:
                        parents[child] = (pre, tuple(closed_ops), chains[c][counts[c]])

        if not children:
            _finish_layer()
            if held is not None:
                # Exhaustion: no configuration can reach any cut again,
                # so every surviving union is final.
                for cut in cuts.values():
                    cut[2] = True
                return _ok_result(*held)
            outcome = CheckOutcome.UNKNOWN if stats.pruned else CheckOutcome.ILLEGAL
            res = CheckResult(outcome, deepest=deepest_of(deep_counts))
            if collect_stats:
                res.stats = stats  # type: ignore[attr-defined]
            return res
        if max_frontier is not None and len(children) > max_frontier:
            if not beam:
                _finish_layer()
                res = CheckResult(
                    CheckOutcome.UNKNOWN, deepest=deepest_of(deep_counts)
                )
                if collect_stats:
                    res.stats = stats  # type: ignore[attr-defined]
                return res
            stats.pruned = True
            if cuts:
                # A pruned branch could still have contributed states to a
                # cut not yet complete; a partial union resumed later can
                # only produce a *false ILLEGAL* — refuse those snapshots.
                for K in [K for K, cut in cuts.items() if not cut[2]]:
                    del cuts[K]
            ranked = sorted(
                children, key=lambda cfg: (opens_taken(cfg[0]), _cfg_digest(cfg))
            )
            children = dict.fromkeys(ranked[:max_frontier])
        _finish_layer()
        frontier = children


def check_frontier_auto(
    history: History,
    beam_width: int = 4096,
    exhaustive_cap: int | None = None,
    collect_stats: bool = False,
    witness: bool = True,
    profile: bool = False,
    init_counts: tuple[int, ...] | None = None,
    init_states: Iterable[StreamState] | None = None,
    snapshot_cuts: Iterable[int] | None = None,
    time_budget_s: float | None = None,
    progress=None,
    prune: bool = False,
) -> CheckResult:
    """Beam-first frontier check with exhaustive escalation.

    Phase 1 runs a pruned (beam) search: fast, and an OK is conclusive.
    Only if the beam dead-ends after pruning does phase 2 re-run without a
    beam — the porcupine-equivalent exhaustive search (optionally bounded by
    ``exhaustive_cap``, beyond which the result is UNKNOWN).  With
    ``profile=True`` the returned stats/timeline describe the phase that
    produced the verdict (the exhaustive pass, when it ran).
    """
    res = check_frontier(
        history,
        max_frontier=beam_width,
        beam=True,
        collect_stats=collect_stats,
        witness=witness,
        profile=profile,
        init_counts=init_counts,
        init_states=init_states,
        snapshot_cuts=snapshot_cuts,
        time_budget_s=time_budget_s,
        progress=progress,
        prune=prune,
    )
    if res.outcome != CheckOutcome.UNKNOWN:
        return res
    return check_frontier(
        history,
        max_frontier=exhaustive_cap,
        collect_stats=collect_stats,
        witness=witness,
        profile=profile,
        init_counts=init_counts,
        init_states=init_states,
        snapshot_cuts=snapshot_cuts,
        time_budget_s=time_budget_s,
        progress=progress,
        prune=prune,
    )
