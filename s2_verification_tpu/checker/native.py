"""ctypes bridge to the native C++ Wing–Gong checker (native/s2check.cpp).

The reference's CPU checking path is native (compiled Go + the porcupine
library); this module gives the framework the same property.  The shared
library is built lazily with ``make -C native`` on first use (g++; no
third-party deps) and the verdict semantics are identical to
:func:`..checker.oracle.check` — differential tests pin the two together.

The native engine consumes the same :class:`~..models.encode.EncodedHistory`
arrays as the device search, so host encode work is shared between backends.
The linearization order it returns is over encoded ops; this wrapper maps it
back to ``History.ops`` indices and prepends the forced prefix.
"""

from __future__ import annotations

import ctypes as ct
import os
import subprocess
import threading
import time as _time

import numpy as np

from ..models.encode import EncodedHistory, encode_history, intern_state
from ..models.stream import StreamState
from .entries import History
from .oracle import CheckOutcome, CheckResult

__all__ = ["native_available", "check_native", "NativeUnavailable"]

_REPO = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
_LIB_PATH = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "_native",
    "libs2check.so",
)
_lock = threading.Lock()
_lib: ct.CDLL | None = None
_build_error: str | None = None


class NativeUnavailable(RuntimeError):
    pass


def _u8(a: np.ndarray) -> np.ndarray:
    return np.ascontiguousarray(a, np.uint8)


def _needs_build() -> bool:
    if not os.path.exists(_LIB_PATH):
        return True
    src = os.path.join(_REPO, "native", "s2check.cpp")
    # A stale .so silently shadowing a newer source is worse than a rebuild.
    return os.path.exists(src) and os.path.getmtime(src) > os.path.getmtime(
        _LIB_PATH
    )


def _load() -> ct.CDLL:
    global _lib, _build_error
    with _lock:
        if _lib is not None:
            return _lib
        if _build_error is not None:
            raise NativeUnavailable(_build_error)
        if _needs_build():
            makefile = os.path.join(_REPO, "native", "Makefile")
            if not os.path.exists(makefile):
                _build_error = f"no prebuilt {_LIB_PATH} and no native/Makefile"
                raise NativeUnavailable(_build_error)
            proc = subprocess.run(
                ["make", "-C", os.path.dirname(makefile)],
                capture_output=True,
                text=True,
            )
            if proc.returncode != 0:
                _build_error = f"native build failed:\n{proc.stderr[-2000:]}"
                raise NativeUnavailable(_build_error)
        try:
            lib = ct.CDLL(_LIB_PATH)
            lib.s2_check.restype = ct.c_int32
        except OSError as e:
            # e.g. a wrong-arch .so copied in from another machine.
            _build_error = f"cannot load {_LIB_PATH}: {e}"
            raise NativeUnavailable(_build_error) from e
        _lib = lib
        return lib


def native_available() -> bool:
    try:
        _load()
        return True
    except NativeUnavailable:
        return False


def _ptr(a: np.ndarray, typ):
    return a.ctypes.data_as(ct.POINTER(typ))


def check_native(
    history: History,
    time_budget_s: float | None = None,
    _states_cap: int = 4096,
    profile: bool = False,
    enc: EncodedHistory | None = None,
    progress=None,
    prune: bool = False,
) -> CheckResult:
    """Decide linearizability with the native engine.

    Verdict semantics match :func:`..checker.oracle.check`, including the
    ``deepest`` linearized set on ILLEGAL/UNKNOWN.  ``_states_cap`` sizes
    the final-state output buffer (test hook; the wrapper retries with the
    exact size on overflow, so the default only affects allocation).

    ``profile=True`` attaches per-phase wall attribution to the result as
    ``res.profile`` — ``{"encode_s", "search_s", "steps", "cache_hits"}``
    (the native search has no BFS layers; DFS steps and memo hits are its
    shape signal).  ``search_s`` accumulates the rare overflow re-invoke.

    ``enc`` lets callers that already encoded ``history`` (the batched
    lane runner encodes a whole launch group up front) skip the second
    encode; it must be ``encode_history(history)`` output for the same
    history.

    ``progress`` is an optional :class:`.progress.ProgressSink`.  The C
    search is one blocking call, so only two offers are possible: a rate
    baseline before the search and a final heartbeat after it (the sink's
    trivial-job rule keeps fast runs silent).

    ``prune=True`` hands the DFS the verdict-exact precedence tables from
    :mod:`.prune` (the ``enc=``-derived append rank order plus the inert
    mask): ranked successful appends are gated to their forced order and
    exhausted identity-op subtrees skip their siblings.  Verdicts are
    unchanged — OK, ILLEGAL and UNKNOWN all match ``prune=False``.
    """
    lib = _load()
    t_enc0 = _time.monotonic() if profile else 0.0
    if enc is None:
        enc = encode_history(history)
    encode_s = (_time.monotonic() - t_enc0) if profile else 0.0

    def _attach(res: CheckResult, search_s: float) -> CheckResult:
        if profile:
            res.profile = {  # type: ignore[attr-defined]
                "encode_s": round(encode_s, 6),
                "search_s": round(search_s, 6),
                "steps": res.steps,
                "cache_hits": res.cache_hits,
            }
        return res
    if enc.total_remaining == 0 and enc.num_ops == 0:
        return _attach(
            CheckResult(
                CheckOutcome.OK,
                linearization=list(enc.forced_prefix),
                final_states=sorted(enc.init_states),
            ),
            0.0,
        )
    n = enc.num_ops

    init = sorted(intern_state(enc, s) for s in enc.init_states)
    init_tail = np.asarray([t for t, _, _, _ in init], np.uint32)
    init_hash = np.asarray(
        [(hi << 32) | lo for _, hi, lo, _ in init], np.uint64
    )
    init_tok = np.asarray([k for _, _, _, k in init], np.int32)

    out_hash = (enc.out_hash_hi.astype(np.uint64) << np.uint64(32)) | enc.out_hash_lo.astype(
        np.uint64
    )
    if prune:
        from .prune import RANK_INF, analyze_encoded

        pt = analyze_encoded(enc)
        app_rank = np.ascontiguousarray(
            np.where(pt.app_rank == RANK_INF, np.int32(-1), pt.app_rank),
            np.int32,
        )
        inert = _u8(pt.inert)
    else:
        app_rank = np.full(max(1, n), -1, np.int32)
        inert = np.zeros(max(1, n), np.uint8)
    order = np.zeros(max(1, n), np.int32)
    order_len = ct.c_int32(0)
    states_cap = _states_cap
    st_tail = np.zeros(states_cap, np.uint32)
    st_hash = np.zeros(states_cap, np.uint64)
    st_tok = np.zeros(states_cap, np.int32)
    states_len = ct.c_int32(0)
    steps = ct.c_int64(0)
    hits = ct.c_int64(0)

    i32, u32, u64, u8 = ct.c_int32, ct.c_uint32, ct.c_uint64, ct.c_uint8

    def invoke(budget_s):
        return lib.s2_check(
        ct.c_int32(n),
        _ptr(np.ascontiguousarray(enc.op_type, np.int32), i32),
        _ptr(_u8(enc.has_set_token), u8),
        _ptr(np.ascontiguousarray(enc.set_token, np.int32), i32),
        _ptr(_u8(enc.has_batch_token), u8),
        _ptr(np.ascontiguousarray(enc.batch_token, np.int32), i32),
        _ptr(_u8(enc.has_match), u8),
        _ptr(np.ascontiguousarray(enc.match_seq, np.uint32), u32),
        _ptr(np.ascontiguousarray(enc.num_records, np.uint32), u32),
        _ptr(np.ascontiguousarray(enc.rh_row, np.int32), i32),
        _ptr(np.ascontiguousarray(enc.rh_len, np.int32), i32),
        ct.c_int32(enc.rh_hi.shape[1]),
        _ptr(np.ascontiguousarray(enc.rh_hi, np.uint32), u32),
        _ptr(np.ascontiguousarray(enc.rh_lo, np.uint32), u32),
        _ptr(_u8(enc.out_failure), u8),
        _ptr(_u8(enc.out_definite), u8),
        _ptr(np.ascontiguousarray(enc.out_tail, np.uint32), u32),
        _ptr(_u8(enc.out_has_hash), u8),
        _ptr(np.ascontiguousarray(out_hash, np.uint64), u64),
        _ptr(np.ascontiguousarray(enc.call, np.int32), i32),
        _ptr(np.ascontiguousarray(enc.ret, np.int32), i32),
        _ptr(app_rank, i32),
        _ptr(inert, u8),
        ct.c_int32(len(init)),
        _ptr(init_tail, u32),
        _ptr(init_hash, u64),
        _ptr(init_tok, i32),
        ct.c_double(budget_s),
        _ptr(order, i32),
        ct.byref(order_len),
        _ptr(st_tail, u32),
        _ptr(st_hash, u64),
        _ptr(st_tok, i32),
            ct.c_int32(states_cap),
            ct.byref(states_len),
            ct.byref(steps),
            ct.byref(hits),
        )

    if progress is not None:
        # Rate baseline only (the sink never emits on first offer).
        progress.update(ops_committed=0, total_ops=n, engine="native")
    t_search0 = _time.monotonic() if profile else 0.0
    rc = invoke(-1.0 if time_budget_s is None else time_budget_s)
    if rc == 0 and states_len.value > states_cap:
        # Final state set overflowed the buffer; re-run with room for all of
        # it (rare: needs >4096 simultaneously-open ambiguous appends).  The
        # retry runs unbudgeted: OK is already proven and the re-derivation
        # is deterministic, so a timeout here must not downgrade the verdict
        # (wall-clock can reach ~2x the budget in this rare case).
        states_cap = int(states_len.value)
        st_tail = np.zeros(states_cap, np.uint32)
        st_hash = np.zeros(states_cap, np.uint64)
        st_tok = np.zeros(states_cap, np.int32)
        rc = invoke(-1.0)
        assert rc == 0 and states_len.value <= states_cap
    search_s = (_time.monotonic() - t_search0) if profile else 0.0
    if progress is not None:
        progress.update(
            ops_committed=n if rc == 0 else int(order_len.value),
            total_ops=n,
            states_expanded=int(steps.value),
            engine="native",
            final=True,
        )

    # Encoded op index → History.ops index (forced-prefix ops were peeled
    # off before encoding).
    keep_index = enc.keep_index()

    if rc != 0:
        outcome = CheckOutcome.UNKNOWN if rc == 2 else CheckOutcome.ILLEGAL
        deepest = list(enc.forced_prefix) + [
            keep_index[j] for j in order[: order_len.value]
        ]
        return _attach(
            CheckResult(
                outcome,
                deepest=deepest,
                steps=int(steps.value),
                cache_hits=int(hits.value),
            ),
            search_s,
        )

    lin = list(enc.forced_prefix) + [
        keep_index[j] for j in order[: order_len.value]
    ]
    final = [
        StreamState(
            tail=int(st_tail[i]),
            stream_hash=int(st_hash[i]),
            fencing_token=enc.token_of_id[int(st_tok[i])],
        )
        for i in range(states_len.value)
    ]
    return _attach(
        CheckResult(
            CheckOutcome.OK,
            linearization=lin,
            deepest=lin,
            final_states=final,
            steps=int(steps.value),
            cache_hits=int(hits.value),
        ),
        search_s,
    )
