"""CPU oracle: Wing–Gong linearizability DFS with just-in-time memoization.

This is the default backend and the correctness oracle for the TPU frontier
search.  It re-implements the published algorithm the reference relies on via
its Porcupine dependency (Wing & Gong 1993; Lowe 2017), specialized to the
powerset-lifted nondeterministic stream model (SURVEY.md §1-L4, §3.5):

- entries are the call/return events in real-time order, on a doubly-linked
  list;
- at each step, try to linearize some pending call by applying the model's
  ``step_set`` to the current candidate-state set; commit if the result is
  non-empty and the ``(linearized-op bitset, state set)`` pair is unseen;
- reaching a return with nothing linearizable backtracks.

Result semantics match ``porcupine.CheckEventsVerbose(model, events, 0)``
(golang/s2-porcupine/main.go:605-606): OK iff some total order of all ops,
consistent with real time, drives the state-set through every observation
without emptying it.
"""

from __future__ import annotations

import time as _time
from dataclasses import dataclass, field
from enum import Enum

from ..models.stream import INIT_STATE, StreamState, step_set
from .entries import History, Op

__all__ = ["CheckOutcome", "CheckResult", "check", "check_events"]


class CheckOutcome(Enum):
    OK = "ok"
    ILLEGAL = "illegal"
    UNKNOWN = "unknown"  # time budget exhausted before a verdict


@dataclass
class CheckResult:
    outcome: CheckOutcome
    #: op indices (into History.ops) in linearization order, when OK
    linearization: list[int] | None = None
    #: deepest set of linearized op indices reached, for diagnostics/viz
    deepest: list[int] = field(default_factory=list)
    #: per distinct deepest configuration: (linearized op indices, op
    #: indices that refused to linearize there) — the failure-diagnostics
    #: analog of porcupine's partial-linearization info (main.go:606,627)
    refusals: list[tuple[list[int], list[int]]] = field(default_factory=list)
    #: states consistent with the full linearization, when OK
    final_states: list[StreamState] = field(default_factory=list)
    #: search statistics
    steps: int = 0
    cache_hits: int = 0

    @property
    def ok(self) -> bool:
        return self.outcome == CheckOutcome.OK


class _Entry:
    __slots__ = ("op", "is_call", "match", "prev", "nxt")

    def __init__(self, op: Op | None, is_call: bool):
        self.op = op
        self.is_call = is_call
        self.match: _Entry | None = None
        self.prev: _Entry | None = None
        self.nxt: _Entry | None = None


def _build_entry_list(ops: list[Op]) -> _Entry:
    """Head sentinel of the doubly-linked call/return entry list."""
    items: list[tuple[int, _Entry]] = []
    for op in ops:
        call = _Entry(op, True)
        ret = _Entry(op, False)
        call.match = ret
        items.append((op.call, call))
        items.append((op.ret, ret))
    items.sort(key=lambda t: t[0])
    head = _Entry(None, False)
    prev = head
    for _, e in items:
        prev.nxt = e
        e.prev = prev
        prev = e
    return head


def _lift(call: _Entry) -> None:
    """Unlink a call entry and its return from the list (order preserved)."""
    ret = call.match
    call.prev.nxt = call.nxt
    if call.nxt is not None:
        call.nxt.prev = call.prev
    ret.prev.nxt = ret.nxt
    if ret.nxt is not None:
        ret.nxt.prev = ret.prev


def _unlift(call: _Entry) -> None:
    """Reinsert a lifted call/return pair using their remembered neighbors.

    Safe because lifts are undone in LIFO order (the DFS backtracks the most
    recent commitment first), so the remembered neighbors are still adjacent.
    """
    ret = call.match
    ret.prev.nxt = ret
    if ret.nxt is not None:
        ret.nxt.prev = ret
    call.prev.nxt = call
    if call.nxt is not None:
        call.nxt.prev = call


def _state_key(states: list[StreamState]) -> frozenset[StreamState]:
    return frozenset(states)


def check(history: History, time_budget_s: float | None = None) -> CheckResult:
    """Decide linearizability of a prepared history."""
    ops = history.ops
    if not ops:
        return CheckResult(CheckOutcome.OK, linearization=[], final_states=[INIT_STATE])

    head = _build_entry_list(ops)
    states: list[StreamState] = [INIT_STATE]
    linearized = 0
    cache: set[tuple[int, frozenset[StreamState]]] = {(0, _state_key(states))}
    # Undo stack of (call entry, states before linearizing it).
    calls: list[tuple[_Entry, list[StreamState]]] = []
    n_lin = 0
    best: tuple[int, int] = (0, 0)  # (count, bitset) deepest point reached
    steps = 0
    cache_hits = 0
    deadline = None if time_budget_s is None else _time.monotonic() + time_budget_s

    entry = head.nxt
    while head.nxt is not None:
        if deadline is not None and steps % 1024 == 0 and _time.monotonic() > deadline:
            return CheckResult(
                CheckOutcome.UNKNOWN,
                deepest=_bits_to_list(best[1]),
                steps=steps,
                cache_hits=cache_hits,
            )
        if entry is None:
            # Fell off the end of the list without crossing a return: every
            # remaining entry was a call we failed to linearize.  Backtrack.
            if not calls:
                return CheckResult(
                    CheckOutcome.ILLEGAL,
                    deepest=_bits_to_list(best[1]),
                    steps=steps,
                    cache_hits=cache_hits,
                )
            entry, states = calls.pop()
            linearized &= ~(1 << entry.op.index)
            n_lin -= 1
            _unlift(entry)
            entry = entry.nxt
            continue
        if entry.is_call:
            steps += 1
            op = entry.op
            new_states = step_set(states, op.inp, op.out)
            if new_states:
                new_lin = linearized | (1 << op.index)
                key = (new_lin, _state_key(new_states))
                if key not in cache:
                    cache.add(key)
                    calls.append((entry, states))
                    states = new_states
                    linearized = new_lin
                    n_lin += 1
                    if n_lin > best[0]:
                        best = (n_lin, new_lin)
                    _lift(entry)
                    entry = head.nxt
                    continue
                cache_hits += 1
            entry = entry.nxt
        else:
            # A return of a not-yet-linearized op: its call must linearize
            # before real time passes this point.  Backtrack.
            if not calls:
                return CheckResult(
                    CheckOutcome.ILLEGAL,
                    deepest=_bits_to_list(best[1]),
                    steps=steps,
                    cache_hits=cache_hits,
                )
            entry, states = calls.pop()
            linearized &= ~(1 << entry.op.index)
            n_lin -= 1
            _unlift(entry)
            entry = entry.nxt

    order = [e.op.index for e, _ in calls]
    return CheckResult(
        CheckOutcome.OK,
        linearization=order,
        deepest=order,
        final_states=list(states),
        steps=steps,
        cache_hits=cache_hits,
    )


def _bits_to_list(bits: int) -> list[int]:
    out = []
    i = 0
    while bits:
        if bits & 1:
            out.append(i)
        bits >>= 1
        i += 1
    return out


def check_events(events, elide_trivial: bool = True, time_budget_s: float | None = None):
    """Convenience: decode-prepared events → CheckResult."""
    from .entries import prepare

    return check(prepare(events, elide_trivial=elide_trivial), time_budget_s=time_budget_s)
