"""History preparation: events → operations for the search engines.

Converts a decoded event stream (call starts/finishes keyed by ``op_id``,
mirroring golang/s2-porcupine/main.go:529-563) into an array of operations
with real-time call/return indices, plus structural metadata the searches
exploit:

- **Pending-call completion.**  A call with no finish (a client crashed before
  its deferred indefinite-failure event was flushed) is completed with the
  weakest consistent output: appends get an indefinite failure (may or may not
  have applied), reads/check-tails a definite failure.  Its return is placed
  after every real event, which gives it the reference's open-op semantics:
  linearizable at any point after its call.

- **Trivial-op elision.**  An op whose output makes ``step`` the identity on
  *every* state — definite append failures and failed reads/check-tails all
  return ``{state}`` unconditionally — constrains nothing.  Such an op can be
  inserted into any legal linearization of the remaining ops (any position
  after everything that returned before its call and before everything that
  called after its return; real-time order guarantees such a slot exists), so
  the searches drop them up front and the result is unchanged.  This is a
  structural optimization the reference's Porcupine search does not perform.

- **Chain structure.**  Ops within one ``client_id`` are sequential in real
  time (the collector's clients issue ops one at a time and never reuse a
  rotated-away client id), so the set of linearized ops within a chain is
  always a prefix.  The device search encodes a configuration's linearized
  set as one counter per chain instead of an op bitset.
"""

from __future__ import annotations

from dataclasses import dataclass, field

from ..models.stream import (
    APPEND,
    StreamInput,
    StreamOutput,
    input_from_start,
    output_from_finish,
)
from ..utils import events as ev

__all__ = ["Op", "History", "HistoryError", "prepare"]


class HistoryError(ValueError):
    """The event stream is not a well-formed history."""


@dataclass(frozen=True)
class Op:
    index: int  # dense op index within the prepared history
    op_id: int  # wire op_id
    client_id: int
    call: int  # index of the call event in real time
    ret: int  # index of the return event; pending ops return after everything
    inp: StreamInput
    out: StreamOutput
    pending: bool = False

    @property
    def is_indefinite_append(self) -> bool:
        return (
            self.inp.input_type == APPEND
            and self.out.failure
            and not self.out.definite_failure
        )

    @property
    def is_trivial(self) -> bool:
        """True iff step(s, inp, out) == {s} for every state s."""
        return self.out.failure and self.out.definite_failure


@dataclass
class History:
    """A prepared history: search-relevant ops plus elided trivial ops."""

    ops: list[Op]
    trivial_ops: list[Op] = field(default_factory=list)
    #: chains[c] = op indices (into ops) of chain c, in call order
    chains: list[list[int]] = field(default_factory=list)
    #: chain_of[i] = chain index of ops[i]
    chain_of: list[int] = field(default_factory=list)

    @property
    def num_ops(self) -> int:
        return len(self.ops)


def _collect_ops(events: list[ev.LabeledEvent]) -> list[Op]:
    calls: dict[int, tuple[int, int, StreamInput]] = {}  # op_id -> (time, client, inp)
    finished: list[Op] = []
    seen_op_ids: set[int] = set()
    for time, le in enumerate(events):
        if le.is_start:
            if le.op_id in seen_op_ids:
                raise HistoryError(f"duplicate call for op_id {le.op_id}")
            calls[le.op_id] = (time, le.client_id, input_from_start(le.event))
            seen_op_ids.add(le.op_id)
        else:
            pending = calls.pop(le.op_id, None)
            if pending is None:
                raise HistoryError(f"finish without call for op_id {le.op_id}")
            call_time, client_id, inp = pending
            if le.client_id != client_id:
                raise HistoryError(
                    f"op_id {le.op_id} finished by client {le.client_id} "
                    f"but called by client {client_id}"
                )
            finished.append(
                Op(
                    index=-1,
                    op_id=le.op_id,
                    client_id=client_id,
                    call=call_time,
                    ret=time,
                    inp=inp,
                    out=output_from_finish(le.event),
                )
            )
    # Complete pending calls with the weakest consistent output, returning
    # after every real event.
    horizon = len(events)
    for op_id, (call_time, client_id, inp) in sorted(calls.items(), key=lambda kv: kv[1][0]):
        if inp.input_type == APPEND:
            out = StreamOutput(failure=True, definite_failure=False)
        else:
            out = StreamOutput(failure=True, definite_failure=True)
        finished.append(
            Op(
                index=-1,
                op_id=op_id,
                client_id=client_id,
                call=call_time,
                ret=horizon,
                inp=inp,
                out=out,
                pending=True,
            )
        )
        horizon += 1
    finished.sort(key=lambda op: op.call)
    return finished


def prepare(events: list[ev.LabeledEvent], elide_trivial: bool = True) -> History:
    """Build a :class:`History` from a decoded event stream."""
    all_ops = _collect_ops(events)

    # Sanity: within a client, ops must be sequential in real time.
    last_ret: dict[int, tuple[int, int]] = {}
    for op in all_ops:
        prev = last_ret.get(op.client_id)
        if prev is not None and op.call < prev[0]:
            raise HistoryError(
                f"client {op.client_id} has overlapping ops "
                f"{prev[1]} and {op.op_id}: histories must be sequential per client"
            )
        last_ret[op.client_id] = (op.ret, op.op_id)

    kept: list[Op] = []
    trivial: list[Op] = []
    for op in all_ops:
        if elide_trivial and op.is_trivial:
            trivial.append(op)
        else:
            kept.append(op)

    ops = [
        Op(
            index=i,
            op_id=op.op_id,
            client_id=op.client_id,
            call=op.call,
            ret=op.ret,
            inp=op.inp,
            out=op.out,
            pending=op.pending,
        )
        for i, op in enumerate(kept)
    ]

    chain_index: dict[int, int] = {}
    chains: list[list[int]] = []
    chain_of: list[int] = []
    for op in ops:
        c = chain_index.get(op.client_id)
        if c is None:
            c = len(chains)
            chain_index[op.client_id] = c
            chains.append([])
        chains[c].append(op.index)
        chain_of.append(c)

    return History(ops=ops, trivial_ops=trivial, chains=chains, chain_of=chain_of)
