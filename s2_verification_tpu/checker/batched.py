"""Cross-job batched checking: one launch decides many same-shape lanes.

Two lane engines behind one result shape:

- **batch-native** — a Python loop over the native C engine with
  *pre-encoded* lanes (``check_native(..., enc=...)``).  The ctypes
  boundary is ~10µs/call and the C search releases the GIL, so on a CPU
  node the per-job win comes from encoding the whole launch group once
  (:func:`..models.encode.encode_batch`) and skipping every per-job
  dispatch layer between verdicts.  Every lane gets the canonical rich
  ``CheckResult`` (witness, refusals, deepest), so this engine is
  drop-in for any job the sequential path could serve.

- **batch-vmap** — the whole launch group runs as ONE compiled
  ``jax.vmap`` of :func:`..checker.device.run_search` over a lane axis.
  ``encode_batch`` makes every lane's arrays shape-identical, per-lane
  ``SearchTables``/``Frontier`` pytrees are stacked on a leading axis,
  and JAX's batched ``while_loop`` gives the continuous-batching lane
  semantics for free: a lane whose search stops (accept/empty) has its
  carry **latched** — the batch keeps stepping for the stragglers, the
  decided lane's result is frozen, and ``RunOut.layers`` records how
  early it decided (the early-exit signal the metrics report).  Beam
  soundness is per lane: OK is conclusive under pruning, EMPTY is
  ILLEGAL only if that lane never pruned; anything else returns ``None``
  and the caller escalates that lane on the sequential path.  No witness
  is recovered (viz-requesting jobs belong on the sequential path).

Launch sizes are bucketed to powers of two (short lanes are padded by
repeating the last real lane and discarding the copies' results) so the
compile-variant count stays bounded exactly like every other shape axis.
"""

from __future__ import annotations

import time
from dataclasses import dataclass

import numpy as np

from ..models.encode import EncodedHistory, round_pow2
from .device import (
    STOP_ACCEPT,
    STOP_EMPTY,
    Frontier,
    build_tables,
    init_frontier,
    run_search,
)
from .entries import History
from .native import check_native, native_available
from .oracle import CheckOutcome, CheckResult

__all__ = [
    "BatchLane",
    "LaneVerdict",
    "check_batch_native",
    "check_batch_vmap",
    "default_engine",
]

#: Beam capacity per vmap lane.  Collector-shaped histories decide at
#: tiny frontiers (the sequential driver *starts* at 16); per-layer fold
#: cost scales with this width for every lane, so the lane default stays
#: small and a pruned dead end escalates that one lane to the sequential
#: path instead of paying 4096-wide layers for everyone.
VMAP_LANE_CAPACITY = 64


@dataclass
class BatchLane:
    """One job's search inputs inside a launch group."""

    history: History
    enc: EncodedHistory
    time_budget_s: float | None = None


@dataclass
class LaneVerdict:
    """Per-lane outcome of a batched launch.

    ``result`` is ``None`` when this engine could not decide the lane
    (vmap lane pruned into a dead end, or the lane was skipped) — the
    caller runs that lane through the sequential portfolio instead.
    ``search_s`` is the per-lane attributed search wall: the lane's own
    C call for batch-native, the shared kernel wall for batch-vmap.
    ``layers`` (vmap only) is how many layers the lane ran before its
    verdict latched — lanes with ``layers`` below the launch maximum
    decided early while the batch kept stepping.
    """

    result: CheckResult | None
    engine: str
    search_s: float
    layers: int = -1
    skipped: str | None = None


def default_engine() -> str:
    """'native' when the C engine is loadable, else 'vmap'."""
    return "native" if native_available() else "vmap"


def check_batch_native(
    lanes: list[BatchLane],
    skip=None,
    profile: bool = False,
    on_lane=None,
    progress=None,
    prune: bool = False,
) -> list[LaneVerdict]:
    """Run each lane through the native engine without re-encoding.

    ``skip(i)`` is consulted immediately before lane *i* dispatches and
    returns a reason string to skip it (cancelled / deadline passed) or
    ``None`` to run it — the late-cancel boundary between lanes that the
    sequential path gets from its per-job cancel checks.

    ``on_lane(i, verdict)`` fires the moment lane *i* decides, while
    later lanes are still searching — the early-exit hook the batcher
    uses to answer clients lane by lane.

    ``progress`` is an optional per-lane sequence of
    :class:`.progress.ProgressSink` (or ``None``) aligned with ``lanes``:
    each lane's heartbeats go to its own sink, so a mega-launch keeps
    per-job attribution.  The C call is blocking, so each lane offers a
    baseline before its search and a final sample after it.
    """
    out: list[LaneVerdict] = []
    for i, lane in enumerate(lanes):
        sink = progress[i] if progress is not None else None
        reason = skip(i) if skip is not None else None
        if reason is not None:
            v = LaneVerdict(None, "batch-native", 0.0, skipped=reason)
        else:
            total = len(lane.history.ops)
            if sink is not None:
                sink.update(
                    ops_committed=0, total_ops=total, engine="batch-native"
                )
            t0 = time.monotonic()
            res = check_native(
                lane.history,
                time_budget_s=lane.time_budget_s,
                profile=profile,
                enc=lane.enc,
                prune=prune,
            )
            v = LaneVerdict(res, "batch-native", time.monotonic() - t0)
            if sink is not None:
                done = (
                    res.linearization
                    if res.outcome == CheckOutcome.OK
                    else res.deepest
                )
                sink.update(
                    ops_committed=len(done or []),
                    total_ops=total,
                    states_expanded=res.steps,
                    engine="batch-native",
                    final=True,
                )
        out.append(v)
        if on_lane is not None:
            on_lane(i, v)
    return out


def _stack(trees):
    import jax
    import jax.numpy as jnp

    return jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *trees)


def _mega_launch(tables, frontier, max_layers):
    """jit(vmap(run_search)) — compiled once per (lane dims, B) bucket."""
    import jax

    fn = jax.vmap(
        lambda t, f, ml: run_search(t, f, ml, allow_prune=True),
        in_axes=(0, 0, None),
    )
    return fn(tables, frontier, max_layers)


def check_batch_vmap(
    lanes: list[BatchLane],
    skip=None,
    capacity: int = VMAP_LANE_CAPACITY,
    progress=None,
    prune: bool = False,
) -> list[LaneVerdict]:
    """One vmapped frontier search over the whole launch group.

    Lanes must come from one :func:`..models.encode.encode_batch` call
    (shape-identical arrays).  Per-lane verdicts follow the beam
    soundness rules; undecidable lanes return ``result=None``.

    ``progress`` is an optional per-lane sink sequence (see
    :func:`check_batch_native`).  The whole group is one compiled launch,
    so each live lane gets a baseline before it and a final sample after,
    with the lane's own latched layer count.
    """
    n = len(lanes)
    verdicts: list[LaneVerdict | None] = [None] * n
    live: list[int] = []  # lane indices that actually launch
    tables_list = []
    frontier_list = []
    for i, lane in enumerate(lanes):
        reason = skip(i) if skip is not None else None
        if reason is not None:
            verdicts[i] = LaneVerdict(None, "batch-vmap", 0.0, skipped=reason)
            continue
        enc = lane.enc
        if enc.total_remaining == 0:
            # Forced prefix consumed every op: trivially OK (same early
            # return as the sequential drivers).
            verdicts[i] = LaneVerdict(
                CheckResult(
                    CheckOutcome.OK,
                    linearization=list(enc.forced_prefix),
                    final_states=sorted(enc.init_states),
                ),
                "batch-vmap",
                0.0,
                layers=0,
            )
            continue
        try:
            frontier_list.append(init_frontier(enc, capacity))
        except ValueError:
            # More initial states than lane capacity: sequential path.
            verdicts[i] = LaneVerdict(
                None, "batch-vmap", 0.0, skipped="init-overflow"
            )
            continue
        tables_list.append(build_tables(enc, prune=prune))
        live.append(i)

    if not live:
        return verdicts  # type: ignore[return-value]  (all entries set)

    # Pad the launch to a power-of-two lane count so compile variants stay
    # bounded; pad lanes repeat the last real lane and are discarded.
    b = round_pow2(len(live), 1)
    while len(tables_list) < b:
        tables_list.append(tables_list[-1])
        frontier_list.append(frontier_list[-1])

    max_layers = max(lanes[i].enc.total_remaining for i in live) + 2
    if progress is not None:
        for i in live:
            if progress[i] is not None:
                progress[i].update(
                    ops_committed=0,
                    total_ops=len(lanes[i].history.ops),
                    engine="batch-vmap",
                )
    t0 = time.monotonic()
    out = _mega_launch(_stack(tables_list), _stack(frontier_list), max_layers)
    stop = np.asarray(out.stop_code)
    pruned = np.asarray(out.pruned_ever)
    layers = np.asarray(out.layers)
    wall = time.monotonic() - t0

    for k, i in enumerate(live):
        code, lane_layers = int(stop[k]), int(layers[k])
        if code == STOP_ACCEPT:
            res: CheckResult | None = CheckResult(CheckOutcome.OK)
        elif code == STOP_EMPTY and not bool(pruned[k]):
            res = CheckResult(CheckOutcome.ILLEGAL)
        else:
            res = None  # pruned dead end / layer cap: escalate this lane
        verdicts[i] = LaneVerdict(res, "batch-vmap", wall, layers=lane_layers)
        sink = progress[i] if progress is not None else None
        if sink is not None:
            total = len(lanes[i].history.ops)
            sink.update(
                ops_committed=total if code == STOP_ACCEPT else 0,
                total_ops=total,
                layer=lane_layers,
                engine="batch-vmap",
                final=True,
            )
    return verdicts  # type: ignore[return-value]
