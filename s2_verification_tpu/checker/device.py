"""Device (TPU) frontier linearizability search.

The jit/vmap twin of :mod:`.frontier`: the whole layer-by-layer search runs
*inside one compiled program* as a ``lax.while_loop`` whose carry is a dense
frontier of configurations, so there is no per-layer host dispatch.  The
host's only jobs are encoding the history (models/encode.py), picking
capacity buckets, and escalating when a run reports it needs a wider
frontier or state set.

A configuration is ``(counts per chain, canonical candidate-state set)``:

- ``counts  [F, C] int32``  — linearized prefix length of every chain;
- ``tail/hash_hi/hash_lo/token  [F, S]`` + ``svalid [F, S] bool`` — the
  state set, canonically sorted (valid first, then by state key) and
  zeroed in invalid slots so equal sets are bitwise equal;
- ``valid [F] bool`` — frontier occupancy.

One layer (the while-loop body):

1. **auto-close** — a nested, vmapped ``lax.while_loop`` advances each
   configuration past indefinite appends whose effect branch is provably
   dead (guards stale against every candidate state, token never settable)
   — the device twin of frontier.py's auto-close;
2. **accept** — a configuration whose remaining ops are all indefinite
   appends accepts the history (table lookup + reduction);
3. **expand** — every (configuration × candidate chain × candidate state)
   triple steps through :func:`~..ops.step_kernel.step_kernel` under two
   nested ``vmap``s; successor sets are deduped and canonicalized with an
   O(S²) comparison matrix + ``lexsort`` per child;
4. **dedup + compact** — children flatten to ``[F*C]`` rows, get a 64-bit
   mixed hash, and a global ``lexsort`` by (validity, lazy-order rank,
   hash) brings equal configurations adjacent for exact-compare dedup; a
   second stable sort compacts survivors into the next frontier.  Layers
   never revisit earlier configurations (sum(counts) grows by one per
   layer) so no cross-layer visited set is needed.

Soundness under capacity pressure mirrors the host beam search: an OK is
always conclusive (every frontier state is genuinely reachable); a dead end
after any pruning or state-set overflow is UNKNOWN, and the driver
escalates to the next capacity bucket, resuming from the last intact
pre-expansion frontier that the compiled program hands back.

Multi-chip: every per-configuration computation is elementwise over the
frontier axis, so sharding ``F`` over a :class:`jax.sharding.Mesh` makes
expansion embarrassingly parallel; the dedup sorts become XLA global sorts
with ICI collectives.  :func:`place_frontier` applies the sharding; the
driver accepts a ``mesh=`` argument.

Reference parity: the verdict semantics match
``porcupine.CheckEventsVerbose(model, events, 0)`` as used by
golang/s2-porcupine/main.go:605-606; the step truth table is
main.go:264-335 (see ops/step_kernel.py).
"""

from __future__ import annotations

import contextlib
import os
from functools import partial
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..models.encode import INF_TIME, EncodedHistory, encode_history, intern_state
from ..models.stream import StreamState
from .entries import History
from .frontier import FrontierStats
from .oracle import CheckOutcome, CheckResult
from ..ops.step_kernel import DeviceOps, DeviceState, step_kernel

__all__ = [
    "SearchTables",
    "Frontier",
    "build_tables",
    "init_frontier",
    "run_search",
    "check_device",
    "check_device_auto",
    "place_frontier",
]

_I32 = jnp.int32
_U32 = jnp.uint32


class SearchTables(NamedTuple):
    """Device-resident static tables for one encoded history."""

    ops: DeviceOps
    #: per-op: indefinite append with a match_seq_num guard (auto-close arm 1)
    ac_match: jnp.ndarray  # [N] bool
    #: per-op: indefinite append whose batch token is never set by any op
    ac_tok: jnp.ndarray  # [N] bool
    #: accept_tab[c, k]: ops k.. of chain c are all indefinite appends
    accept_tab: jnp.ndarray  # [C, Lc+1] bool
    #: opens_tab[c, k]: # indefinite appends among the first k ops of chain c
    opens_tab: jnp.ndarray  # [C, Lc+1] int32


class Frontier(NamedTuple):
    counts: jnp.ndarray  # [F, C] int32
    tail: jnp.ndarray  # [F, S] uint32
    hi: jnp.ndarray  # [F, S] uint32
    lo: jnp.ndarray  # [F, S] uint32
    tok: jnp.ndarray  # [F, S] int32
    svalid: jnp.ndarray  # [F, S] bool
    valid: jnp.ndarray  # [F] bool

    @property
    def capacity(self) -> int:
        return int(self.valid.shape[0])

    @property
    def state_slots(self) -> int:
        return int(self.tail.shape[1])


class RunOut(NamedTuple):
    """Result carry of one compiled search run."""

    frontier: Frontier  # final: accepting/resume frontier (closed) or children
    stop_code: jnp.ndarray  # 0 running, 1 accept, 2 empty, 3 capacity
    accept_idx: jnp.ndarray
    layers: jnp.ndarray
    pruned_ever: jnp.ndarray
    overflow_ever: jnp.ndarray
    max_live: jnp.ndarray
    max_state_set: jnp.ndarray
    auto_closed: jnp.ndarray
    expanded: jnp.ndarray


STOP_RUNNING, STOP_ACCEPT, STOP_EMPTY, STOP_CAPACITY = 0, 1, 2, 3


def build_tables(enc: EncodedHistory) -> SearchTables:
    n = enc.num_ops
    c, lc = enc.chain_ops.shape

    is_indef = enc.out_failure & ~enc.out_definite & (enc.op_type == 0)
    settable = set()
    for j in range(n):
        if enc.has_set_token[j]:
            settable.add(int(enc.set_token[j]))
    tok_never = np.array(
        [
            bool(enc.has_batch_token[j]) and int(enc.batch_token[j]) not in settable
            for j in range(n)
        ],
        bool,
    )
    ac_match = is_indef & enc.has_match
    ac_tok = is_indef & tok_never

    accept_tab = np.ones((c, lc + 1), bool)
    opens_tab = np.zeros((c, lc + 1), np.int32)
    for ci in range(c):
        ln = int(enc.chain_len[ci])
        for k in range(ln):
            opens_tab[ci, k + 1] = opens_tab[ci, k] + int(
                is_indef[enc.chain_ops[ci, k]]
            )
        for k in range(ln - 1, -1, -1):
            accept_tab[ci, k] = accept_tab[ci, k + 1] and bool(
                is_indef[enc.chain_ops[ci, k]]
            )
    return SearchTables(
        ops=DeviceOps.from_encoded(enc),
        ac_match=jnp.asarray(ac_match),
        ac_tok=jnp.asarray(ac_tok),
        accept_tab=jnp.asarray(accept_tab),
        opens_tab=jnp.asarray(opens_tab),
    )


def init_frontier(
    enc: EncodedHistory, capacity: int, state_slots: int
) -> Frontier:
    c = enc.num_chains
    states = [intern_state(enc, s) for s in enc.init_states]
    states.sort()
    if len(states) > state_slots:
        raise ValueError(
            f"{len(states)} initial states exceed {state_slots} state slots"
        )
    counts = np.zeros((capacity, c), np.int32)
    counts[:] = enc.chain_start[None, :]
    tail = np.zeros((capacity, state_slots), np.uint32)
    hi = np.zeros((capacity, state_slots), np.uint32)
    lo = np.zeros((capacity, state_slots), np.uint32)
    tok = np.zeros((capacity, state_slots), np.int32)
    svalid = np.zeros((capacity, state_slots), bool)
    for i, (t, h, l, k) in enumerate(states):
        tail[0, i], hi[0, i], lo[0, i], tok[0, i] = t, h, l, k
        svalid[0, i] = True
    valid = np.zeros(capacity, bool)
    valid[0] = True
    return Frontier(
        counts=jnp.asarray(counts),
        tail=jnp.asarray(tail),
        hi=jnp.asarray(hi),
        lo=jnp.asarray(lo),
        tok=jnp.asarray(tok),
        svalid=jnp.asarray(svalid),
        valid=jnp.asarray(valid),
    )


def place_frontier(frontier: Frontier, mesh, axis: str = "fr") -> Frontier:
    """Shard the frontier axis over a device mesh; tables stay replicated."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    def put(x):
        spec = P(axis, *([None] * (x.ndim - 1)))
        return jax.device_put(x, NamedSharding(mesh, spec))

    return jax.tree.map(put, frontier)


# ---------------------------------------------------------------------------
# Per-configuration pieces (to be vmapped over the frontier axis)
# ---------------------------------------------------------------------------


def _next_and_cands(tables: SearchTables, counts):
    """Next-op index per chain and the candidate mask, for one config."""
    ops = tables.ops
    has_next = counts < ops.chain_len
    idx = jnp.minimum(counts, jnp.maximum(ops.chain_len - 1, 0))
    nxt = jnp.take_along_axis(ops.chain_ops, idx[:, None], axis=1)[:, 0]
    nxt = jnp.where(has_next, nxt, 0)
    nret = jnp.where(has_next, ops.ret[nxt], INF_TIME)
    m = jnp.min(nret)
    cand = has_next & (ops.call[nxt] < m)
    return nxt, cand


def _dead_mask(tables: SearchTables, nxt, cand, st_tail, st_tok, svalid):
    """Candidates whose indefinite-append effect branch is dead forever."""
    ops = tables.ops
    ms = ops.match_seq[nxt]  # [C] u32
    all_gt = ((~svalid)[None, :] | (st_tail[None, :] > ms[:, None])).all(axis=1)
    bt = ops.batch_token[nxt]
    none_match = ((~svalid)[None, :] | (st_tok[None, :] != bt[:, None])).all(axis=1)
    dead = (tables.ac_match[nxt] & all_gt) | (tables.ac_tok[nxt] & none_match)
    return cand & dead


def _auto_close_one(tables: SearchTables, counts, st_tail, st_tok, svalid, cfg_valid):
    def dead_now(c):
        nxt, cand = _next_and_cands(tables, c)
        return _dead_mask(tables, nxt, cand, st_tail, st_tok, svalid)

    def cond(c):
        return cfg_valid & dead_now(c).any()

    def body(c):
        return c + dead_now(c).astype(_I32)

    closed = lax.while_loop(cond, body, counts)
    return closed, (closed - counts).sum()


def _canon_states(t, h, l, k, v, s):
    """Dedup + canonically sort one candidate state set into ``s`` slots.

    Inputs are flat arrays of 2S successor states (+ validity); returns the
    sorted, zero-padded set plus an overflow flag (more than ``s`` distinct
    valid states)."""
    n2 = t.shape[0]
    eqm = (
        (t[:, None] == t[None, :])
        & (h[:, None] == h[None, :])
        & (l[:, None] == l[None, :])
        & (k[:, None] == k[None, :])
    )
    lower = jnp.tril(jnp.ones((n2, n2), bool), -1)  # [i, j] = j < i
    dup = (eqm & lower & v[None, :]).any(axis=1)
    keep = v & ~dup
    order = jnp.lexsort((k.astype(_U32), l, h, t, (~keep).astype(_I32)))
    keep_s = keep[order][:s]
    z = lambda x: jnp.where(keep_s, x[order][:s], 0)
    return (
        z(t),
        z(h),
        z(l),
        jnp.where(keep_s, k[order][:s].astype(_I32), 0),
        keep_s,
        keep.sum() > s,
    )


def _step_states(tables: SearchTables, o, st_tail, st_hi, st_lo, st_tok, svalid):
    """Apply op ``o`` to a candidate state set; returns the flat 2S successor
    candidates (optimistic + no-effect branches) with validity."""

    def per_state(t, h, l, k):
        return step_kernel(tables.ops, o, DeviceState(t, h, l, k))

    a, va, b, vb = jax.vmap(per_state)(st_tail, st_hi, st_lo, st_tok)
    t2 = jnp.concatenate([a.tail, b.tail])
    h2 = jnp.concatenate([a.hash_hi, b.hash_hi])
    l2 = jnp.concatenate([a.hash_lo, b.hash_lo])
    k2 = jnp.concatenate([a.token, b.token])
    v2 = jnp.concatenate([va & svalid, vb & svalid])
    return t2, h2, l2, k2, v2


def _expand_one(tables: SearchTables, counts, st_tail, st_hi, st_lo, st_tok, svalid, cfg_valid):
    """All children of one configuration: one per candidate chain.

    Returns per-chain arrays: child counts [C, C], canonical child state
    sets [C, S]×4 (+ svalid), child validity [C], per-chain overflow [C].
    """
    c = counts.shape[0]
    s = st_tail.shape[0]
    nxt, cand = _next_and_cands(tables, counts)

    t2, h2, l2, k2, v2 = jax.vmap(
        lambda o: _step_states(tables, o, st_tail, st_hi, st_lo, st_tok, svalid)
    )(nxt)  # [C, 2S] each

    ct, ch, cl, ck, cv, over = jax.vmap(partial(_canon_states, s=s))(
        t2, h2, l2, k2, v2
    )
    child_counts = counts[None, :] + jnp.eye(c, dtype=_I32)
    child_valid = cfg_valid & cand & cv.any(axis=1)
    overflow = (child_valid & over).any()
    return child_counts, ct, ch, cl, ck, cv, child_valid, overflow, cand.sum()


def _accept_one(tables: SearchTables, counts, cfg_valid):
    c = counts.shape[0]
    return cfg_valid & tables.accept_tab[jnp.arange(c), counts].all()


def _fast_layer(tables: SearchTables, frontier: Frontier):
    """One forced step on the unique live configuration.

    Precondition (checked by the caller): exactly one configuration is live
    and its candidate window holds exactly one chain.  The single child
    needs no cross-configuration dedup or compaction, so the layer skips
    the frontier-wide lexsorts — the dominant cost on the long sequential
    stretches of collector histories.  Return signature matches
    :func:`_expand_layer`.
    """
    s = frontier.state_slots
    idx = jnp.argmax(frontier.valid)
    counts = frontier.counts[idx]
    nxt, cand = _next_and_cands(tables, counts)
    chain = jnp.argmax(cand)
    o = nxt[chain]
    t2, h2, l2, k2, v2 = _step_states(
        tables,
        o,
        frontier.tail[idx],
        frontier.hi[idx],
        frontier.lo[idx],
        frontier.tok[idx],
        frontier.svalid[idx],
    )
    ct, ch, cl, ck, cv, over = _canon_states(t2, h2, l2, k2, v2, s)
    child_valid = cv.any()
    children = Frontier(
        counts=frontier.counts.at[idx, chain].add(1),
        tail=frontier.tail.at[idx].set(ct),
        hi=frontier.hi.at[idx].set(ch),
        lo=frontier.lo.at[idx].set(cl),
        tok=frontier.tok.at[idx].set(ck),
        svalid=frontier.svalid.at[idx].set(cv),
        valid=frontier.valid.at[idx].set(child_valid),
    )
    n_unique = child_valid.astype(_I32)
    mss = cv.sum().astype(_I32)
    return (
        children,
        jnp.zeros((), bool),
        over & child_valid,
        n_unique,
        jnp.ones((), _I32),
        mss,
    )


# ---------------------------------------------------------------------------
# The batched layer and the compiled search loop
# ---------------------------------------------------------------------------


def _mix_hash(cols, n, seed):
    """FNV-1a-style column mix → one u32 lane hash per row."""
    h = jnp.full(n, seed, _U32)
    for x in cols:
        h = (h ^ x.astype(_U32)) * _U32(0x01000193)
        h = ((h << 13) | (h >> 19)) ^ (h >> 7)
    # final avalanche
    h = (h ^ (h >> 16)) * _U32(0x7FEB352D)
    h = (h ^ (h >> 15)) * _U32(0x846CA68B)
    return h ^ (h >> 16)


def _expand_layer(tables: SearchTables, frontier: Frontier):
    """Expand + dedup + compact one layer.  Returns (children, pruned,
    overflow, n_unique, expanded, max_state_set)."""
    f, c = frontier.counts.shape
    s = frontier.state_slots

    (ccounts, ct, ch, cl, ck, cv, cvalid, over, ncand) = jax.vmap(
        partial(_expand_one, tables)
    )(
        frontier.counts,
        frontier.tail,
        frontier.hi,
        frontier.lo,
        frontier.tok,
        frontier.svalid,
        frontier.valid,
    )
    e = f * c
    flat = lambda x: x.reshape((e,) + x.shape[2:])
    ccounts, ct, ch, cl, ck, cv = map(flat, (ccounts, ct, ch, cl, ck, cv))
    cvalid = cvalid.reshape(e)
    overflow = over.any()
    expanded = jnp.where(frontier.valid, ncand, 0).sum()

    # Lazy-order rank: total indefinite appends linearized (fewest first).
    # Invalid children can carry counts one past a finished chain; clamp.
    idx = jnp.minimum(ccounts.T, tables.opens_tab.shape[1] - 1)
    opens = jnp.take_along_axis(tables.opens_tab, idx, axis=1).sum(axis=0)

    cols = (
        [ccounts[:, i] for i in range(c)]
        + [ct[:, i] for i in range(s)]
        + [ch[:, i] for i in range(s)]
        + [cl[:, i] for i in range(s)]
        + [ck[:, i] for i in range(s)]
        + [cv[:, i] for i in range(s)]
    )
    h1 = _mix_hash(cols, e, 0x811C9DC5)
    h2 = _mix_hash(cols, e, 0x9747B28C)

    order = jnp.lexsort((h2, h1, opens.astype(_I32), (~cvalid).astype(_I32)))
    ccounts, ct, ch, cl, ck, cv = (
        x[order] for x in (ccounts, ct, ch, cl, ck, cv)
    )
    cvalid, opens, h1, h2 = cvalid[order], opens[order], h1[order], h2[order]

    eq_prev = jnp.ones(e, bool)
    for x in (ccounts, ct, ch, cl, ck, cv):
        eq_prev &= (x == jnp.roll(x, 1, axis=0)).all(axis=1)
    eq_prev = eq_prev.at[0].set(False)
    dup = cvalid & jnp.roll(cvalid, 1) & eq_prev
    keep = cvalid & ~dup
    n_unique = keep.sum()

    order2 = jnp.lexsort(((~keep).astype(_I32),), axis=0)
    take = lambda x: x[order2][:f]
    children = Frontier(
        counts=take(ccounts),
        tail=take(ct),
        hi=take(ch),
        lo=take(cl),
        tok=take(ck),
        svalid=take(cv),
        valid=keep[order2][:f],
    )
    pruned = n_unique > f
    max_state_set = jnp.where(children.valid, children.svalid.sum(axis=1), 0).max()
    return children, pruned, overflow, n_unique, expanded, max_state_set


@partial(jax.jit, static_argnames=("allow_prune",))
def run_search(tables: SearchTables, frontier: Frontier, max_layers, *, allow_prune: bool) -> RunOut:
    """Run the frontier search to a verdict inside one compiled while_loop.

    ``allow_prune=True``: capacity overruns prune to the lazy-best
    configurations and the search continues (OK conclusive; dead ends
    inconclusive).  ``allow_prune=False``: the loop exits with
    STOP_CAPACITY and the pre-expansion frontier, so the driver can
    escalate capacity and resume exactly (no information lost).
    """

    def body(carry: RunOut) -> RunOut:
        cur = carry.frontier

        closed_counts, ac_n = jax.vmap(partial(_auto_close_one, tables))(
            cur.counts, cur.tail, cur.tok, cur.svalid, cur.valid
        )
        closed = cur._replace(counts=closed_counts)
        acc_row = jax.vmap(partial(_accept_one, tables))(closed.counts, closed.valid)
        accept_any = acc_row.any()

        def do_expand(fr):
            return lax.cond(
                fastable, partial(_fast_layer, tables), partial(_expand_layer, tables), fr
            )

        def no_expand(fr):
            zero = jnp.zeros((), _I32)
            return fr, jnp.zeros((), bool), jnp.zeros((), bool), zero, zero, zero

        # Fast path: a lone live configuration with a single-chain candidate
        # window — the forced-step regime of low-concurrency stretches.
        live_idx = jnp.argmax(closed.valid)
        _, cand1 = _next_and_cands(tables, closed.counts[live_idx])
        fastable = (closed.valid.sum() == 1) & (cand1.sum() == 1)

        children, pruned, overflow, n_unique, expanded, mss = lax.cond(
            accept_any, no_expand, do_expand, closed
        )
        empty = ~accept_any & (n_unique == 0)
        need_cap = (not allow_prune) & (pruned | overflow)
        stop = jnp.where(
            accept_any,
            STOP_ACCEPT,
            jnp.where(empty, STOP_EMPTY, jnp.where(need_cap, STOP_CAPACITY, STOP_RUNNING)),
        ).astype(_I32)

        resume = accept_any | need_cap
        nxt = jax.tree.map(
            lambda a, b: jnp.where(
                resume.reshape((-1,) + (1,) * (a.ndim - 1)), a, b
            ),
            closed,
            children,
        )
        # A capacity stop abandons this layer's expansion (the driver resumes
        # from the pre-expansion frontier and replays it), so only committed
        # layers contribute to the counters — resumed stats stay exact.
        committed = ~need_cap
        return RunOut(
            frontier=nxt,
            stop_code=stop,
            accept_idx=jnp.argmax(acc_row).astype(_I32),
            layers=carry.layers + committed.astype(_I32),
            pruned_ever=carry.pruned_ever | pruned,
            overflow_ever=carry.overflow_ever | overflow,
            max_live=jnp.maximum(
                carry.max_live, jnp.where(committed, children.valid.sum(), 0)
            ),
            max_state_set=jnp.maximum(
                carry.max_state_set, jnp.where(committed, mss, 0)
            ),
            # auto_closed stays ungated: the resume frontier handed back on a
            # capacity stop is post-auto-close, so that work IS committed and
            # will not be replayed.
            auto_closed=carry.auto_closed + jnp.where(cur.valid, ac_n, 0).sum(),
            expanded=carry.expanded
            + jnp.where(committed, expanded, jnp.zeros((), _I32)),
        )

    def cond(carry: RunOut):
        return (carry.stop_code == STOP_RUNNING) & (carry.layers < max_layers)

    zero = jnp.zeros((), _I32)
    init = RunOut(
        frontier=frontier,
        stop_code=zero,
        accept_idx=zero,
        layers=zero,
        pruned_ever=jnp.zeros((), bool),
        overflow_ever=jnp.zeros((), bool),
        max_live=frontier.valid.sum().astype(_I32),
        max_state_set=jnp.where(frontier.valid, frontier.svalid.sum(axis=1), 0)
        .max()
        .astype(_I32),
        auto_closed=zero,
        expanded=zero,
    )
    return lax.while_loop(cond, body, init)


# ---------------------------------------------------------------------------
# Host driver
# ---------------------------------------------------------------------------


def _round_pow2(n: int, lo: int) -> int:
    v = lo
    while v < n:
        v *= 2
    return v


def _floor_pow2(n: int, lo: int) -> int:
    """Largest power of two ≤ n (but ≥ lo) — honors a caller's capacity cap."""
    v = lo
    while v * 2 <= n:
        v *= 2
    return v


def _final_states(enc: EncodedHistory, frontier: Frontier, idx: int) -> list[StreamState]:
    tail = np.asarray(frontier.tail[idx])
    hi = np.asarray(frontier.hi[idx])
    lo = np.asarray(frontier.lo[idx])
    tok = np.asarray(frontier.tok[idx])
    sv = np.asarray(frontier.svalid[idx])
    out = []
    for i in range(sv.shape[0]):
        if sv[i]:
            out.append(
                StreamState(
                    tail=int(tail[i]),
                    stream_hash=(int(hi[i]) << 32) | int(lo[i]),
                    fencing_token=enc.token_of_id[int(tok[i])],
                )
            )
    return sorted(out)


def check_device(
    history: History,
    *,
    max_frontier: int = 4096,
    state_slots: int = 4,
    beam: bool = True,
    start_frontier: int = 16,
    mesh=None,
    collect_stats: bool = False,
    checkpoint_path: str | None = None,
    checkpoint_every: int = 512,
) -> CheckResult:
    """Decide linearizability on device.  Verdict semantics match
    :func:`..checker.frontier.check_frontier`: OK and un-pruned ILLEGAL are
    conclusive; a dead end after pruning/overflow is UNKNOWN.

    Both modes start in a small frontier bucket and escalate (doubling,
    resuming from the returned pre-expansion frontier) on capacity stops —
    so cheap histories stay cheap.  At ``max_frontier`` a beam run switches
    to prune-and-continue (lazy-order beam) inside the compiled loop, while
    an exhaustive run concedes UNKNOWN.

    Caveat: in a pruning beam run, a per-configuration state-set overflow
    drops candidate states (OK stays sound — surviving states are genuinely
    reachable — but ``final_states`` may then be a subset of the host
    engine's).  ``stats.pruned`` records that this happened
    (``collect_stats=True``).

    ``checkpoint_path``: snapshot the search frontier to this file every
    ``checkpoint_every`` layers (and at capacity escalations) so a long
    search survives preemption; an existing snapshot for the *same* history
    is resumed from, and a conclusive verdict removes it.  A new capability
    over the reference, whose checking is one-shot in-memory (SURVEY.md §5).
    """
    enc = encode_history(history)
    stats = FrontierStats()
    if enc.total_remaining == 0:
        res = CheckResult(
            CheckOutcome.OK,
            linearization=list(enc.forced_prefix),
            final_states=sorted(enc.init_states),
        )
        if collect_stats:
            res.stats = stats  # type: ignore[attr-defined]
        return res
    tables = build_tables(enc)
    cap_layers = int(enc.total_remaining) + 2

    f_cap = _floor_pow2(max_frontier, 2)
    f = _round_pow2(min(start_frontier, f_cap), 2)
    s = _round_pow2(max(len(enc.init_states), state_slots), 2)
    max_state_slots = 256
    frontier = None

    if checkpoint_path is not None:
        import dataclasses

        from .checkpoint import (
            Checkpoint,
            CheckpointError,
            history_fingerprint,
            load_checkpoint,
            save_checkpoint,
        )

        fingerprint = history_fingerprint(enc)
        if os.path.exists(checkpoint_path):
            ck = load_checkpoint(checkpoint_path)
            if ck.fingerprint != fingerprint:
                raise CheckpointError(
                    f"checkpoint {checkpoint_path} belongs to a different "
                    "history (fingerprint mismatch)"
                )
            if ck.beam != beam:
                # A pruned beam frontier must never seed an exhaustive pass
                # (its dead ends would be inconclusive forever), and vice
                # versa a wider exhaustive frontier under beam rules skews
                # stats; refuse rather than silently degrade.
                raise CheckpointError(
                    f"checkpoint {checkpoint_path} was written by a "
                    f"{'beam' if ck.beam else 'exhaustive'} search and cannot "
                    f"resume a {'beam' if beam else 'exhaustive'} one"
                )
            f = ck.f
            for k, v in ck.stats.items():
                setattr(stats, k, v)
            stats.layers = ck.layers_done
            frontier = Frontier(
                counts=jnp.asarray(ck.counts),
                tail=jnp.asarray(ck.tail),
                hi=jnp.asarray(ck.hi),
                lo=jnp.asarray(ck.lo),
                tok=jnp.asarray(ck.tok),
                svalid=jnp.asarray(ck.svalid),
                valid=jnp.asarray(ck.valid),
            )

        def _snapshot(fr: Frontier) -> None:
            save_checkpoint(
                checkpoint_path,
                Checkpoint(
                    fingerprint=fingerprint,
                    counts=np.asarray(fr.counts),
                    tail=np.asarray(fr.tail),
                    hi=np.asarray(fr.hi),
                    lo=np.asarray(fr.lo),
                    tok=np.asarray(fr.tok),
                    svalid=np.asarray(fr.svalid),
                    valid=np.asarray(fr.valid),
                    f=f,
                    beam=beam,
                    layers_done=stats.layers,
                    stats=dataclasses.asdict(stats),
                ),
            )

    def _requeue(fr_np: Frontier, *, snapshot: bool) -> Frontier:
        """Snapshot a host-side frontier and hand it back to the device."""
        if snapshot and checkpoint_path is not None:
            _snapshot(fr_np)
        dev_fr = jax.tree.map(jnp.asarray, fr_np)
        return place_frontier(dev_fr, mesh) if mesh is not None else dev_fr

    if frontier is None:
        frontier = init_frontier(enc, f, s)
    if mesh is not None:
        frontier = place_frontier(frontier, mesh)

    while True:
        allow_prune = beam and f >= f_cap
        layers_budget = cap_layers - stats.layers
        if checkpoint_path is not None and checkpoint_every > 0:
            layers_budget = min(layers_budget, checkpoint_every)
        out = jax.device_get(
            run_search(
                tables, frontier, np.int32(layers_budget), allow_prune=allow_prune
            )
        )
        stats.layers += int(out.layers)
        stats.max_frontier = max(stats.max_frontier, int(out.max_live))
        stats.max_state_set = max(stats.max_state_set, int(out.max_state_set))
        stats.auto_closed += int(out.auto_closed)
        stats.expanded += int(out.expanded)
        if allow_prune:
            stats.pruned = (
                stats.pruned or bool(out.pruned_ever) or bool(out.overflow_ever)
            )
        code = int(out.stop_code)
        if code == STOP_ACCEPT:
            res = CheckResult(
                CheckOutcome.OK,
                linearization=None,
                final_states=_final_states(enc, out.frontier, int(out.accept_idx)),
            )
            break
        if code == STOP_EMPTY:
            outcome = CheckOutcome.UNKNOWN if stats.pruned else CheckOutcome.ILLEGAL
            res = CheckResult(outcome)
            break
        if code == STOP_CAPACITY:
            # Capacity wall below the cap: escalate and resume from the
            # returned pre-expansion frontier (no information was lost).
            resume = Frontier(*(np.asarray(x) for x in out.frontier))
            if bool(out.overflow_ever) and resume.state_slots >= max_state_slots:
                # Widening the frontier cannot fix a per-configuration
                # state-set overflow.  A beam run jumps straight to the
                # pruning regime (state drops keep OK sound — see caveat
                # above); an exhaustive run must concede.
                if beam and f < f_cap:
                    f = f_cap
                    resume = _regrow(resume, f, resume.state_slots)
                else:
                    stats.pruned = True
                    res = CheckResult(CheckOutcome.UNKNOWN)
                    break
            elif bool(out.overflow_ever):
                resume = _regrow(resume, resume.capacity, resume.state_slots * 2)
            elif f < f_cap:
                f = min(f * 2, f_cap)
                resume = _regrow(resume, f, resume.state_slots)
            else:
                stats.pruned = True
                res = CheckResult(CheckOutcome.UNKNOWN)
                break
            frontier = _requeue(resume, snapshot=True)
            continue
        if code == STOP_RUNNING and stats.layers < cap_layers:
            # Chunk boundary (checkpoint cadence): snapshot and keep going
            # from the returned post-expansion frontier.
            nxt = Frontier(*(np.asarray(x) for x in out.frontier))
            frontier = _requeue(nxt, snapshot=True)
            continue
        # Layer cap hit without a verdict: should be impossible (each layer
        # linearizes exactly one op); treat as inconclusive.
        res = CheckResult(CheckOutcome.UNKNOWN)
        break

    if checkpoint_path is not None and res.outcome != CheckOutcome.UNKNOWN:
        with contextlib.suppress(FileNotFoundError):
            os.remove(checkpoint_path)
    if collect_stats:
        res.stats = stats  # type: ignore[attr-defined]
    return res


def _regrow(fr: Frontier, capacity: int, state_slots: int) -> Frontier:
    """Re-pad a frontier into a (capacity, state_slots) bucket."""
    f0, c = np.asarray(fr.counts).shape
    s0 = fr.state_slots

    def grow1(x):
        out = np.zeros(capacity, np.asarray(x).dtype)
        out[:f0] = np.asarray(x)
        return out

    def grow_c(x):
        out = np.zeros((capacity, c), np.asarray(x).dtype)
        out[:f0] = np.asarray(x)
        return out

    def grow_s(x):
        out = np.zeros((capacity, state_slots), np.asarray(x).dtype)
        out[:f0, :s0] = np.asarray(x)
        return out

    return Frontier(
        counts=grow_c(fr.counts),
        tail=grow_s(fr.tail),
        hi=grow_s(fr.hi),
        lo=grow_s(fr.lo),
        tok=grow_s(fr.tok),
        svalid=grow_s(fr.svalid),
        valid=grow1(fr.valid),
    )


def check_device_auto(
    history: History,
    *,
    beam_width: int = 4096,
    exhaustive_cap: int = 16384,
    state_slots: int = 4,
    mesh=None,
    collect_stats: bool = False,
    checkpoint_path: str | None = None,
    checkpoint_every: int = 512,
) -> CheckResult:
    """Beam-first device check with exhaustive escalation, mirroring
    :func:`..checker.frontier.check_frontier_auto`.

    The beam and exhaustive phases use distinct checkpoint files (a beam
    snapshot must not resume an exhaustive pass, whose soundness rules
    differ); a conceded beam phase leaves a marker so a preempted
    exhaustive phase does not replay the whole beam search on restart."""
    marker = f"{checkpoint_path}.beam.conceded" if checkpoint_path else None
    fingerprint = None
    beam_already_conceded = False
    if checkpoint_path is not None:
        from .checkpoint import history_fingerprint

        fingerprint = history_fingerprint(encode_history(history))
        if os.path.exists(marker):
            try:
                with open(marker, encoding="utf-8") as fh:
                    beam_already_conceded = fh.read().strip() == fingerprint
            except OSError:
                beam_already_conceded = False
            if not beam_already_conceded:
                with contextlib.suppress(FileNotFoundError):
                    os.remove(marker)

    if not beam_already_conceded:
        res = check_device(
            history,
            max_frontier=beam_width,
            state_slots=state_slots,
            beam=True,
            mesh=mesh,
            collect_stats=collect_stats,
            checkpoint_path=(
                f"{checkpoint_path}.beam" if checkpoint_path is not None else None
            ),
            checkpoint_every=checkpoint_every,
        )
        if res.outcome != CheckOutcome.UNKNOWN:
            if marker is not None:
                with contextlib.suppress(FileNotFoundError):
                    os.remove(marker)
            return res
        if checkpoint_path is not None:
            # The conceded beam phase's snapshot must not linger (it would
            # fingerprint-clash with the next history under this path), and
            # the marker spares a preempted exhaustive phase from replaying
            # the whole beam search on restart.
            with contextlib.suppress(FileNotFoundError):
                os.remove(f"{checkpoint_path}.beam")
            with open(marker, "w", encoding="utf-8") as fh:
                fh.write(fingerprint)
    res = check_device(
        history,
        max_frontier=exhaustive_cap,
        state_slots=state_slots,
        beam=False,
        mesh=mesh,
        collect_stats=collect_stats,
        checkpoint_path=checkpoint_path,
        checkpoint_every=checkpoint_every,
    )
    # On a conclusive verdict the marker is spent.  On UNKNOWN it stays,
    # paired with the kept exhaustive snapshot: a retry (e.g. with a larger
    # cap) skips straight past the already-conceded beam phase.
    if marker is not None and res.outcome != CheckOutcome.UNKNOWN:
        with contextlib.suppress(FileNotFoundError):
            os.remove(marker)
    return res
