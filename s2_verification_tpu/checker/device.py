"""Device (TPU) frontier linearizability search.

The jit/vmap twin of :mod:`.frontier`: the whole layer-by-layer search runs
*inside one compiled program* as a ``lax.while_loop`` whose carry is a dense
frontier of configurations, so there is no per-layer host dispatch.  The
host's only jobs are encoding the history (models/encode.py), picking
capacity buckets, and escalating when a run reports it needs a wider
frontier.

A configuration row is ``(counts per chain, ONE candidate state)``:

- ``counts  [F, C] int32`` — linearized prefix length of every chain;
- ``tail/hi/lo/tok  [F]`` — one model state;
- ``valid [F] bool`` — row occupancy.

This is the per-state flattening of the powerset-lifted search the host
engines run: a configuration's candidate-state *set* is non-empty iff at
least one member survives, and members step independently (``step_set`` is
a union of per-member steps), so tracking ``(counts, member)`` rows and
deduping them yields identical OK/ILLEGAL verdicts while keeping every
vector lane a real state — no per-row set dimension, no padding, no
per-child set canonicalization.  (Reference semantics:
``porcupine.CheckEventsVerbose(model, events, 0)`` as driven by
golang/s2-porcupine/main.go:605-606; step truth table main.go:264-335.)

One layer (the while-loop body):

1. **auto-close** — a vmapped nested ``lax.while_loop`` advances each row
   past indefinite appends whose effect branch is provably dead (stale
   ``match_seq_num`` guard under monotone tails, or a fencing token no
   remaining op can set);
2. **accept** — a row whose remaining ops are all indefinite appends
   accepts the history (table lookup + reduction);
3. **expand** — every (row × candidate chain) steps through
   :func:`~..ops.step_kernel.step_kernel` under nested ``vmap``; an
   indefinite append emits two child rows (effect / no-effect), everything
   else one;
4. **dedup + compact** — children get a 64-bit (2×u32) mixed hash of
   (Zobrist counts hash, state) and dedup through a scatter-min hash
   table with exact compare against each slot winner — O(children) work,
   no global sort.  Unresolved hash collisions are *kept* (a missed merge
   only costs capacity, never soundness).  Survivors compact into the next
   frontier with a cumsum scatter; beam pruning selects the lazy-best
   (fewest linearized indefinite appends) via a bincount threshold, also
   sort-free.

Layers never revisit earlier configurations (sum(counts) grows by one per
layer) so no cross-layer visited set is needed.

Soundness under capacity pressure mirrors the host beam search: an OK is
always conclusive (every frontier row is genuinely reachable); a dead end
after any pruning is UNKNOWN, and the driver escalates to the next
capacity bucket, resuming from the last intact pre-expansion frontier that
the compiled program hands back.

Multi-chip: every per-row computation is elementwise over the frontier
axis, so sharding ``F`` over a :class:`jax.sharding.Mesh` makes expansion
embarrassingly parallel; the dedup table scatter/gather become XLA
collective ops.  :func:`place_frontier` applies the sharding; the driver
accepts a ``mesh=`` argument.
"""

from __future__ import annotations

import contextlib
import logging
import os
import time
from collections import deque
from functools import partial
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..models.encode import (
    INF_TIME,
    EncodedHistory,
    encode_history,
    intern_state,
    round_pow2,
)
from ..models.stream import StreamState
from ..obs.introspect import observe_jit
from ..utils.cache import enable_persistent_cache
from .entries import History
from .frontier import FrontierStats
from .oracle import CheckOutcome, CheckResult
from .prune import PIN_INF, RANK_INF
from ..ops import u64
from ..ops.step_kernel import DeviceOps, DeviceState, step_kernel

__all__ = [
    "SearchTables",
    "Frontier",
    "build_tables",
    "init_frontier",
    "run_search",
    "check_device",
    "check_device_auto",
    "place_frontier",
]

enable_persistent_cache()

log = logging.getLogger("s2_verification_tpu.device")

#: The module's host<->device fetch surface.  Every driver fetch goes
#: through these module-level names (not the jax/np globals) so the
#: transfer-discipline regression test can spy on exactly this module's
#: fetches without patching the process-global functions.
device_get = jax.device_get
asarray = np.asarray

_I32 = jnp.int32
_U32 = jnp.uint32

#: prune-table sentinels (checker/prune.py), as device scalars
_RANK_INF = jnp.int32(RANK_INF)
_PIN_INF = jnp.uint32(PIN_INF)

#: Opt-in: exact sort dedup for tiny layers (see _expand_layer).  Read at
#: import so the flag is uniform across every program this process traces.
_TINY_SORT = os.environ.get("S2VTPU_TINY_SORT") == "1"

#: beam-priority classes (linearized-indefinite-append counts) are clamped
#: here; ties above the clamp only coarsen pruning priority, never verdicts.
_OPENS_CAP = 256


class SearchTables(NamedTuple):
    """Device-resident static tables for one encoded history."""

    ops: DeviceOps
    #: per-op: indefinite append (two-branch step)
    is_indef: jnp.ndarray  # [N] bool
    #: per-op: indefinite append with a match_seq_num guard (auto-close arm 1)
    ac_match: jnp.ndarray  # [N] bool
    #: per-op: indefinite append whose batch token is never set by any op
    ac_tok: jnp.ndarray  # [N] bool
    #: accept_tab[c, k]: ops k.. of chain c are all indefinite appends
    accept_tab: jnp.ndarray  # [C, Lc+1] bool
    #: opens_tab[c, k]: # indefinite appends among the first k ops of chain c
    opens_tab: jnp.ndarray  # [C, Lc+1] int32
    #: Zobrist tables for incremental counts hashing: zob*[c, k] is the
    #: contribution of "chain c has linearized k ops"
    zob1: jnp.ndarray  # [C, Lc+2] uint32
    zob2: jnp.ndarray  # [C, Lc+2] uint32
    #: mixed-radix strides (u64 as hi/lo u32 words) for the exact packed
    #: counts key: key = sum_c counts[c] * stride[c].  Exact (collision-free)
    #: iff prod(chain_len + 1) <= 2^64 (:func:`can_exact_pack`); zeros when
    #: that product overflows and the generic full-vector compare is used.
    pack_hi: jnp.ndarray  # [C] uint32
    pack_lo: jnp.ndarray  # [C] uint32
    #: commutativity-prune tables (checker/prune.py).  Always present so
    #: pruning on/off is a table-content change, never a retrace: neutral
    #: fills (RANK_INF ranks, PIN_INF pins, all-false masks) make every
    #: consumer a provable no-op.
    #: per-op rank in the forced successful-append order (RANK_INF unranked)
    app_rank: jnp.ndarray  # [N] int32
    #: minrank_tab[c, k]: min rank among chain c ops at positions >= k
    minrank_tab: jnp.ndarray  # [C, Lc+1] int32
    #: pintail_tab[c, k]: min statically-pinned tail among those ops
    pintail_tab: jnp.ndarray  # [C, Lc+1] uint32
    #: per-op: identity on every state (eager-commit unconditionally)
    inert: jnp.ndarray  # [N] bool
    #: per-op: successful read/check_tail (eager-commit when it passes)
    filter_succ: jnp.ndarray  # [N] bool


class Frontier(NamedTuple):
    counts: jnp.ndarray  # [F, C] int32
    tail: jnp.ndarray  # [F] uint32
    hi: jnp.ndarray  # [F] uint32
    lo: jnp.ndarray  # [F] uint32
    tok: jnp.ndarray  # [F] int32
    valid: jnp.ndarray  # [F] bool

    @property
    def capacity(self) -> int:
        return int(self.valid.shape[0])


class RunOut(NamedTuple):
    """Result carry of one compiled search run."""

    frontier: Frontier  # final: accepting/resume frontier (closed) or children
    stop_code: jnp.ndarray  # 0 running, 1 accept, 2 empty, 3 capacity
    accept_idx: jnp.ndarray
    layers: jnp.ndarray
    pruned_ever: jnp.ndarray
    overflow_ever: jnp.ndarray
    max_live: jnp.ndarray
    auto_closed: jnp.ndarray
    expanded: jnp.ndarray
    #: prune counters: candidate filters/inert ops committed by the eager
    #: sweep, and rows dropped by the tail-pin dead-row rule
    eager_closed: jnp.ndarray
    pin_killed: jnp.ndarray
    #: speculation counters: dive layers advanced (incl. rolled back),
    #: dives that found the accept, dives discarded on misprediction
    spec_layers: jnp.ndarray
    spec_accepts: jnp.ndarray
    spec_rollbacks: jnp.ndarray
    #: counts of one live row of the deepest committed layer (diagnostics)
    deep_counts: jnp.ndarray  # [C] int32
    #: on STOP_CAPACITY: the aborted layer's unique-children count — the
    #: driver escalates straight to a bucket that fits it
    want: jnp.ndarray
    #: witness log (shape [log_layers, F]; [0, F] when logging is off):
    #: per committed expansion layer, each child row's parent row index and
    #: op*2+branch (-1 = no child), for linearization recovery on accept
    wparent: jnp.ndarray
    wop: jnp.ndarray


STOP_RUNNING, STOP_ACCEPT, STOP_EMPTY, STOP_CAPACITY = 0, 1, 2, 3


def _pack_strides(chain_len: np.ndarray) -> tuple[bool, np.ndarray]:
    """Mixed-radix strides for the packed counts key, as Python-int math.

    Returns ``(exact, strides_u64)``: ``exact`` iff every reachable counts
    vector maps to a distinct value below 2^64 (prod(chain_len+1) <= 2^64),
    in which case two counts vectors are equal iff their packed keys are —
    the dedup compare collapses to one u64 word per row."""
    strides = []
    acc = 1
    for ln in chain_len:
        strides.append(acc % (1 << 64))
        acc *= int(ln) + 1
    exact = acc <= (1 << 64)
    return exact, np.array(strides, dtype=np.uint64)


def can_exact_pack(enc: EncodedHistory) -> bool:
    """Whether this history's counts vectors pack exactly into u64 keys."""
    return _pack_strides(enc.chain_len)[0]


def build_tables(enc: EncodedHistory, prune: bool = False) -> SearchTables:
    # Padded length, not enc.num_ops: the derived masks must match the
    # (shape-bucketed) array sizes; padded entries are inert by
    # construction (trivial outputs, no tokens, in no chain).
    n = int(enc.op_type.shape[0])
    c, lc = enc.chain_ops.shape

    from .prune import analyze_encoded, neutral_tables

    pt = analyze_encoded(enc) if prune else neutral_tables(n, (c, lc))

    is_indef = enc.out_failure & ~enc.out_definite & (enc.op_type == 0)
    settable = set()
    for j in range(n):
        if enc.has_set_token[j]:
            settable.add(int(enc.set_token[j]))
    tok_never = np.array(
        [
            bool(enc.has_batch_token[j]) and int(enc.batch_token[j]) not in settable
            for j in range(n)
        ],
        bool,
    )
    ac_match = is_indef & enc.has_match
    ac_tok = is_indef & tok_never

    accept_tab = np.ones((c, lc + 1), bool)
    opens_tab = np.zeros((c, lc + 1), np.int32)
    for ci in range(c):
        ln = int(enc.chain_len[ci])
        for k in range(ln):
            opens_tab[ci, k + 1] = opens_tab[ci, k] + int(
                is_indef[enc.chain_ops[ci, k]]
            )
        for k in range(ln - 1, -1, -1):
            accept_tab[ci, k] = accept_tab[ci, k + 1] and bool(
                is_indef[enc.chain_ops[ci, k]]
            )
    rng = np.random.Generator(np.random.PCG64(0x52C0FFEE))
    zob = rng.integers(0, 1 << 32, size=(2, c, lc + 2), dtype=np.uint32)
    exact, strides = _pack_strides(enc.chain_len)
    if not exact:
        strides = np.zeros(c, np.uint64)
    return SearchTables(
        ops=DeviceOps.from_encoded(enc),
        is_indef=jnp.asarray(is_indef),
        ac_match=jnp.asarray(ac_match),
        ac_tok=jnp.asarray(ac_tok),
        accept_tab=jnp.asarray(accept_tab),
        opens_tab=jnp.asarray(opens_tab),
        zob1=jnp.asarray(zob[0]),
        zob2=jnp.asarray(zob[1]),
        pack_hi=jnp.asarray((strides >> np.uint64(32)).astype(np.uint32)),
        pack_lo=jnp.asarray(strides.astype(np.uint32)),
        app_rank=jnp.asarray(pt.app_rank),
        minrank_tab=jnp.asarray(pt.minrank_tab),
        pintail_tab=jnp.asarray(pt.pintail_tab),
        inert=jnp.asarray(pt.inert),
        filter_succ=jnp.asarray(pt.filter_succ),
    )


def init_frontier(
    enc: EncodedHistory, capacity: int, state_slots: int | None = None
) -> Frontier:
    """One row per initial state.  ``state_slots`` is accepted for driver
    compatibility and ignored (rows are single states)."""
    del state_slots
    c = enc.num_chains
    states = sorted(intern_state(enc, s) for s in enc.init_states)
    if len(states) > capacity:
        raise ValueError(
            f"{len(states)} initial states exceed frontier capacity {capacity}"
        )
    counts = np.zeros((capacity, c), np.int32)
    counts[:] = enc.chain_start[None, :]
    tail = np.zeros(capacity, np.uint32)
    hi = np.zeros(capacity, np.uint32)
    lo = np.zeros(capacity, np.uint32)
    tok = np.zeros(capacity, np.int32)
    valid = np.zeros(capacity, bool)
    for i, (t, h, l, k) in enumerate(states):
        tail[i], hi[i], lo[i], tok[i] = t, h, l, k
        valid[i] = True
    return Frontier(
        counts=jnp.asarray(counts),
        tail=jnp.asarray(tail),
        hi=jnp.asarray(hi),
        lo=jnp.asarray(lo),
        tok=jnp.asarray(tok),
        valid=jnp.asarray(valid),
    )


def place_frontier(frontier: Frontier, mesh, axis: str = "fr") -> Frontier:
    """Shard the frontier axis over a device mesh; tables stay replicated."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    def put(x):
        spec = P(axis, *([None] * (x.ndim - 1)))
        return jax.device_put(x, NamedSharding(mesh, spec))

    return jax.tree.map(put, frontier)


def _shard_occupancy(frontier: Frontier, mesh):
    """Per-device live-row counts of a mesh-placed frontier.

    The frontier axis is sharded evenly over the mesh's 1-D device axis
    (``place_frontier``), so reshaping ``valid`` to ``(n_shards, -1)`` and
    reducing axis 1 is a shard-local sum — XLA keeps each partial on its
    device and only the [n_shards] result crosses the interconnect.  The
    fetch is also the segment's cross-shard sync barrier: its wall time is
    the collective/straggler cost the per-shard metrics report.
    """
    n = int(mesh.devices.size)
    t0 = time.monotonic()
    per = device_get(frontier.valid.reshape(n, -1).sum(axis=1))
    return np.asarray(per, dtype=np.int64), time.monotonic() - t0


def _note_shard_stats(stats, mesh, live_per_shard, sync_s: float) -> None:
    """Fold one segment's per-shard occupancy into ``stats.shards``.

    A checkpoint resumed onto a *different* chip set (verifyd re-grant)
    carries the old grant's shard summary; the summary describes the
    current mesh, so a device-set mismatch starts it fresh.
    """
    devs = [str(d) for d in mesh.devices.flat]
    if len(stats.shards) != len(devs) or any(
        e.get("device") != d for e, d in zip(stats.shards, devs)
    ):
        stats.shards = [
            {
                "shard": i,
                "device": d,
                "peak_occupancy": 0,
                "occupancy_sum": 0,
                "segments": 0,
                "collective_wall_s": 0.0,
                "skew": 1.0,
            }
            for i, d in enumerate(devs)
        ]
    for e, n in zip(stats.shards, live_per_shard):
        e["peak_occupancy"] = max(e["peak_occupancy"], int(n))
        e["occupancy_sum"] += int(n)
        e["segments"] += 1
        e["collective_wall_s"] = round(e["collective_wall_s"] + sync_s, 6)
    mean_peak = sum(e["peak_occupancy"] for e in stats.shards) / len(devs)
    for e in stats.shards:
        e["skew"] = round(e["peak_occupancy"] / mean_peak, 4) if mean_peak else 1.0


# ---------------------------------------------------------------------------
# Per-row pieces (to be vmapped over the frontier axis)
# ---------------------------------------------------------------------------


def _next_and_cands(tables: SearchTables, counts):
    """Next-op index per chain and the candidate mask, for one row."""
    ops = tables.ops
    has_next = counts < ops.chain_len
    idx = jnp.minimum(counts, jnp.maximum(ops.chain_len - 1, 0))
    nxt = jnp.take_along_axis(ops.chain_ops, idx[:, None], axis=1)[:, 0]
    nxt = jnp.where(has_next, nxt, 0)
    nret = jnp.where(has_next, ops.ret[nxt], INF_TIME)
    m = jnp.min(nret)
    cand = has_next & (ops.call[nxt] < m)
    # Rank gate (checker/prune.py): successful appends linearize in
    # out_tail order in every accepting interleaving, so a ranked
    # candidate above the minimum remaining rank heads a branch that can
    # never accept — drop it from the window.  Neutral tables (all
    # RANK_INF) reduce the gate to `cand & True`.
    minrank = jnp.min(
        jnp.take_along_axis(tables.minrank_tab, counts[:, None], axis=1)[:, 0]
    )
    rank_nxt = tables.app_rank[nxt]
    cand = cand & ((rank_nxt == _RANK_INF) | (rank_nxt <= minrank))
    return nxt, cand


def _row_tail_pin(tables: SearchTables, counts):
    """Smallest statically-pinned tail among one row's remaining ops."""
    return jnp.min(
        jnp.take_along_axis(tables.pintail_tab, counts[:, None], axis=1)[:, 0]
    )


def _auto_close_row(tables: SearchTables, counts, tail, hi, lo, tok, cfg_valid):
    """Advance one row past candidate ops that are provably identity here.

    Tails are monotone along every path, so a stale ``match_seq_num`` can
    never match again; a fencing token no remaining op sets can never come
    to match either.  Linearizing such an op immediately (no-effect branch)
    is sound and complete — see frontier.py's auto-close notes.

    With prune tables loaded, the same sweep also eager-commits inert ops
    and successful filters that PASS this row's state (tail and, when
    observed, hash): filters never mutate, so any accepting continuation
    that linearizes one later can be reordered to linearize it now with
    every other op seeing identical states (checker/prune.py).  Returns
    ``(closed_counts, n_closed, n_eager)``; neutral tables make
    ``n_eager`` identically zero.
    """

    def advance_now(c):
        nxt, cand = _next_and_cands(tables, c)
        ms = tables.ops.match_seq[nxt]
        bt = tables.ops.batch_token[nxt]
        dead = (tables.ac_match[nxt] & (tail > ms)) | (
            tables.ac_tok[nxt] & (tok != bt)
        )
        fpass = (
            tables.filter_succ[nxt]
            & (tail == tables.ops.out_tail[nxt])
            & (
                ~tables.ops.out_has_hash[nxt]
                | (
                    (hi == tables.ops.out_hash_hi[nxt])
                    & (lo == tables.ops.out_hash_lo[nxt])
                )
            )
        )
        eager = cand & (tables.inert[nxt] | fpass) & ~dead
        return cand & dead | eager, eager

    def cond(st):
        c, _ne = st
        return cfg_valid & advance_now(c)[0].any()

    def body(st):
        c, ne = st
        adv, eager = advance_now(c)
        return c + adv.astype(_I32), ne + eager.astype(_I32).sum()

    closed, n_eager = lax.while_loop(cond, body, (counts, jnp.zeros((), _I32)))
    return closed, (closed - counts).sum(), n_eager


def _accept_one(tables: SearchTables, counts, cfg_valid):
    c = counts.shape[0]
    return cfg_valid & tables.accept_tab[jnp.arange(c), counts].all()


def _fast_layer(tables: SearchTables, frontier: Frontier):
    """One forced step on the unique live row.

    Precondition (checked by the caller): exactly one row is live, its
    candidate window holds exactly one chain, and the op is not an
    indefinite append (single successor).  The child needs no dedup or
    compaction, so the layer skips the frontier-wide hash table — the
    dominant cost on the long sequential stretches of collector histories.
    Return signature matches :func:`_expand_layer`.  Used when the
    witness log is on (one log row per layer); log-free runs take
    :func:`_fast_multi` instead.
    """
    idx = jnp.argmax(frontier.valid)
    counts = frontier.counts[idx]
    nxt, cand = _next_and_cands(tables, counts)
    chain = jnp.argmax(cand)
    o = nxt[chain]
    st = DeviceState(frontier.tail[idx], frontier.hi[idx], frontier.lo[idx], frontier.tok[idx])
    sa, va, _sb, _vb = step_kernel(tables.ops, o, st)
    children = Frontier(
        counts=frontier.counts.at[idx, chain].add(1),
        tail=frontier.tail.at[idx].set(sa.tail),
        hi=frontier.hi.at[idx].set(sa.hash_hi),
        lo=frontier.lo.at[idx].set(sa.hash_lo),
        tok=frontier.tok.at[idx].set(sa.token),
        valid=frontier.valid.at[idx].set(va),
    )
    f = frontier.valid.shape[0]
    c = frontier.counts.shape[1]
    wparent = jnp.zeros(f, _I32).at[idx].set(idx.astype(_I32))
    wop = jnp.full(f, -1, _I32).at[idx].set(jnp.where(va, o * 2, -1))
    return (
        children,
        jnp.zeros((), bool),
        jnp.zeros((), bool),
        va.astype(_I32),
        jnp.ones((), _I32),
        wparent,
        wop,
        jnp.ones((), _I32),
        jnp.zeros(c, _I32),
        jnp.zeros((), bool),
    )


def _fast_multi(tables: SearchTables, budget, frontier: Frontier):
    """A RUN of forced steps on the unique live row, inside ONE layer.

    Entry precondition is :func:`_fast_layer`'s, checked by the caller for
    the first step; the inner ``while_loop`` keeps stepping while the row
    stays alive, its candidate window stays single-chain, and the next op
    is not an indefinite append — consuming a whole sequential stretch of
    a collector history per outer-loop iteration, so the full-frontier
    auto-close and accept sweeps are paid once per *stretch* instead of
    once per *op*.  Only used when the witness log is off (a multi-op
    layer has no per-layer log row; OK verdicts recover their witness
    from the accept counts via :func:`_recover_witness_bounded`).

    Returns the :func:`_expand_layer` 9-tuple; the 8th element is the
    number of ops consumed (the layer counter advances by it) and the 9th
    the deepest counts actually reached (a row that dies mid-stretch is
    deeper than the stretch's entry counts — the diagnostics must not
    under-report it).
    """
    f = frontier.valid.shape[0]
    idx = jnp.argmax(frontier.valid)

    def nxt_op(counts):
        nxt, cand = _next_and_cands(tables, counts)
        chain = jnp.argmax(cand)
        return nxt[chain], chain, cand.sum() == 1

    # The candidate sweep is CARRIED across iterations (computed once per
    # op, in the step that produced the configuration) instead of being
    # re-evaluated by both cond and step — the loop is latency-bound on an
    # accelerator (tiny kernels on 1 lane), so halving the per-op gather
    # chains matters there and costs nothing elsewhere.
    def cond(st):
        counts, tail, hi, lo, tok, valid, n, o, chain, single = st
        return valid & single & ~tables.is_indef[o] & (n < budget)

    def step(st):
        counts, tail, hi, lo, tok, valid, n, o, chain, _single = st
        sa, va, _sb, _vb = step_kernel(
            tables.ops, o, DeviceState(tail, hi, lo, tok)
        )
        # A refused op is NOT part of the linearized prefix: on failure
        # keep the pre-attempt counts AND state, so the exit carry is the
        # exact death-point configuration — the refusal diagnostics replay
        # from it (a stretch-entry snapshot would name no culprit).
        new = lambda good, old: jnp.where(va, good, old)
        counts2 = new(counts.at[chain].add(1), counts)
        o2, chain2, single2 = nxt_op(counts2)
        return (
            counts2,
            new(sa.tail, tail),
            new(sa.hash_hi, hi),
            new(sa.hash_lo, lo),
            new(sa.token, tok),
            va,
            n + 1,
            o2,
            chain2,
            single2,
        )

    counts0 = frontier.counts[idx]
    o0, chain0, single0 = nxt_op(counts0)
    st = (
        counts0,
        frontier.tail[idx],
        frontier.hi[idx],
        frontier.lo[idx],
        frontier.tok[idx],
        jnp.ones((), bool),
        jnp.zeros((), _I32),
        o0,
        chain0,
        single0,
    )
    out_st = lax.while_loop(cond, step, st)
    counts, tail, hi, lo, tok, valid, n = out_st[:7]
    # The idx row stays marked valid even when it died: on STOP_EMPTY the
    # driver's refusal diagnostics need the death-point configuration (the
    # 10th return element routes this frontier to them); n_unique carries
    # the real liveness, so the stop logic is unaffected.
    children = Frontier(
        counts=frontier.counts.at[idx].set(counts),
        tail=frontier.tail.at[idx].set(tail),
        hi=frontier.hi.at[idx].set(hi),
        lo=frontier.lo.at[idx].set(lo),
        tok=frontier.tok.at[idx].set(tok),
        valid=frontier.valid,
    )
    return (
        children,
        jnp.zeros((), bool),
        jnp.zeros((), bool),
        valid.astype(_I32),
        n,
        jnp.zeros(f, _I32),
        jnp.full(f, -1, _I32),
        n,
        counts,
        jnp.ones((), bool),
    )


# ---------------------------------------------------------------------------
# The batched layer and the compiled search loop
# ---------------------------------------------------------------------------


def _mix_hash(cols, n, seed):
    """FNV-1a-style column mix → one u32 lane hash per row."""
    h = jnp.full(n, seed, _U32)
    for x in cols:
        h = (h ^ x.astype(_U32)) * _U32(0x01000193)
        h = ((h << 13) | (h >> 19)) ^ (h >> 7)
    # final avalanche
    h = (h ^ (h >> 16)) * _U32(0x7FEB352D)
    h = (h ^ (h >> 15)) * _U32(0x846CA68B)
    return h ^ (h >> 16)


def _zob_fold(zob, counts):
    """XOR-fold a Zobrist table over a counts matrix: [F, C] → [F] u32."""
    f, c = counts.shape
    contrib = zob[jnp.arange(c)[None, :], counts]  # [F, C]
    return lax.reduce(contrib, _U32(0), lax.bitwise_xor, dimensions=(1,))


def _dedup_sort(invalid, ident, values=()):
    """Total-order sort + adjacent-equality head mask over a 6-word packed
    identity — THE dedup kernel shared by the one-shot sort path, the
    chunked per-chunk pass, and the chunked cross-chunk pass (one
    implementation so the identity tuple can never silently diverge).

    ``invalid`` keys last; a per-row index is appended as the final key so
    the order is total (deterministic without a stable sort) and the first
    row of every equal-identity run has the smallest index.  Returns
    ``(head, sorted_ident, sorted_idx, sorted_values)`` — ``head`` marks,
    in sorted space, the first (winning) row of each valid identity run.
    """
    n = invalid.shape[0]
    nk = 2 + len(ident)  # invalid + identity words + index tiebreak
    out = lax.sort((invalid, *ident, lax.iota(_I32, n), *values), num_keys=nk)
    sb, sid, sidx, svals = out[0], out[1 : nk - 1], out[nk - 1], out[nk:]
    shift = lambda x: jnp.concatenate([x[:1], x[:-1]])
    same_prev = (lax.iota(_I32, n) > 0)
    for w in sid:
        same_prev = same_prev & (w == shift(w))
    head = ~sb & ~same_prev
    return head, sid, sidx, svals


def _u64_sum_axis1(x: u64.U64) -> u64.U64:
    """Carry-correct sum of a U64 ``[F, C]`` matrix along axis 1 as a
    log2(C)-depth tree of u64 adds — graph size O(log C), not O(C), so
    many-chain histories don't grow the compiled layer."""
    c = x.lo.shape[1]
    n = 1 << max(0, (c - 1).bit_length())
    hi = jnp.pad(x.hi, ((0, 0), (0, n - c)))
    lo = jnp.pad(x.lo, ((0, 0), (0, n - c)))
    while n > 1:
        n //= 2
        s = u64.add(
            u64.from_arrays(hi[:, :n], lo[:, :n]),
            u64.from_arrays(hi[:, n:], lo[:, n:]),
        )
        hi, lo = s.hi, s.lo
    return u64.from_arrays(hi[:, 0], lo[:, 0])


def _expand_slice(
    tables: SearchTables,
    counts_s,
    tail_s,
    hi_s,
    lo_s,
    tok_s,
    valid_s,
    *,
    pallas_fold: bool = False,
):
    """Expansion preamble for one frontier slice, shared by the one-shot
    layer and the chunked per-chunk pass (one implementation so the
    no-effect-fork handling and index arithmetic can never diverge):
    candidate sweep, step kernel, and the flattened per-child arrays.

    ``pallas_fold=True`` precomputes the chain-hash folds for the whole
    slice in one Pallas kernel call (accumulator stays in VMEM across the
    batch; ops/fold_pallas.py) instead of the per-lane ``lax.scan``;
    callers gate on :func:`..ops.fold_pallas.pallas_fold_eligible`.

    Returns ``(t2, h2, l2, k2, valid2, op2, parent2, chain2, cand)`` where
    the ``*2`` arrays have 2*rows*C lanes (slot A then slot B) and
    ``parent2`` is slice-local.
    """
    fs, c = counts_s.shape
    ops = tables.ops
    e = fs * c
    e2 = 2 * e

    nxt, cand = jax.vmap(partial(_next_and_cands, tables))(counts_s)
    cand = cand & valid_s[:, None]

    if pallas_fold:
        from ..ops.fold_pallas import fold_lanes_pallas

        fh, flo = fold_lanes_pallas(
            jnp.broadcast_to(hi_s[:, None], (fs, c)).reshape(e),
            jnp.broadcast_to(lo_s[:, None], (fs, c)).reshape(e),
            ops.rh_row[nxt].reshape(e),
            ops.rh_len[nxt].reshape(e),
            ops.rh_hi,
            ops.rh_lo,
            interpret=jax.default_backend() != "tpu",
        )
        folded = (fh.reshape(fs, c), flo.reshape(fs, c))
    else:
        folded = None

    def row_step(t, h, l, k, nxt_row, f_row):
        def per_chain(o, f_ch):
            sa, va, _sb, vb = step_kernel(
                ops,
                o,
                DeviceState(t, h, l, k),
                folded=None if f_ch is None else u64.from_arrays(*f_ch),
            )
            return sa, va, vb

        return jax.vmap(per_chain)(nxt_row, f_row)

    # folded=None flows through both vmap levels as an empty pytree, so
    # one traversal serves both fold paths.
    sa, va, vb = jax.vmap(row_step)(tail_s, hi_s, lo_s, tok_s, nxt, folded)
    # slot A: the op's effect outcome; slot B: the no-effect fork (parent
    # state), live only for indefinite append failures.
    va = va & cand
    vb = vb & cand

    # Index maps from iota arithmetic, NOT repeat/tile of arange: XLA
    # constant-folds those into O(F*C) literals embedded in the executable,
    # which made compile time, cache size, and cache-load time scale with
    # frontier capacity (35 MB executables at F=65536).
    idx2 = lax.iota(_I32, e2)
    within = lax.rem(idx2, _I32(e))
    parent2 = within // _I32(c)
    chain2 = lax.rem(within, _I32(c))
    fl = lambda x: x.reshape(e)
    parent = parent2[:e]
    t2 = jnp.concatenate([fl(sa.tail), tail_s[parent]])
    h2 = jnp.concatenate([fl(sa.hash_hi), hi_s[parent]])
    l2 = jnp.concatenate([fl(sa.hash_lo), lo_s[parent]])
    k2 = jnp.concatenate([fl(sa.token), tok_s[parent]])
    valid2 = jnp.concatenate([fl(va), fl(vb)])
    op2 = jnp.concatenate([fl(nxt), fl(nxt)])
    return t2, h2, l2, k2, valid2, op2, parent2, chain2, cand


def _expand_layer(
    tables: SearchTables,
    frontier: Frontier,
    *,
    allow_prune: bool,
    exact_pack: bool = False,
    sort_dedup: bool = False,
    pallas_fold: bool = False,
):
    """Expand + dedup + compact one layer.  Returns the 10-tuple
    (children, pruned, overflow, n_unique, expanded, wparent, wop,
    n_steps, deep_row, children_are_diag): wparent/wop are the per-child
    witness-log row (parent row index and op*2+branch, -1 = no child),
    used to walk an accepting path back for the linearization; n_steps is
    the ops consumed (1 here; a fast stretch consumes more), deep_row a
    deeper-than-pre-expansion counts candidate (zeros here), and
    children_are_diag whether, on extinction, ``children`` rather than the
    pre-expansion frontier holds the diagnosable configuration (False
    here)."""
    f, c = frontier.counts.shape
    e = f * c
    e2 = 2 * e
    idx2 = lax.iota(_I32, e2)
    t2, h2, l2, k2, valid2, op2, parent2, chain2, cand = _expand_slice(
        tables,
        frontier.counts,
        frontier.tail,
        frontier.hi,
        frontier.lo,
        frontier.tok,
        frontier.valid,
        pallas_fold=pallas_fold,
    )

    if exact_pack:
        # Exact mixed-radix counts key (prod(chain_len+1) <= 2^64, see
        # _pack_strides): parent keys from an [F, C] u64 product + tree
        # sum, child keys incrementally (+stride of the linearized chain).
        # The dedup compare below is then two u32 words per row instead of
        # a gathered [e2, C] counts compare — which both cuts the layer's
        # peak HBM (the [e2, C] temporaries dominated at wide buckets)
        # and drops the Zobrist gathers from the hash.
        terms = u64.mul(
            u64.from_arrays(
                jnp.zeros((f, c), _U32), frontier.counts.astype(_U32)
            ),
            u64.from_arrays(
                jnp.broadcast_to(tables.pack_hi[None, :], (f, c)),
                jnp.broadcast_to(tables.pack_lo[None, :], (f, c)),
            ),
        )
        pk = _u64_sum_axis1(terms)
        pk2 = u64.add(
            u64.from_arrays(pk.hi[parent2], pk.lo[parent2]),
            u64.from_arrays(tables.pack_hi[chain2], tables.pack_lo[chain2]),
        )
        pkh2, pkl2 = pk2.hi, pk2.lo
        hh1 = _mix_hash([pkh2, pkl2, t2, h2, l2, k2], e2, 0x811C9DC5)
        hh2 = _mix_hash([pkl2, pkh2, t2, h2, l2, k2], e2, 0x9747B28C)
    else:
        # Zobrist counts hash, updated incrementally per child.
        pz1 = _zob_fold(tables.zob1, frontier.counts)  # [F]
        pz2 = _zob_fold(tables.zob2, frontier.counts)
        cnt_pc = frontier.counts[parent2, chain2]  # [e2]
        d1 = tables.zob1[chain2, cnt_pc] ^ tables.zob1[chain2, cnt_pc + 1]
        d2 = tables.zob2[chain2, cnt_pc] ^ tables.zob2[chain2, cnt_pc + 1]
        cz1 = pz1[parent2] ^ d1
        cz2 = pz2[parent2] ^ d2

        hh1 = _mix_hash([cz1, t2, h2, l2, k2], e2, 0x811C9DC5)
        hh2 = _mix_hash([cz2, t2, h2, l2, k2], e2, 0x9747B28C)

    # S2VTPU_TINY_SORT=1: tiny layers take the sort path when the packed
    # key exists — one 6-word sort of a few hundred rows is exact,
    # scatter-free, and fewer kernels than three probe rounds, a latency
    # trade for the collector regime's tiny buckets on an accelerator.
    # NOT the default: measured 0.23s -> 0.37s on host cores (XLA:CPU
    # tuple-sort overhead beats the probe rounds there); the on-chip
    # runbook ablates it.  (Fewer PROBE rounds at tiny sizes is not an
    # alternative: at e2=192 the table is 256 slots = 0.75 load factor,
    # and dropped rounds keep colliding duplicates — measured 1.6x slower
    # via frontier bloat.)
    tiny_sort = _TINY_SORT and e2 <= 4096
    if exact_pack and (sort_dedup or tiny_sort):
        # Sort-based exact dedup: with the packed key the whole child
        # identity is six u32 words, so one lexicographic sort (invalid
        # rows keyed last) puts every duplicate adjacent to its twin —
        # PERFECT dedup (no missed merges), deterministic by total order
        # (idx2 is the final key, see below — do not drop it: it is what
        # keeps the smallest original row first in each run without
        # relying on sort stability), and scatter-free except one
        # unique-index boolean write-back.
        # The alternative below costs three colliding scatter-min passes
        # over a 2x table, which TPU serializes per colliding update.
        head, _sid, sidx, _sv = _dedup_sort(
            ~valid2, (pkh2, pkl2, t2, h2, l2, k2)
        )
        keep = jnp.zeros(e2, bool).at[sidx].set(head, mode="drop")
        n_unique = head.sum()
    else:
        # Scatter-min hash-table dedup: equal children share both hashes
        # so all copies land in one slot; the smallest row index wins,
        # copies that exact-compare equal to the winner drop, unequal
        # collisions re-probe.  Rows still colliding after the probe
        # rounds are kept — a missed merge wastes a row but never changes
        # a verdict.
        tsz = 1 << max(1, (e2 - 1).bit_length())
        idx = idx2
        keep_u = jnp.zeros(e2, bool)
        surv = valid2
        # Loop-invariant pieces of the exact compare, hoisted out of the
        # probe rounds (only the winner side depends on the round).
        if not exact_pack:
            ar = lax.iota(_I32, c)[None, :]
            cc_i = frontier.counts[parent2] + (chain2[:, None] == ar).astype(
                _I32
            )
        for r in range(3):
            slot = (hh1 + _U32(r) * (hh2 | _U32(1))) & _U32(tsz - 1)
            tbl = jnp.full(tsz, e2, _I32).at[slot].min(
                jnp.where(surv, idx, e2), mode="drop"
            )
            win = tbl[slot]
            w = jnp.minimum(win, e2 - 1)
            is_win = surv & (win == idx)
            # Exact child-counts equality.  Full equality — NOT a
            # same-chain shortcut — is load-bearing: the adversarial
            # family's dedup merges are exactly the cross-chain A-then-B
            # vs B-then-A reorderings, and requiring equal last chains
            # blew the k=10 frontier up 10x (sequences instead of sets).
            # With an exact packed key it is two u32 words; otherwise a
            # fused gather-compare-reduce — no materialized [e2, C]
            # child-counts matrix (the old layer's largest buffer).
            if exact_pack:
                cnt_eq = u64.eq(pk2, u64.from_arrays(pkh2[w], pkl2[w]))
            else:
                cc_w = frontier.counts[parent2[w]] + (
                    chain2[w][:, None] == ar
                ).astype(_I32)
                cnt_eq = (cc_i == cc_w).all(axis=1)
            eq = (
                (t2 == t2[w])
                & (h2 == h2[w])
                & (l2 == l2[w])
                & (k2 == k2[w])
                & cnt_eq
            )
            dup = surv & ~is_win & eq
            keep_u = keep_u | is_win
            surv = surv & ~is_win & ~dup
        keep = keep_u | surv
        n_unique = keep.sum()

    # Lazy-order rank: total indefinite appends linearized (fewest first).
    p_opens = jnp.take_along_axis(
        tables.opens_tab,
        jnp.minimum(frontier.counts.T, tables.opens_tab.shape[1] - 1),
        axis=1,
    ).sum(axis=0)  # [F]
    opens2 = jnp.minimum(
        p_opens[parent2] + tables.is_indef[op2].astype(_I32), _OPENS_CAP - 1
    )

    if allow_prune:
        # Sort-free beam selection: bincount the priority classes, find the
        # threshold class, keep lower classes whole and the threshold class
        # partially (first-come within the layer, deterministic).
        cnt = jnp.zeros(_OPENS_CAP, _I32).at[opens2].add(keep.astype(_I32))
        cum = jnp.cumsum(cnt)
        over = cum > f
        any_over = over.any()
        vstar = jnp.argmax(over).astype(_I32)
        below_ct = jnp.where(vstar > 0, cum[jnp.maximum(vstar - 1, 0)], 0)
        in_class = keep & (opens2 == vstar)
        within = jnp.cumsum(in_class.astype(_I32))
        sel = in_class & (within <= f - below_ct)
        final_keep = jnp.where(any_over, keep & ((opens2 < vstar) | sel), keep)
        pruned = any_over
    else:
        final_keep = keep
        pruned = n_unique > f

    pos = jnp.cumsum(final_keep.astype(_I32)) - 1
    dst = jnp.where(final_keep & (pos < f), pos, e2)
    opbr = op2 * 2 + (idx2 >= e).astype(_I32)
    wparent = jnp.zeros(f, _I32).at[dst].set(parent2, mode="drop")
    wop = jnp.full(f, -1, _I32).at[dst].set(opbr, mode="drop")
    valid_next = jnp.zeros(f, bool).at[dst].set(final_keep, mode="drop")
    # Child counts are recomputed per selected row from the compacted
    # (parent, chain) maps — an [F, C] gather instead of an [e2, C] scatter.
    sel_chain = jnp.zeros(f, _I32).at[dst].set(chain2, mode="drop")
    counts_next = jnp.where(
        valid_next[:, None],
        frontier.counts[wparent]
        + (sel_chain[:, None] == lax.iota(_I32, c)[None, :]).astype(_I32),
        0,
    )
    children = Frontier(
        counts=counts_next,
        tail=jnp.zeros(f, _U32).at[dst].set(t2, mode="drop"),
        hi=jnp.zeros(f, _U32).at[dst].set(h2, mode="drop"),
        lo=jnp.zeros(f, _U32).at[dst].set(l2, mode="drop"),
        tok=jnp.zeros(f, _I32).at[dst].set(k2, mode="drop"),
        valid=valid_next,
    )
    expanded = cand.sum()
    return (
        children,
        pruned,
        jnp.zeros((), bool),
        n_unique,
        expanded,
        wparent,
        wop,
        jnp.ones((), _I32),
        jnp.zeros(c, _I32),
        jnp.zeros((), bool),
    )


def _expand_layer_chunked(
    tables: SearchTables,
    frontier: Frontier,
    *,
    chunk_rows: int,
    pallas_fold: bool = False,
):
    """One exhaustive expansion layer over a frontier too wide to expand in
    one piece: the frontier stays device-resident at full width F while the
    expansion working set (2*chunk*C lanes) is bounded by ``chunk_rows``.

    This is the middle tier between in-core expansion and the host-RAM
    spill: a frontier that fits HBM but whose one-shot expansion buffers
    would not (e.g. the adversarial k=12 peak, 10.85 M rows — trivially
    HBM-resident, yet e2 = 2FC lanes of working set at full width would
    need tens of GB).  The host spill streams every peak layer over
    host<->device transfers, which ride a slow tunnel in this environment;
    chunking keeps everything on device.

    Requires the exact packed counts key (identity = 6 u32 words, enforced
    by the caller's gating): each chunk dedups internally with
    :func:`_dedup_sort` and appends its unique children behind a write
    cursor; when an append would overflow, the buffer is first compacted
    by a cross-chunk dedup (duplicates of rows appended by earlier chunks
    are merged) and only a still-overflowing append reports capacity —
    children incomplete, pre-expansion frontier intact, same contract as
    the one-shot layer.  The fit test is conservative: the incoming
    chunk's rows are not merged against the buffer before testing, so a
    chunk whose rows mostly duplicate buffered ones can report capacity
    even though the true union fits — costing an early escalation or
    spill, never a verdict.  A final cross-chunk pass dedups and compacts the
    committed buffer.  Exhaustive only (no beam).  Returns the
    :func:`_expand_layer` 10-tuple; on overflow the n_unique element
    carries the total appended-rows estimate so the driver's
    jump-to-fitting-bucket escalation keeps working (post-dedup counts
    are capped at F and would degenerate it to fixed x4 steps).
    """
    f, c = frontier.counts.shape
    assert f % chunk_rows == 0 and chunk_rows < f
    ce = chunk_rows * c  # slot-A lanes per chunk; slot B doubles it

    # Children buffer: identity words + witness metadata, written densely
    # behind a cursor.  Validity of slot i is "i < cursor".
    cb0 = (
        jnp.zeros(f, _U32),  # pkh
        jnp.zeros(f, _U32),  # pkl
        jnp.zeros(f, _U32),  # tail
        jnp.zeros(f, _U32),  # hash hi
        jnp.zeros(f, _U32),  # hash lo
        jnp.zeros(f, _I32),  # token
        jnp.zeros(f, _I32),  # parent row (global)
        jnp.zeros(f, _I32),  # op*2+branch
    )

    # Parent packed keys for the WHOLE frontier (one cheap [F, C] pass);
    # chunks gather their slices.
    terms = u64.mul(
        u64.from_arrays(jnp.zeros((f, c), _U32), frontier.counts.astype(_U32)),
        u64.from_arrays(
            jnp.broadcast_to(tables.pack_hi[None, :], (f, c)),
            jnp.broadcast_to(tables.pack_lo[None, :], (f, c)),
        ),
    )
    pk_all = _u64_sum_axis1(terms)

    def compact_cb(state):
        """Dedup the buffer across chunks-so-far and re-pack it."""
        cb, cursor = state
        head, sid, _sidx, svals = _dedup_sort(
            lax.iota(_I32, f) >= cursor, cb[:6], cb[6:]
        )
        pos = jnp.cumsum(head.astype(_I32)) - 1
        dst = jnp.where(head, pos, f)
        new_cb = tuple(
            jnp.zeros(f, a.dtype).at[dst].set(v, mode="drop")
            for a, v in zip(cb, (*sid, *svals))
        )
        return new_cb, head.sum()

    def chunk_body(chunk_i, carry):
        # fori_loop, not a Python loop: the graph stays one chunk big no
        # matter how many chunks the frontier needs (an unrolled loop at
        # F/chunk = 64 took minutes to compile).
        cb, cursor, overflow, expanded, appended = carry
        base = chunk_i * chunk_rows
        dsl = lambda a: lax.dynamic_slice_in_dim(a, base, chunk_rows)
        counts_s = lax.dynamic_slice(
            frontier.counts, (base, 0), (chunk_rows, c)
        )
        tail_s = dsl(frontier.tail)
        hi_s = dsl(frontier.hi)
        lo_s = dsl(frontier.lo)
        tok_s = dsl(frontier.tok)
        valid_s = dsl(frontier.valid)
        pkh_s = dsl(pk_all.hi)
        pkl_s = dsl(pk_all.lo)

        t2, h2, l2, k2, valid2, op2, parent2, chain2, cand = _expand_slice(
            tables, counts_s, tail_s, hi_s, lo_s, tok_s, valid_s,
            pallas_fold=pallas_fold,
        )
        pk2 = u64.add(
            u64.from_arrays(pkh_s[parent2], pkl_s[parent2]),
            u64.from_arrays(tables.pack_hi[chain2], tables.pack_lo[chain2]),
        )

        head, sid, sidx, _sv = _dedup_sort(
            ~valid2, (pk2.hi, pk2.lo, t2, h2, l2, k2)
        )
        u = head.sum()
        # If this chunk's uniques do not fit behind the cursor, first merge
        # duplicates the buffer accumulated across earlier chunks; only a
        # still-overflowing append drops children and reports capacity.
        cb, cursor = lax.cond(
            cursor + u > f, compact_cb, lambda st: st, (cb, cursor)
        )
        # Append this chunk's unique children at the cursor (any order —
        # the final cross-chunk sort re-orders).
        pos = jnp.cumsum(head.astype(_I32)) - 1
        dst = jnp.where(head & (cursor + pos < f), cursor + pos, f)
        gparent = base + lax.rem(sidx, _I32(ce)) // _I32(c)
        gop = op2[sidx] * 2 + (sidx >= ce).astype(_I32)
        vals = (*sid, gparent, gop)
        cb = tuple(
            a.at[dst].set(v.astype(a.dtype), mode="drop")
            for a, v in zip(cb, vals)
        )
        return (
            cb,
            jnp.minimum(cursor + u, f),
            overflow | (cursor + u > f),
            expanded + cand.sum(),
            appended + u,
        )

    cb, cursor, overflow, expanded, appended = lax.fori_loop(
        0,
        f // chunk_rows,
        chunk_body,
        (cb0, jnp.zeros((), _I32), jnp.zeros((), bool), jnp.zeros((), _I32), jnp.zeros((), _I32)),
    )

    # Final cross-chunk dedup + compaction of the committed buffer.  A
    # duplicate can only pair rows appended by different chunks; any
    # deterministic winner preserves verdicts (identical identities are
    # interchangeable — the witness metadata of equal rows differs only in
    # which parent the recovered path threads through, and both are valid).
    head, sid, _sidx, svals = _dedup_sort(
        lax.iota(_I32, f) >= cursor, cb[:6], cb[6:]
    )
    s_pkh, s_pkl, s_t, s_h, s_l, s_k = sid
    v_par, v_op = svals
    n_unique = head.sum()

    pos = jnp.cumsum(head.astype(_I32)) - 1
    dst = jnp.where(head & (pos < f), pos, f)
    wparent = jnp.zeros(f, _I32).at[dst].set(v_par, mode="drop")
    wop = jnp.full(f, -1, _I32).at[dst].set(v_op, mode="drop")
    valid_next = jnp.zeros(f, bool).at[dst].set(head, mode="drop")
    sel_chain = jnp.zeros(f, _I32).at[dst].set(
        tables.ops.chain_of[v_op // 2], mode="drop"
    )
    counts_next = jnp.where(
        valid_next[:, None],
        frontier.counts[wparent]
        + (sel_chain[:, None] == lax.iota(_I32, c)[None, :]).astype(_I32),
        0,
    )
    children = Frontier(
        counts=counts_next,
        tail=jnp.zeros(f, _U32).at[dst].set(s_t, mode="drop"),
        hi=jnp.zeros(f, _U32).at[dst].set(s_h, mode="drop"),
        lo=jnp.zeros(f, _U32).at[dst].set(s_l, mode="drop"),
        tok=jnp.zeros(f, _I32).at[dst].set(s_k.astype(_I32), mode="drop"),
        valid=valid_next,
    )
    return (
        children,
        jnp.zeros((), bool),
        overflow,
        # On overflow, report the appended-rows estimate (an upper bound on
        # the layer's uniques) so the driver escalates to a fitting bucket.
        jnp.where(overflow, jnp.maximum(n_unique, appended), n_unique),
        expanded,
        wparent,
        wop,
        jnp.ones((), _I32),
        jnp.zeros(c, _I32),
        jnp.zeros((), bool),
    )


def _spec_dive(
    tables: SearchTables,
    init: "RunOut",
    depth: int,
    width: int,
    exact_pack: bool,
    sort_dedup: bool,
    pallas_fold: bool,
) -> "RunOut":
    """One speculative beam dive per launch, inside the compiled program.

    Copies the ``width`` best rows off the (closed, pinned) entry frontier
    — value-ordered by the lazy beam priority, fewest linearized
    indefinite appends first — and expands them up to ``depth`` layers,
    checking for an accept after each.  Every dive row is a real reachable
    configuration (each layer step-validates its states through the exact
    expansion kernel), so finding an accepting row is conclusive: the dive
    returns an accept carry with ``layers`` advanced by the dive depth.  A
    dive that exhausts its depth (or its beam) without accepting is
    discarded wholesale — the entry carry passes through untouched except
    for the speculation counters, and the exact single-layer loop proceeds
    as if the dive never ran.
    """
    src = init.frontier
    f = src.valid.shape[0]

    closed_counts, _n, _ne = jax.vmap(partial(_auto_close_row, tables))(
        src.counts, src.tail, src.hi, src.lo, src.tok, src.valid
    )
    pin = jax.vmap(partial(_row_tail_pin, tables))(closed_counts)
    valid = src.valid & ~(src.tail > pin)
    opens = jax.vmap(
        lambda cnt: jnp.take_along_axis(tables.opens_tab, cnt[:, None], axis=1)[
            :, 0
        ].sum()
    )(closed_counts)
    key = jnp.where(
        valid, jnp.minimum(opens, _OPENS_CAP), jnp.int32(2 * _OPENS_CAP)
    )
    order = jnp.argsort(key)[:width]
    beam = Frontier(
        counts=closed_counts[order],
        tail=src.tail[order],
        hi=src.hi[order],
        lo=src.lo[order],
        tok=src.tok[order],
        valid=valid[order],
    )

    def acc_of(fr):
        return jax.vmap(partial(_accept_one, tables))(fr.counts, fr.valid)

    def cond(st):
        fr, k, done = st
        return ~done & (k < depth) & fr.valid.any()

    def step(st):
        fr, k, _done = st
        children = _expand_layer(
            tables,
            fr,
            allow_prune=True,
            exact_pack=exact_pack,
            sort_dedup=sort_dedup,
            pallas_fold=pallas_fold,
        )[0]
        ccounts, _cn, _ce = jax.vmap(partial(_auto_close_row, tables))(
            children.counts,
            children.tail,
            children.hi,
            children.lo,
            children.tok,
            children.valid,
        )
        cpin = jax.vmap(partial(_row_tail_pin, tables))(ccounts)
        nfr = children._replace(
            counts=ccounts, valid=children.valid & ~(children.tail > cpin)
        )
        return nfr, k + 1, acc_of(nfr).any()

    # An already-accepting entry frontier is the exact loop's business
    # (it owns the real accept bookkeeping); the dive stands down.
    entry_acc = acc_of(beam).any()
    fr, k, _done = lax.while_loop(
        cond, step, (beam, jnp.zeros((), _I32), entry_acc)
    )
    acc = acc_of(fr)
    found = acc.any() & ~entry_acc
    idx = jnp.argmax(acc)

    acc_frontier = Frontier(
        counts=src.counts.at[0].set(fr.counts[idx]),
        tail=src.tail.at[0].set(fr.tail[idx]),
        hi=src.hi.at[0].set(fr.hi[idx]),
        lo=src.lo.at[0].set(fr.lo[idx]),
        tok=src.tok.at[0].set(fr.tok[idx]),
        valid=jnp.zeros(f, bool).at[0].set(True),
    )
    new_frontier = jax.tree.map(
        lambda a, b: jnp.where(found, a, b), acc_frontier, src
    )
    return init._replace(
        frontier=new_frontier,
        stop_code=jnp.where(found, jnp.int32(STOP_ACCEPT), init.stop_code).astype(
            _I32
        ),
        accept_idx=jnp.where(found, 0, init.accept_idx).astype(_I32),
        layers=init.layers + jnp.where(found, k, 0),
        deep_counts=jnp.where(found, fr.counts[idx], init.deep_counts),
        spec_layers=init.spec_layers + k,
        spec_accepts=init.spec_accepts + found.astype(_I32),
        spec_rollbacks=init.spec_rollbacks + ((~found) & (k > 0)).astype(_I32),
    )


@partial(
    jax.jit,
    static_argnames=(
        "allow_prune",
        "log_layers",
        "exact_pack",
        "sort_dedup",
        "chunk_rows",
        "pallas_fold",
        "spec_depth",
        "spec_width",
    ),
)
def run_search(
    tables: SearchTables,
    frontier: Frontier,
    max_layers,
    *,
    allow_prune: bool,
    log_layers: int = 0,
    exact_pack: bool = False,
    sort_dedup: bool = False,
    chunk_rows: int = 0,
    pallas_fold: bool = False,
    spec_depth: int = 0,
    spec_width: int = 0,
) -> RunOut:
    """Run the frontier search to a verdict inside one compiled while_loop.

    ``allow_prune=True``: capacity overruns prune to the lazy-best rows and
    the search continues (OK conclusive; dead ends inconclusive).
    ``allow_prune=False``: the loop exits with STOP_CAPACITY and the
    pre-expansion frontier, so the driver can escalate capacity and resume
    exactly (no information lost).

    ``log_layers > 0`` additionally records, for each of the first
    ``log_layers`` committed expansion layers, every child row's (parent
    row, op*2+branch) — the witness log the driver walks backwards from the
    accept row to recover a concrete linearization.  The caller must keep
    ``max_layers <= log_layers``.

    ``exact_pack=True`` (valid only when :func:`can_exact_pack` holds for
    the encoded history) switches dedup to the exact u64 packed counts key
    — same verdicts, far less HBM at wide buckets.  ``sort_dedup=True``
    (requires ``exact_pack``) replaces the scatter-min probe table with a
    lexicographic sort over the full child identity: perfect dedup, no
    colliding scatters — the variant built for TPU, where scatter updates
    on colliding indices serialize.

    ``spec_depth > 0`` prepends one speculative dive per launch
    (:func:`_spec_dive`): a ``spec_width``-row value-ordered beam copied
    off the entry frontier expands up to ``spec_depth`` layers inside the
    same compiled program, checking for an accept after each.  Dive rows
    are real reachable configurations (each expansion step-validates its
    states), so a dive accept is conclusive and returns immediately with
    ``layers`` advanced by the whole dive depth; a dive that finds
    nothing is discarded wholesale (``spec_rollbacks``) and the exact
    loop proceeds from the untouched entry frontier.  Incompatible with
    the witness log (``log_layers`` must be 0 when ``spec_depth > 0``) —
    a speculative accept recovers its linearization from the accept
    counts instead.
    """
    assert not (spec_depth and log_layers), "speculation drops the witness log"

    def body(carry: RunOut) -> RunOut:
        cur = carry.frontier

        closed_counts, ac_n, eager_n = jax.vmap(partial(_auto_close_row, tables))(
            cur.counts, cur.tail, cur.hi, cur.lo, cur.tok, cur.valid
        )
        closed = cur._replace(counts=closed_counts)
        # Tail-pin dead rows (checker/prune.py): a row whose tail has
        # passed the smallest statically-pinned tail among its remaining
        # ops can never linearize that op — drop it.  Exact (the row has
        # no accepting extension), and a no-op under neutral tables.
        pin = jax.vmap(partial(_row_tail_pin, tables))(closed.counts)
        pin_dead = closed.valid & (closed.tail > pin)
        closed = closed._replace(valid=closed.valid & ~pin_dead)
        acc_row = jax.vmap(partial(_accept_one, tables))(closed.counts, closed.valid)
        accept_any = acc_row.any()

        def do_expand(fr):
            # Log-free runs take the multi-step fast path (whole forced
            # stretches per layer); logged runs must keep one op per layer
            # so the witness log rows stay walkable.
            fast = (
                partial(_fast_layer, tables)
                if log_layers
                else partial(_fast_multi, tables, max_layers - carry.layers)
            )
            if chunk_rows and chunk_rows < frontier.valid.shape[0]:
                expand = partial(
                    _expand_layer_chunked,
                    tables,
                    chunk_rows=chunk_rows,
                    pallas_fold=pallas_fold,
                )
            else:
                expand = partial(
                    _expand_layer,
                    tables,
                    allow_prune=allow_prune,
                    exact_pack=exact_pack,
                    sort_dedup=sort_dedup,
                    pallas_fold=pallas_fold,
                )
            return lax.cond(fastable, fast, expand, fr)

        f = frontier.valid.shape[0]
        c = frontier.counts.shape[1]

        def no_expand(fr):
            zero = jnp.zeros((), _I32)
            return (
                fr,
                jnp.zeros((), bool),
                jnp.zeros((), bool),
                zero,
                zero,
                jnp.zeros(f, _I32),
                jnp.full(f, -1, _I32),
                jnp.ones((), _I32),
                jnp.zeros(c, _I32),
                jnp.zeros((), bool),
            )

        # Fast path: a lone live row with a single-chain candidate window
        # and a single-successor op — the forced-step regime of
        # low-concurrency stretches.
        live_idx = jnp.argmax(closed.valid)
        nxt1, cand1 = _next_and_cands(tables, closed.counts[live_idx])
        op1 = nxt1[jnp.argmax(cand1)]
        fastable = (
            (closed.valid.sum() == 1)
            & (cand1.sum() == 1)
            & ~tables.is_indef[op1]
        )

        (
            children,
            pruned,
            overflow,
            n_unique,
            expanded,
            wparent,
            wop,
            n_steps,
            deep_row,
            children_are_diag,
        ) = lax.cond(accept_any, no_expand, do_expand, closed)
        empty = ~accept_any & (n_unique == 0)
        need_cap = (not allow_prune) & (pruned | overflow)
        stop = jnp.where(
            accept_any,
            STOP_ACCEPT,
            jnp.where(empty, STOP_EMPTY, jnp.where(need_cap, STOP_CAPACITY, STOP_RUNNING)),
        ).astype(_I32)

        # On accept/capacity the caller needs the pre-expansion frontier to
        # conclude or resume; on extinction it needs the deepest diagnosable
        # frontier for refusal diagnostics — the pre-expansion rows for a
        # batched layer (their candidates all refused), but the death-POINT
        # configuration for a multi-op fast stretch (the entry snapshot
        # would be many ops shallower and name no culprit).
        resume = accept_any | need_cap | (empty & ~children_are_diag)
        nxt = jax.tree.map(
            lambda a, b: jnp.where(
                resume.reshape((-1,) + (1,) * (a.ndim - 1)), a, b
            ),
            closed,
            children,
        )
        # A capacity stop abandons this layer's expansion (the driver resumes
        # from the pre-expansion frontier and replays it), so only committed
        # layers contribute to the counters — resumed stats stay exact.
        committed = ~need_cap
        if log_layers:
            # The accept layer's row is all -1 (no expansion ran); a
            # capacity-stop row is overwritten on resume because ``layers``
            # does not advance past it.
            li = jnp.minimum(carry.layers, log_layers - 1)
            new_wparent = lax.dynamic_update_index_in_dim(
                carry.wparent, wparent, li, 0
            )
            new_wop = lax.dynamic_update_index_in_dim(carry.wop, wop, li, 0)
        else:
            new_wparent, new_wop = carry.wparent, carry.wop
        # A multi-step fast layer may die mid-stretch: its deepest reached
        # counts (deep_row) beat the pre-expansion snapshot.
        deep_new = jnp.where(
            deep_row.sum() > closed.counts[live_idx].sum(),
            deep_row,
            closed.counts[live_idx],
        )
        return RunOut(
            frontier=nxt,
            stop_code=stop,
            accept_idx=jnp.argmax(acc_row).astype(_I32),
            layers=carry.layers + jnp.where(committed, n_steps, 0),
            pruned_ever=carry.pruned_ever | pruned,
            overflow_ever=carry.overflow_ever | overflow,
            max_live=jnp.maximum(
                carry.max_live, jnp.where(committed, children.valid.sum(), 0)
            ),
            # auto_closed stays ungated: the resume frontier handed back on a
            # capacity stop is post-auto-close, so that work IS committed and
            # will not be replayed.
            auto_closed=carry.auto_closed + jnp.where(cur.valid, ac_n, 0).sum(),
            eager_closed=carry.eager_closed
            + jnp.where(cur.valid, eager_n, 0).sum(),
            pin_killed=carry.pin_killed + pin_dead.astype(_I32).sum(),
            spec_layers=carry.spec_layers,
            spec_accepts=carry.spec_accepts,
            spec_rollbacks=carry.spec_rollbacks,
            expanded=carry.expanded
            + jnp.where(committed, expanded, jnp.zeros((), _I32)),
            deep_counts=jnp.where(committed, deep_new, carry.deep_counts),
            want=jnp.where(need_cap, n_unique, carry.want),
            wparent=new_wparent,
            wop=new_wop,
        )

    def cond(carry: RunOut):
        return (carry.stop_code == STOP_RUNNING) & (carry.layers < max_layers)

    zero = jnp.zeros((), _I32)
    init = RunOut(
        frontier=frontier,
        stop_code=zero,
        accept_idx=zero,
        layers=zero,
        pruned_ever=jnp.zeros((), bool),
        overflow_ever=jnp.zeros((), bool),
        max_live=frontier.valid.sum().astype(_I32),
        auto_closed=zero,
        expanded=zero,
        eager_closed=zero,
        pin_killed=zero,
        spec_layers=zero,
        spec_accepts=zero,
        spec_rollbacks=zero,
        deep_counts=frontier.counts[0],
        want=zero,
        wparent=jnp.zeros((log_layers, frontier.valid.shape[0]), _I32),
        wop=jnp.full((log_layers, frontier.valid.shape[0]), -1, _I32),
    )
    if spec_depth > 0 and spec_width > 0:
        init = _spec_dive(
            tables,
            init,
            spec_depth,
            min(spec_width, frontier.valid.shape[0]),
            exact_pack,
            sort_dedup,
            pallas_fold,
        )
    return lax.while_loop(cond, body, init)


# ---------------------------------------------------------------------------
# Host driver
# ---------------------------------------------------------------------------


def _round_pow2(n: int, lo: int) -> int:
    # Shared with the encoder's shape bucketing (one rule for all
    # compiled-program dimensions).
    return round_pow2(n, lo)


def _floor_pow2(n: int, lo: int) -> int:
    """Largest power of two ≤ n (but ≥ lo) — honors a caller's capacity cap."""
    v = lo
    while v * 2 <= n:
        v *= 2
    return v


@jax.jit
def _accept_set_device(fr: Frontier, idx):
    """Compact the accept configuration's candidate-state set into the
    frontier's leading rows, on device — so the host fetches only the
    (small) set itself, never the whole frontier."""
    same = fr.valid & (fr.counts == fr.counts[idx]).all(axis=1)
    _, tail, hi, lo, tok, n = _compact_rows_device(fr._replace(valid=same))
    return tail, hi, lo, tok, n


def _final_states_device(
    enc: EncodedHistory, frontier: Frontier, idx: int
) -> list[StreamState]:
    """States of every valid row sharing the accept row's counts — the
    accept configuration's candidate-state set.  Compacts on device and
    transfers just the set itself (host↔device traffic is the scarce
    resource — see check_device)."""
    tails, his, los, toks, m = _accept_set_device(frontier, np.int32(idx))
    m = int(m)
    tails, his, los, toks = device_get(
        (tails[:m], his[:m], los[:m], toks[:m])
    )
    out = {
        StreamState(
            tail=int(tails[i]),
            stream_hash=(int(his[i]) << 32) | int(los[i]),
            fencing_token=enc.token_of_id[int(toks[i])],
        )
        for i in range(m)
    }
    return sorted(out)


@jax.jit
def _accept_sweep_device(tables: SearchTables, fr: Frontier, accept_counts):
    """Auto-close every row, then compact the states of rows whose closed
    counts equal the accept configuration's — one slab's piece of the
    accept set.  The accept check runs post-auto-close in the compiled
    layer, so the sweep applies the same (deterministic) closure before
    matching."""
    closed, _, _ = jax.vmap(partial(_auto_close_row, tables))(
        fr.counts, fr.tail, fr.hi, fr.lo, fr.tok, fr.valid
    )
    match = fr.valid & (closed == accept_counts[None, :]).all(axis=1)
    _, tail, hi, lo, tok, n = _compact_rows_device(fr._replace(valid=match))
    return tail, hi, lo, tok, n


def _spill_accept_states(
    enc: EncodedHistory,
    tables: SearchTables,
    host: np.ndarray,
    accept_counts: np.ndarray,
    to_device,
    fill: int,
) -> list[StreamState]:
    """Accept-configuration candidate states unioned across EVERY slab of
    the accept layer, not just the slab that happened to accept first — so
    a spill OK reports the same ``final_states`` as the in-core path
    (``_final_states_device``).  One extra upload-only sweep of the layer;
    auto-close never grows a slab, so the sweep reuses the same buckets."""
    out: set[StreamState] = set()
    acc = jnp.asarray(accept_counts)
    for j in range(0, len(host), fill):
        fr = to_device(host[j : j + fill])
        tail, hi, lo, tok, n = _accept_sweep_device(tables, fr, acc)
        n = int(n)
        tail, hi, lo, tok = device_get((tail[:n], hi[:n], lo[:n], tok[:n]))
        for i in range(n):
            out.add(
                StreamState(
                    tail=int(tail[i]),
                    stream_hash=(int(hi[i]) << 32) | int(lo[i]),
                    fencing_token=enc.token_of_id[int(tok[i])],
                )
            )
    return sorted(out)


@jax.jit
def _compact_rows_device(fr: Frontier):
    """Compact valid rows to the frontier's leading slots, on device.
    Returns ``(counts, tail, hi, lo, tok, n_valid)`` so callers can fetch
    exactly the live rows and nothing else."""
    f = fr.valid.shape[0]
    pos = jnp.cumsum(fr.valid.astype(_I32)) - 1
    dst = jnp.where(fr.valid, pos, f)
    counts = jnp.zeros_like(fr.counts).at[dst].set(fr.counts, mode="drop")
    g1 = lambda x: jnp.zeros(f, x.dtype).at[dst].set(x, mode="drop")
    return (
        counts,
        g1(fr.tail),
        g1(fr.hi),
        g1(fr.lo),
        g1(fr.tok),
        fr.valid.sum(),
    )


@partial(jax.jit, static_argnames=("capacity",))
def _regrow_device(fr: Frontier, *, capacity: int) -> Frontier:
    """Re-bucket a frontier without leaving the device (escalation and
    post-peak downsizing must not round-trip through the host): pad up,
    or slice the dense prefix down — valid rows are always a prefix
    (init_frontier and every expansion layer compact children to the
    front), and callers must keep ``capacity`` at or above the live
    count when shrinking."""
    f0, c = fr.counts.shape
    if capacity <= f0:
        return jax.tree.map(lambda x: x[:capacity], fr)
    pad = capacity - f0
    g1 = lambda x: jnp.concatenate([x, jnp.zeros(pad, x.dtype)])
    return Frontier(
        counts=jnp.concatenate([fr.counts, jnp.zeros((pad, c), _I32)]),
        tail=g1(fr.tail),
        hi=g1(fr.hi),
        lo=g1(fr.lo),
        tok=g1(fr.tok),
        valid=g1(fr.valid),
    )


# JIT observability (obs/introspect.py): every jitted entry point above
# reports compiles / retraces / executable-cache hits to the process
# introspector, keyed by abstract shape signature and attributed to the
# serving job context when one is set.  The wrapper is a dict probe on
# the hit path — nothing here touches the compiled computation.
run_search = observe_jit("run_search")(run_search)
_accept_set_device = observe_jit("accept_set")(_accept_set_device)
_accept_sweep_device = observe_jit("accept_sweep")(_accept_sweep_device)
_compact_rows_device = observe_jit("compact_rows")(_compact_rows_device)
_regrow_device = observe_jit("regrow")(_regrow_device)


_WITNESS_CHUNK = 512
#: layer budget per run_search segment while the frontier is above the
#: expansion bucket — short enough for timely post-peak downsizing, long
#: enough that segment dispatch overhead stays negligible.
_BIG_TIER_CHUNK = 8


def check_device(
    history: History,
    *,
    max_frontier: int = 65536,
    state_slots: int | None = None,
    beam: bool = True,
    start_frontier: int = 16,
    mesh=None,
    collect_stats: bool = False,
    profile: bool = False,
    checkpoint_path: str | None = None,
    checkpoint_every: int = 512,
    witness: bool = True,
    witness_max_frontier: int = 0,
    spill: bool = False,
    spill_host_cap: int = 1 << 26,
    exact_pack: bool | None = None,
    sort_dedup: bool | None = None,
    device_rows_cap: int = 0,
    pallas_fold: bool | None = None,
    progress=None,
    prune: bool = False,
    speculate_depth: int = 0,
    speculate_width: int = 64,
) -> CheckResult:
    """Decide linearizability on device.  Verdict semantics match
    :func:`..checker.frontier.check_frontier`: OK and un-pruned ILLEGAL are
    conclusive; a dead end after pruning is UNKNOWN.

    Both modes start in a small frontier bucket and escalate (x4, resuming
    from the returned pre-expansion frontier) on capacity stops — so cheap
    histories stay cheap.  At ``max_frontier`` a beam run switches to
    prune-and-continue (lazy-order beam) inside the compiled loop, while an
    exhaustive run concedes UNKNOWN.

    ``state_slots`` is accepted for API compatibility and ignored: frontier
    rows are single states, so candidate-state sets are as wide as the
    frontier itself (they were previously capped by a slot bucket).

    ``checkpoint_path``: snapshot the search frontier to this file every
    ``checkpoint_every`` layers (and at capacity escalations) so a long
    search survives preemption; an existing snapshot for the *same* history
    is resumed from, and a conclusive verdict removes it.  A new capability
    over the reference, whose checking is one-shot in-memory (SURVEY.md §5).

    ``witness``: produce a concrete linearization on OK (the analog of the
    linearization info ``porcupine.CheckEventsVerbose`` hands
    ``Visualize``, main.go:605-631).  The default mechanism is the
    counts-bounded host re-search (:func:`_recover_witness_bounded`) run
    once at accept — it adds nothing to the compiled search, survives
    every scale the engine decides at (huge frontiers, checkpoint resume,
    spill), and frees the loop to take multi-op fast layers
    (:func:`_fast_multi`), which is worth ~3x steady-state on collector
    histories.  Setting ``witness_max_frontier > 0`` instead records a
    per-layer (parent row, op, branch) log inside the compiled loop while
    the frontier fits the cap and walks it backwards at accept — the
    exact search path, at the cost of one-op-per-layer execution and
    O(layers x F) device memory; past the cap (or on checkpoint resume)
    the log is dropped and recovery takes over anyway.

    ``device_rows_cap > max_frontier`` (exhaustive + packed-key only)
    enables the HBM-resident middle tier: when the frontier outgrows the
    ``max_frontier`` expansion bucket, it keeps growing on device up to
    ``device_rows_cap`` rows, expanded in ``max_frontier``-row chunks per
    layer (:func:`_expand_layer_chunked`) — no host round-trips.  Only
    past ``device_rows_cap`` (or when packing is unavailable) does the
    search concede UNKNOWN or, with ``spill=True``, hand off to host RAM.

    ``spill=True`` (exhaustive mode only): when the frontier outgrows
    ``max_frontier`` (and ``device_rows_cap``, if set), spill it to host
    RAM and stream slabs through the chip — layer by layer, each slab one
    compiled single-layer pass, with
    exact host-side dedup between layers — instead of conceding UNKNOWN.
    Out-of-core exhaustion stays conclusive (nothing is ever dropped) up
    to ``spill_host_cap`` host rows; the per-layer witness log does not
    survive the spill, but an OK verdict still recovers a linearization
    from the accept counts (:func:`_recover_witness_bounded`).  A
    capability past the reference, whose search is bounded by one
    process's memory.

    ``profile=True`` (implies ``collect_stats``) records a timeline entry
    per *compiled segment* (the driver's steering granularity — per-layer
    scalars never leave the device) on ``stats.timeline``: cumulative
    layer count, segment-max live rows, ops auto-closed, elapsed wall
    seconds, and the stop code.  Spilled searches append one entry per
    out-of-core layer.

    ``progress`` is an optional :class:`.progress.ProgressSink`: the host
    regains control only at compiled-segment boundaries, so that is the
    honest heartbeat cadence — one offer per segment, from scalars the
    driver already fetched.

    ``prune=True`` activates the verdict-exact commutativity prunes
    (:mod:`.prune`): the append rank gate, eager commit of inert and
    passing-filter candidates, and tail-pin dead-row elimination.  Never a
    verdict change — OK, ILLEGAL and UNKNOWN are all preserved (unlike the
    beam, these prunes never set ``stats.pruned``).

    ``speculate_depth > 0`` runs one speculative beam dive per compiled
    launch (:func:`_spec_dive`): the best ``speculate_width`` rows expand
    up to ``speculate_depth`` layers inside the same program, conclusively
    accepting if a dive row accepts and rolling back wholesale otherwise.
    Incompatible with the per-layer witness log — speculation is silently
    disabled while the log is active (an OK verdict still recovers its
    witness via :func:`_recover_witness_bounded`).
    """
    del state_slots
    collect_stats = collect_stats or profile
    # Whether the CALLER wants a witness; the working ``witness`` flag may
    # be dropped mid-run (cap, resume, spill), after which an OK verdict
    # falls back to counts-bounded recovery (_recover_witness_bounded).
    witness_requested = witness
    enc = encode_history(history)
    # Exact packed-key dedup whenever the counts space fits u64 (every
    # realistic workload but very-wide-and-long collector histories);
    # ``exact_pack`` forces it on/off for differential testing.  Validate
    # before any early return so the forced flag's contract is uniform.
    if exact_pack and not can_exact_pack(enc):
        # Zeroed strides would alias every counts vector to key 0 and
        # silently merge distinct configurations — refuse instead.
        raise ValueError(
            "exact_pack=True requires prod(chain_len+1) <= 2^64 "
            "(can_exact_pack); this history's counts space overflows u64"
        )
    stats = FrontierStats()
    t_run0 = time.monotonic()
    if enc.total_remaining == 0:
        res = CheckResult(
            CheckOutcome.OK,
            linearization=list(enc.forced_prefix),
            final_states=sorted(enc.init_states),
        )
        if collect_stats:
            res.stats = stats  # type: ignore[attr-defined]
        return res
    tables = build_tables(enc, prune=prune)
    prune_pt = None
    if prune:
        from .prune import analyze_encoded

        prune_pt = analyze_encoded(enc)
    xp = can_exact_pack(enc) if exact_pack is None else bool(exact_pack)
    # Sort-based dedup needs the packed identity.  An explicit
    # sort_dedup=True on an unpackable history refuses (same contract as
    # exact_pack=True — silently measuring the probe path instead would
    # invalidate the experiment the flag exists for); the env-var opt-in
    # (S2VTPU_SORT_DEDUP=1, usable across mixed workloads) degrades to
    # the probe table with a debug note.  Default off pending an on-chip
    # measurement.
    if sort_dedup and not xp:
        raise ValueError(
            "sort_dedup=True requires the exact packed counts key "
            "(can_exact_pack / exact_pack); this history cannot pack"
        )
    if sort_dedup is None:
        sort_dedup = os.environ.get("S2VTPU_SORT_DEDUP") == "1"
        if sort_dedup and not xp:
            log.debug(
                "S2VTPU_SORT_DEDUP=1 ignored: history's counts space "
                "overflows the u64 packed key; using the probe table"
            )
    sd = bool(sort_dedup) and xp
    # Pallas fold: VMEM-resident batch fold (ops/fold_pallas.py).  Same
    # contract shape as sort_dedup: explicit True on an ineligible history
    # refuses; the env opt-in degrades to the scan fold with a note.
    from ..ops.fold_pallas import pallas_fold_eligible

    pf_ok = pallas_fold_eligible(np.asarray(enc.rh_hi))
    if pallas_fold and not pf_ok:
        raise ValueError(
            "pallas_fold=True requires a VMEM-sized record-hash table "
            "(pallas_fold_eligible); this history's is too large"
        )
    if pallas_fold is None:
        pallas_fold = os.environ.get("S2VTPU_PALLAS_FOLD") == "1"
        if pallas_fold and not pf_ok:
            log.debug(
                "S2VTPU_PALLAS_FOLD=1 ignored: record-hash table too "
                "large for VMEM; using the scan fold"
            )
    pf = bool(pallas_fold) and pf_ok
    cap_layers = int(enc.total_remaining) + 2

    f_cap = _floor_pow2(max_frontier, 2)
    # HBM-resident middle tier: frontier may outgrow the expansion bucket
    # up to big_cap rows, expanded in f_cap-row chunks (exhaustive +
    # packed-key only; a beam run prunes at the bucket instead).  Not
    # under a mesh: sharding already divides the expansion working set
    # per device, and chunk slices across the sharded frontier axis would
    # force cross-shard gathers — aggregate-HBM growth comes from the
    # mesh itself there.
    big_cap = (
        _floor_pow2(device_rows_cap, 2)
        if device_rows_cap > f_cap and not beam and xp and mesh is None
        else f_cap
    )
    f = _round_pow2(
        max(min(start_frontier, f_cap), len(enc.init_states)), 2
    )
    if mesh is not None:
        # Even sharding needs the frontier axis divisible by the shard
        # count; the smallest bucket under a mesh is one row per device.
        f = max(f, _round_pow2(int(mesh.devices.size), 2))
    frontier = None

    if checkpoint_path is not None:
        import dataclasses

        from .checkpoint import (
            Checkpoint,
            CheckpointError,
            fingerprint_mismatch_reason,
            history_fingerprint,
            load_checkpoint,
            save_checkpoint,
        )

        fingerprint = history_fingerprint(enc)
        spill_snapshot = f"{checkpoint_path}.spill.npz"
        if os.path.exists(spill_snapshot):
            data = np.load(spill_snapshot, allow_pickle=False)
            if str(data["fingerprint"]) != fingerprint:
                raise CheckpointError(
                    f"spill checkpoint {spill_snapshot} "
                    + fingerprint_mismatch_reason(
                        str(data["fingerprint"]), fingerprint
                    )
                )
            if beam or not spill:
                raise CheckpointError(
                    f"spill checkpoint {spill_snapshot} requires an "
                    "exhaustive spill-enabled run to resume"
                )
            stats.layers = int(data["layers"])
            deep0 = asarray(data["deep"])
            res = _spill_search(
                enc,
                tables,
                asarray(data["host"]),
                stats,
                _floor_pow2(max_frontier, 2),
                int(enc.total_remaining) + 2,
                mesh=mesh,
                host_cap=spill_host_cap,
                deep_counts=deep0 if len(deep0) else None,
                checkpoint_path=checkpoint_path,
                fingerprint=fingerprint,
                history=history,
                witness_requested=witness_requested,
                exact_pack=xp,
                sort_dedup=sd,
                pallas_fold=pf,
                profile=profile,
                profile_t0=t_run0,
            )
            if res.outcome != CheckOutcome.UNKNOWN:
                with contextlib.suppress(FileNotFoundError):
                    os.remove(checkpoint_path)
            if collect_stats:
                res.stats = stats  # type: ignore[attr-defined]
            return res
        if os.path.exists(checkpoint_path):
            ck = load_checkpoint(checkpoint_path)
            if ck.fingerprint != fingerprint:
                raise CheckpointError(
                    f"checkpoint {checkpoint_path} "
                    + fingerprint_mismatch_reason(ck.fingerprint, fingerprint)
                )
            if ck.beam != beam:
                # A pruned beam frontier must never seed an exhaustive pass
                # (its dead ends would be inconclusive forever), and vice
                # versa a wider exhaustive frontier under beam rules skews
                # stats; refuse rather than silently degrade.
                raise CheckpointError(
                    f"checkpoint {checkpoint_path} was written by a "
                    f"{'beam' if ck.beam else 'exhaustive'} search and cannot "
                    f"resume a {'beam' if beam else 'exhaustive'} one"
                )
            f = ck.f
            for k, v in ck.stats.items():
                setattr(stats, k, v)
            stats.layers = ck.layers_done
            # Earlier layers' witness logs predate this process.
            witness = witness and stats.layers == 0
            frontier = Frontier(
                counts=jnp.asarray(ck.counts),
                tail=jnp.asarray(ck.tail),
                hi=jnp.asarray(ck.hi),
                lo=jnp.asarray(ck.lo),
                tok=jnp.asarray(ck.tok),
                valid=jnp.asarray(ck.valid),
            )
            if mesh is not None and f < int(mesh.devices.size):
                # Resumed onto a wider mesh than the snapshot's bucket
                # (re-grant): grow to one row per device so the placement
                # below shards evenly.
                f = _round_pow2(int(mesh.devices.size), 2)
                frontier = _regrow_device(frontier, capacity=f)

        def _snapshot(fr: Frontier) -> None:
            save_checkpoint(
                checkpoint_path,
                Checkpoint(
                    fingerprint=fingerprint,
                    counts=asarray(fr.counts),
                    tail=asarray(fr.tail),
                    hi=asarray(fr.hi),
                    lo=asarray(fr.lo),
                    tok=asarray(fr.tok),
                    valid=asarray(fr.valid),
                    f=f,
                    beam=beam,
                    layers_done=stats.layers,
                    stats=dataclasses.asdict(stats),
                ),
            )

    if frontier is None:
        frontier = init_frontier(enc, f)
    if mesh is not None:
        frontier = place_frontier(frontier, mesh)

    log.debug(
        "device search: %d ops over %d chains, frontier=%d (cap %d), %s",
        enc.num_ops,
        enc.num_chains,
        f,
        f_cap,
        "beam" if beam else "exhaustive",
    )
    wlogs: list[tuple[np.ndarray, np.ndarray, np.ndarray]] = []
    deep_counts = None
    while True:
        allow_prune = beam and f >= f_cap
        if witness and f > witness_max_frontier:
            if witness_max_frontier > 0:
                log.debug(
                    "witness log dropped: frontier %d exceeds witness cap %d",
                    f,
                    witness_max_frontier,
                )
            witness = False
            wlogs = []
        layers_budget = cap_layers - stats.layers
        if checkpoint_path is not None and checkpoint_every > 0:
            layers_budget = min(layers_budget, checkpoint_every)
        if witness:
            layers_budget = min(layers_budget, _WITNESS_CHUNK)
        if f > f_cap:
            # Short big-tier segments: after the peak the frontier decays,
            # and the driver can only downsize (below) at a segment
            # boundary — full-width chunked layers over a mostly-dead
            # frontier would otherwise dominate the post-peak wall-clock.
            layers_budget = min(layers_budget, _BIG_TIER_CHUNK)
        out = run_search(
            tables,
            frontier,
            np.int32(layers_budget),
            allow_prune=allow_prune,
            log_layers=_WITNESS_CHUNK if witness else 0,
            exact_pack=xp,
            sort_dedup=sd,
            pallas_fold=pf,
            # Chunked expansion only when the big tier is eligible
            # (exhaustive + packed key, big_cap > f_cap).  A checkpoint
            # resumed at f > f_cap WITHOUT eligibility (beam resume, or an
            # unpackable history whose zeroed strides would alias every
            # identity) must run the one-shot expander at width f instead.
            chunk_rows=f_cap if (big_cap > f_cap and f > f_cap) else 0,
            # Speculation shares the launch with the witness log in no
            # compiled program (the dive cannot record per-layer parents);
            # while the log is live the dive stands down.
            spec_depth=0 if witness else int(speculate_depth),
            spec_width=int(speculate_width) if speculate_depth else 0,
        )
        # Scalar-only fetch: the frontier itself stays on device.  Pulling
        # the whole frontier back per segment (the previous design) moved
        # ~70MB/segment at k=10 scale and dominated wall-clock many-fold
        # over the compiled layers themselves; everything the driver needs
        # to steer is a handful of scalars plus the [C] deep-counts row.
        (
            code,
            seg_layers,
            seg_max_live,
            seg_auto_closed,
            seg_expanded,
            seg_pruned,
            want,
            accept_idx,
            deep_np,
            live,
            seg_eager,
            seg_pin,
            seg_spec_layers,
            seg_spec_accepts,
            seg_spec_rollbacks,
        ) = device_get(
            (
                out.stop_code,
                out.layers,
                out.max_live,
                out.auto_closed,
                out.expanded,
                out.pruned_ever,
                out.want,
                out.accept_idx,
                out.deep_counts,
                out.frontier.valid.sum(),
                out.eager_closed,
                out.pin_killed,
                out.spec_layers,
                out.spec_accepts,
                out.spec_rollbacks,
            )
        )
        code = int(code)
        log.debug(
            "segment done: stop=%s layers=%d/%d live=%d auto_closed=%d expanded=%d",
            ("RUNNING", "ACCEPT", "EMPTY", "CAPACITY")[code],
            stats.layers + int(seg_layers),
            cap_layers,
            int(live),
            stats.auto_closed + int(seg_auto_closed),
            stats.expanded + int(seg_expanded),
        )
        stats.layers += int(seg_layers)
        stats.max_frontier = max(stats.max_frontier, int(seg_max_live))
        # max_state_set stays 0: frontier rows are single states, so the
        # candidate-set-width statistic is meaningful only for host engines.
        stats.auto_closed += int(seg_auto_closed)
        stats.expanded += int(seg_expanded)
        stats.prune_commits += int(seg_eager)
        stats.prune_dead += int(seg_pin)
        stats.spec_layers += int(seg_spec_layers)
        stats.spec_accepts += int(seg_spec_accepts)
        stats.spec_rollbacks += int(seg_spec_rollbacks)
        if speculate_depth and not witness:
            stats.spec_launches += 1
        seg_shards = None
        if mesh is not None and collect_stats:
            seg_shards, sync_s = _shard_occupancy(out.frontier, mesh)
            _note_shard_stats(stats, mesh, seg_shards, sync_s)
        if profile:
            entry = {
                "layer": stats.layers,
                "frontier": int(seg_max_live),
                "states": int(live),
                "auto_closed": int(seg_auto_closed),
                "elapsed_s": round(time.monotonic() - t_run0, 6),
                "stop": ("RUNNING", "ACCEPT", "EMPTY", "CAPACITY")[code],
                "bucket": f,
            }
            if seg_shards is not None:
                entry["shards"] = [int(x) for x in seg_shards]
                entry["sync_s"] = round(sync_s, 6)
            stats.timeline.append(entry)
        if progress is not None:
            progress.update(
                ops_committed=int(np.asarray(deep_np).sum()),
                total_ops=enc.num_ops,
                frontier_width=int(live),
                states_expanded=stats.expanded,
                layer=stats.layers,
                engine="device",
            )
        deep_counts = deep_np
        if allow_prune:
            stats.pruned = stats.pruned or bool(seg_pruned)
        if witness:
            # Committed expansion layers of this segment, sparsified.  The
            # accept layer expands nothing (its log row is all -1) and a
            # capacity-aborted layer is not committed; neither is consumed.
            # Only the committed slice of the log is transferred.
            n_rows = int(seg_layers) - (1 if code == STOP_ACCEPT else 0)
            if n_rows > 0:
                wp, wo = device_get(
                    (out.wparent[:n_rows], out.wop[:n_rows])
                )
                for l in range(n_rows):
                    rows = np.flatnonzero(wo[l] >= 0)
                    wlogs.append((rows, wp[l][rows], wo[l][rows]))
        if code == STOP_ACCEPT:
            lin = (
                _witness_linearization(enc, wlogs, int(accept_idx), pt=prune_pt)
                if witness
                else None
            )
            if lin is None and witness_requested:
                # Log dropped (witness cap / checkpoint resume) or
                # inconsistent: recover from the accept counts instead.
                lin = _recover_witness_bounded(
                    enc,
                    history,
                    device_get(out.frontier.counts[int(accept_idx)]),
                )
            res = CheckResult(
                CheckOutcome.OK,
                linearization=lin,
                final_states=_final_states_device(
                    enc, out.frontier, int(accept_idx)
                ),
            )
            break
        if code == STOP_EMPTY:
            outcome = CheckOutcome.UNKNOWN if stats.pruned else CheckOutcome.ILLEGAL
            res = CheckResult(
                outcome,
                deepest=_deepest_ops(enc, deep_counts),
                refusals=_device_refusals(enc, history, out.frontier),
            )
            break
        if code == STOP_CAPACITY:
            # Capacity wall below the cap: escalate and resume from the
            # returned pre-expansion frontier (no information was lost).
            # Past f_cap the frontier keeps growing HBM-resident (chunked
            # expansion) until big_cap.
            if f < big_cap:
                # Jump straight to a bucket that fits the aborted layer's
                # children (x2 headroom) instead of stepping x4 through
                # intermediate buckets — each distinct capacity is its own
                # XLA program, so skipped buckets are skipped compiles.
                need = _round_pow2(max(int(want) * 2, f * 4), 2)
                f = min(need, big_cap)
                log.debug("capacity stop: escalating frontier to %d and resuming", f)
                frontier = _regrow_device(out.frontier, capacity=f)
                if mesh is not None:
                    frontier = place_frontier(frontier, mesh)
                if checkpoint_path is not None:
                    _snapshot(Frontier(*(asarray(x) for x in frontier)))
                continue
            if not beam and spill:
                # Out-of-core hand-off: the frontier goes to the host here
                # (that is the point), but compacted on device first —
                # _spill_search's to_host fetches only the live rows.
                res = _spill_search(
                    enc,
                    tables,
                    out.frontier,
                    stats,
                    f_cap,
                    cap_layers,
                    mesh=mesh,
                    host_cap=spill_host_cap,
                    deep_counts=deep_counts,
                    checkpoint_path=checkpoint_path,
                    fingerprint=fingerprint if checkpoint_path else None,
                    history=history,
                    witness_requested=witness_requested,
                    exact_pack=xp,
                    sort_dedup=sd,
                    pallas_fold=pf,
                    profile=profile,
                    profile_t0=t_run0,
                )
                break
            stats.pruned = True
            res = CheckResult(CheckOutcome.UNKNOWN)
            break
        if code == STOP_RUNNING and stats.layers < cap_layers:
            # Chunk boundary (checkpoint cadence): snapshot and keep going
            # from the returned post-expansion frontier, which never leaves
            # the device unless a checkpoint file asked for a host copy.
            frontier = out.frontier
            if f > f_cap and int(live) * 4 <= f:
                # Post-peak decay: drop back to a bucket the live prefix
                # fits with headroom (never below the expansion bucket) so
                # later layers stop paying full-width chunked sorts.
                f = max(_round_pow2(max(int(live), 1) * 4, 2), f_cap)
                log.debug("post-peak downsize: frontier bucket -> %d", f)
                frontier = _regrow_device(frontier, capacity=f)
            if checkpoint_path is not None:
                _snapshot(Frontier(*(asarray(x) for x in frontier)))
            continue
        # Layer cap hit without a verdict: should be impossible (each layer
        # linearizes exactly one op); treat as inconclusive.
        res = CheckResult(CheckOutcome.UNKNOWN)
        break

    if checkpoint_path is not None and res.outcome != CheckOutcome.UNKNOWN:
        with contextlib.suppress(FileNotFoundError):
            os.remove(checkpoint_path)
    if collect_stats:
        res.stats = stats  # type: ignore[attr-defined]
    return res


def _host_close(
    enc: EncodedHistory,
    counts,
    tail: int,
    tok: int,
    h: int | None = None,
    pt=None,
) -> list[int]:
    """Host mirror of :func:`_auto_close_row`: advance every dead candidate
    (all at once per sweep, chain order within a sweep) until a fixpoint;
    returns the encoded op indices closed, mutating ``counts``.

    ``pt`` (a :class:`..checker.prune.PruneTables`) mirrors the eager-commit
    branch of a pruned device run: inert candidates close unconditionally
    and successful filters close when they pass the row's state (``tail``
    plus, when ``h`` — the full 64-bit stream hash — is given, the hash
    guard).  Required for witness replay of a ``prune=True`` search, whose
    logged expansion path excludes eagerly-closed ops."""
    is_indef = enc.out_failure & ~enc.out_definite & (enc.op_type == 0)
    settable = {int(enc.set_token[j]) for j in range(enc.num_ops) if enc.has_set_token[j]}
    closed: list[int] = []
    while True:
        nxt, cand = _host_next_cands(enc, counts)
        dead = []
        for c in np.flatnonzero(cand):
            j = nxt[c]
            if pt is not None:
                if pt.inert[j]:
                    dead.append(c)
                    continue
                if (
                    pt.filter_succ[j]
                    and (tail & 0xFFFFFFFF) == int(enc.out_tail[j])
                    and (
                        not enc.out_has_hash[j]
                        or (
                            h is not None
                            and (h & 0xFFFFFFFFFFFFFFFF)
                            == (int(enc.out_hash_hi[j]) << 32)
                            | int(enc.out_hash_lo[j])
                        )
                    )
                ):
                    dead.append(c)
                    continue
            if not is_indef[j]:
                continue
            if enc.has_match[j] and tail > int(enc.match_seq[j]):
                dead.append(c)
            elif (
                enc.has_batch_token[j]
                and int(enc.batch_token[j]) not in settable
                and tok != int(enc.batch_token[j])
            ):
                dead.append(c)
        if not dead:
            return closed
        for c in dead:
            closed.append(int(nxt[c]))
        for c in dead:
            counts[c] += 1


def _host_next_cands(enc: EncodedHistory, counts):
    """Host mirror of :func:`_next_and_cands` for one counts vector."""
    c = enc.num_chains
    nxt = np.zeros(c, np.int64)
    has_next = counts < enc.chain_len
    m = INF_TIME
    for ci in range(c):
        if has_next[ci]:
            nxt[ci] = enc.chain_ops[ci, counts[ci]]
            m = min(m, int(enc.ret[nxt[ci]]))
    cand = has_next & (enc.call[nxt] < m)
    return nxt, cand


def _witness_linearization(
    enc: EncodedHistory, wlogs, accept_idx: int, pt=None
) -> list[int] | None:
    """Recover a concrete linearization from the accept row's logged path.

    Walk the per-layer (parent, op, branch) log backwards from the accept
    row to the initial row, then replay forwards — re-running the
    deterministic auto-close between logged steps so closed ops land at
    their true positions — and finish with the accept configuration's
    remaining (all-indefinite-append) ops in call order, which is always a
    valid completion.  Returns ``History.ops`` indices in linearization
    order, or None if the log is inconsistent (never expected; the caller
    then just omits the witness, matching the verdict-only behavior).
    """
    path: list[int] = []  # opbr per expansion layer, first → last
    r = accept_idx
    for rows, parents, opbrs in reversed(wlogs):
        i = np.searchsorted(rows, r)
        if i >= len(rows) or rows[i] != r:
            log.warning("witness log inconsistent at row %d; omitting witness", r)
            return None
        path.append(int(opbrs[i]))
        r = int(parents[i])
    path.reverse()

    states = sorted(intern_state(enc, s) for s in enc.init_states)
    if r >= len(states):
        log.warning("witness walk ended at invalid init row %d", r)
        return None
    tail, hi, lo, tok = states[r]
    h = (hi << 32) | lo

    from ..utils.hashing import fold_record_hashes

    counts = np.array(enc.chain_start, np.int64)
    order: list[int] = []

    def apply_effect(j: int) -> None:
        nonlocal tail, h, tok
        if enc.op_type[j] == 0 and not (enc.out_failure[j] and enc.out_definite[j]):
            row, ln = int(enc.rh_row[j]), int(enc.rh_len[j])
            hashes = [
                (int(enc.rh_hi[row, i]) << 32) | int(enc.rh_lo[row, i])
                for i in range(ln)
            ]
            h = fold_record_hashes(h, hashes)
            tail = (tail + int(enc.num_records[j])) & 0xFFFFFFFF
            if enc.has_set_token[j]:
                tok = int(enc.set_token[j])

    for opbr in path:
        j, br = opbr // 2, opbr % 2
        order.extend(_host_close(enc, counts, tail, tok, h=h, pt=pt))
        nxt, cand = _host_next_cands(enc, counts)
        c = int(enc.chain_of[j])
        if not cand[c] or int(nxt[c]) != j:
            log.warning("witness replay diverged at op %d; omitting witness", j)
            return None
        counts[c] += 1
        order.append(j)
        if br == 0:
            apply_effect(j)
    order.extend(_host_close(enc, counts, tail, tok, h=h, pt=pt))

    remaining = _accept_remaining(enc, counts)
    if remaining is None:
        return None
    order.extend(remaining)

    ki = enc.keep_index()
    return list(enc.forced_prefix) + [ki[j] for j in order]


def _accept_remaining(enc: EncodedHistory, counts) -> list[int] | None:
    """The accept configuration's remaining ops in call order — the shared
    completion tail of both witness paths (log walk and counts-bounded
    recovery).  The remaining ops are all indefinite appends (that is what
    accept means), and linearizing them in call order respects both chain
    order and real time (each one's no-effect branch is unconditionally
    valid); returns None if a remainder is not an indefinite append (never
    expected)."""
    is_indef = enc.out_failure & ~enc.out_definite & (enc.op_type == 0)
    remaining = [
        int(enc.chain_ops[c, k])
        for c in range(enc.num_chains)
        for k in range(int(counts[c]), int(enc.chain_len[c]))
    ]
    if not all(is_indef[j] for j in remaining):
        log.warning("witness accept state has non-indefinite remainders")
        return None
    remaining.sort(key=lambda j: int(enc.call[j]))
    return remaining


def _recover_witness_bounded(
    enc: EncodedHistory,
    history: History,
    accept_counts,
    node_budget: int = 500_000,
) -> list[int] | None:
    """Recover a linearization when the per-layer witness log is gone
    (frontier beyond the witness cap, checkpoint resume, out-of-core
    spill).

    The OK verdict hands us the accept configuration's counts vector, and
    that vector collapses the problem: a witness only needs a valid order
    of the ops *below* it (every chain's accept prefix), so the search
    space shrinks from all reachable configurations to the sub-lattice
    ``counts <= accept_counts`` — for the adversarial family that is the
    orderings of the applied subset (~k! / e), thousands of nodes where
    the full search needed millions of rows.  A plain host Wing–Gong DFS
    with (counts, state) memoization walks it in milliseconds; the
    remaining (all-indefinite-append) ops complete the order as in
    :func:`_witness_linearization`.  Returns None (witness omitted, the
    verdict-only behavior) if the node budget is exhausted — possible
    only when the accept prefix is itself search-hard, which the huge-
    frontier regimes this path serves never are.

    Reference analog: the linearization info ``CheckEventsVerbose`` hands
    ``Visualize`` (golang/s2-porcupine/main.go:605-631), which the
    reference produces at every scale its engine can decide.
    """
    from ..models.stream import step_set

    ki = enc.keep_index()
    n_chains = enc.num_chains
    target = np.asarray(accept_counts, np.int64)
    counts0 = np.asarray(enc.chain_start, np.int64)
    chain_len = np.asarray(enc.chain_len, np.int64)
    if (target < counts0).any() or (target > chain_len).any():
        log.warning("witness recovery: accept counts out of range; omitting")
        return None

    prefix_ops = [
        int(enc.chain_ops[c, k])
        for c in range(n_chains)
        for k in range(int(counts0[c]), int(target[c]))
    ]
    remaining = _accept_remaining(enc, target)
    if remaining is None:
        return None
    # Completion soundness (same property _witness_linearization relies
    # on): appending the remaining ops after the whole prefix respects
    # real time iff no remaining op returned before a prefix op's call.
    # Reachability of the accept row guarantees it; check anyway.
    if prefix_ops and remaining:
        if min(int(enc.ret[j]) for j in remaining) < max(
            int(enc.call[j]) for j in prefix_ops
        ):
            log.warning(
                "witness recovery: completion would violate real-time "
                "order; omitting"
            )
            return None

    def skey(s):
        return (s.tail, s.stream_hash, s.fencing_token)

    tt = tuple(int(x) for x in target)
    start = tuple(int(x) for x in counts0)
    parent: dict = {}
    stack = []
    for s in enc.init_states:
        key = (start, skey(s))
        if key not in parent:
            parent[key] = None
            stack.append((start, s))
    budget = node_budget
    goal_key = None
    while stack:
        counts_t, state = stack.pop()
        if counts_t == tt:
            goal_key = (counts_t, skey(state))
            break
        counts = np.asarray(counts_t, np.int64)
        nxt, cand = _host_next_cands(enc, counts)
        for c in range(n_chains):
            if not cand[c] or counts_t[c] >= tt[c]:
                continue
            j = int(nxt[c])
            op = history.ops[ki[j]]
            nct = counts_t[:c] + (counts_t[c] + 1,) + counts_t[c + 1 :]
            for ns in step_set([state], op.inp, op.out):
                key = (nct, skey(ns))
                if key in parent:
                    continue
                budget -= 1
                if budget <= 0:
                    log.warning(
                        "witness recovery exhausted its %d-node budget; "
                        "omitting witness",
                        node_budget,
                    )
                    return None
                parent[key] = ((counts_t, skey(state)), j)
                stack.append((nct, ns))
    if goal_key is None:
        # Never expected: the device search proved the configuration
        # reachable.
        log.warning(
            "witness recovery found no path to the accept configuration; "
            "omitting witness"
        )
        return None

    order: list[int] = []
    node = goal_key
    while parent[node] is not None:
        node, j = parent[node]
        order.append(j)
    order.reverse()
    order.extend(remaining)
    return list(enc.forced_prefix) + [ki[j] for j in order]


def _deepest_ops(enc: EncodedHistory, deep_counts) -> list[int]:
    """History op indices of the deepest committed row's linearized set."""
    if deep_counts is None:
        return list(enc.forced_prefix)
    chain_ops = asarray(enc.chain_ops)
    out = list(enc.forced_prefix)
    keep_index = enc.keep_index()
    for c in range(chain_ops.shape[0]):
        for k in range(int(deep_counts[c])):
            j = int(chain_ops[c, k])
            if j >= 0:
                out.append(keep_index[j])
    return out


def _refusal_diagnostics(
    enc: EncodedHistory,
    history: History,
    rows,
    max_signatures: int = 8,
) -> list[tuple[list[int], list[int]]]:
    """Per distinct counts signature among ``rows`` (post-auto-close host
    values ``(counts, tail, hi, lo, tok)``): the linearized prefix and the
    window-open candidate ops whose outputs that row's state refuses — the
    failure-diagnostics analog of porcupine's partial-linearization info
    (main.go:606,627), one report per deepest configuration instead of a
    single outline."""
    from ..models.stream import step_set

    ki = enc.keep_index()
    reports: list[tuple[list[int], list[int]]] = []
    seen: set[tuple[int, ...]] = set()
    for counts, tail, hi, lo, tok in rows:
        counts64 = np.asarray(counts, np.int64)
        sig = tuple(int(x) for x in counts64)
        if sig in seen:
            continue
        seen.add(sig)
        state = StreamState(
            tail=int(tail) & 0xFFFFFFFF,
            stream_hash=((int(hi) & 0xFFFFFFFF) << 32) | (int(lo) & 0xFFFFFFFF),
            fencing_token=enc.token_of_id[int(tok)],
        )
        nxt, cand = _host_next_cands(enc, counts64)
        refused = []
        for c in np.flatnonzero(cand):
            j = int(nxt[c])
            op = history.ops[ki[j]]
            if not step_set([state], op.inp, op.out):
                refused.append(ki[j])
        prefix = _deepest_ops(enc, counts64)
        reports.append((sorted(prefix), sorted(refused)))
        if len(reports) >= max_signatures:
            break
    return reports


def _device_refusals(
    enc: EncodedHistory,
    history: History | None,
    frontier: Frontier,
    sample: int = 256,
    max_signatures: int = 8,
) -> list[tuple[list[int], list[int]]]:
    """Refusal reports from a pre-extinction device frontier (the frontier
    ``run_search`` hands back on STOP_EMPTY): compact on device, fetch a
    small row sample, diagnose host-side."""
    if history is None:
        return []
    counts_m, tail_m, hi_m, lo_m, tok_m, n = _compact_rows_device(frontier)
    m = min(int(n), sample)
    if m == 0:
        return []
    cm, tm, hm, lm, km = device_get(
        (counts_m[:m], tail_m[:m], hi_m[:m], lo_m[:m], tok_m[:m])
    )
    rows = [(cm[i], tm[i], hm[i], lm[i], km[i]) for i in range(m)]
    return _refusal_diagnostics(enc, history, rows, max_signatures)


def _host_row_refusals(
    enc: EncodedHistory,
    history: History | None,
    host: np.ndarray,
    max_signatures: int = 8,
) -> list[tuple[list[int], list[int]]]:
    """Refusal reports from the spill path's host frontier (the final
    streamed layer's input rows, which all died).  Rows are pre-auto-close;
    the deterministic closure is applied before diagnosis so reports match
    the device engine's post-close view."""
    if history is None:
        return []
    c = enc.num_chains
    rows = []
    for i in range(min(len(host), 2048)):
        counts = host[i, :c].astype(np.int64).copy()
        tail = int(host[i, c]) & 0xFFFFFFFF
        tok = int(host[i, c + 3])
        _host_close(enc, counts, tail, tok)
        rows.append(
            (counts, host[i, c], host[i, c + 1], host[i, c + 2], tok)
        )
    return _refusal_diagnostics(enc, history, rows, max_signatures)


def _dedup_rows(mat: np.ndarray, _key_bits: int = 64) -> np.ndarray:
    """Exact row dedup for the spill frontier.

    ``np.unique(axis=0)`` lexicographically sorts the full c+4-column rows
    (it views each row as one big void scalar), which dominates spill-layer
    time at tens of millions of rows.  One u64-hash argsort gets equal rows
    adjacent with a single key sort; an exact fixup pass re-checks the rare
    rows whose hash run still holds more than one distinct row, so the
    result is exactly ``np.unique``'s row set (order differs; the frontier
    is a set).  ``_key_bits`` narrows the key in tests to force collisions
    through the fixup path.
    """
    n = len(mat)
    if n <= 1:
        return mat
    u = mat.view(np.uint32)
    # Two u32 FNV-style lane hashes, folded column-by-column in place (u64
    # per-column temps doubled the memory traffic and dominated the cost).
    h1 = np.full(n, 0x811C9DC5, np.uint32)
    h2 = np.full(n, 0x9747B28C, np.uint32)
    tmp = np.empty(n, np.uint32)
    for j in range(mat.shape[1]):
        col = u[:, j]
        np.bitwise_xor(h1, col, out=h1)
        np.multiply(h1, np.uint32(0x01000193), out=h1)
        np.left_shift(col, np.uint32(1), out=tmp)
        np.bitwise_or(tmp, np.uint32(1), out=tmp)
        np.bitwise_xor(h2, tmp, out=h2)
        np.multiply(h2, np.uint32(0x7FEB352D), out=h2)
    key = (h1.astype(np.uint64) << np.uint64(32)) | h2
    if _key_bits < 64:
        key &= np.uint64((1 << _key_bits) - 1)
    order = np.argsort(key)  # unstable is fine: the frontier is a set, and
    # the run-based fixup below is order-independent
    key_s = key[order]
    mat_s = mat[order]
    same_key = np.empty(n, bool)
    same_key[0] = False
    same_key[1:] = key_s[1:] == key_s[:-1]
    dup = np.zeros(n, bool)
    dup[1:] = same_key[1:] & (mat_s[1:] == mat_s[:-1]).all(axis=1)
    kept = ~dup
    # A key run holding >=2 kept rows is either a hash collision or equal
    # rows a collision separated; re-check those runs with np.unique.  The
    # whole run goes to the fixup together — equal rows always share a run,
    # so none can be split between the plain and fixed partitions.  With
    # 64-bit keys this pass almost never triggers, so probe cheaply first:
    # a kept row opening neither a new run nor following its run's opener
    # can only exist under collisions.
    if not np.count_nonzero(kept & same_key):
        return mat_s[kept]
    run_id = np.cumsum(~same_key) - 1
    kept_per_run = np.bincount(run_id[kept], minlength=int(run_id[-1]) + 1)
    ambiguous = kept & (kept_per_run[run_id] >= 2)
    plain = mat_s[kept & ~ambiguous]
    if not ambiguous.any():
        return plain
    fixed = np.unique(mat_s[ambiguous], axis=0)
    return np.concatenate([plain, fixed])


def _spill_search(
    enc: EncodedHistory,
    tables: SearchTables,
    seed: "Frontier | np.ndarray",
    stats: FrontierStats,
    f_cap: int,
    cap_layers: int,
    *,
    mesh,
    host_cap: int,
    deep_counts,
    checkpoint_path: str | None = None,
    fingerprint: str | None = None,
    history: History | None = None,
    witness_requested: bool = False,
    exact_pack: bool = False,
    sort_dedup: bool = False,
    pallas_fold: bool = False,
    profile: bool = False,
    profile_t0: float | None = None,
) -> CheckResult:
    """Out-of-core exhaustive search: frontier in host RAM, slabs on device.

    Each layer streams the host frontier through ``run_search(max_layers=1)``
    in slabs of a device bucket (``f_cap``, raised to at least ``4*C`` so a
    single row's children always fit): auto-close, accept check, one
    expansion, and in-slab dedup all run compiled; exact cross-slab dedup
    happens host-side (``_dedup_rows``) between layers.  Whenever the
    deduped frontier fits back inside half the device bucket, the search
    resumes fully in-core (multi-layer, no host round-trips) until it
    overflows again — streaming is paid only at the peak layers.  Nothing is ever
    pruned, so OK and ILLEGAL both stay conclusive; UNKNOWN only when the
    host frontier exceeds ``host_cap`` rows (checked inside the slab loop
    too — transient children are bounded, not just the post-dedup set).
    The slab fill resets each layer; on a growth spike the overflowing
    range is retried in halves and the layer-wide fill halves with it.
    Up to two slabs stay in flight so transfers overlap device compute,
    degrading to one if that second bucket exhausts device memory.  On OK
    the reported ``final_states`` are the accept configuration's full
    candidate-state set, unioned across every slab of the accept layer by
    a second upload-only sweep (``_spill_accept_states``) — identical to
    the in-core result; and when ``witness_requested`` (the caller asked
    ``check_device(witness=True)``), a linearization is recovered from
    the accept counts (``_recover_witness_bounded``) even though the
    per-layer log cannot survive the spill.
    With ``checkpoint_path``, the host frontier is snapshotted at
    streamed-layer and in-core-segment boundaries (``<path>.spill.npz``) —
    a preemption mid-segment replays that segment's layers — and a
    matching snapshot is resumed from.
    """
    c = enc.num_chains
    if profile_t0 is None:
        profile_t0 = time.monotonic()

    def _profile_entry(frontier_rows: int, states: int, stop: str) -> None:
        if profile:
            stats.timeline.append(
                {
                    "layer": stats.layers,
                    "frontier": frontier_rows,
                    "states": states,
                    "auto_closed": stats.auto_closed,
                    "elapsed_s": round(time.monotonic() - profile_t0, 6),
                    "stop": stop,
                    "spill": True,
                }
            )

    # A bucket that always fits one row's <= 2C children, whatever the
    # caller's max_frontier was.
    f_cap = max(f_cap, _round_pow2(4 * max(c, 1), 2))
    spill_ck = f"{checkpoint_path}.spill.npz" if checkpoint_path else None

    def to_host(fr: Frontier) -> np.ndarray:
        # Compact valid rows to the front on device so only live data
        # crosses the host boundary (the padded bucket tail never does).
        counts, tail, hi, lo, tok, n = _compact_rows_device(fr)
        n = int(n)
        counts, tail, hi, lo, tok = device_get(
            (counts[:n], tail[:n], hi[:n], lo[:n], tok[:n])
        )
        mat = np.empty((n, c + 4), np.int32)
        mat[:, :c] = counts
        mat[:, c] = tail.view(np.int32)
        mat[:, c + 1] = hi.view(np.int32)
        mat[:, c + 2] = lo.view(np.int32)
        mat[:, c + 3] = tok
        return mat

    def to_device(mat: np.ndarray) -> Frontier:
        # Upload only a tight power-of-two bucket around the live rows and
        # pad to the slab capacity on device.
        n = mat.shape[0]
        p2 = min(_round_pow2(max(n, 1), 64), f_cap)
        counts = np.zeros((p2, c), np.int32)
        counts[:n] = mat[:, :c]
        one = lambda col, dt: np.concatenate(
            [mat[:, col].astype(np.int32).view(dt), np.zeros(p2 - n, dt)]
        )
        valid = np.zeros(p2, bool)
        valid[:n] = True
        fr = Frontier(
            counts=jnp.asarray(counts),
            tail=jnp.asarray(one(c, np.uint32)),
            hi=jnp.asarray(one(c + 1, np.uint32)),
            lo=jnp.asarray(one(c + 2, np.uint32)),
            tok=jnp.asarray(one(c + 3, np.int32)),
            valid=jnp.asarray(valid),
        )
        if p2 < f_cap:
            fr = _regrow_device(fr, capacity=f_cap)
        return place_frontier(fr, mesh) if mesh is not None else fr

    def unknown() -> CheckResult:
        stats.pruned = True
        return CheckResult(CheckOutcome.UNKNOWN)

    def conclude(res: CheckResult) -> CheckResult:
        """A conclusive verdict spends the spill snapshot."""
        if spill_ck is not None:
            with contextlib.suppress(FileNotFoundError):
                os.remove(spill_ck)
        return res

    # A Frontier seed just overflowed the same bucket in the escalation
    # driver, so an immediate in-core retry would deterministically fail
    # again; checkpoint-resume ndarray seeds carry no such knowledge.
    try_incore = isinstance(seed, np.ndarray)
    host = seed if isinstance(seed, np.ndarray) else to_host(seed)
    deep = asarray(deep_counts) if deep_counts is not None else None
    deep_sum = int(deep.sum()) if deep is not None else -1
    log.debug(
        "spilling to host: %d rows, device bucket %d", len(host), f_cap
    )

    while stats.layers < cap_layers:
        if spill_ck is not None:
            tmp = spill_ck + ".tmp.npz"
            np.savez_compressed(
                tmp,
                fingerprint=np.array(fingerprint or ""),
                host=host,
                layers=np.int64(stats.layers),
                deep=deep if deep is not None else np.zeros(0, np.int32),
            )
            os.replace(tmp, spill_ck)
        if try_incore and len(host) <= f_cap // 2:
            # Hybrid resume: the frontier fits the device bucket again, so
            # run whole in-core layers (no host round-trips) until it
            # outgrows the bucket — streaming is paid only at the peak
            # layers.  f_cap//2 leaves expansion headroom; a segment that
            # commits no layer (immediate overflow) falls through to one
            # streamed layer before retrying.
            out = run_search(
                tables,
                to_device(host),
                np.int32(cap_layers - stats.layers),
                allow_prune=False,
                exact_pack=exact_pack,
                sort_dedup=sort_dedup,
                pallas_fold=pallas_fold,
            )
            code, seg_layers, seg_live, seg_ac, seg_ex, accept_idx, dc = (
                device_get(
                    (
                        out.stop_code,
                        out.layers,
                        out.max_live,
                        out.auto_closed,
                        out.expanded,
                        out.accept_idx,
                        out.deep_counts,
                    )
                )
            )
            code = int(code)
            stats.layers += int(seg_layers)
            stats.max_frontier = max(stats.max_frontier, int(seg_live))
            stats.auto_closed += int(seg_ac)
            stats.expanded += int(seg_ex)
            _profile_entry(
                int(seg_live),
                int(seg_live),
                ("RUNNING", "ACCEPT", "EMPTY", "CAPACITY")[code],
            )
            log.debug(
                "spill in-core segment: stop=%s +%d layers",
                ("RUNNING", "ACCEPT", "EMPTY", "CAPACITY")[code],
                int(seg_layers),
            )
            if int(asarray(dc).sum()) > deep_sum:
                deep_sum, deep = int(asarray(dc).sum()), asarray(dc)
            if code == STOP_ACCEPT:
                lin = (
                    _recover_witness_bounded(
                        enc,
                        history,
                        device_get(out.frontier.counts[int(accept_idx)]),
                    )
                    if witness_requested and history is not None
                    else None
                )
                return conclude(
                    CheckResult(
                        CheckOutcome.OK,
                        linearization=lin,
                        final_states=_final_states_device(
                            enc, out.frontier, int(accept_idx)
                        ),
                    )
                )
            if code == STOP_EMPTY:
                return conclude(
                    CheckResult(
                        CheckOutcome.ILLEGAL,
                        deepest=_deepest_ops(enc, deep),
                        refusals=_device_refusals(enc, history, out.frontier),
                    )
                )
            # STOP_CAPACITY: back to streaming from the returned
            # (post-auto-close, pre-expansion) frontier.  The frontier just
            # proved it cannot expand in-core, so re-running it in-core
            # (even after committed layers) would deterministically
            # capacity-stop again with 0 layers — one wasted full-bucket
            # run.  The streamed layer's dedup re-enables try_incore.
            host = to_host(out.frontier)
            try_incore = False
            continue
        children: list[np.ndarray] = []
        children_rows = 0
        fill = max(1, f_cap // 4)
        # Dispatch-ahead pipeline: keep up to two slabs in flight so D2H of
        # one slab's children overlaps device compute of the next.  Each
        # queue entry is an independent (start, length) row range; on a
        # children overflow the layer-wide fill halves (growth is usually
        # uniform across rows, so remaining ranges pre-split instead of
        # each overflowing once) and the failed range is retried in halves.
        # The one compiled program serves every fill level.  If holding two
        # buckets exhausts device memory (spill runs exactly when memory is
        # tight), the pipeline degrades to depth one and retries.
        work = deque(
            (j, min(fill, len(host) - j)) for j in range(0, len(host), fill)
        )
        inflight: deque = deque()
        max_inflight = 2
        def degrade(ranges, outs) -> bool:
            """Drop to pipeline depth 1 after RESOURCE_EXHAUSTED: requeue
            the ranges and block until every held result's program has
            quiesced before dropping it, so its buffers are actually free
            by the time the depth-1 retry uploads.  Returns False when
            already at depth 1 (nothing left to shed)."""
            nonlocal max_inflight
            if max_inflight == 1:
                return False
            log.warning(
                "spill pipeline exhausted device memory; degrading to depth 1"
            )
            max_inflight = 1
            for r in ranges:
                work.appendleft(r)
            for o in outs:
                with contextlib.suppress(Exception):
                    jax.block_until_ready(o.stop_code)
            outs.clear()
            return True

        while work or inflight:
            pending_range = None
            out = None
            try:
                while work and len(inflight) < max_inflight:
                    s0, t0 = work.popleft()
                    if t0 > fill:
                        work.appendleft((s0 + fill, t0 - fill))
                        t0 = fill
                    pending_range = (s0, t0)
                    inflight.append(
                        (
                            s0,
                            t0,
                            run_search(
                                tables,
                                to_device(host[s0 : s0 + t0]),
                                np.int32(1),
                                allow_prune=False,
                                exact_pack=exact_pack,
                                sort_dedup=sort_dedup,
                                pallas_fold=pallas_fold,
                            ),
                        )
                    )
                    pending_range = None
                s0, t0, out = inflight.popleft()
                # Scalar-only fetch; children cross back compacted
                # (to_host).
                code, seg_ac, seg_ex, accept_idx, dc = device_get(
                    (
                        out.stop_code,
                        out.auto_closed,
                        out.expanded,
                        out.accept_idx,
                        out.deep_counts,
                    )
                )
            except jax.errors.JaxRuntimeError as e:
                if "RESOURCE_EXHAUSTED" not in str(e):
                    raise
                # Exhaustion can surface at dispatch (to_device upload /
                # program launch) or at the fetch; requeue whichever ranges
                # are in limbo and release every held device result.
                requeue = [pending_range] if pending_range is not None else []
                if out is not None:
                    requeue.append((s0, t0))
                requeue += [(s1, t1) for s1, t1, _ in inflight]
                outs = [o for _, _, o in inflight]
                if out is not None:
                    outs.append(out)
                inflight.clear()
                out = None
                if not degrade(requeue, outs):
                    # Already at depth 1: a single fill-sized slab does not
                    # fit.  Shed load further by halving the layer-wide
                    # fill (dispatch re-splits oversized queued ranges), so
                    # the memory-tight regime spill exists for degrades
                    # gracefully instead of crashing check_device.
                    if fill == 1:
                        raise
                    fill = max(1, fill // 2)
                    log.warning(
                        "spill slab exhausted device memory at depth 1; "
                        "halving fill -> %d",
                        fill,
                    )
                    for r in requeue:
                        work.appendleft(r)
                    for o in outs:
                        with contextlib.suppress(Exception):
                            jax.block_until_ready(o.stop_code)
                    # Drop the quiesced results so their f_cap-sized buffers
                    # are actually free before the halved-fill retry uploads
                    # (mirrors degrade()).
                    outs.clear()
                continue
            code = int(code)
            if code == STOP_CAPACITY:
                if t0 == 1:
                    # Unreachable: f_cap >= 4C fits one row's children.
                    return unknown()
                half = t0 // 2
                fill = max(1, min(fill, half))
                log.debug(
                    "slab overflow: retrying %d rows in halves, fill -> %d",
                    t0,
                    fill,
                )
                work.appendleft((s0 + half, t0 - half))
                work.appendleft((s0, half))
                continue
            stats.auto_closed += int(seg_ac)
            stats.expanded += int(seg_ex)
            if code == STOP_ACCEPT:
                stats.layers += 1
                # The accepting slab holds only its own share of the accept
                # configuration's candidate-state set; sweep every slab of
                # this layer so the reported set matches the in-core path.
                accept_counts = device_get(
                    out.frontier.counts[int(accept_idx)]
                )
                lin = (
                    _recover_witness_bounded(enc, history, accept_counts)
                    if witness_requested and history is not None
                    else None
                )
                return conclude(
                    CheckResult(
                        CheckOutcome.OK,
                        linearization=lin,
                        final_states=_spill_accept_states(
                            enc, tables, host, accept_counts, to_device, fill
                        ),
                    )
                )
            if int(dc.sum()) > deep_sum:
                deep_sum, deep = int(dc.sum()), dc
            if code != STOP_EMPTY:
                ch = to_host(out.frontier)
                children.append(ch)
                children_rows += len(ch)
                if children_rows > 2 * host_cap:
                    # Bound transient host memory, not just the post-dedup
                    # set: a layer's raw children can exceed the cap
                    # many-fold before np.unique runs.
                    log.warning(
                        "spill children %d exceed 2x spill_host_cap %d; UNKNOWN",
                        children_rows,
                        host_cap,
                    )
                    return unknown()
        stats.layers += 1
        if not children:
            return conclude(
                CheckResult(
                    CheckOutcome.ILLEGAL,
                    deepest=_deepest_ops(enc, deep),
                    refusals=_host_row_refusals(enc, history, host),
                )
            )
        host = _dedup_rows(np.concatenate(children))
        try_incore = True
        stats.max_frontier = max(stats.max_frontier, len(host))
        _profile_entry(len(host), len(host), "STREAMED")
        log.debug(
            "spill layer %d: %d host rows", stats.layers, len(host)
        )
        if len(host) > host_cap:
            log.warning(
                "host frontier %d exceeds spill_host_cap %d; UNKNOWN",
                len(host),
                host_cap,
            )
            return unknown()
    return unknown()


def check_device_auto(
    history: History,
    *,
    beam_width: int = 65536,
    exhaustive_cap: int = 1 << 20,
    state_slots: int | None = None,
    mesh=None,
    collect_stats: bool = False,
    profile: bool = False,
    checkpoint_path: str | None = None,
    checkpoint_every: int = 512,
    witness: bool = True,
    witness_max_frontier: int = 0,
    spill: bool = True,
    spill_host_cap: int = 1 << 26,
    device_rows_cap: int | None = None,
    progress=None,
    prune: bool = False,
    speculate_depth: int = 0,
    speculate_width: int = 64,
) -> CheckResult:
    """Beam-first device check with exhaustive escalation, mirroring
    :func:`..checker.frontier.check_frontier_auto`.

    The exhaustive phase keeps the frontier HBM-resident up to
    ``device_rows_cap`` rows (chunked expansion past ``exhaustive_cap``;
    packed-key histories only) before handing off to the host spill — so
    the escalation ladder is beam → in-core exhaustive → on-device
    chunked → out-of-core.  The default is backend-aware (measured,
    BASELINE.md): 2^23 rows on any accelerator backend, whose host
    round-trips are the expensive resource, and 0 (straight to spill) on
    the CPU backend — there "device" and host memory are the same RAM so
    the spill's round-trips are free while the chunked tier's per-chunk
    sorts are not (exhaustion sweep: 4 744 s chunked vs 4 117 s
    spilled).  With ``spill=False`` the CPU backend keeps the tier: it
    is then the last conclusive rung.

    The beam and exhaustive phases use distinct checkpoint files (a beam
    snapshot must not resume an exhaustive pass, whose soundness rules
    differ); a conceded beam phase leaves a marker so a preempted
    exhaustive phase does not replay the whole beam search on restart."""
    del state_slots
    if device_rows_cap is None:
        # Accelerators (anything but the cpu backend) keep the tier: their
        # host round-trips are the expensive resource.  The cpu backend
        # skips it only when the spill can take over — with spill=False
        # the tier is the last conclusive rung, so keep it there too.
        on_cpu = jax.default_backend() == "cpu"
        device_rows_cap = 0 if (on_cpu and spill) else 1 << 23
    if 0 < device_rows_cap <= exhaustive_cap:
        # The tier only engages above the exhaustive bucket; a smaller
        # value is indistinguishable from plain bucket search, which a
        # caller "capping" rows would not expect silently.
        log.warning(
            "device_rows_cap %d <= exhaustive bucket %d: the HBM-resident "
            "tier is disabled (use 0 to disable it explicitly)",
            device_rows_cap,
            exhaustive_cap,
        )
    marker = f"{checkpoint_path}.beam.conceded" if checkpoint_path else None
    fingerprint = None
    beam_already_conceded = False
    if checkpoint_path is not None:
        from .checkpoint import history_fingerprint

        fingerprint = history_fingerprint(encode_history(history))
        if os.path.exists(marker):
            try:
                with open(marker, encoding="utf-8") as fh:
                    beam_already_conceded = fh.read().strip() == fingerprint
            except OSError:
                beam_already_conceded = False
            if not beam_already_conceded:
                with contextlib.suppress(FileNotFoundError):
                    os.remove(marker)

    if not beam_already_conceded:
        res = check_device(
            history,
            max_frontier=beam_width,
            beam=True,
            mesh=mesh,
            collect_stats=collect_stats,
            profile=profile,
            checkpoint_path=(
                f"{checkpoint_path}.beam" if checkpoint_path is not None else None
            ),
            checkpoint_every=checkpoint_every,
            witness=witness,
            witness_max_frontier=witness_max_frontier,
            progress=progress,
            prune=prune,
            speculate_depth=speculate_depth,
            speculate_width=speculate_width,
        )
        if res.outcome != CheckOutcome.UNKNOWN:
            if marker is not None:
                with contextlib.suppress(FileNotFoundError):
                    os.remove(marker)
            return res
        if checkpoint_path is not None:
            # The conceded beam phase's snapshot must not linger (it would
            # fingerprint-clash with the next history under this path), and
            # the marker spares a preempted exhaustive phase from replaying
            # the whole beam search on restart.
            with contextlib.suppress(FileNotFoundError):
                os.remove(f"{checkpoint_path}.beam")
            with open(marker, "w", encoding="utf-8") as fh:
                fh.write(fingerprint)
    res = check_device(
        history,
        max_frontier=exhaustive_cap,
        beam=False,
        mesh=mesh,
        collect_stats=collect_stats,
        profile=profile,
        checkpoint_path=checkpoint_path,
        checkpoint_every=checkpoint_every,
        witness=witness,
        witness_max_frontier=witness_max_frontier,
        spill=spill,
        spill_host_cap=spill_host_cap,
        device_rows_cap=device_rows_cap,
        progress=progress,
        prune=prune,
        speculate_depth=speculate_depth,
        speculate_width=speculate_width,
    )
    # On a conclusive verdict the marker is spent.  On UNKNOWN it stays,
    # paired with the kept exhaustive snapshot: a retry (e.g. with a larger
    # cap) skips straight past the already-conceded beam phase.
    if marker is not None and res.outcome != CheckOutcome.UNKNOWN:
        with contextlib.suppress(FileNotFoundError):
            os.remove(marker)
    return res
