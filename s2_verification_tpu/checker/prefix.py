"""Prefix-closed boundaries and resumable frontier carries.

The soundness backbone of incremental verification (service/prefixstore.py,
the daemon's ``follow`` op).  A boundary after op K of a prepared history is
**prefix-closed** when every one of the first K ops returned before any
later op was called::

    max(ret over ops[:K])  <  min(call over ops[K:])

Because the frontier search's candidate rule only admits an op whose call
precedes the minimum outstanding return, every linearization of the full
history commits *exactly* ``ops[:K]`` (as a set, in some order) before any
op of the suffix — the boundary is a cut no interleaving crosses.  At such
a cut the entire search state is one configuration: the forced per-chain
counts plus the **union** of every reachable state set.  ``step_set``
distributes over unions and the candidate/acceptance rules depend only on
counts, so resuming from ``(counts_K, union_K)`` is verdict-equivalent to
a cold search — provided the union is *exact*.  A subset (e.g. from a
pruned search) could produce a false ILLEGAL on resume; supersets cannot
occur because collection only ever records reachable states.  The
completeness bookkeeping lives in checker/frontier.py (``snapshot_cuts``).

A boundary crossed by an in-flight op is never closed: a pending op's
completed return is placed at the event horizon, past every real call, so
any pending op in the prefix kills every later boundary except the trivial
K = num_ops one — which callers must additionally refuse when the history
has pending ops at all (the op's effect is not yet decided, so a carry
would bake an unfinished op into the committed prefix; see
``has_open_ops``).

Everything here is pure op-index geometry; the chain-hash keys that name
cuts on the wire live in service/prefixstore.py.
"""

from __future__ import annotations

from bisect import bisect_left
from dataclasses import dataclass

from ..models.stream import StreamState
from .entries import History

__all__ = [
    "PrefixCarry",
    "boundary_counts",
    "choose_cuts",
    "closed_boundaries",
    "has_open_ops",
]


def closed_boundaries(history: History) -> list[int]:
    """Every prefix-closed op boundary K, ascending, 0 < K <= num_ops.

    Ops are call-sorted (checker/entries.py), so the suffix minimum call is
    just ``ops[K].call`` and the scan is linear.  K = num_ops (empty
    suffix) is vacuously closed and always included for non-empty
    histories; whether it is *usable* further depends on
    :func:`has_open_ops`.
    """
    ops = history.ops
    n = len(ops)
    if n == 0:
        return []
    out: list[int] = []
    max_ret = -1
    for K in range(1, n):
        max_ret = max(max_ret, ops[K - 1].ret)
        if max_ret < ops[K].call:
            out.append(K)
    out.append(n)
    return out


def has_open_ops(history: History) -> bool:
    """True when any op (including elided trivial ops) never finished.

    A pending op's outcome is undecided — the checker completes it with the
    weakest consistent output, which is fine for a one-shot verdict but
    must never be committed into a carried prefix: the real finish may
    arrive in the next window and re-prepare differently.  Stores and the
    ``follow`` handler refuse to snapshot such histories.
    """
    return any(op.pending for op in history.ops) or any(
        op.pending for op in history.trivial_ops
    )


def boundary_counts(history: History, K: int) -> tuple[int, ...]:
    """The forced per-chain counts at closed cut K.

    Chain lists hold op indices in ascending order, so the number of a
    chain's ops inside ``ops[:K]`` is a bisect.
    """
    return tuple(bisect_left(chain, K) for chain in history.chains)


def choose_cuts(history: History, max_cuts: int = 8) -> list[int]:
    """Pick snapshot cuts: the deepest closed boundary always, plus up to
    ``max_cuts - 1`` more spread evenly across the remaining closed
    boundaries (shallow cuts catch short extensions, deep cuts long ones).
    """
    bounds = closed_boundaries(history)
    if len(bounds) <= max_cuts:
        return bounds
    picked = {bounds[-1]}
    step = (len(bounds) - 1) / max(1, max_cuts - 1)
    for i in range(max_cuts - 1):
        picked.add(bounds[int(round(i * step))])
    return sorted(picked)


@dataclass(frozen=True)
class PrefixCarry:
    """A decided prefix: resume the search at op ``ops`` from ``states``.

    ``ops`` counts *cumulative* committed ops (across every prior window
    for follow lineages); ``states`` is the exact reachable-state union at
    the cut, as produced by ``check_frontier(..., snapshot_cuts=...)``.
    """

    ops: int
    states: tuple[StreamState, ...]

    def to_payload(self) -> dict:
        return {
            "n": self.ops,
            "s": [[s.tail, s.stream_hash, s.fencing_token] for s in self.states],
        }

    @classmethod
    def from_payload(cls, payload: dict) -> "PrefixCarry":
        states = tuple(
            StreamState(tail=int(t), stream_hash=int(h), fencing_token=tok)
            for t, h, tok in payload["s"]
        )
        if not states:
            raise ValueError("prefix carry with empty state union")
        return cls(ops=int(payload["n"]), states=states)
