from . import entries, oracle

__all__ = ["entries", "oracle"]
