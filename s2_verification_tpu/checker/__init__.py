from . import entries, frontier, oracle

__all__ = ["entries", "frontier", "oracle", "device"]


def __getattr__(name):
    # device imports jax; keep it lazy so pure-host users (event decoding,
    # oracle checking) never pay jax startup.
    if name == "device":
        from . import device

        return device
    raise AttributeError(name)
