"""Search progress heartbeats: the one hook every engine shares.

A running search is otherwise a black box between ``start`` and ``done``;
the Wing–Gong frontier loop has natural progress structure (ops committed
out of total, frontier width per layer) that the engines can surface for
almost nothing.  :class:`ProgressSink` is the low-overhead carrier: each
engine calls :meth:`ProgressSink.update` wherever the host already holds
fresh counters (per BFS layer on the host search, per compiled segment on
the device search, start/final only around the native engine's blocking C
call) and the sink decides whether a heartbeat actually leaves — emission
is **time-gated**, so a trivial job that decides inside one interval emits
nothing at all, and a hot layer loop costs one clock read per layer.

The sink is engine-agnostic on purpose: ``emit`` receives a plain dict,
so the service layer can fold heartbeats into its per-job table
(service/progress.py), a supervised child can spool them to a file for
its parent (service/supervise.py), and tests can capture them in a list.
"""

from __future__ import annotations

import time

__all__ = ["ProgressSink"]


class ProgressSink:
    """Time-gated progress heartbeat emitter.

    ``emit`` is called with one dict per heartbeat::

        {"ops_committed", "total_ops", "frontier_width",
         "states_expanded", "layer_rate", "engine", "final"[, "layer"]}

    Cadence contract: at most one heartbeat per ``min_interval_s`` of
    wall clock, however often the engine calls :meth:`update`.  The very
    first call only records the rate baseline (never emits), and a
    ``final=True`` heartbeat is emitted only when the search outlived one
    interval — so trivial jobs produce **zero** heartbeats.  ``time_fn``
    is injectable for deterministic tests.
    """

    def __init__(
        self,
        emit,
        *,
        min_interval_s: float = 0.5,
        time_fn=time.monotonic,
        engine: str | None = None,
        lane: int | None = None,
    ) -> None:
        self._emit = emit
        self.min_interval_s = min_interval_s
        self._time = time_fn
        self.engine = engine
        self.lane = lane
        self.emitted = 0
        self._started: float | None = None
        self._last_emit: float | None = None
        #: rate baseline: (time, layer, ops) of the previous emission (or
        #: of the first update when nothing has been emitted yet)
        self._ref: tuple[float, int, int] | None = None

    def update(
        self,
        *,
        ops_committed: int,
        total_ops: int,
        frontier_width: int = 0,
        states_expanded: int = 0,
        layer: int | None = None,
        engine: str | None = None,
        final: bool = False,
    ) -> bool:
        """Offer a progress sample; returns True iff a heartbeat left."""
        now = self._time()
        if self._ref is None:
            self._started = now
            self._ref = (
                now,
                int(layer) if layer is not None else 0,
                int(ops_committed),
            )
            if not final:
                return False
        since = self._last_emit if self._last_emit is not None else self._started
        if now - since < self.min_interval_s:
            # Bounded cadence — and a final offer inside the very first
            # interval stays silent too (the trivial-job rule).
            if not final or self._last_emit is None:
                return False
        ref_t, ref_layer, ref_ops = self._ref
        dt = max(now - ref_t, 1e-9)
        # ``layer`` is cumulative, so a multi-layer jump (a speculative
        # K-layer launch, a multi-op fast stretch) is attributed in full:
        # the rate is the layer DELTA over the interval, never "one
        # heartbeat = one layer".
        if layer is not None:
            rate = (int(layer) - ref_layer) / dt
        else:
            rate = (int(ops_committed) - ref_ops) / dt
        rec = {
            "ops_committed": int(ops_committed),
            "total_ops": int(total_ops),
            "frontier_width": int(frontier_width),
            "states_expanded": int(states_expanded),
            "layer_rate": round(max(rate, 0.0), 3),
            "engine": engine or self.engine or "other",
            "final": bool(final),
        }
        if layer is not None:
            rec["layer"] = int(layer)
        if self.lane is not None:
            rec["lane"] = self.lane
        # A layer-less offer (native engine, service-side folds) carries
        # the previous layer baseline forward — resetting it to 0 would
        # inflate the next layer-bearing update's rate by the whole
        # cumulative layer count.
        self._ref = (
            now,
            int(layer) if layer is not None else ref_layer,
            int(ops_committed),
        )
        self._last_emit = now
        self.emitted += 1
        self._emit(rec)
        return True
