"""jit-hygiene: static companions to the runtime retrace-storm detector.

Rules
-----

``jit-unwrapped`` (error)
    Every module-level ``jax.jit`` / ``jax.pmap`` product (decorated def or
    ``name = jax.jit(f)`` assignment) must be rebound through the
    ``JitIntrospector`` wrapper — ``name = observe_jit("site")(name)`` — or
    carry an ``@observe_jit(...)`` decorator.  Unwrapped sites are invisible
    to compile/retrace tracking, so a retrace storm there never alerts.
    Inline ``jax.vmap`` inside an already-jitted function is exempt (it is
    traced as part of the enclosing jit, which *is* wrapped).

``jit-in-loop`` (error)
    Calling ``jax.jit``/``jax.pmap`` inside a ``for``/``while`` body builds a
    fresh transform (and usually a fresh compile) per iteration — the exact
    failure mode the retrace-storm alert pages on, caught before commit.

``jit-unhashable-static`` (error)
    ``static_argnums`` / ``static_argnames`` given as a list/set/dict display.
    jax hashes static arguments into the compile cache key; unhashable
    containers raise at call time on cache-miss paths only.

``jit-traced-branch`` (error)
    A Python ``if``/``while`` test inside a jitted function that reads a
    non-static parameter directly.  Branching on a traced value raises
    ``TracerBoolConversionError`` at trace time (or silently bakes in one
    branch under ``concrete``).  Shape/dtype/ndim attribute reads and
    ``len``/``isinstance`` calls are static and allowed.
"""

from __future__ import annotations

import ast

from .engine import ERROR, FileInfo, FilePass, Finding, dotted_name

_JIT_NAMES = {"jax.jit", "jax.pmap", "jit", "pmap"}
_STATIC_KWARGS = ("static_argnums", "static_argnames")
_ALLOWED_CALLS = {"len", "isinstance", "getattr", "hasattr", "callable"}
_STATIC_ATTRS = {"shape", "ndim", "dtype", "size"}


def _is_jit_func(node: ast.AST) -> bool:
    name = dotted_name(node)
    return name in _JIT_NAMES


def _jit_call(node: ast.AST) -> ast.Call | None:
    """The ``jax.jit(...)``/``partial(jax.jit, ...)`` call inside ``node``,
    if ``node`` is a jit transform application or a partial thereof."""
    if not isinstance(node, ast.Call):
        return None
    if _is_jit_func(node.func):
        return node
    fname = dotted_name(node.func)
    if fname in ("partial", "functools.partial") and node.args and _is_jit_func(node.args[0]):
        return node
    return None


def _decorator_jit(dec: ast.AST) -> ast.Call | None:
    """jit info for a decorator node: bare ``@jax.jit`` or ``@partial(jax.jit,…)``."""
    if _is_jit_func(dec):
        return ast.Call(func=dec, args=[], keywords=[])  # synthetic, no kwargs
    return _jit_call(dec)


def _static_param_names(call: ast.Call, fn: ast.FunctionDef) -> set[str]:
    params = [a.arg for a in fn.args.posonlyargs + fn.args.args]
    static: set[str] = set()
    for kw in call.keywords:
        if kw.arg == "static_argnames":
            if isinstance(kw.value, ast.Constant) and isinstance(kw.value.value, str):
                static.add(kw.value.value)
            elif isinstance(kw.value, (ast.Tuple, ast.List)):
                for el in kw.value.elts:
                    if isinstance(el, ast.Constant) and isinstance(el.value, str):
                        static.add(el.value)
        elif kw.arg == "static_argnums":
            nums = []
            if isinstance(kw.value, ast.Constant) and isinstance(kw.value.value, int):
                nums = [kw.value.value]
            elif isinstance(kw.value, (ast.Tuple, ast.List)):
                nums = [
                    el.value
                    for el in kw.value.elts
                    if isinstance(el, ast.Constant) and isinstance(el.value, int)
                ]
            for n in nums:
                if 0 <= n < len(params):
                    static.add(params[n])
    return static


def _is_observe_wrap(node: ast.expr, target: str) -> bool:
    """``observe_jit("site")(target)`` — the wrapper rebind."""
    if not isinstance(node, ast.Call) or len(node.args) != 1:
        return False
    arg = node.args[0]
    if not (isinstance(arg, ast.Name) and arg.id == target):
        return False
    inner = node.func
    return isinstance(inner, ast.Call) and dotted_name(inner.func) in (
        "observe_jit",
        "introspect.observe_jit",
    )


class JitHygienePass(FilePass):
    name = "jit-hygiene"

    def check_file(self, info: FileInfo) -> list[Finding]:
        tree = info.tree
        assert tree is not None
        src = info.text
        if "jax" not in src:
            return []
        out: list[Finding] = []

        # --- collect module-level jit products and observe_jit rebinds -----
        jit_products: dict[str, tuple[int, ast.Call]] = {}  # name -> (line, call)
        wrapped: set[str] = set()
        for node in tree.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for dec in node.decorator_list:
                    call = _decorator_jit(dec)
                    if call is not None:
                        jit_products[node.name] = (node.lineno, call)
                    if dotted_name(dec) == "observe_jit" or (
                        isinstance(dec, ast.Call) and dotted_name(dec.func) == "observe_jit"
                    ):
                        wrapped.add(node.name)
            elif isinstance(node, ast.Assign) and len(node.targets) == 1:
                t = node.targets[0]
                if not isinstance(t, ast.Name):
                    continue
                call = _jit_call(node.value)
                if call is not None:
                    jit_products[t.id] = (node.lineno, call)
                if _is_observe_wrap(node.value, t.id):
                    wrapped.add(t.id)

        for name, (line, _call) in sorted(jit_products.items()):
            if name not in wrapped:
                out.append(
                    Finding(
                        "jit-unwrapped",
                        ERROR,
                        info.rel,
                        line,
                        f"jit product '{name}' is not routed through observe_jit() — "
                        "compiles/retraces here are invisible to the introspector",
                    )
                )

        # --- jit-in-loop + unhashable statics + traced branches ------------
        in_loop: set[int] = set()
        for node in ast.walk(tree):
            if isinstance(node, (ast.For, ast.While, ast.AsyncFor)):
                for sub in ast.walk(node):
                    if sub is not node and isinstance(sub, ast.Call):
                        call = _jit_call(sub)
                        if call is not None and id(call) not in in_loop:
                            in_loop.add(id(call))
                            out.append(
                                Finding(
                                    "jit-in-loop",
                                    ERROR,
                                    info.rel,
                                    sub.lineno,
                                    "jax.jit/pmap applied inside a loop body builds a "
                                    "new transform (and compile) every iteration",
                                )
                            )
            call = _jit_call(node) if isinstance(node, ast.Call) else None
            if call is not None:
                for kw in call.keywords:
                    if kw.arg in _STATIC_KWARGS and isinstance(
                        kw.value, (ast.List, ast.Set, ast.Dict)
                    ):
                        out.append(
                            Finding(
                                "jit-unhashable-static",
                                ERROR,
                                info.rel,
                                kw.value.lineno,
                                f"{kw.arg} given as an unhashable "
                                f"{type(kw.value).__name__.lower()} display — jax hashes "
                                "static args into the compile cache key; use a tuple",
                            )
                        )

        # traced-branch: inspect bodies of jit-decorated module functions
        for node in tree.body:
            if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            call = None
            for dec in node.decorator_list:
                call = _decorator_jit(dec) or call
            if call is None:
                continue
            static = _static_param_names(call, node)
            params = {
                a.arg
                for a in node.args.posonlyargs + node.args.args + node.args.kwonlyargs
            } - static - {"self"}
            out.extend(self._traced_branches(info, node, params))
        return out

    def _traced_branches(
        self, info: FileInfo, fn: ast.FunctionDef, traced: set[str]
    ) -> list[Finding]:
        out = []
        for node in ast.walk(fn):
            if not isinstance(node, (ast.If, ast.While)):
                continue
            name = self._offending_name(node.test, traced)
            if name:
                out.append(
                    Finding(
                        "jit-traced-branch",
                        ERROR,
                        info.rel,
                        node.lineno,
                        f"Python branch on traced parameter '{name}' inside jitted "
                        f"'{fn.name}' — raises at trace time; use lax.cond/select or "
                        "mark the arg static",
                    )
                )
        return out

    def _offending_name(self, test: ast.expr, traced: set[str]) -> str | None:
        """A traced param read *as a value* in the test — excluding static
        contexts: ``x.shape``-style attribute reads, ``len(x)``, subscript
        bases, and comparisons of those."""
        skip: set[int] = set()
        for node in ast.walk(test):
            if isinstance(node, ast.Attribute) and node.attr in _STATIC_ATTRS:
                for sub in ast.walk(node.value):
                    skip.add(id(sub))
            elif isinstance(node, ast.Call):
                if dotted_name(node.func) in _ALLOWED_CALLS:
                    for arg in node.args:
                        for sub in ast.walk(arg):
                            skip.add(id(sub))
                else:
                    # any other call on a traced value yields a traced value;
                    # the Name itself inside the call is what we flag
                    pass
            elif isinstance(node, ast.Subscript):
                # x[0] on a traced value is traced — do not skip
                pass
        for node in ast.walk(test):
            if (
                isinstance(node, ast.Name)
                and isinstance(node.ctx, ast.Load)
                and node.id in traced
                and id(node) not in skip
            ):
                return node.id
        return None
