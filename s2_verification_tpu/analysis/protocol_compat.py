"""protocol-compat: frame sites must agree with protocol.py's field table.

``service/protocol.py`` now carries the wire contract explicitly:
``FRAME_FIELDS`` (op -> {field: required|optional}) and ``UNSIGNED_FIELDS``
(the MAC exclusion list).  This pass holds every construction site
(``client.py``) and parse site (``daemon.py`` / ``router.py``) to it, so a
frame field can only be added by declaring it — and because the MAC covers
everything outside ``UNSIGNED_FIELDS``, a declared field is HMAC-covered by
construction.

Rules (all error severity)
--------------------------

``protocol-no-table``
    ``protocol.py`` found but ``FRAME_FIELDS``/``UNSIGNED_FIELDS`` missing
    or not statically readable.

``protocol-unknown-op``
    A frame literal ``{"op": X}`` with an op the table does not declare.

``protocol-unknown-field``
    A construction site sends, or a parse site reads, a field no op
    declares.  Constant-resolution covers the ``TRACE_FIELD`` import and
    ``for key in ("shape", "backend", ...)`` literal loops.

``protocol-missing-required``
    A frame literal omits a required field of its op (and no later
    ``req["field"] = ...`` store in the same function supplies it).

``protocol-unguarded-read``
    A parse site reads an optional field with bare ``req["f"]`` outside an
    ``if req.get("f")``-style guard — optional-with-default is the
    compatibility contract, so an unguarded subscript is a KeyError on
    every older peer.

``protocol-unsigned-mismatch``
    ``_frame_mac``'s exclusion set disagrees with ``UNSIGNED_FIELDS`` —
    fields silently escaping (or double-entering) the authenticated region.
"""

from __future__ import annotations

import ast

from .engine import (
    ERROR,
    FileInfo,
    Finding,
    Pass,
    TreeContext,
    const_str,
    literal_str_tuple,
    module_constants,
    name_resolver,
)

_PARSE_BASENAMES = {"client.py", "daemon.py", "router.py"}
_REQ_NAMES = {"req", "frame", "request"}


def _load_table(info: FileInfo) -> tuple[dict[str, dict[str, str]] | None, list[str] | None]:
    consts = module_constants(info.tree)
    table_expr = consts.get("FRAME_FIELDS")
    unsigned_expr = consts.get("UNSIGNED_FIELDS")
    table: dict[str, dict[str, str]] | None = None
    if isinstance(table_expr, ast.Dict):
        table = {}
        for k, v in zip(table_expr.keys, table_expr.values):
            op = const_str(k) if k is not None else None
            if op is None or not isinstance(v, ast.Dict):
                return None, None
            fields: dict[str, str] = {}
            for fk, fv in zip(v.keys, v.values):
                fname = const_str(fk) if fk is not None else None
                fmode = const_str(fv)
                if fname is None or fmode not in ("required", "optional"):
                    return None, None
                fields[fname] = fmode
            table[op] = fields
    unsigned = literal_str_tuple(unsigned_expr) if unsigned_expr is not None else None
    return table, unsigned


def _mac_exclusions(info: FileInfo, resolve) -> set[str] | None:
    """The key-exclusion set of ``_frame_mac``'s body comprehension."""
    for node in ast.walk(info.tree):
        if isinstance(node, ast.FunctionDef) and node.name == "_frame_mac":
            for sub in ast.walk(node):
                if not isinstance(sub, (ast.DictComp, ast.SetComp, ast.GeneratorExp)):
                    continue
                for gen in sub.generators:
                    for cond in gen.ifs:
                        if not (
                            isinstance(cond, ast.Compare) and len(cond.ops) == 1
                        ):
                            continue
                        comp = cond.comparators[0]
                        if isinstance(cond.ops[0], ast.NotEq):
                            s = const_str(comp)
                            if s is not None:
                                return {s}
                        elif isinstance(cond.ops[0], ast.NotIn):
                            lits = literal_str_tuple(comp)
                            if lits is None and isinstance(comp, ast.Name):
                                lits = literal_str_tuple(resolve(comp.id))
                            if lits is not None:
                                return set(lits)
            return None
    return None


def _resolve_key(node: ast.expr, resolve) -> str | None:
    s = const_str(node)
    if s is not None:
        return s
    if isinstance(node, ast.Name):
        return const_str(resolve(node.id))
    return None


class ProtocolCompatPass(Pass):
    name = "protocol-compat"

    def run(self, ctx: TreeContext) -> list[Finding]:
        out: list[Finding] = []
        protos = [f for f in ctx.by_basename("protocol.py") if f.tree is not None]
        if not protos:
            return []  # nothing speaking the wire protocol in scope
        proto = protos[0]
        table, unsigned = _load_table(proto)
        if table is None or unsigned is None:
            out.append(
                Finding(
                    "protocol-no-table",
                    ERROR,
                    proto.rel,
                    1,
                    "FRAME_FIELDS / UNSIGNED_FIELDS missing or not statically "
                    "readable — the wire contract must be declared",
                )
            )
            return out

        resolve_proto = name_resolver(ctx, proto)
        excl = _mac_exclusions(proto, resolve_proto)
        if excl is not None and excl != set(unsigned):
            out.append(
                Finding(
                    "protocol-unsigned-mismatch",
                    ERROR,
                    proto.rel,
                    1,
                    f"_frame_mac excludes {sorted(excl)} but UNSIGNED_FIELDS "
                    f"declares {sorted(unsigned)} — the authenticated region "
                    "and the declaration must agree",
                )
            )

        all_fields: set[str] = {"op", *unsigned}
        for fields in table.values():
            all_fields.update(fields)

        for info in ctx.files:
            if info.tree is None:
                continue
            base = info.rel.rsplit("/", 1)[-1]
            if base not in _PARSE_BASENAMES:
                continue
            resolve = name_resolver(ctx, info)
            for fn in ast.walk(info.tree):
                if isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    self._check_function(info, fn, table, unsigned, all_fields, resolve, out)
        # nested defs are visited both standalone and inside their parent's
        # walk — collapse the duplicates
        seen: set[tuple] = set()
        deduped: list[Finding] = []
        for f in out:
            key = (f.rule, f.path, f.line, f.message)
            if key not in seen:
                seen.add(key)
                deduped.append(f)
        return deduped

    # -- per-function checks ------------------------------------------------

    def _check_function(
        self,
        info: FileInfo,
        fn: ast.AST,
        table: dict[str, dict[str, str]],
        unsigned: list[str],
        all_fields: set[str],
        resolve,
        out: list[Finding],
    ) -> None:
        implicit = {"op", *unsigned}
        # op-dict variables: var name -> (op, keys seen so far)
        op_vars: dict[str, str] = {}
        dict_lits: list[tuple[ast.Dict, str, str | None]] = []  # (node, op, var)

        for node in ast.walk(fn):
            if isinstance(node, ast.Dict):
                op = None
                for k, v in zip(node.keys, node.values):
                    if k is not None and const_str(k) == "op":
                        op = const_str(v)
                if op is not None:
                    dict_lits.append((node, op, None))
            elif isinstance(node, ast.Assign) and len(node.targets) == 1:
                t = node.targets[0]
                if isinstance(t, ast.Name) and isinstance(node.value, ast.Dict):
                    for k, v in zip(node.value.keys, node.value.values):
                        if k is not None and const_str(k) == "op":
                            opv = const_str(v)
                            if opv is not None:
                                op_vars[t.id] = opv

        # literal contents + later key stores
        stores: dict[str, set[str]] = {v: set() for v in op_vars}
        for node in ast.walk(fn):
            if (
                isinstance(node, ast.Assign)
                and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Subscript)
                and isinstance(node.targets[0].value, ast.Name)
                and node.targets[0].value.id in op_vars
            ):
                key = _resolve_key(node.targets[0].slice, resolve)
                var = node.targets[0].value.id
                if key is not None:
                    stores[var].add(key)
                    self._check_field(
                        info, node.lineno, op_vars[var], key, table, implicit, out
                    )

        for dnode, op, _var in dict_lits:
            if op not in table:
                out.append(
                    Finding(
                        "protocol-unknown-op",
                        ERROR,
                        info.rel,
                        dnode.lineno,
                        f"frame op '{op}' is not declared in FRAME_FIELDS",
                    )
                )
                continue
            lit_keys: set[str] = set()
            for k, _v in zip(dnode.keys, dnode.values):
                if k is None:
                    continue
                key = _resolve_key(k, resolve)
                if key is None:
                    continue
                lit_keys.add(key)
                if key != "op":
                    self._check_field(info, k.lineno, op, key, table, implicit, out)
            # required-field coverage: literal keys + later stores on the
            # variable this literal was assigned to (if any)
            var = next((v for v, o in op_vars.items() if o == op), None)
            supplied = lit_keys | (stores.get(var, set()) if var else set())
            for f, mode in table[op].items():
                if mode == "required" and f not in supplied:
                    out.append(
                        Finding(
                            "protocol-missing-required",
                            ERROR,
                            info.rel,
                            dnode.lineno,
                            f"frame op '{op}' omits required field '{f}'",
                        )
                    )

        # parse-site reads
        self._check_reads(info, fn, all_fields, resolve, table, out)

    def _check_field(
        self,
        info: FileInfo,
        line: int,
        op: str,
        key: str,
        table: dict[str, dict[str, str]],
        implicit: set[str],
        out: list[Finding],
    ) -> None:
        if op in table and key not in table[op] and key not in implicit:
            out.append(
                Finding(
                    "protocol-unknown-field",
                    ERROR,
                    info.rel,
                    line,
                    f"field '{key}' is not declared for frame op '{op}' — add "
                    "it to FRAME_FIELDS as optional-with-default",
                )
            )

    def _check_reads(
        self,
        info: FileInfo,
        fn: ast.AST,
        all_fields: set[str],
        resolve,
        table: dict[str, dict[str, str]],
        out: list[Finding],
    ) -> None:
        # loop vars ranging over literal key tuples: for key in ("a","b")
        loop_keys: dict[str, list[str]] = {}
        for node in ast.walk(fn):
            if isinstance(node, (ast.For, ast.AsyncFor)) and isinstance(
                node.target, ast.Name
            ):
                lits = literal_str_tuple(node.iter)
                if lits is None and isinstance(node.iter, ast.Name):
                    lits = literal_str_tuple(resolve(node.iter.id))
                if lits is not None:
                    loop_keys[node.target.id] = lits
        for node in ast.walk(fn):
            if isinstance(node, (ast.ListComp, ast.SetComp, ast.DictComp, ast.GeneratorExp)):
                for gen in node.generators:
                    if isinstance(gen.target, ast.Name):
                        lits = literal_str_tuple(gen.iter)
                        if lits is None and isinstance(gen.iter, ast.Name):
                            lits = literal_str_tuple(resolve(gen.iter.id))
                        if lits is not None:
                            loop_keys[gen.target.id] = lits

        required_somewhere = {
            f for fields in table.values() for f, m in fields.items() if m == "required"
        }

        stack: list[tuple[ast.AST, list[ast.AST]]] = [(fn, [])]
        while stack:
            node, parents = stack.pop()
            for child in ast.iter_child_nodes(node):
                stack.append((child, parents + [node]))
            # req.get("x") / req.get(key)
            if (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "get"
                and isinstance(node.func.value, ast.Name)
                and node.func.value.id in _REQ_NAMES
                and node.args
            ):
                for key in self._read_keys(node.args[0], resolve, loop_keys):
                    if key not in all_fields:
                        out.append(
                            Finding(
                                "protocol-unknown-field",
                                ERROR,
                                info.rel,
                                node.lineno,
                                f"parse site reads undeclared frame field '{key}'",
                            )
                        )
            # req["x"]
            elif (
                isinstance(node, ast.Subscript)
                and isinstance(node.value, ast.Name)
                and node.value.id in _REQ_NAMES
                and isinstance(node.ctx, ast.Load)
            ):
                keys = self._read_keys(node.slice, resolve, loop_keys)
                for key in keys:
                    if key not in all_fields:
                        out.append(
                            Finding(
                                "protocol-unknown-field",
                                ERROR,
                                info.rel,
                                node.lineno,
                                f"parse site reads undeclared frame field '{key}'",
                            )
                        )
                    elif key not in required_somewhere and not self._guarded(
                        node, parents
                    ):
                        out.append(
                            Finding(
                                "protocol-unguarded-read",
                                ERROR,
                                info.rel,
                                node.lineno,
                                f"optional frame field '{key}' read with bare "
                                "subscript — guard with req.get() so older "
                                "peers' frames keep parsing",
                            )
                        )

    @staticmethod
    def _read_keys(node: ast.expr, resolve, loop_keys: dict[str, list[str]]) -> list[str]:
        s = const_str(node)
        if s is not None:
            return [s]
        if isinstance(node, ast.Name):
            if node.id in loop_keys:
                return loop_keys[node.id]
            s = const_str(resolve(node.id))
            if s is not None:
                return [s]
        return []

    @staticmethod
    def _guarded(sub: ast.Subscript, parents: list[ast.AST]) -> bool:
        """A bare req[key] read is fine under `if req.get(key) ...:`."""

        def mentions_get(test: ast.expr) -> bool:
            for n in ast.walk(test):
                if (
                    isinstance(n, ast.Call)
                    and isinstance(n.func, ast.Attribute)
                    and n.func.attr == "get"
                    and isinstance(n.func.value, ast.Name)
                    and n.func.value.id in _REQ_NAMES
                ):
                    return True
                if (
                    isinstance(n, ast.Compare)
                    and len(n.ops) == 1
                    and isinstance(n.ops[0], ast.In)
                    and isinstance(n.comparators[0], ast.Name)
                    and n.comparators[0].id in _REQ_NAMES
                ):
                    return True
            return False

        for p in parents:
            if isinstance(p, (ast.If, ast.IfExp)) and mentions_get(p.test):
                return True
            if isinstance(p, ast.Try):
                return True  # KeyError-handled access is its own guard
        return False
