"""concurrency: unlocked shared-state writes in thread-spawning classes.

Rule ``concurrency-unlocked-write`` (error)
-------------------------------------------

Scope: classes that spawn threads (``threading.Thread``/``Timer`` with a
``target=`` bound to ``self`` or to a nested closure), hand bound methods to
an executor (``pool.submit(self.x)``), or register bound-method callbacks
invoked from foreign threads (``gc.callbacks.append(self.x)``).

Within such a class we build the ``self.*`` call graph and compute, for
every method, the set of *entry points* that can reach it:

- each spawned/submitted/registered target is its own entry (one thread);
- the public surface (non-underscore methods, ``__call__``/``__enter__``/
  ``__exit__``) is one collective entry — any caller thread.

An attribute is **shared** when an *unlocked write* to it happens in method
M and *any* access happens in method N with ``entries(M) ∪ entries(N)`` ≥ 2
distinct entries (M may equal N: a method both public and used as a thread
target races against itself).  Shared attributes must be written under a
held ``with self._lock``-style context (any ``with`` whose subject name
matches ``lock|cv|cond|mu``) or be a declared thread-safe type.

Exemptions — the repo's established discipline, encoded:

- ``__init__`` / ``__del__`` bodies (construction happens-before publish);
- methods named ``*_locked`` (contract: caller holds the lock);
- attributes constructed in ``__init__`` from thread-safe types
  (``threading.Event/Lock/RLock/Condition/Semaphore``, ``queue.*``,
  ``collections.deque``, ``itertools.count`` — their mutators are atomic);
- attributes whose own name matches the lock pattern.
"""

from __future__ import annotations

import ast
import re

from .engine import ERROR, FileInfo, FilePass, Finding, dotted_name

_LOCKISH = re.compile(r"lock|cv|cond|mu(tex)?$", re.I)
_THREADSAFE_CTORS = re.compile(
    r"(^|\.)(Event|Lock|RLock|Condition|Semaphore|BoundedSemaphore|Barrier|"
    r"Queue|SimpleQueue|LifoQueue|PriorityQueue|deque|count)$"
)
_MUTATORS = {
    "append",
    "appendleft",
    "add",
    "update",
    "pop",
    "popleft",
    "popitem",
    "setdefault",
    "clear",
    "extend",
    "remove",
    "discard",
    "insert",
    "sort",
    "reverse",
}
_SPAWN_CALLS = re.compile(r"(^|\.)(Thread|Timer)$")
_PUBLIC_DUNDERS = {"__call__", "__enter__", "__exit__", "__iter__", "__next__"}
_EXEMPT_METHODS = {"__init__", "__del__", "__post_init__"}

PUBLIC = "<public>"


def _self_attr(node: ast.AST) -> str | None:
    if (
        isinstance(node, ast.Attribute)
        and isinstance(node.value, ast.Name)
        and node.value.id == "self"
    ):
        return node.attr
    return None


def _target_self_method(node: ast.expr) -> str | None:
    """self.X, or functools.partial(self.X, ...) -> 'X'."""
    attr = _self_attr(node)
    if attr:
        return attr
    if isinstance(node, ast.Call):
        fname = dotted_name(node.func)
        if fname in ("partial", "functools.partial") and node.args:
            return _self_attr(node.args[0])
    return None


def _target_local_name(node: ast.expr) -> str | None:
    if isinstance(node, ast.Name):
        return node.id
    return None


def _lockish_expr(node: ast.expr) -> bool:
    attr = _self_attr(node)
    if attr is not None:
        return bool(_LOCKISH.search(attr))
    if isinstance(node, ast.Name):
        return bool(_LOCKISH.search(node.id))
    if isinstance(node, ast.Attribute):
        return bool(_LOCKISH.search(node.attr))
    return False


class _Access:
    __slots__ = ("attr", "write", "locked", "atomic", "line", "method")

    def __init__(self, attr: str, write: bool, locked: bool, atomic: bool, line: int, method: str):
        self.attr = attr
        self.write = write
        self.locked = locked
        self.atomic = atomic  # plain rebind vs read-modify-write
        self.line = line
        self.method = method


class _MethodFacts:
    def __init__(self, name: str):
        self.name = name
        self.accesses: list[_Access] = []
        self.calls: set[str] = set()  # self.X() targets (and local closures)
        self.spawn_entries: set[str] = set()  # methods/closures used as targets


class _MethodVisitor(ast.NodeVisitor):
    """Collect accesses/calls for one method body; nested closures become
    their own pseudo-methods named ``outer.<inner>`` and are implicitly
    'called' by the outer method unless only used as a thread target."""

    def __init__(self, facts: _MethodFacts, all_facts: dict[str, _MethodFacts]):
        self.facts = facts
        self.all = all_facts
        self.lock_depth = 0

    # -- lock scoping ------------------------------------------------------
    def visit_With(self, node: ast.With) -> None:
        lockish = any(_lockish_expr(item.context_expr) for item in node.items)
        for item in node.items:
            self.visit(item.context_expr)
        if lockish:
            self.lock_depth += 1
        for stmt in node.body:
            self.visit(stmt)
        if lockish:
            self.lock_depth -= 1

    visit_AsyncWith = visit_With  # type: ignore[assignment]

    # -- nested closures ---------------------------------------------------
    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        sub_name = f"{self.facts.name}.{node.name}"
        sub = _MethodFacts(sub_name)
        self.all[sub_name] = sub
        v = _MethodVisitor(sub, self.all)
        for stmt in node.body:
            v.visit(stmt)
        # outer method can call the closure locally
        self.facts.calls.add(sub_name)

    visit_AsyncFunctionDef = visit_FunctionDef  # type: ignore[assignment]

    def visit_Lambda(self, node: ast.Lambda) -> None:
        self.visit(node.body)

    # -- accesses ----------------------------------------------------------
    def _rec(self, attr: str | None, write: bool, atomic: bool, line: int) -> None:
        if attr is None:
            return
        self.facts.accesses.append(
            _Access(attr, write, self.lock_depth > 0, atomic, line, self.facts.name)
        )

    def visit_Assign(self, node: ast.Assign) -> None:
        for t in node.targets:
            if isinstance(t, ast.Tuple):
                for el in t.elts:
                    self._assign_target(el)
            else:
                self._assign_target(t)
        self.visit(node.value)

    def _assign_target(self, t: ast.expr) -> None:
        attr = _self_attr(t)
        if attr is not None:
            self._rec(attr, write=True, atomic=True, line=t.lineno)
            return
        if isinstance(t, ast.Subscript):
            attr = _self_attr(t.value)
            if attr is not None:
                self._rec(attr, write=True, atomic=False, line=t.lineno)
            else:
                self.visit(t.value)
            self.visit(t.slice)

    def visit_AugAssign(self, node: ast.AugAssign) -> None:
        attr = _self_attr(node.target)
        if attr is not None:
            self._rec(attr, write=True, atomic=False, line=node.lineno)
        elif isinstance(node.target, ast.Subscript):
            sub = _self_attr(node.target.value)
            if sub is not None:
                self._rec(sub, write=True, atomic=False, line=node.lineno)
        self.visit(node.value)

    def visit_Delete(self, node: ast.Delete) -> None:
        for t in node.targets:
            if isinstance(t, ast.Subscript):
                attr = _self_attr(t.value)
                if attr is not None:
                    self._rec(attr, write=True, atomic=False, line=node.lineno)
        self.generic_visit(node)

    def visit_Call(self, node: ast.Call) -> None:
        # self.X(...)  -> call edge
        callee = _self_attr(node.func)
        if callee is not None:
            self.facts.calls.add(callee)
        # self.attr.mutator(...)  -> non-atomic write to attr
        if isinstance(node.func, ast.Attribute) and node.func.attr in _MUTATORS:
            attr = _self_attr(node.func.value)
            if attr is not None:
                self._rec(attr, write=True, atomic=False, line=node.lineno)
        # thread spawn / executor submit / callback registration
        fname = dotted_name(node.func) or ""
        if _SPAWN_CALLS.search(fname):
            for kw in node.keywords:
                if kw.arg == "target":
                    self._record_entry(kw.value)
            # Timer(interval, self.cb)
            if fname.endswith("Timer") and len(node.args) >= 2:
                self._record_entry(node.args[1])
        elif isinstance(node.func, ast.Attribute) and node.func.attr == "submit" and node.args:
            self._record_entry(node.args[0])
        elif (
            isinstance(node.func, ast.Attribute)
            and node.func.attr == "append"
            and dotted_name(node.func.value) in ("gc.callbacks",)
            and node.args
        ):
            self._record_entry(node.args[0])
        self.generic_visit(node)

    def _record_entry(self, node: ast.expr) -> None:
        m = _target_self_method(node)
        if m is not None:
            self.facts.spawn_entries.add(m)
            return
        local = _target_local_name(node)
        if local is not None:
            self.facts.spawn_entries.add(f"{self.facts.name}.{local}")

    def visit_Attribute(self, node: ast.Attribute) -> None:
        attr = _self_attr(node)
        if attr is not None and isinstance(node.ctx, ast.Load):
            self._rec(attr, write=False, atomic=True, line=node.lineno)
        self.generic_visit(node)


class ConcurrencyPass(FilePass):
    name = "concurrency"

    def check_file(self, info: FileInfo) -> list[Finding]:
        tree = info.tree
        assert tree is not None
        out: list[Finding] = []
        for node in ast.walk(tree):
            if isinstance(node, ast.ClassDef):
                out.extend(self._check_class(info, node))
        return out

    def _check_class(self, info: FileInfo, cls: ast.ClassDef) -> list[Finding]:
        facts: dict[str, _MethodFacts] = {}
        threadsafe_attrs: set[str] = set()

        for item in cls.body:
            if not isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            mf = _MethodFacts(item.name)
            facts[item.name] = mf
            v = _MethodVisitor(mf, facts)
            for stmt in item.body:
                v.visit(stmt)
            if item.name == "__init__":
                for stmt in ast.walk(item):
                    if isinstance(stmt, ast.Assign) and isinstance(stmt.value, ast.Call):
                        ctor = dotted_name(stmt.value.func) or ""
                        if _THREADSAFE_CTORS.search(ctor):
                            for t in stmt.targets:
                                attr = _self_attr(t)
                                if attr:
                                    threadsafe_attrs.add(attr)

        # thread entries declared anywhere in the class
        entries: set[str] = set()
        for mf in facts.values():
            entries.update(e for e in mf.spawn_entries if e in facts)
        if not entries:
            return []  # class spawns nothing trackable — out of scope

        public = {
            name
            for name in facts
            if (not name.startswith("_") and "." not in name) or name in _PUBLIC_DUNDERS
        }

        # entry -> reachable methods via the self-call graph
        def reachable(start: set[str]) -> set[str]:
            seen = set(start)
            work = list(start)
            while work:
                cur = work.pop()
                mf = facts.get(cur)
                if mf is None:
                    continue
                for callee in mf.calls:
                    if callee in facts and callee not in seen:
                        seen.add(callee)
                        work.append(callee)
            return seen

        method_entries: dict[str, set[str]] = {name: set() for name in facts}
        for e in sorted(entries):
            for m in reachable({e}):
                method_entries[m].add(e)
        for m in reachable(public):
            method_entries[m].add(PUBLIC)

        # collect per-attribute access sites
        by_attr: dict[str, list[_Access]] = {}
        for mf in facts.values():
            segments = mf.name.split(".")
            if segments[0] in _EXEMPT_METHODS:
                continue
            if any(seg.endswith("_locked") for seg in segments):
                continue
            for acc in mf.accesses:
                by_attr.setdefault(acc.attr, []).append(acc)

        out: list[Finding] = []
        reported: set[tuple[str, str]] = set()
        for attr, accs in sorted(by_attr.items()):
            if attr in threadsafe_attrs or _LOCKISH.search(attr):
                continue
            first_read: dict[str, int] = {}
            for a in accs:
                if not a.write:
                    cur = first_read.get(a.method)
                    if cur is None or a.line < cur:
                        first_read[a.method] = a.line
            for w in accs:
                if not w.write or w.locked:
                    continue
                # A plain rebind not preceded by a read of the same attr in
                # the same method is one-shot publication (`self._stop = ev`,
                # `self._loop = get_running_loop()` then local use): the GIL
                # makes the store atomic and the repo's Event-handshake idiom
                # orders it.  Only read-THEN-write shapes (delta computation,
                # check-then-act) race.
                if w.atomic and first_read.get(w.method, w.line + 1) > w.line:
                    continue
                w_entries = method_entries.get(w.method, set())
                for other in accs:
                    o_entries = method_entries.get(other.method, set())
                    joint = w_entries | o_entries
                    if len(joint) < 2:
                        continue
                    key = (attr, w.method)
                    if key in reported:
                        break
                    reported.add(key)
                    out.append(
                        Finding(
                            "concurrency-unlocked-write",
                            ERROR,
                            info.rel,
                            w.line,
                            f"{cls.name}.{attr} written outside a lock in "
                            f"'{w.method}' but reachable from multiple thread "
                            "entry points — guard with the instance lock or use "
                            "a thread-safe type",
                        )
                    )
                    break
        return out
