"""verifylint engine: pass registry, caching, suppressions, baseline ratchet.

Design notes
------------

*Findings* carry a stable **key** — ``path::rule::message`` — deliberately
excluding the line number, so a committed baseline survives unrelated edits
shuffling lines around.  The ratchet compares multisets of keys: a key not in
the baseline fails the gate; a baselined key that no longer fires is reported
as *stale* so the baseline only ever shrinks.

*Suppressions* are source comments::

    x = 1  # verifylint: disable=metric-open-label
    # verifylint: disable=metric-open-label,concurrency-unlocked-write
    # verifylint: disable-file=jit-unwrapped

A same-line or preceding-line ``disable`` silences that rule at that site;
``disable-file`` silences the rule for the whole file.  ``disable=all``
matches every rule.  Suppressions are counted, never silent.

*Caching*: per-file passes are cached keyed on the sha256 of the file's bytes
(plus the engine's cache schema version), so a no-op re-run over the tree is
dominated by hashing, not parsing.  Tree passes (event-schema,
protocol-compat) are whole-program and always re-run — they are the cheap
ones anyway (one AST walk each over already-parsed trees).
"""

from __future__ import annotations

import ast
import hashlib
import json
import os
from dataclasses import dataclass, field
from typing import Callable, Iterable

ERROR = "error"
WARNING = "warning"
INFO = "info"

_SEV_ORDER = {ERROR: 0, WARNING: 1, INFO: 2}

CACHE_SCHEMA = 4  # bump to invalidate caches when pass logic changes

_SKIP_DIRS = {"__pycache__", ".git", ".mypy_cache", ".pytest_cache", "node_modules"}


@dataclass(frozen=True)
class Finding:
    rule: str
    severity: str  # error | warning | info
    path: str  # repo-root-relative, '/' separated
    line: int
    message: str

    @property
    def key(self) -> str:
        return f"{self.path}::{self.rule}::{self.message}"

    def to_dict(self) -> dict:
        return {
            "rule": self.rule,
            "severity": self.severity,
            "path": self.path,
            "line": self.line,
            "message": self.message,
        }

    @staticmethod
    def from_dict(d: dict) -> "Finding":
        return Finding(
            rule=str(d["rule"]),
            severity=str(d.get("severity", ERROR)),
            path=str(d["path"]),
            line=int(d.get("line", 0)),
            message=str(d.get("message", "")),
        )


class FileInfo:
    """Lazily-parsed view of one source file, shared across passes."""

    def __init__(self, root: str, rel: str):
        self.root = root
        self.rel = rel
        self._data: bytes | None = None
        self._tree: ast.AST | None = None
        self._tree_err: str | None = None
        self._sha: str | None = None

    @property
    def abspath(self) -> str:
        return os.path.join(self.root, self.rel.replace("/", os.sep))

    @property
    def data(self) -> bytes:
        if self._data is None:
            with open(self.abspath, "rb") as f:
                self._data = f.read()
        return self._data

    @property
    def sha(self) -> str:
        if self._sha is None:
            self._sha = hashlib.sha256(self.data).hexdigest()
        return self._sha

    @property
    def text(self) -> str:
        return self.data.decode("utf-8", "replace")

    @property
    def tree(self) -> ast.AST | None:
        if self._tree is None and self._tree_err is None:
            try:
                self._tree = ast.parse(self.text, filename=self.rel)
            except SyntaxError as e:  # surfaced as a finding by the engine
                self._tree_err = f"{e.msg} (line {e.lineno})"
        return self._tree

    @property
    def parse_error(self) -> str | None:
        self.tree
        return self._tree_err


class TreeContext:
    """All files under the lint roots, with shared parse caching."""

    def __init__(self, root: str, rel_paths: list[str]):
        self.root = root
        self.files = [FileInfo(root, rel) for rel in sorted(rel_paths)]
        self._by_rel = {f.rel: f for f in self.files}

    def get(self, rel: str) -> FileInfo | None:
        return self._by_rel.get(rel)

    def by_basename(self, name: str) -> list[FileInfo]:
        return [f for f in self.files if os.path.basename(f.rel) == name]


class Pass:
    """Base: a whole-tree pass.  Subclasses override ``run``."""

    name = "pass"
    #: per-file passes are cacheable; tree passes always run
    per_file = False

    def run(self, ctx: TreeContext) -> list[Finding]:  # pragma: no cover
        raise NotImplementedError

    def check_file(self, info: FileInfo) -> list[Finding]:  # pragma: no cover
        raise NotImplementedError


class FilePass(Pass):
    per_file = True

    def run(self, ctx: TreeContext) -> list[Finding]:
        out: list[Finding] = []
        for info in ctx.files:
            if info.tree is not None:
                out.extend(self.check_file(info))
        return out


# --------------------------------------------------------------------------
# suppressions


def scan_suppressions(text: str) -> tuple[dict[int, set[str]], set[str]]:
    """Return (line -> suppressed rules, file-level suppressed rules).

    A ``disable=`` comment applies to its own line and the line below it
    (so a comment-only line shields the statement it precedes).
    """
    per_line: dict[int, set[str]] = {}
    file_level: set[str] = set()
    for i, line in enumerate(text.splitlines(), start=1):
        idx = line.find("# verifylint:")
        if idx < 0:
            continue
        directive = line[idx + len("# verifylint:") :].strip()
        if directive.startswith("disable-file="):
            file_level.update(
                r.strip() for r in directive[len("disable-file=") :].split(",") if r.strip()
            )
        elif directive.startswith("disable="):
            rules = {r.strip() for r in directive[len("disable=") :].split(",") if r.strip()}
            stripped = line[:idx].strip()
            per_line.setdefault(i, set()).update(rules)
            if not stripped:  # comment-only line: shield the next line
                per_line.setdefault(i + 1, set()).update(rules)
    return per_line, file_level


def _suppressed(f: Finding, per_line: dict[int, set[str]], file_level: set[str]) -> bool:
    if "all" in file_level or f.rule in file_level:
        return True
    rules = per_line.get(f.line, ())
    return "all" in rules or f.rule in rules


# --------------------------------------------------------------------------
# baseline ratchet


def load_baseline(path: str) -> dict[str, int]:
    """Baseline file -> {finding key: allowed count}."""
    if not os.path.exists(path):
        return {}
    with open(path, "r", encoding="utf-8") as f:
        doc = json.load(f)
    out: dict[str, int] = {}
    for ent in doc.get("findings", []):
        key = f"{ent['path']}::{ent['rule']}::{ent['message']}"
        out[key] = out.get(key, 0) + int(ent.get("count", 1))
    return out


def write_baseline(findings: Iterable[Finding], path: str, justifications: dict[str, str] | None = None) -> None:
    """Write the error-severity findings as the new baseline, preserving any
    existing per-entry ``justification`` strings keyed by finding key."""
    just = dict(justifications or {})
    if os.path.exists(path):
        try:
            with open(path, "r", encoding="utf-8") as f:
                for ent in json.load(f).get("findings", []):
                    k = f"{ent['path']}::{ent['rule']}::{ent['message']}"
                    if ent.get("justification") and k not in just:
                        just[k] = ent["justification"]
        except (OSError, ValueError):
            pass
    counts: dict[str, dict] = {}
    for f in findings:
        if f.severity != ERROR:
            continue
        ent = counts.setdefault(
            f.key, {"rule": f.rule, "path": f.path, "message": f.message, "count": 0}
        )
        ent["count"] += 1
    entries = []
    for key in sorted(counts):
        ent = counts[key]
        if key in just:
            ent["justification"] = just[key]
        entries.append(ent)
    doc = {
        "comment": "verifylint baseline ratchet: existing debt, may only shrink. "
        "Regenerate with `lint --write-baseline`; every kept entry needs a "
        "justification.",
        "version": 1,
        "findings": entries,
    }
    with open(path, "w", encoding="utf-8") as f:
        json.dump(doc, f, indent=2, sort_keys=False)
        f.write("\n")


@dataclass
class RatchetResult:
    new_errors: list[Finding] = field(default_factory=list)
    baselined: list[Finding] = field(default_factory=list)
    stale_keys: list[str] = field(default_factory=list)


def apply_baseline(findings: list[Finding], baseline: dict[str, int]) -> RatchetResult:
    res = RatchetResult()
    budget = dict(baseline)
    for f in findings:
        if f.severity != ERROR:
            continue
        if budget.get(f.key, 0) > 0:
            budget[f.key] -= 1
            res.baselined.append(f)
        else:
            res.new_errors.append(f)
    res.stale_keys = sorted(k for k, n in budget.items() if n > 0)
    return res


# --------------------------------------------------------------------------
# engine


@dataclass
class RunResult:
    findings: list[Finding]  # post-suppression, sorted
    suppressed: int
    files: int
    passes: list[str]
    cache_hits: int = 0

    @property
    def errors(self) -> list[Finding]:
        return [f for f in self.findings if f.severity == ERROR]


def _sort_key(f: Finding):
    return (f.path, f.line, _SEV_ORDER.get(f.severity, 9), f.rule, f.message)


def default_passes() -> list[Pass]:
    from .concurrency import ConcurrencyPass
    from .event_schema import EventSchemaPass
    from .jit_hygiene import JitHygienePass
    from .metrics_cardinality import MetricsCardinalityPass
    from .protocol_compat import ProtocolCompatPass

    return [
        JitHygienePass(),
        MetricsCardinalityPass(),
        ConcurrencyPass(),
        EventSchemaPass(),
        ProtocolCompatPass(),
    ]


def discover_files(root: str, paths: list[str] | None = None) -> list[str]:
    """Repo-relative .py paths under ``paths`` (default: the package dir)."""
    if not paths:
        paths = ["s2_verification_tpu"]
    rels: set[str] = set()
    for p in paths:
        absp = p if os.path.isabs(p) else os.path.join(root, p)
        if os.path.isfile(absp) and absp.endswith(".py"):
            rels.add(os.path.relpath(absp, root).replace(os.sep, "/"))
            continue
        for dirpath, dirnames, filenames in os.walk(absp):
            dirnames[:] = [d for d in dirnames if d not in _SKIP_DIRS]
            for fn in filenames:
                if fn.endswith(".py"):
                    rel = os.path.relpath(os.path.join(dirpath, fn), root)
                    rels.add(rel.replace(os.sep, "/"))
    return sorted(rels)


class LintEngine:
    def __init__(
        self,
        root: str,
        passes: list[Pass] | None = None,
        cache_path: str | None = None,
    ):
        self.root = root
        self.passes = passes if passes is not None else default_passes()
        self.cache_path = cache_path
        self._cache: dict = {}
        if cache_path and os.path.exists(cache_path):
            try:
                with open(cache_path, "r", encoding="utf-8") as f:
                    doc = json.load(f)
                if doc.get("schema") == CACHE_SCHEMA:
                    self._cache = doc.get("files", {})
            except (OSError, ValueError):
                self._cache = {}

    def _save_cache(self) -> None:
        if not self.cache_path:
            return
        try:
            with open(self.cache_path, "w", encoding="utf-8") as f:
                json.dump({"schema": CACHE_SCHEMA, "files": self._cache}, f)
        except OSError:
            pass

    def run(self, rel_paths: list[str] | None = None, paths: list[str] | None = None) -> RunResult:
        selected = rel_paths if rel_paths is not None else discover_files(self.root, paths)
        if rel_paths is None and paths is None:
            ctx = TreeContext(self.root, selected)
            scope: set | None = None
        else:
            # Tree passes resolve cross-file references (emit sites, the
            # wire table, one-hop imports), so a partial scan still parses
            # the whole package — only the *findings* are scoped to the
            # selected files.  Otherwise `lint --changed` on a consumer
            # file would report every event as never-emitted.
            scope = set(selected)
            ctx = TreeContext(
                self.root, sorted(scope | set(discover_files(self.root, None)))
            )
        raw: list[Finding] = []
        cache_hits = 0

        for info in ctx.files:
            if scope is not None and info.rel not in scope:
                continue
            if info.parse_error is not None:
                raw.append(
                    Finding("parse-error", ERROR, info.rel, 0, f"syntax error: {info.parse_error}")
                )

        for p in self.passes:
            if p.per_file:
                for info in ctx.files:
                    if scope is not None and info.rel not in scope:
                        continue
                    ent = self._cache.get(info.rel)
                    if ent and ent.get("sha") == info.sha and p.name in ent.get("passes", {}):
                        raw.extend(Finding.from_dict(d) for d in ent["passes"][p.name])
                        cache_hits += 1
                        continue
                    if info.tree is None:
                        continue
                    found = p.check_file(info)
                    raw.extend(found)
                    ent = self._cache.setdefault(info.rel, {"sha": info.sha, "passes": {}})
                    if ent.get("sha") != info.sha:
                        ent["sha"] = info.sha
                        ent["passes"] = {}
                    ent["passes"][p.name] = [f.to_dict() for f in found]
            else:
                raw.extend(
                    f
                    for f in p.run(ctx)
                    if scope is None or f.path in scope
                )

        # drop cache entries for files no longer scanned? keep — cheap, stable.
        self._save_cache()

        suppressed = 0
        kept: list[Finding] = []
        supp_cache: dict[str, tuple[dict[int, set[str]], set[str]]] = {}
        for f in raw:
            info = ctx.get(f.path)
            if info is None:
                kept.append(f)
                continue
            if f.path not in supp_cache:
                supp_cache[f.path] = scan_suppressions(info.text)
            per_line, file_level = supp_cache[f.path]
            if _suppressed(f, per_line, file_level):
                suppressed += 1
            else:
                kept.append(f)
        kept.sort(key=_sort_key)
        return RunResult(
            findings=kept,
            suppressed=suppressed,
            files=len(ctx.files) if scope is None else len(scope),
            passes=[p.name for p in self.passes],
            cache_hits=cache_hits,
        )


# --------------------------------------------------------------------------
# small shared AST helpers used by several passes


def dotted_name(node: ast.AST) -> str | None:
    """'a.b.c' for Name/Attribute chains, else None."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def const_str(node: ast.AST) -> str | None:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def module_constants(tree: ast.AST) -> dict[str, ast.expr]:
    """Module-level NAME = <expr> simple assignments."""
    out: dict[str, ast.expr] = {}
    for node in getattr(tree, "body", []):
        if isinstance(node, ast.Assign) and len(node.targets) == 1:
            t = node.targets[0]
            if isinstance(t, ast.Name):
                out[t.id] = node.value
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            if isinstance(node.target, ast.Name):
                out[node.target.id] = node.value
    return out


def literal_str_tuple(node: ast.expr | None) -> list[str] | None:
    """['a','b'] if node is a tuple/list/set of string constants, else None."""
    if isinstance(node, (ast.Tuple, ast.List, ast.Set)):
        vals = []
        for el in node.elts:
            s = const_str(el)
            if s is None:
                return None
            vals.append(s)
        return vals
    return None


def walk_with_parents(root: ast.AST) -> Iterable[tuple[ast.AST, list[ast.AST]]]:
    """Yield (node, ancestor-stack) depth-first.  Stack excludes the node."""
    stack: list[tuple[ast.AST, list[ast.AST]]] = [(root, [])]
    while stack:
        node, parents = stack.pop()
        yield node, parents
        child_parents = parents + [node]
        for child in ast.iter_child_nodes(node):
            stack.append((child, child_parents))


def iter_functions(tree: ast.AST) -> Iterable[ast.FunctionDef]:
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield node  # type: ignore[misc]


Resolver = Callable[[str], ast.expr | None]


def name_resolver(ctx: TreeContext, info: FileInfo) -> Resolver:
    """Resolve NAME -> module-level constant expr, following one-hop
    ``from X import NAME`` imports into sibling modules in the tree."""
    consts = module_constants(info.tree) if info.tree else {}
    imports: dict[str, str] = {}
    for node in getattr(info.tree, "body", []):
        if isinstance(node, ast.ImportFrom) and node.module:
            for alias in node.names:
                imports[alias.asname or alias.name] = f"{node.module}.{alias.name}"

    def resolve(name: str) -> ast.expr | None:
        if name in consts:
            return consts[name]
        target = imports.get(name)
        if not target:
            return None
        mod, _, attr = target.rpartition(".")
        modfile = mod.split(".")[-1] + ".py"
        for cand in ctx.by_basename(modfile):
            if cand.tree is None:
                continue
            other = module_constants(cand.tree)
            if attr in other:
                return other[attr]
        return None

    return resolve
