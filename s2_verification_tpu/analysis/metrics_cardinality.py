"""metrics-cardinality: label values must come from closed literal sets.

Rules
-----

``metric-open-label`` (error)
    A keyword label on ``.inc()`` / ``.set()`` / ``.observe()`` whose value
    cannot be proven drawn from a closed set of literals.  Unbounded label
    values (f-strings, fingerprints, event-field passthroughs, user input)
    mint a new timeseries per distinct value — the classic cardinality
    explosion that OOMs the scrape path.  ``exemplar=`` is an exemplar, not
    a label, and is exempt by design (that is its whole point).

    A value is *closed* when it is:

    - a literal constant;
    - ``str(<closed>)`` of one;
    - a name assigned **only** from literals in the enclosing function;
    - a name that passes the repo's validation idiom before use::

          reason = str(fields.get("reason", "other"))
          if reason not in ("deadline", "client_gone", "shutdown"):
              reason = "other"

      (membership test against a literal tuple with a literal fallback
      rebind — the fold used by job_cancelled / admission_shed);
    - a name validated against a **frozen instance attribute**::

          # __init__: self._nodes = tuple(sorted(targets))
          if node not in self._nodes:
              node = "other"

      (the fleet-membership idiom: the attribute must be assigned in the
      class's ``__init__`` from a ``tuple(...)``/``frozenset(...)`` call,
      so the value set is fixed at construction — bounded by deployment
      config like the router's ``--backend`` list, not by traffic);
    - a for-loop variable ranging over a literal tuple/list;
    - ``<MODULE_CONST_DICT>.get(x, "literal")`` where the module-level dict
      has only literal values (the verdict-label table idiom).

    Labels on **info-style gauges** — families ending ``_info``, the
    Prometheus convention for build/version metadata (one series, value
    1, identity carried in labels) — are exempt: their labels are
    inherently open (version strings) but the family is one-series by
    construction, so there is no cardinality to explode.

``metric-name`` (error)
    Registered metric families must follow the exposition conventions:
    names start ``verifyd_``; counters end ``_total``; histograms end in a
    unit suffix (``_seconds``/``_bytes``/``_layers``/``_ratio``/``_ops``/
    ``_lanes``).
"""

from __future__ import annotations

import ast
import re

from .engine import (
    ERROR,
    FileInfo,
    FilePass,
    Finding,
    const_str,
    dotted_name,
    literal_str_tuple,
    module_constants,
)

_METRIC_METHODS = {"inc", "set", "observe"}
_REG_METHODS = {"counter": "_total", "gauge": None, "histogram": "UNIT"}
_HIST_SUFFIXES = ("_seconds", "_bytes", "_layers", "_ratio", "_ops", "_lanes")
_RECEIVER_RE = re.compile(r"(^|_)(m|g|h|metric|counter|gauge|hist(ogram)?)(_|$)", re.I)


def _is_literal(node: ast.expr) -> bool:
    return isinstance(node, ast.Constant)


def _frozen_attrs(cls: ast.ClassDef) -> set[str]:
    """Instance attributes assigned in ``__init__`` from a
    ``tuple(...)``/``frozenset(...)`` call — fixed at construction, so a
    membership test against them proves a closed value set."""
    out: set[str] = set()
    for stmt in cls.body:
        if not (isinstance(stmt, ast.FunctionDef) and stmt.name == "__init__"):
            continue
        for node in ast.walk(stmt):
            if not isinstance(node, ast.Assign):
                continue
            if not (
                isinstance(node.value, ast.Call)
                and dotted_name(node.value.func) in ("tuple", "frozenset")
            ):
                continue
            for t in node.targets:
                if (
                    isinstance(t, ast.Attribute)
                    and isinstance(t.value, ast.Name)
                    and t.value.id == "self"
                ):
                    out.add(t.attr)
    return out


class _FnScope:
    """Per-function facts about local names: literal-only assignment and
    the membership-validation idiom."""

    def __init__(
        self,
        fn: ast.AST,
        mod_consts: dict[str, ast.expr],
        frozen_attrs: set[str] | None = None,
    ):
        self.literal_only: dict[str, bool] = {}
        self.validated: set[str] = set()
        self.loop_literal: set[str] = set()
        self.mod_consts = mod_consts
        self.frozen_attrs = frozen_attrs or set()
        for node in ast.walk(fn):
            if isinstance(node, ast.Assign):
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        closed = self._closed_expr(node.value, shallow=True)
                        prev = self.literal_only.get(t.id, True)
                        self.literal_only[t.id] = prev and closed
            elif isinstance(node, ast.AugAssign) and isinstance(node.target, ast.Name):
                self.literal_only[node.target.id] = False
            elif isinstance(node, (ast.For, ast.AsyncFor)):
                if isinstance(node.target, ast.Name) and literal_str_tuple(node.iter) is not None:
                    self.loop_literal.add(node.target.id)
            elif isinstance(node, ast.If):
                self._scan_validation(node)

    def _closed_container(self, node: ast.expr) -> bool:
        """Membership-test comparators that prove a closed set: a literal
        tuple, or a frozen instance attribute (``self._nodes`` assigned in
        ``__init__`` from ``tuple(...)``/``frozenset(...)``)."""
        if literal_str_tuple(node) is not None:
            return True
        return (
            isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name)
            and node.value.id == "self"
            and node.attr in self.frozen_attrs
        )

    def _scan_validation(self, node: ast.If) -> None:
        """``if X not in <closed container>: X = <literal>`` marks X
        validated (see :meth:`_closed_container` for what qualifies)."""
        test = node.test
        if not (
            isinstance(test, ast.Compare)
            and len(test.ops) == 1
            and isinstance(test.ops[0], ast.NotIn)
            and isinstance(test.left, ast.Name)
            and self._closed_container(test.comparators[0])
        ):
            return
        var = test.left.id
        for stmt in node.body:
            if (
                isinstance(stmt, ast.Assign)
                and len(stmt.targets) == 1
                and isinstance(stmt.targets[0], ast.Name)
                and stmt.targets[0].id == var
                and _is_literal(stmt.value)
            ):
                self.validated.add(var)

    def _closed_expr(self, node: ast.expr, shallow: bool = False) -> bool:
        if _is_literal(node):
            return True
        if isinstance(node, ast.Call):
            fname = dotted_name(node.func)
            if fname == "str" and len(node.args) == 1:
                return self._closed_expr(node.args[0], shallow)
            # MODULE_DICT.get(x, "lit") with all-literal dict values
            if (
                isinstance(node.func, ast.Attribute)
                and node.func.attr == "get"
                and isinstance(node.func.value, ast.Name)
                and len(node.args) == 2
                and _is_literal(node.args[1])
            ):
                table = self.mod_consts.get(node.func.value.id)
                if isinstance(table, ast.Dict) and all(
                    _is_literal(v) for v in table.values
                ):
                    return True
            return False
        if isinstance(node, ast.Name) and not shallow:
            return self.closed_name(node.id)
        if isinstance(node, (ast.IfExp,)):
            return self._closed_expr(node.body, shallow) and self._closed_expr(
                node.orelse, shallow
            )
        return False

    def closed_name(self, name: str) -> bool:
        if name in self.validated or name in self.loop_literal:
            return True
        if self.literal_only.get(name):
            return True
        const = self.mod_consts.get(name)
        return const is not None and _is_literal(const)

    def closed(self, node: ast.expr) -> bool:
        if isinstance(node, ast.Name):
            return self.closed_name(node.id)
        return self._closed_expr(node)


def _looks_like_metric_receiver(recv: ast.expr) -> bool:
    """Heuristic gate so ``.set()`` on non-metric objects is not swept in."""
    if isinstance(recv, ast.Attribute):
        return bool(_RECEIVER_RE.search(recv.attr))
    if isinstance(recv, ast.Name):
        return bool(_RECEIVER_RE.search(recv.id))
    if isinstance(recv, ast.Call):
        fname = dotted_name(recv.func) or ""
        tail = fname.rsplit(".", 1)[-1]
        return tail in _REG_METHODS or bool(_RECEIVER_RE.search(tail))
    if isinstance(recv, ast.Subscript):
        return _looks_like_metric_receiver(recv.value)
    return False


class MetricsCardinalityPass(FilePass):
    name = "metrics-cardinality"

    def check_file(self, info: FileInfo) -> list[Finding]:
        tree = info.tree
        assert tree is not None
        out: list[Finding] = []
        mod_consts = module_constants(tree)

        # registration naming lint (works at module or method level)
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call) or not isinstance(node.func, ast.Attribute):
                continue
            kind = node.func.attr
            if kind not in _REG_METHODS or not node.args:
                continue
            name = const_str(node.args[0])
            if name is None:
                continue
            msgs = []
            if not name.startswith("verifyd_"):
                msgs.append("must start with 'verifyd_'")
            if kind == "counter" and not name.endswith("_total"):
                msgs.append("counter must end with '_total'")
            if kind == "histogram" and not name.endswith(_HIST_SUFFIXES):
                msgs.append(
                    "histogram must end with a unit suffix "
                    f"({'/'.join(_HIST_SUFFIXES)})"
                )
            for m in msgs:
                out.append(
                    Finding(
                        "metric-name",
                        ERROR,
                        info.rel,
                        node.lineno,
                        f"metric family '{name}': {m}",
                    )
                )

        # receivers bound to *_info families: labels exempt (one-series
        # identity metrics — the Prometheus info-gauge convention)
        info_receivers: set[str] = set()
        for node in ast.walk(tree):
            if not (
                isinstance(node, ast.Assign)
                and isinstance(node.value, ast.Call)
            ):
                continue
            call = node.value
            if not (
                isinstance(call.func, ast.Attribute)
                and call.func.attr in _REG_METHODS
                and call.args
            ):
                continue
            fam = const_str(call.args[0])
            if fam is None or not fam.endswith("_info"):
                continue
            for t in node.targets:
                if isinstance(t, ast.Attribute):
                    info_receivers.add(t.attr)
                elif isinstance(t, ast.Name):
                    info_receivers.add(t.id)

        # label closedness, per enclosing function (class-aware: frozen
        # instance attributes are closed membership containers)
        scopes: dict[int, _FnScope] = {}
        class_attrs: dict[int, set[str]] = {}

        def scope_for(parents: list[ast.AST]) -> _FnScope | None:
            frozen: set[str] = set()
            for p in reversed(parents):
                if isinstance(p, ast.ClassDef):
                    if id(p) not in class_attrs:
                        class_attrs[id(p)] = _frozen_attrs(p)
                    frozen = class_attrs[id(p)]
                    break
            for p in reversed(parents):
                if isinstance(p, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    if id(p) not in scopes:
                        scopes[id(p)] = _FnScope(p, mod_consts, frozen)
                    return scopes[id(p)]
            return None

        stack: list[tuple[ast.AST, list[ast.AST]]] = [(tree, [])]
        while stack:
            node, parents = stack.pop()
            for child in ast.iter_child_nodes(node):
                stack.append((child, parents + [node]))
            if not (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in _METRIC_METHODS
                and node.keywords
                and _looks_like_metric_receiver(node.func.value)
            ):
                continue
            recv = node.func.value
            if isinstance(recv, ast.Call):
                fn = recv.func
                if (
                    isinstance(fn, ast.Attribute)
                    and fn.attr in _REG_METHODS
                    and recv.args
                    and (const_str(recv.args[0]) or "").endswith("_info")
                ):
                    continue  # inline-registered info gauge
            recv_name = (
                recv.attr
                if isinstance(recv, ast.Attribute)
                else recv.id if isinstance(recv, ast.Name) else None
            )
            if recv_name is not None and recv_name in info_receivers:
                continue
            scope = scope_for(parents)
            for kw in node.keywords:
                if kw.arg is None or kw.arg == "exemplar":
                    continue
                closed = (
                    scope.closed(kw.value) if scope is not None else _is_literal(kw.value)
                )
                if not closed:
                    out.append(
                        Finding(
                            "metric-open-label",
                            ERROR,
                            info.rel,
                            kw.value.lineno,
                            f"label '{kw.arg}' value is not provably from a closed "
                            "literal set — fold it through a validated enum "
                            "(`if v not in (...): v = 'other'`) before labeling",
                        )
                    )
        return out
