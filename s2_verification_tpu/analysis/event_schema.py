"""event-schema: the ServiceStats event registry, cross-checked.

The stream's schema is implicit today: producers call
``stats.emit("name", **fields)`` and five consumer families pattern-match on
names and fields — the ``_count`` counter chain in ``service/stats.py``,
``AlertRule`` literals, the flight-recorder doctor sections, the archive /
sentinel / SLO-health ``observe_event`` folds, and soak scoring.  Drift
between them is exactly the bug class PR 11's false-verdict sentinel catches
at runtime; this pass catches it at commit time.

Extraction
----------

*Emit sites*: ``<expr>.emit("name", k=v, **kw)`` — a ``**kw`` splat marks
the event *open* (field set not statically known) — plus dict-literal feeds
``observe_event({"ev": "name", ...})`` / ``record_event({...})``.

*Consumers*:

- the ``name = ev.get("ev") or ev.get("event")`` idiom followed by
  ``name == "lit"`` / ``name in (...)`` / ``if name != "lit": return``
  branches, with ``ev.get("f")`` / ``ev["f"]`` field reads (comparator
  tuples resolve through module constants, e.g. ``_GOOD_EVENTS``);
- functions that compare a parameter against string literals while reading
  a dict parameter in the branches (the ``_count(event, fields)`` shape);
- ``AlertRule(event="...", field="...")`` keyword literals;
- ``{k: ev[k] for k in _COPY_FIELDS}`` comprehensions resolve the field
  tuple through module constants.

Rules
-----

``event-never-emitted`` (error)
    A consumer matches an event name no emit site produces — dead consumer
    code, or a producer someone renamed out from under it.

``event-field-unwritten`` (error)
    A consumer reads field F of event E, every emit site of E is closed
    (no ``**`` splat), and none of them writes F.  Auto fields (``t``,
    ``ev``, ``event``) are exempt.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field

from .engine import (
    ERROR,
    FileInfo,
    Finding,
    Pass,
    TreeContext,
    const_str,
    dotted_name,
    literal_str_tuple,
    name_resolver,
)

_AUTO_FIELDS = {"t", "ev", "event"}
_FEED_FUNCS = {"observe_event", "record_event", "record"}


@dataclass
class EmitSite:
    path: str
    line: int
    fields: set[str]
    open: bool


@dataclass
class ConsumerRef:
    path: str
    line: int
    kind: str  # counter | alert-rule | fold
    field: str | None = None


@dataclass
class EventEntry:
    emits: list[EmitSite] = field(default_factory=list)
    consumers: list[ConsumerRef] = field(default_factory=list)

    @property
    def open(self) -> bool:
        return any(e.open for e in self.emits)

    @property
    def fields(self) -> set[str]:
        out: set[str] = set()
        for e in self.emits:
            out |= e.fields
        return out


Registry = dict[str, EventEntry]


# --------------------------------------------------------------------------
# extraction


def _collect_emits(info: FileInfo, reg: Registry) -> None:
    for node in ast.walk(info.tree):
        if not isinstance(node, ast.Call):
            continue
        if isinstance(node.func, ast.Attribute) and node.func.attr == "emit" and node.args:
            name = const_str(node.args[0])
            if name is None:
                continue
            fields = {kw.arg for kw in node.keywords if kw.arg is not None}
            is_open = any(kw.arg is None for kw in node.keywords)
            reg.setdefault(name, EventEntry()).emits.append(
                EmitSite(info.rel, node.lineno, fields, is_open)
            )
        elif (
            isinstance(node.func, ast.Attribute)
            and node.func.attr in _FEED_FUNCS
            and node.args
            and isinstance(node.args[0], ast.Dict)
        ):
            d = node.args[0]
            name = None
            fields: set[str] = set()
            is_open = False
            for k, v in zip(d.keys, d.values):
                if k is None:
                    is_open = True
                    continue
                ks = const_str(k)
                if ks in ("ev", "event"):
                    name = const_str(v) or name
                elif ks is not None:
                    fields.add(ks)
            if name is not None:
                reg.setdefault(name, EventEntry()).emits.append(
                    EmitSite(info.rel, node.lineno, fields, is_open)
                )


def _is_name_assign(node: ast.stmt) -> tuple[str, str] | None:
    """``N = D.get("ev") or D.get("event")`` (or a single get) -> (N, D)."""
    if not (
        isinstance(node, ast.Assign)
        and len(node.targets) == 1
        and isinstance(node.targets[0], ast.Name)
    ):
        return None
    calls: list[ast.expr] = []
    v = node.value
    if isinstance(v, ast.BoolOp) and isinstance(v.op, ast.Or):
        calls = list(v.values)
    else:
        calls = [v]
    dvar = None
    for c in calls:
        if not (
            isinstance(c, ast.Call)
            and isinstance(c.func, ast.Attribute)
            and c.func.attr == "get"
            and isinstance(c.func.value, ast.Name)
            and c.args
            and const_str(c.args[0]) in ("ev", "event")
        ):
            return None
        if dvar is None:
            dvar = c.func.value.id
        elif dvar != c.func.value.id:
            return None
    if dvar is None:
        return None
    return node.targets[0].id, dvar


def _events_in_test(test: ast.expr, nvar: str, resolve) -> tuple[list[str], bool]:
    """Events matched by an If test on the name var.

    Returns (events, negated): ``negated`` means the test *excludes* the
    events (the ``if name != "done": return`` guard shape).
    """
    if not (
        isinstance(test, ast.Compare)
        and len(test.ops) == 1
        and isinstance(test.left, ast.Name)
        and test.left.id == nvar
    ):
        return [], False
    comp = test.comparators[0]
    op = test.ops[0]
    if isinstance(op, (ast.Eq, ast.NotEq)):
        s = const_str(comp)
        return ([s] if s is not None else []), isinstance(op, ast.NotEq)
    if isinstance(op, (ast.In, ast.NotIn)):
        lits = literal_str_tuple(comp)
        if lits is None and isinstance(comp, ast.Name):
            lits = literal_str_tuple(resolve(comp.id))
        return (lits or []), isinstance(op, ast.NotIn)
    return [], False


def _field_reads(nodes: list[ast.stmt], dvar: str, resolve) -> list[tuple[str, int]]:
    """(field, line) reads on the payload var within the given statements."""
    out: list[tuple[str, int]] = []
    comp_vars: dict[str, list[str]] = {}  # comprehension var -> resolved fields
    for stmt in nodes:
        for node in ast.walk(stmt):
            if isinstance(node, (ast.ListComp, ast.SetComp, ast.DictComp, ast.GeneratorExp)):
                for gen in node.generators:
                    if isinstance(gen.target, ast.Name):
                        lits = literal_str_tuple(gen.iter)
                        if lits is None and isinstance(gen.iter, ast.Name):
                            lits = literal_str_tuple(resolve(gen.iter.id))
                        if lits is not None:
                            comp_vars[gen.target.id] = lits
    for stmt in nodes:
        for node in ast.walk(stmt):
            if (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "get"
                and isinstance(node.func.value, ast.Name)
                and node.func.value.id == dvar
                and node.args
            ):
                f = const_str(node.args[0])
                if f is not None:
                    out.append((f, node.lineno))
            elif (
                isinstance(node, ast.Subscript)
                and isinstance(node.value, ast.Name)
                and node.value.id == dvar
                and isinstance(node.ctx, ast.Load)
            ):
                f = const_str(node.slice)
                if f is not None:
                    out.append((f, node.lineno))
                elif isinstance(node.slice, ast.Name) and node.slice.id in comp_vars:
                    out.extend((cf, node.lineno) for cf in comp_vars[node.slice.id])
    return out


def _guard_exits(body: list[ast.stmt]) -> bool:
    """True when the branch body unconditionally leaves (return/continue/raise)."""
    return bool(body) and isinstance(body[-1], (ast.Return, ast.Continue, ast.Raise))


@dataclass
class _Consumption:
    event: str
    kind: str
    path: str
    line: int
    reads: list[tuple[str, int]] = field(default_factory=list)


def _collect_fold_consumers(info: FileInfo, resolve, out: list[_Consumption]) -> None:
    for fn in ast.walk(info.tree):
        if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        pairs: list[tuple[str, str]] = []  # (name var, payload var)
        for stmt in ast.walk(fn):
            if isinstance(stmt, ast.stmt):
                p = _is_name_assign(stmt)
                if p is not None:
                    pairs.append(p)
        # the _count(event, fields) shape: an `event` param compared to
        # literals while a `fields`/`payload` dict param is read in the
        # branches.  Restricted to the conventional parameter names — a
        # looser match sweeps in every string-dispatch function in the tree
        # (CLI backend selection, campaign fault classes).
        params = [a.arg for a in fn.args.args if a.arg != "self"]
        nvars = [p for p in params if p in ("event", "ev")]
        dvars = [p for p in params if p in ("fields", "payload")]
        for nvar in nvars:
            for dvar in dvars:
                pairs.append((nvar, dvar))
        if not pairs:
            continue
        for nvar, dvar in dict.fromkeys(pairs):
            _walk_branches(fn.body, nvar, dvar, resolve, info, out)


def _walk_branches(
    body: list[ast.stmt],
    nvar: str,
    dvar: str,
    resolve,
    info: FileInfo,
    out: list[_Consumption],
    _depth: int = 0,
) -> None:
    if _depth > 20:
        return
    for idx, stmt in enumerate(body):
        if isinstance(stmt, ast.If):
            evs, negated = _events_in_test(stmt.test, nvar, resolve)
            if evs and not negated:
                reads = _field_reads(stmt.body, dvar, resolve)
                for ev in evs:
                    out.append(_Consumption(ev, "fold", info.rel, stmt.lineno, reads))
                _walk_branches(stmt.orelse, nvar, dvar, resolve, info, out, _depth + 1)
                continue
            if evs and negated and _guard_exits(stmt.body):
                # `if name != "done": return` — the rest of this block is
                # the "done" branch.
                rest = body[idx + 1 :]
                reads = _field_reads(rest, dvar, resolve)
                for ev in evs:
                    out.append(_Consumption(ev, "fold", info.rel, stmt.lineno, reads))
                break
            _walk_branches(stmt.body, nvar, dvar, resolve, info, out, _depth + 1)
            _walk_branches(stmt.orelse, nvar, dvar, resolve, info, out, _depth + 1)
        elif isinstance(stmt, (ast.For, ast.While, ast.With, ast.Try)):
            inner: list[ast.stmt] = []
            for attr in ("body", "orelse", "finalbody", "handlers"):
                part = getattr(stmt, attr, None)
                if not part:
                    continue
                for el in part:
                    if isinstance(el, ast.ExceptHandler):
                        inner.extend(el.body)
                    else:
                        inner.append(el)
            _walk_branches(inner, nvar, dvar, resolve, info, out, _depth + 1)


def _collect_alert_rules(info: FileInfo, out: list[_Consumption]) -> None:
    for node in ast.walk(info.tree):
        if not isinstance(node, ast.Call):
            continue
        fname = dotted_name(node.func) or ""
        if fname.rsplit(".", 1)[-1] != "AlertRule":
            continue
        ev = fld = None
        for kw in node.keywords:
            if kw.arg == "event":
                ev = const_str(kw.value)
            elif kw.arg == "field":
                fld = const_str(kw.value)
        if ev is None:
            continue
        reads = [(fld, node.lineno)] if fld else []
        out.append(_Consumption(ev, "alert-rule", info.rel, node.lineno, reads))


# --------------------------------------------------------------------------
# the pass


def build_registry(ctx: TreeContext) -> tuple[Registry, list[_Consumption]]:
    reg: Registry = {}
    cons: list[_Consumption] = []
    for info in ctx.files:
        if info.tree is None:
            continue
        _collect_emits(info, reg)
    for info in ctx.files:
        if info.tree is None:
            continue
        resolve = name_resolver(ctx, info)
        _collect_fold_consumers(info, resolve, cons)
        _collect_alert_rules(info, cons)
    for c in cons:
        ent = reg.setdefault(c.event, EventEntry())
        if c.reads:
            for f, _line in sorted(set(c.reads)):
                ent.consumers.append(ConsumerRef(c.path, c.line, c.kind, f))
        else:
            ent.consumers.append(ConsumerRef(c.path, c.line, c.kind))
    return reg, cons


class EventSchemaPass(Pass):
    name = "event-schema"

    def run(self, ctx: TreeContext) -> list[Finding]:
        reg, cons = build_registry(ctx)
        out: list[Finding] = []
        for c in cons:
            ent = reg.get(c.event)
            if ent is None or not ent.emits:
                out.append(
                    Finding(
                        "event-never-emitted",
                        ERROR,
                        c.path,
                        c.line,
                        f"consumer matches event '{c.event}' but no emit site "
                        "produces it",
                    )
                )
                continue
            if ent.open:
                continue
            known = ent.fields | _AUTO_FIELDS
            for f, line in sorted(set(c.reads)):
                if f not in known:
                    out.append(
                        Finding(
                            "event-field-unwritten",
                            ERROR,
                            c.path,
                            line,
                            f"consumer reads field '{f}' of event '{c.event}' "
                            "but no emit site writes it",
                        )
                    )
        return out


# --------------------------------------------------------------------------
# docs generation (satellite: docs/EVENTS.md)

_EVENTS_MD_HEADER = """\
# ServiceStats event registry

<!-- Generated by `s2-verification-tpu lint --events-md docs/EVENTS.md`.
     Do not edit by hand: `scripts/lint_check.py` (and `make lint` via
     `--check-events-md`) fails when this file drifts from the tree. -->

Every event on the ServiceStats stream, extracted statically by the
`event-schema` verifylint pass: emit sites, the union of closed-form
fields (an *open* event has at least one `**splat` emitter, so its field
set is a lower bound), and every consumer that pattern-matches on the
event.  Auto fields `t` (emit wall clock) and `ev` (the name itself) ride
on every line and are not listed.
"""


def render_events_md(ctx: TreeContext) -> str:
    reg, _cons = build_registry(ctx)
    lines = [_EVENTS_MD_HEADER]
    for name in sorted(reg):
        ent = reg[name]
        if not ent.emits:
            continue  # never-emitted names are lint errors, not docs
        lines.append(f"## `{name}`\n")
        fields = sorted(ent.fields)
        suffix = " *(open: `**` emitter — lower bound)*" if ent.open else ""
        lines.append(
            "- **Fields:** " + (", ".join(f"`{f}`" for f in fields) if fields else "—") + suffix
        )
        emits = ", ".join(f"`{e.path}:{e.line}`" for e in sorted(ent.emits, key=lambda e: (e.path, e.line)))
        lines.append(f"- **Emitted from:** {emits}")
        if ent.consumers:
            seen: list[str] = []
            for c in sorted(ent.consumers, key=lambda c: (c.path, c.line, c.field or "")):
                tag = f"{c.kind} `{c.path}:{c.line}`"
                if c.field:
                    tag += f" (reads `{c.field}`)"
                if tag not in seen:
                    seen.append(tag)
            lines.append("- **Consumers:** " + "; ".join(seen))
        else:
            lines.append("- **Consumers:** — (flight recorder archives all events)")
        lines.append("")
    return "\n".join(lines).rstrip() + "\n"
