"""verifylint — domain-aware static analysis for the serving + obs stack.

Zero-dependency ``ast``-level passes encoding the repo's invariants:

- ``jit_hygiene``        — every module-level jit/pmap product routes through
                           ``observe_jit``; no jit-in-loop, no unhashable
                           ``static_argnums``, no Python ``if`` on traced values.
- ``event_schema``       — the ServiceStats event registry (name × field set),
                           cross-checked against every consumer: ``stats.py``
                           counters, ``AlertRule`` literals, flight/doctor and
                           archive/sentinel ``observe_event`` branches.
- ``metrics_cardinality``— metric label values must be provably drawn from
                           closed literal sets; naming lint for the
                           ``verifyd_*`` / ``_total`` / ``_seconds`` conventions.
- ``concurrency``        — in thread-spawning classes, attributes reachable
                           from ≥2 thread entry points must be written under a
                           held ``self._lock``-style context.
- ``protocol_compat``    — frame construction and parse sites in
                           ``client.py``/``daemon.py``/``router.py`` must agree
                           with ``protocol.py``'s ``FRAME_FIELDS`` table, and
                           the HMAC must cover everything but ``UNSIGNED_FIELDS``.

Entry points: the ``lint`` CLI subcommand, ``make lint``, and
``scripts/lint_check.py`` (the fixture-corpus gate).
"""

from .engine import (  # noqa: F401
    ERROR,
    INFO,
    WARNING,
    Finding,
    LintEngine,
    RunResult,
    apply_baseline,
    default_passes,
    load_baseline,
    write_baseline,
)
