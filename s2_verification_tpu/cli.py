"""Command-line interface: the reference's two binaries as one CLI.

``check``   — the ``s2-porcupine`` equivalent (golang/s2-porcupine/main.go:566-640):
              reads a JSONL history (``-file``, '-' = stdin), decides
              linearizability, always writes an HTML visualization under
              ``./porcupine-outputs/``, exits 0 on OK / 1 on not-linearizable.
``collect`` — the ``collect-history`` equivalent
              (rust/s2-verification/src/bin/collect-history.rs:26-201), run
              against the in-process fault-injecting fake S2 (this
              environment has no network): writes
              ``./data/records.<epoch>.jsonl`` and prints the path.
``serve``   — run ``verifyd``, the resident batched verification daemon
              (service/), on a unix socket and optionally an authenticated
              TCP listener (``--tcp`` + shared secret): admission queue
              with explicit backpressure, shape-grouped scheduling
              (compiles amortize across requests), verdict cache,
              supervised device jobs.  ``--state-dir`` makes the verdict
              cache and the admission queue crash-safe (CRC-checked
              segment logs; a restarted daemon answers decided
              fingerprints warm and re-runs orphaned accepted jobs).
``submit``  — send one history to a running ``verifyd`` (unix socket path
              or ``host:port``) and exit with the ``check`` exit code for
              its verdict (75 = queue full after retries, 69 = no daemon
              ever answered, 76 = a daemon was reached but refused after
              retries — bad secret, persistent frame errors).
``follow``  — continuous stream monitoring: tail a growing JSONL history
              (file or stdin), cut it into closed windows (no call left
              dangling across the cut), and verify each window
              incrementally against a ``--prefix`` daemon — the daemon
              carries the decided frontier forward under a chain-hash
              token, so window N+1 costs its own ops, not the stream's.
              An unknown frontier (evicted, node swapped) resyncs with
              one full-history submit.
``soak``    — the closed verification loop: generate ground-truth-labeled
              histories from seeded fault campaigns (``collect
              --list-campaigns``), submit each to a live daemon or router
              fleet, and score every verdict against its label.  Any
              contradiction raises the ``checker_false_verdict`` builtin
              alert, dumps a flight-recorder marker (fingerprint +
              campaign seed = one-command repro), and exits 1; a loop that
              could not prove itself clean (lost submissions, UNKNOWN
              verdicts, unconfirmed injections) exits 3.
``profiles``— query the durable per-job profile archive: live against a
              running daemon (``--socket``) or cold from a dead daemon's
              ``--state-dir``; filter by shape/backend/client/verdict/
              time, rank by wall time, export CSV/JSONL for offline
              analysis (the learned-cost-model training set).

Backends for ``check``:

- ``oracle``   — Wing–Gong DFS with memoization (Python; the semantic oracle).
- ``native``   — the same search compiled to native code (native/s2check.cpp),
                 the reference's compiled-Go/porcupine equivalent.
- ``frontier`` — host BFS frontier engine (CPU; the device twin's reference).
- ``device``   — the compiled TPU frontier search.
- ``auto``     — native (or oracle) with a time budget, escalating to the
                 device search when the budget expires (CPU stays the default
                 path; the accelerator handles what the CPU cannot).  If the
                 device search is itself inconclusive and the user set no
                 explicit budget, an unbounded CPU run closes the check —
                 reference semantics are unbounded (timeout 0, main.go:606),
                 so no decidable instance is ever conceded.

Exit codes: 0 linearizable, 1 not linearizable, 2 inconclusive, 64 usage /
decode errors (argparse usage errors included; the reference distinguishes
only 0/1 — UNKNOWN has no reference analog because Porcupine's timeout-0
runs are unbounded).
"""

from __future__ import annotations

import argparse
import logging
import os
import sys
import tempfile
import time

from . import version as _version
from .checker.entries import History, prepare
from .checker.oracle import CheckOutcome, CheckResult, check
from .collector.collect import CollectConfig, collect_to_file
from .collector.fake_s2 import FaultPlan
from .utils import events as ev
from .utils.platform import pin_platform

__all__ = ["main"]

log = logging.getLogger("s2_verification_tpu")

USAGE_EXIT = 64


def _umask() -> int:
    cur = os.umask(0)
    os.umask(cur)
    return cur


class _Parser(argparse.ArgumentParser):
    """argparse exits 2 on usage errors, which would collide with the
    'inconclusive' verdict; route usage errors to the documented 64."""

    def error(self, message: str) -> None:  # noqa: D401 - argparse hook
        self.print_usage(sys.stderr)
        self.exit(USAGE_EXIT, f"{self.prog}: error: {message}\n")


def _read_events(path: str) -> list[ev.LabeledEvent]:
    if path == "-":
        return list(ev.iter_history(sys.stdin))
    return ev.read_history(path)


def _cpu_check(
    hist: History,
    budget: float | None,
    profile: bool = False,
    prune: bool = False,
) -> CheckResult:
    """Native engine when buildable, Python oracle otherwise."""
    from .checker.native import NativeUnavailable, check_native

    try:
        return check_native(
            hist, time_budget_s=budget, profile=profile, prune=prune
        )
    except NativeUnavailable as e:
        log.debug("native checker unavailable (%s); using the Python oracle", e)
        return check(hist, time_budget_s=budget)


def _cpu(
    hist: History, budget: float | None, profile: bool, prune: bool = False
) -> CheckResult:
    # Extra kwargs only when asked: test doubles for _cpu_check keep the
    # plain (hist, budget) signature.
    kw = {}
    if profile:
        kw["profile"] = True
    if prune:
        kw["prune"] = True
    if kw:
        return _cpu_check(hist, budget, **kw)
    return _cpu_check(hist, budget)


def _run_backend(
    backend: str,
    hist: History,
    time_budget_s: float | None,
    checkpoint: str | None = None,
    device_rows: int | None = None,
    collect_stats: bool = False,
    profile: bool = False,
    prune: bool = False,
    speculate_depth: int = 0,
) -> CheckResult:
    # Budget 0 = run to completion, the reference's unbounded default
    # (CheckEventsVerbose timeout 0, main.go:606).
    unbounded = time_budget_s is not None and time_budget_s <= 0
    if unbounded:
        time_budget_s = None
    device_only = backend in ("device", "auto") and not (
        backend == "auto" and unbounded
    )
    if checkpoint is not None and not device_only:
        log.warning(
            "-checkpoint only applies to the device search; the %s backend "
            "will not snapshot",
            f"{backend} (unbounded CPU)" if backend == "auto" else backend,
        )
    if device_rows is not None and not device_only:
        log.warning(
            "-device-rows only applies to the device search; the %s backend "
            "ignores it",
            f"{backend} (unbounded CPU)" if backend == "auto" else backend,
        )
    if backend == "oracle":
        return check(hist, time_budget_s=time_budget_s)
    if backend == "native":
        from .checker.native import check_native

        return check_native(
            hist, time_budget_s=time_budget_s, profile=profile, prune=prune
        )
    if backend == "frontier":
        from .checker.frontier import check_frontier_auto

        return check_frontier_auto(
            hist, collect_stats=collect_stats, profile=profile, prune=prune
        )
    dev_kw = {} if device_rows is None else {"device_rows_cap": device_rows}
    if collect_stats:
        dev_kw["collect_stats"] = True
    if profile:
        dev_kw["profile"] = True
    if prune:
        dev_kw["prune"] = True
    if speculate_depth:
        dev_kw["speculate_depth"] = int(speculate_depth)
    if backend == "device":
        pin_platform()
        from .checker.device import check_device_auto

        return check_device_auto(hist, checkpoint_path=checkpoint, **dev_kw)
    if backend == "auto":
        if unbounded:
            # Never concede a decidable instance: CPU runs to completion.
            return _cpu(hist, None, profile, prune)
        budget = time_budget_s if time_budget_s is not None else 10.0
        res = _cpu(hist, budget, profile, prune)
        if res.outcome != CheckOutcome.UNKNOWN:
            return res
        log.info(
            "CPU engine hit its %.1fs budget; escalating to the device search",
            budget,
        )
        pin_platform()
        from .checker.device import check_device_auto

        res = check_device_auto(hist, checkpoint_path=checkpoint, **dev_kw)
        if res.outcome != CheckOutcome.UNKNOWN or time_budget_s is not None:
            return res
        # Device caps exhausted (beam + exhaustive + spill) with no
        # user-imposed bound: the reference's default is unbounded
        # (CheckEventsVerbose timeout 0, main.go:606), so never concede a
        # decidable instance — close with an unbounded CPU run.
        log.info(
            "device search inconclusive; falling back to the unbounded "
            "CPU engine (no -time-budget was set)"
        )
        return _cpu(hist, None, profile, prune)
    raise ValueError(f"unknown backend {backend!r}")


def _resolve_corpus(file_arg: str) -> list[str] | None:
    """Corpus mode: a directory or glob pattern as ``-file`` expands to a
    sorted list of histories checked in ONE process — the shape-bucketed
    encoding amortizes every compile across the corpus (the engine checks
    thousands of histories in minutes this way; one process per file
    would pay backend + compile-cache startup each).  Returns None for
    the single-file case (including stdin)."""
    if file_arg == "-":
        return None
    import glob as _glob

    if os.path.isdir(file_arg):
        pattern = os.path.join(file_arg, "*.jsonl")
    elif any(ch in file_arg for ch in "*?[") and not os.path.isfile(file_arg):
        # A literal filename that merely CONTAINS glob characters (e.g.
        # records[2026].jsonl) stays a single-file check.
        pattern = file_arg
    else:
        return None
    # Glob matches can include directories (x.jsonl dirs, `data/*`).
    return sorted(p for p in _glob.glob(pattern) if os.path.isfile(p))


def _cmd_check(args: argparse.Namespace) -> int:
    corpus = _resolve_corpus(args.file)
    if corpus is not None:
        if not corpus:
            log.error("no histories match %s", args.file)
            return USAGE_EXIT
        if args.checkpoint:
            # One snapshot path cannot serve many histories (the
            # fingerprint binds it to one); refusing beats a clash error
            # halfway through the corpus.
            log.warning("-checkpoint is ignored in corpus mode")
            args.checkpoint = None
        if args.profile:
            # Same single-output constraint: one profile file cannot hold
            # a corpus of timelines.
            log.warning("--profile is ignored in corpus mode")
            args.profile = None
        seen: set[int] = set()
        for path in corpus:
            # One unreadable/malformed file must not abort the corpus and
            # discard verdicts already found — record it and keep going.
            rc = _check_one(args, path)
            seen.add(rc)
            print(
                f"{path}: "
                + {0: "OK", 1: "ILLEGAL", 2: "UNKNOWN", 64: "ERROR"}.get(
                    rc, str(rc)
                ),
                flush=True,
            )
        # Worst verdict wins: ILLEGAL > unreadable file > UNKNOWN > OK.
        for code in (1, USAGE_EXIT, 2):
            if code in seen:
                return code
        return 0
    return _check_one(args, args.file)


def _check_one(args: argparse.Namespace, file_path: str) -> int:
    try:
        events = _read_events(file_path)
    except (OSError, ValueError) as e:
        log.error("failed to read history: %s", e)
        return 64
    try:
        checked = prepare(events, elide_trivial=True)
    except ValueError as e:
        log.error("malformed history: %s", e)
        return 64

    t0 = time.monotonic()
    try:
        res = _run_backend(
            args.backend,
            checked,
            args.time_budget,
            checkpoint=args.checkpoint,
            device_rows=args.device_rows,
            collect_stats=args.stats,
            profile=bool(args.profile),
            prune=args.prune,
            speculate_depth=args.speculate_depth,
        )
    except Exception as e:  # backend/environment failure, not a verdict
        from .checker.checkpoint import CheckpointError
        from .checker.native import NativeUnavailable

        if isinstance(e, NativeUnavailable):
            log.error("native backend unavailable: %s", e)
            return USAGE_EXIT
        if isinstance(e, CheckpointError):
            log.error(
                "%s — remove the file or point -checkpoint elsewhere", e
            )
            return USAGE_EXIT
        raise
    dt = time.monotonic() - t0

    if (
        not args.no_viz
        and res.outcome in (CheckOutcome.ILLEGAL, CheckOutcome.UNKNOWN)
        and not res.refusals
    ):
        # Backends that don't produce refusal reports themselves (oracle,
        # native, frontier) get them re-derived from the deepest prefix
        # (an immediate failure's prefix is empty — the culprit refuses
        # from the initial state and must still be named), so the artifact
        # names the culprit ops whichever engine decided.  (Only the
        # visualization consumes refusals, hence the no_viz gate.)
        from .checker.diagnostics import deepest_refusals

        report = deepest_refusals(checked, res.deepest or [])
        if report is not None:
            res.refusals = [report]

    if not args.no_viz:
        # Always emit the visualization, success or not, like the reference
        # (main.go:608-631): porcupine-outputs/<base>-<unique>.html.
        from .viz import write_visualization

        full = prepare(events, elide_trivial=False)
        os.makedirs(args.out_dir, exist_ok=True)
        base = "stdin" if file_path == "-" else os.path.basename(file_path)
        fd, path = tempfile.mkstemp(
            prefix=f"{base}-", suffix=".html", dir=args.out_dir
        )
        os.close(fd)
        # mkstemp reserves a unique name but creates it 0600; the artifact
        # is a report, not a secret.
        os.chmod(path, 0o644 & ~_umask())
        write_visualization(
            path,
            full,
            res,
            title=f"s2 linearizability check — {base}",
            checked=checked,
        )
        log.info("wrote visualization to %s", path)

    if args.profile:
        # Search-shape profile: FrontierStats fields + per-layer timeline
        # (+ native phase attribution), the same schema verifyd attaches
        # to its `done` events — so offline and service profiling feed the
        # same tooling.
        import json as _json

        from .service.scheduler import job_profile

        prof = job_profile(res)
        prof.update(
            file=file_path,
            outcome=res.outcome.value,
            backend=args.backend,
            wall_s=round(dt, 4),
            ops=len(checked.ops),
        )
        with open(args.profile, "w", encoding="utf-8") as f:
            _json.dump(prof, f, indent=2)
            f.write("\n")
        log.info("wrote search profile to %s", args.profile)

    if args.stats:
        # One machine-readable line on stdout — the per-check analog of
        # bench.py's metric contract (verdict, wall, search statistics,
        # witness presence), for scripting over many histories.
        import json as _json

        line = {
            "file": file_path,
            "outcome": res.outcome.value,
            "backend": args.backend,
            "wall_s": round(dt, 4),
            "ops": len(checked.ops),
            "witness": res.linearization is not None,
        }
        st = getattr(res, "stats", None)
        if st is not None:
            line.update(
                layers=st.layers,
                max_frontier=st.max_frontier,
                expanded=st.expanded,
                auto_closed=st.auto_closed,
                pruned=st.pruned,
            )
        if res.steps:
            line["steps"] = res.steps
        print(_json.dumps(line), flush=True)

    if res.outcome == CheckOutcome.OK:
        log.info(
            "history is linearizable (%s backend, %.3fs, %d ops)",
            args.backend,
            dt,
            len(checked.ops),
        )
        return 0
    if res.outcome == CheckOutcome.ILLEGAL:
        log.error(
            "history is NOT linearizable (%s backend, %.3fs)", args.backend, dt
        )
        return 1
    log.error("check inconclusive (%s backend, %.3fs)", args.backend, dt)
    return 2


def _cmd_collect(args: argparse.Namespace) -> int:
    if args.list_campaigns:
        from .collector.campaign import builtin_campaigns

        for name, c in sorted(builtin_campaigns().items()):
            print(
                f"{name:16s} workflow={c.workflow:13s} "
                f"violation={c.violation_class() or '-':15s} {c.description}"
            )
        return 0
    if args.campaign:
        from .collector.campaign import collect_labeled_to_file, get_campaign

        try:
            campaign = get_campaign(args.campaign)
        except KeyError as e:
            log.error("%s", e.args[0])
            return USAGE_EXIT
        if args.socket:
            log.error(
                "--campaign needs the in-process path (per-client fault "
                "facades); --socket is unsupported"
            )
            return USAGE_EXIT
        if args.workflow is not None and args.workflow != campaign.workflow:
            log.warning(
                "--workflow %s ignored: campaign %r runs workflow %s",
                args.workflow,
                campaign.name,
                campaign.workflow,
            )
        path, lpath, label = collect_labeled_to_file(
            campaign,
            args.seed,
            out_dir=args.out_dir,
            clients=args.num_concurrent_clients,
            ops=args.num_ops_per_client,
        )
        log.info(
            "ground-truth label expect=%s (violation=%s confirmed=%s) at %s",
            label["expect"],
            label["violation"],
            label["confirmed"],
            lpath,
        )
        print(path)
        return 0
    faults = FaultPlan.chaos(args.chaos) if args.chaos > 0 else FaultPlan()
    cfg = CollectConfig(
        num_concurrent_clients=(
            5 if args.num_concurrent_clients is None else args.num_concurrent_clients
        ),
        num_ops_per_client=(
            100 if args.num_ops_per_client is None else args.num_ops_per_client
        ),
        workflow=args.workflow if args.workflow is not None else "regular",
        seed=args.seed,
        faults=faults,
    )
    if args.socket:
        # Loopback-socket transport: the stream state + fault injection
        # live in a server on another thread/loop, and the collector
        # speaks the seam protocol over a real async IO boundary — the
        # stand-in for the reference's network endpoint config
        # (collect-history.rs:70-94).
        from .collector.collect import default_stream
        from .collector.socket_s2 import S2SocketServer, S2SocketTransport

        with S2SocketServer(default_stream(cfg), args.socket):
            path = collect_to_file(
                cfg, stream=S2SocketTransport(args.socket), out_dir=args.out_dir
            )
    else:
        path = collect_to_file(cfg, out_dir=args.out_dir)
    # The reference prints the history path as its last act
    # (collect-history.rs:195-200).
    print(path)
    return 0


def _read_secret(args: argparse.Namespace) -> bytes | None:
    """Shared secret for the TCP transport: ``--secret-file`` wins, then
    the ``VERIFYD_SECRET`` environment variable (never a CLI argument —
    process listings leak those)."""
    if getattr(args, "secret_file", None):
        with open(args.secret_file, "rb") as f:
            secret = f.read().strip()
        if not secret:
            raise SystemExit(f"secret file {args.secret_file} is empty")
        return secret
    env = os.environ.get("VERIFYD_SECRET", "")
    return env.encode("utf-8") if env else None


def _resolve_mesh_devices(spec: str | None) -> int | None:
    """Resolve ``serve --mesh-devices N|auto`` to a device-pool size.

    ``auto`` counts visible devices: in-process when pinned to CPU (no
    tunnel to hang on), else via a bounded probe child — the daemon
    process itself must never initialize jax (a dead TPU tunnel *hangs*
    backend init; see service/supervise.py).
    """
    if spec is None:
        return None
    s = str(spec).strip().lower()
    if s in ("", "0", "none", "off"):
        return None
    if s != "auto":
        n = int(s)
        if n < 1:
            raise SystemExit(f"--mesh-devices must be >= 1, got {n}")
        return n
    if os.environ.get("JAX_PLATFORMS", "").strip().lower() == "cpu":
        from .utils.platform import pin_platform

        pin_platform()
        import jax

        return len(jax.devices())
    import subprocess
    import sys as _sys

    code = (
        "import os, jax\n"
        "p = os.environ.get('JAX_PLATFORMS')\n"
        "if p: jax.config.update('jax_platforms', p)\n"
        "print(len(jax.devices()))\n"
    )
    try:
        proc = subprocess.run(
            [_sys.executable, "-c", code],
            capture_output=True,
            text=True,
            timeout=120,
        )
        if proc.returncode == 0:
            return max(1, int(proc.stdout.strip().splitlines()[-1]))
    except (subprocess.TimeoutExpired, OSError, ValueError, IndexError):
        pass
    log.warning(
        "--mesh-devices auto: device probe failed; serving without a pool"
    )
    return None


def _cmd_serve(args: argparse.Namespace) -> int:
    from .service.daemon import Verifyd, VerifydConfig

    if os.path.exists(args.socket):
        # A stale socket file from a crashed daemon only a clean exit
        # removes; refusing with a clear message beats a bind error.
        log.error(
            "%s already exists — another verifyd running? (remove the file "
            "if it is stale)",
            args.socket,
        )
        return USAGE_EXIT
    secret = _read_secret(args)
    if args.tcp and not secret:
        log.error(
            "--tcp requires a shared secret (--secret-file or VERIFYD_SECRET)"
        )
        return USAGE_EXIT
    alert_rules: tuple = ()
    if args.alert_rule:
        from .obs.alerts import parse_rule

        try:
            alert_rules = tuple(args.alert_rule)
            for spec in alert_rules:
                parse_rule(spec)
        except ValueError as e:
            log.error("bad --alert-rule: %s", e)
            return USAGE_EXIT
        if not args.alert_url:
            log.error("--alert-rule requires --alert-url")
            return USAGE_EXIT
    mesh_devices = _resolve_mesh_devices(args.mesh_devices)
    if (
        mesh_devices is not None
        and os.environ.get("JAX_PLATFORMS", "").strip().lower() == "cpu"
    ):
        # CPU rehearsal: provision the virtual devices *now*, before any
        # jax init, so inline escalations and spawned children both see
        # the requested topology (XLA_FLAGS is inherited through env).
        from .utils.platform import ensure_host_device_count

        ensure_host_device_count(mesh_devices)
    cfg = VerifydConfig(
        socket_path=args.socket,
        queue_depth=args.queue_depth,
        workers=args.workers,
        batch_max=args.batch_max,
        time_budget_s=args.time_budget,
        device=args.device,
        out_dir=args.out_dir,
        no_viz=args.no_viz,
        stats_log=args.stats_log or None,
        device_rows=args.device_rows,
        tcp=args.tcp or None,
        secret=secret,
        state_dir=args.state_dir or None,
        fsync=args.fsync,
        metrics_port=args.metrics_port,
        trace_capacity=args.trace_capacity,
        profile=args.profile,
        mesh_devices=mesh_devices,
        log_format=args.log_format,
        slo_target=args.slo_target,
        slo_latency_target_s=args.slo_latency_target,
        alert_url=args.alert_url or None,
        alert_rules=alert_rules,
        alert_dedup_s=args.alert_dedup,
        drain_timeout_s=args.drain_timeout,
        sentinel_band=args.sentinel_band,
        sentinel_min_samples=args.sentinel_min_samples,
        resource_sample_s=args.resource_sample,
        retrace_storm_threshold=args.retrace_storm,
        dashboard_sample_s=args.dashboard_sample,
        telemetry_dir=args.telemetry_dir,
        telemetry_sample_s=args.telemetry_sample,
        max_rss_frac=args.max_rss_frac,
        deadline_grace_s=args.deadline_grace,
        quarantine_threshold=args.quarantine_threshold,
        fast_admission=args.fast_admission,
        batching=args.batching,
        batch_engine=args.batch_engine,
        prefix_enabled=args.prefix,
        prefix_capacity=args.prefix_capacity,
        prefix_min_ops=args.prefix_min_ops,
        prefix_cuts=args.prefix_cuts,
        prefix_max_segments=args.prefix_max_segments,
        prune=args.prune,
        speculate_depth=args.speculate_depth,
    )
    daemon = Verifyd(cfg)

    # Route stdlib-logging diagnostics (this module, scheduler, supervise,
    # resilient) through the daemon's structured logger so every line —
    # events and diagnostics alike — shares one format and one stream.
    from .obs.log import StructuredHandler

    pkg_log = logging.getLogger("s2_verification_tpu")
    handler = StructuredHandler(daemon.logger)
    pkg_log.addHandler(handler)
    pkg_log.propagate = False

    import signal as _signal

    def _stop(signum, frame):
        # Black-box dump before teardown: SIGTERM is how orchestration
        # kills a daemon, and the flight tail is the post-mortem story.
        daemon.dump_flight(
            "sigterm" if signum == _signal.SIGTERM else "sigint"
        )
        if signum == _signal.SIGTERM and cfg.drain_timeout_s > 0:
            # Rolling-restart contract: finish what was admitted, close
            # the journal cleanly, then exit.
            log.info(
                "signal %d: draining verifyd (up to %.0fs)",
                signum,
                cfg.drain_timeout_s,
            )
            daemon.request_drain()
            return
        log.info("signal %d: stopping verifyd", signum)
        daemon.request_stop()

    for sig in (_signal.SIGINT, _signal.SIGTERM):
        _signal.signal(sig, _stop)
    try:
        return daemon.serve_forever()
    finally:
        pkg_log.removeHandler(handler)
        pkg_log.propagate = True


def _cmd_route_serve(args: argparse.Namespace) -> int:
    from .service.router import BackendSpec, RouterConfig, VerifydRouter

    secret = _read_secret(args)
    is_tcp = ":" in args.listen and not args.listen.startswith(("/", "."))
    if is_tcp and not secret:
        log.error(
            "a TCP --listen requires a shared secret (--secret-file or "
            "VERIFYD_SECRET)"
        )
        return USAGE_EXIT
    if not is_tcp and os.path.exists(args.listen):
        log.error(
            "%s already exists — another router running? (remove the file "
            "if it is stale)",
            args.listen,
        )
        return USAGE_EXIT
    try:
        backends = tuple(BackendSpec.parse(spec) for spec in args.backend)
    except ValueError as e:
        log.error("bad --backend: %s", e)
        return USAGE_EXIT
    try:
        cfg = RouterConfig(
            listen=args.listen,
            backends=backends,
            secret=secret,
            probe_interval_s=args.probe_interval,
            breaker_failures=args.breaker_failures,
            breaker_reset_s=args.breaker_reset,
            steal_depth=args.steal_depth,
            max_failovers=args.max_failovers,
            submit_timeout_s=args.submit_timeout,
            ring_replicas=args.ring_replicas,
            drain_timeout_s=args.drain_timeout,
            cache_capacity=args.cache_capacity,
            metrics_port=args.metrics_port,
            trace_capacity=args.trace_capacity,
            slo_target=args.slo_target,
            slo_latency_target_s=args.slo_latency_target,
            state_dir=args.state_dir,
            distsearch_segments=args.distsearch_segments,
            distsearch_straggler_s=args.distsearch_straggler,
            distsearch_max_regrants=args.distsearch_max_regrants,
            scrape_interval_s=args.scrape_interval,
            telemetry_dir=args.telemetry_dir,
            telemetry_sample_s=args.telemetry_sample,
        )
        router = VerifydRouter(cfg)
    except ValueError as e:
        log.error("%s", e)
        return USAGE_EXIT

    import signal as _signal

    def _stop(signum, frame):
        log.info("signal %d: stopping verifyd-router", signum)
        router.request_stop()

    for sig in (_signal.SIGINT, _signal.SIGTERM):
        _signal.signal(sig, _stop)
    return router.serve_forever()


def _route_client(args: argparse.Namespace):
    from .service.client import VerifydClient

    return VerifydClient(args.socket, secret=_read_secret(args))


def _cmd_route_drain(args: argparse.Namespace) -> int:
    from .service.client import VerifydError, VerifydUnavailable
    from .service.protocol import EXIT_PROTOCOL, EXIT_UNAVAILABLE

    try:
        reply = _route_client(args).drain(
            args.node, drain_timeout_s=args.timeout, timeout=None
        )
    except ValueError as e:
        log.error("%s", e)
        return USAGE_EXIT
    except VerifydUnavailable as e:
        log.error("cannot reach the router on %s: %s", args.socket, e.msg)
        return EXIT_UNAVAILABLE
    except VerifydError as e:
        log.error("drain failed: %s", e)
        return EXIT_PROTOCOL
    log.info(
        "node %s drained (in-flight clear: %s, waited %.2fs); backend "
        "shutdown: %s",
        reply.get("node"),
        reply.get("drained"),
        reply.get("waited_s", 0.0),
        reply.get("shutdown"),
    )
    return 0 if reply.get("drained") else 1


def _cmd_route_undrain(args: argparse.Namespace) -> int:
    from .service.client import VerifydError, VerifydUnavailable
    from .service.protocol import EXIT_PROTOCOL, EXIT_UNAVAILABLE

    try:
        reply = _route_client(args).undrain(args.node)
    except ValueError as e:
        log.error("%s", e)
        return USAGE_EXIT
    except VerifydUnavailable as e:
        log.error("cannot reach the router on %s: %s", args.socket, e.msg)
        return EXIT_UNAVAILABLE
    except VerifydError as e:
        log.error("undrain failed: %s", e)
        return EXIT_PROTOCOL
    log.info("node %s back in the routable set", reply.get("node"))
    return 0


def _cmd_route_fleet(args: argparse.Namespace) -> int:
    import json as _json

    from .service.client import VerifydError, VerifydUnavailable
    from .service.protocol import EXIT_PROTOCOL, EXIT_UNAVAILABLE

    try:
        reply = _route_client(args).fleet()
    except ValueError as e:
        log.error("%s", e)
        return USAGE_EXIT
    except VerifydUnavailable as e:
        log.error("cannot reach the router on %s: %s", args.socket, e.msg)
        return EXIT_UNAVAILABLE
    except VerifydError as e:
        log.error("fleet query failed: %s", e)
        return EXIT_PROTOCOL
    if args.json:
        print(_json.dumps(reply, indent=2, sort_keys=True))
        return 0
    ring = reply.get("ring", {})
    print(
        f"ring: {len(ring.get('nodes', []))} nodes × "
        f"{ring.get('replicas')} replicas"
    )
    for b in reply.get("backends", []):
        up = {True: "up", False: "DOWN", None: "unprobed"}[b.get("up")]
        flags = []
        if b.get("draining"):
            flags.append("draining")
        if b.get("breaker") != "closed":
            flags.append(f"breaker={b.get('breaker')}")
        if b.get("last_error"):
            flags.append(f"last_error={b['last_error']}")
        build = b.get("build") or {}
        build_str = ""
        if build:
            # The scraper captured verifyd_build_info labels off this node.
            build_str = (
                f"  build=v{build.get('version', '?')}"
                f"/{build.get('backend', '?')}"
                f"/py{build.get('python', '?')}"
            )
        print(
            f"  {b.get('name')}: {up}  addr={b.get('address')}  "
            f"in_flight={b.get('in_flight')}"
            + build_str
            + (f"  [{', '.join(flags)}]" if flags else "")
        )
    return 0


def _cmd_doctor(args: argparse.Namespace) -> int:
    """Read-only post-mortem of a (dead) daemon's --state-dir."""
    from .obs.flight import postmortem, render_postmortem

    if not os.path.isdir(args.state_dir):
        log.error("state dir %s does not exist", args.state_dir)
        return USAGE_EXIT
    pm = postmortem(args.state_dir, tail=max(1, args.tail))
    if args.json:
        import json as _json

        print(_json.dumps(pm, default=str), flush=True)
    else:
        print(render_postmortem(pm, tail=max(1, args.tail)), end="", flush=True)
    # Exit codes mirror the verdict: 0 clean shutdown, 1 unclean death —
    # scriptable ("did the last run die?") without parsing the report.
    return 0 if pm["clean_shutdown"] else 1


def _cmd_tsq(args: argparse.Namespace) -> int:
    """Query telemetry history: live off a daemon/router (--socket, the
    ``tsq`` op) or cold off a telemetry directory — same store, same
    answer, the daemon doesn't even have to be alive."""
    import json as _json

    labels: dict[str, str] = {}
    for spec in args.label or []:
        key, sep, val = spec.partition("=")
        if not sep or not key:
            log.error("bad --label %r: expected KEY=VALUE", spec)
            return USAGE_EXIT
        labels[key] = val

    if args.socket:
        from .service.client import (
            VerifydClient,
            VerifydError,
            VerifydUnavailable,
        )
        from .service.protocol import EXIT_PROTOCOL, EXIT_UNAVAILABLE

        try:
            client = VerifydClient(args.socket, secret=_read_secret(args))
            reply = client.tsq(
                res=args.res,
                metric=args.metric or None,
                labels=labels or None,
                since=args.since,
                until=args.until,
                limit=args.limit,
                info=args.info,
            )
        except ValueError as e:
            log.error("%s", e)
            return USAGE_EXIT
        except VerifydUnavailable as e:
            log.error("cannot reach verifyd on %s: %s", args.socket, e.msg)
            return EXIT_UNAVAILABLE
        except VerifydError as e:
            log.error("tsq refused: %s", e)
            return EXIT_PROTOCOL
    else:
        from .obs.tsdb import default_dir, query, telemetry_info

        tdir = args.telemetry_dir or (
            default_dir(args.state_dir) if args.state_dir else None
        )
        if not tdir:
            log.error(
                "tsq needs --socket (live) or --telemetry-dir / "
                "--state-dir (cold)"
            )
            return USAGE_EXIT
        if not os.path.isdir(tdir):
            log.error("telemetry dir %s does not exist", tdir)
            return USAGE_EXIT
        if args.info:
            reply = telemetry_info(tdir)
        else:
            reply = query(
                tdir,
                res=args.res,
                metric=args.metric or None,
                labels=labels or None,
                since=args.since,
                until=args.until,
                limit=args.limit,
            )

    if args.json:
        print(_json.dumps(reply, sort_keys=True), flush=True)
        return 0

    if args.info:
        print(f"telemetry store: {reply.get('dir', args.socket)}")
        for res, info in sorted((reply.get("resolutions") or {}).items()):
            rec = info.get("recovery") or {}
            print(
                f"  {res:<3s} {info.get('records', 0):>6} record(s) "
                f"{info.get('series', 0):>4} series "
                f"{info.get('bytes', 0):>9}B  "
                f"torn tail {rec.get('torn_tail_bytes', 0)}B, "
                f"{rec.get('bad_segments', 0)} bad segment(s)"
            )
        return 0

    series = reply.get("series") or {}
    if args.rate:
        # Cumulative counters → per-second rates.  Counters reset to 0
        # at every daemon boot, so a negative delta marks a restart, not
        # a decrease — clamp it to 0 instead of plotting nonsense.
        for key, pts in series.items():
            rated = []
            for (t0, v0), (t1, v1) in zip(pts, pts[1:]):
                dt = t1 - t0
                if dt > 0:
                    rated.append([t1, max(0.0, (v1 - v0) / dt)])
            series[key] = rated

    if args.csv:
        import csv as _csv

        w = _csv.writer(sys.stdout)
        w.writerow(["series", "t", "value"])
        for key in sorted(series):
            for t, v in series[key]:
                w.writerow([key, t, v])
        return 0

    rng = reply.get("range") or [None, None]
    span = (
        f"{_fmt_wall(rng[0])} .. {_fmt_wall(rng[1])}"
        if rng[0] is not None
        else "(empty)"
    )
    print(
        f"res={reply.get('res')}  {len(series)} series, "
        f"{reply.get('points', 0)} point(s)  {span}"
        + ("  [rate/s]" if args.rate else "")
    )
    for key in sorted(series):
        vals = [p[1] for p in series[key]]
        if not vals:
            continue
        print(
            f"  {_spark(vals, args.width)}  "
            f"n={len(vals):<4d} min={min(vals):<10.6g} "
            f"max={max(vals):<10.6g} last={vals[-1]:<10.6g} {key}"
        )
    if not series:
        print("  no matching series")
    return 0


def _fmt_wall(t) -> str:
    try:
        return time.strftime("%H:%M:%S", time.localtime(float(t)))
    except (TypeError, ValueError, OverflowError):
        return "?"


def _print_quarantine_entries(entries: list, threshold) -> None:
    if not entries:
        print("quarantine empty", flush=True)
        return
    print(f"{'FINGERPRINT':36.36s} {'CRASHES':>7s} {'SINCE':20s} KINDS")
    import time as _time

    for ent in entries:
        since = ent.get("since")
        when = (
            _time.strftime("%Y-%m-%dT%H:%M:%S", _time.gmtime(float(since)))
            if since
            else "?"
        )
        kinds = ",".join(
            f"{k}={v}" for k, v in sorted((ent.get("kinds") or {}).items())
        )
        print(
            f"{str(ent.get('fingerprint', '?')):36.36s} "
            f"{ent.get('crashes', '?'):>7} {when:20s} {kinds}"
        )
    print(
        f"-- {len(entries)} quarantined (threshold {threshold}); "
        "release with: quarantine release FINGERPRINT",
        flush=True,
    )


def _cmd_quarantine(args: argparse.Namespace) -> int:
    """Poison-job quarantine: list / inspect / release, against a live
    daemon (socket) or a dead one's --state-dir (cold file read; release
    cold requires the daemon to be stopped)."""
    import json as _json

    action = args.quarantine_cmd
    fp = getattr(args, "fingerprint", None)
    if not args.state_dir and not args.socket:
        log.error("quarantine %s needs --socket or --state-dir", action)
        return USAGE_EXIT
    if args.state_dir:
        from .service.overload import QuarantineStore

        store = QuarantineStore(os.path.join(args.state_dir, "quarantine"))
        if action == "list":
            _print_quarantine_entries(store.list(), store.threshold)
            return 0
        if action == "inspect":
            info = store.get(fp)
            if info is None:
                log.error("%s is not quarantined", fp)
                return 1
            print(_json.dumps(info, sort_keys=True), flush=True)
            return 0
        released = store.release(fp)
        print(_json.dumps({"released": released, "fingerprint": fp}), flush=True)
        return 0 if released else 1

    from .service.client import (
        VerifydClient,
        VerifydError,
        VerifydUnavailable,
    )
    from .service.protocol import EXIT_PROTOCOL, EXIT_UNAVAILABLE

    try:
        client = VerifydClient(args.socket, secret=_read_secret(args))
    except ValueError as e:
        log.error("%s", e)
        return USAGE_EXIT
    try:
        reply = client.quarantine(action, fp)
    except VerifydUnavailable as e:
        log.error("cannot reach verifyd on %s: %s", args.socket, e.msg)
        return EXIT_UNAVAILABLE
    except VerifydError as e:
        log.error("quarantine %s refused: %s", action, e)
        return EXIT_PROTOCOL
    except (OSError, TimeoutError) as e:
        log.error("cannot reach verifyd on %s: %s", args.socket, e)
        return EXIT_UNAVAILABLE
    if action == "list":
        _print_quarantine_entries(
            reply.get("entries", []), reply.get("threshold", "?")
        )
        return 0
    print(_json.dumps(reply, sort_keys=True), flush=True)
    if action == "release" and not reply.get("released"):
        return 1
    return 0


#: export column order — stable so downstream scripts can rely on it.
_PROFILE_COLUMNS = (
    "t",
    "job",
    "client",
    "shape",
    "backend",
    "verdict",
    "wall_s",
    "queue_wait_s",
    "lease_wait_s",
    "ops",
    "shards",
    "fp",
)


def _profile_filters(args: argparse.Namespace) -> dict:
    return {
        k: v
        for k, v in {
            "shape": args.shape,
            "backend": args.backend,
            "client": args.client,
            "verdict": args.verdict,
            "since": args.since,
            "slowest": args.slowest,
            "limit": args.limit,
        }.items()
        if v is not None
    }


def _csv_cell(value) -> str:
    """One RFC-4180-safe cell: containers (the ``shards`` summary, op
    breakdowns) become JSON — their Python reprs hold commas and quotes
    that round-trip badly — and everything else is stringified for the
    writer to quote as needed."""
    import json as _json

    if isinstance(value, (dict, list, tuple)):
        return _json.dumps(value, sort_keys=True, default=str)
    if value is None:
        return ""
    return str(value)


def _export_profiles(records: list[dict], path, fmt: str) -> None:
    import json as _json

    if fmt == "jsonl":
        for rec in records:
            path.write(_json.dumps(rec, sort_keys=True))
            path.write("\n")
        return
    import csv as _csv

    # Explicit dialect: QUOTE_MINIMAL wraps any cell holding a comma,
    # quote, or newline (doubling embedded quotes per RFC 4180), and the
    # fixed "\n" terminator keeps stdout export ("-", opened without
    # newline="") from emitting \r\r\n on platforms that translate.
    w = _csv.writer(path, quoting=_csv.QUOTE_MINIMAL, lineterminator="\n")
    w.writerow(_PROFILE_COLUMNS)
    for rec in records:
        w.writerow([_csv_cell(rec.get(col, "")) for col in _PROFILE_COLUMNS])


def _cmd_profiles(args: argparse.Namespace) -> int:
    """Query the durable job-profile archive, live or cold."""
    filters = _profile_filters(args)
    if args.socket:
        from .service.client import (
            VerifydClient,
            VerifydError,
            VerifydUnavailable,
        )
        from .service.protocol import EXIT_PROTOCOL, EXIT_UNAVAILABLE

        try:
            client = VerifydClient(args.socket, secret=_read_secret(args))
        except ValueError as e:
            log.error("%s", e)
            return USAGE_EXIT
        try:
            reply = client.profiles(**filters)
        except VerifydUnavailable as e:
            log.error("cannot reach verifyd on %s: %s", args.socket, e.msg)
            return EXIT_UNAVAILABLE
        except VerifydError as e:
            log.error("profile query refused: %s", e)
            return EXIT_PROTOCOL
        except (OSError, TimeoutError) as e:
            log.error("cannot reach verifyd on %s: %s", args.socket, e)
            return EXIT_UNAVAILABLE
        records = reply.get("records", [])
        total = reply.get("total", len(records))
    elif args.state_dir:
        from .obs.archive import filter_records, read_archive

        if not os.path.isdir(args.state_dir):
            log.error("state dir %s does not exist", args.state_dir)
            return USAGE_EXIT
        archived = read_archive(args.state_dir)
        records = filter_records(archived, **filters)
        total = len(archived)
    else:
        log.error("profiles needs --socket (live) or --state-dir (cold)")
        return USAGE_EXIT

    if args.export:
        fmt = args.format
        if args.export == "-":
            _export_profiles(records, sys.stdout, fmt)
        else:
            newline = "" if fmt == "csv" else None
            with open(
                args.export, "w", encoding="utf-8", newline=newline
            ) as f:
                _export_profiles(records, f, fmt)
            log.info(
                "exported %d of %d archived profiles to %s (%s)",
                len(records),
                total,
                args.export,
                fmt,
            )
        return 0

    if not records:
        print(f"no matching records ({total} archived)", flush=True)
        return 0
    hdr = (
        f"{'when':19s} {'job':>6s} {'client':12s} {'shape':28s} "
        f"{'backend':18s} {'vd':>2s} {'wall_ms':>9s} {'queue_ms':>9s} "
        f"{'lease_ms':>9s}"
    )
    print(hdr)
    for rec in records:
        when = time.strftime(
            "%Y-%m-%d %H:%M:%S", time.localtime(float(rec.get("t", 0.0)))
        )
        wall = float(rec.get("wall_s") or 0.0) * 1e3
        qw = float(rec.get("queue_wait_s") or 0.0) * 1e3
        lw = float(rec.get("lease_wait_s") or 0.0) * 1e3
        print(
            f"{when:19s} {str(rec.get('job', '?')):>6s} "
            f"{str(rec.get('client', '?')):12.12s} "
            f"{str(rec.get('shape', '?')):28.28s} "
            f"{str(rec.get('backend', '?')):18.18s} "
            f"{str(rec.get('verdict', '?')):>2s} {wall:9.1f} {qw:9.1f} "
            f"{lw:9.1f}"
        )
    print(f"-- {len(records)} of {total} archived records", flush=True)
    return 0


def _cmd_trace(args: argparse.Namespace) -> int:
    from .service.client import (
        VerifydClient,
        VerifydError,
        VerifydUnavailable,
    )
    from .service.protocol import EXIT_PROTOCOL, EXIT_UNAVAILABLE

    try:
        client = VerifydClient(args.socket, secret=_read_secret(args))
    except ValueError as e:
        log.error("%s", e)
        return USAGE_EXIT
    try:
        trace = client.trace()
    except VerifydUnavailable as e:
        log.error("cannot reach verifyd on %s: %s", args.socket, e.msg)
        return EXIT_UNAVAILABLE
    except VerifydError as e:
        log.error("trace fetch refused: %s", e)
        return EXIT_PROTOCOL
    except (OSError, TimeoutError) as e:
        log.error("cannot reach verifyd on %s: %s", args.socket, e)
        return EXIT_UNAVAILABLE

    import json as _json

    warning = (trace.get("otherData") or {}).get("warning")
    if warning:
        log.warning("%s", warning)
    text = _json.dumps(trace)
    if args.out == "-":
        print(text, flush=True)
    else:
        with open(args.out, "w", encoding="utf-8") as f:
            f.write(text)
            f.write("\n")
        log.info(
            "wrote %d trace events to %s (load in ui.perfetto.dev or "
            "chrome://tracing)",
            len(trace.get("traceEvents", [])),
            args.out,
        )
    return 0


_SPARK_BLOCKS = "▁▂▃▄▅▆▇█"


def _spark(values: list[float], width: int = 32) -> str:
    """Unicode sparkline over the last ``width`` values (terminal `top`
    aesthetics; empty history renders as spaces, flat history as ▁s)."""
    vals = [float(v) for v in values][-width:]
    if not vals:
        return " " * width
    lo, hi = min(vals), max(vals)
    span = hi - lo
    out = []
    for v in vals:
        idx = 0 if span <= 0 else int((v - lo) / span * (len(_SPARK_BLOCKS) - 1))
        out.append(_SPARK_BLOCKS[idx])
    return "".join(out).rjust(width)


def _cmd_dash(args: argparse.Namespace) -> int:
    """`verifyd top`: poll the stats op and render terminal sparklines."""
    from .service.client import (
        VerifydClient,
        VerifydError,
        VerifydUnavailable,
    )
    from .service.protocol import EXIT_PROTOCOL, EXIT_UNAVAILABLE

    try:
        client = VerifydClient(args.socket, secret=_read_secret(args))
    except ValueError as e:
        log.error("%s", e)
        return USAGE_EXIT

    hist: dict[str, list[float]] = {
        "throughput": [],
        "queue": [],
        "active": [],
        "rss_mb": [],
        "compiles": [],
    }
    prev_completed: float | None = None
    prev_compiles: float | None = None
    prev_t: float | None = None
    n = 0
    try:
        while True:
            try:
                snap = client.stats()
            except VerifydUnavailable as e:
                log.error("cannot reach verifyd on %s: %s", args.socket, e.msg)
                return EXIT_UNAVAILABLE
            except VerifydError as e:
                log.error("stats refused: %s", e)
                return EXIT_PROTOCOL
            except (OSError, TimeoutError) as e:
                log.error("cannot reach verifyd on %s: %s", args.socket, e)
                return EXIT_UNAVAILABLE
            now = time.time()
            completed = float(snap.get("completed", 0))
            intro = snap.get("introspection") or {}
            jit = intro.get("jit") or {}
            compiles = float(sum((jit.get("compiles") or {}).values()))
            if prev_t is not None and now > prev_t:
                hist["throughput"].append(
                    max(0.0, completed - (prev_completed or 0.0)) / (now - prev_t)
                )
                hist["compiles"].append(max(0.0, compiles - (prev_compiles or 0.0)))
            prev_completed, prev_compiles, prev_t = completed, compiles, now
            hist["queue"].append(float(snap.get("queue_depth_now", 0)))
            hist["active"].append(float(snap.get("active", 0)))
            res = (intro.get("resources") or {}).get("last") or {}
            hist["rss_mb"].append(float(res.get("rss_bytes", 0) or 0) / (1 << 20))
            for k in hist:
                hist[k] = hist[k][-args.width :]

            lines = [
                "verifyd dash  socket=%s  uptime=%.0fs  completed=%d  "
                "cache_hits=%d  errors=%d"
                % (
                    args.socket,
                    float(snap.get("uptime_s", 0.0)),
                    int(snap.get("completed", 0)),
                    int(snap.get("cache_hits", 0)),
                    int(snap.get("errors", 0)),
                )
            ]
            rows = (
                ("throughput", "jobs/s", hist["throughput"]),
                ("queue", "depth", hist["queue"]),
                ("active", "jobs", hist["active"]),
                ("rss", "MiB", hist["rss_mb"]),
                ("compiles", "per tick", hist["compiles"]),
            )
            for name, unit, series in rows:
                cur = series[-1] if series else 0.0
                lines.append(
                    "  %-10s %s  %10.2f %s"
                    % (name, _spark(series, args.width), cur, unit)
                )
            storms = int(snap.get("retrace_storms", 0))
            if storms:
                lines.append("  !! retrace storms latched: %d" % storms)
            active_searches = [
                r
                for r in (snap.get("progress") or [])
                if isinstance(r, dict) and not r.get("done")
            ]
            for r in active_searches[:8]:
                eta = r.get("eta_s")
                lines.append(
                    "  >> job=%-5s %-11s %5.1f%%  %s/%s ops  eta=%s"
                    % (
                        r.get("job"),
                        r.get("engine") or "?",
                        100.0 * float(r.get("progress_ratio") or 0.0),
                        r.get("ops_committed"),
                        r.get("total_ops"),
                        "%.0fs" % float(eta) if eta is not None else "?",
                    )
                )
            print("\n".join(lines), flush=True)
            n += 1
            if args.iterations and n >= args.iterations:
                return 0
            time.sleep(max(0.1, args.interval))
    except KeyboardInterrupt:
        return 0


def _cmd_watch(args: argparse.Namespace) -> int:
    """`verifyd watch`: live progress board for running searches.

    Polls the ``watch`` op (daemon or router — the router fans out and
    aggregates distributed partitions) and renders one frame per poll:
    per-job progress ratio with a climbing sparkline, committed/total
    ops, frontier width, ops rate and the EWMA-smoothed ETA.  A named
    selector that was visible and then answers the definite
    ``UnknownJob`` means the job finished — that's a clean exit, not an
    error.
    """
    from .service.client import (
        VerifydClient,
        VerifydError,
        VerifydUnavailable,
    )
    from .service.protocol import (
        ERR_UNKNOWN_JOB,
        EXIT_PROTOCOL,
        EXIT_UNAVAILABLE,
    )

    try:
        client = VerifydClient(args.socket, secret=_read_secret(args))
    except ValueError as e:
        log.error("%s", e)
        return USAGE_EXIT
    import json as _json

    ratios: dict[tuple, list[float]] = {}
    seen = False
    n = 0
    try:
        while True:
            try:
                got = client.watch(
                    job=args.job,
                    fingerprint=args.fingerprint,
                    search=args.search,
                    part=args.part,
                )
            except VerifydUnavailable as e:
                log.error("cannot reach verifyd on %s: %s", args.socket, e.msg)
                return EXIT_UNAVAILABLE
            except VerifydError as e:
                if e.cls == ERR_UNKNOWN_JOB:
                    if seen:
                        # It was on the board and now is not: it finished.
                        log.info("watched job left the progress surface (done)")
                        return 0
                    log.error("nothing to watch: %s", e.msg)
                    return EXIT_PROTOCOL
                log.error("watch refused: %s", e)
                return EXIT_PROTOCOL
            except (OSError, TimeoutError) as e:
                log.error("cannot reach verifyd on %s: %s", args.socket, e)
                return EXIT_UNAVAILABLE

            rows = [r for r in got.get("progress") or [] if isinstance(r, dict)]
            seen = seen or bool(rows)
            if args.json:
                print(_json.dumps(got, sort_keys=True), flush=True)
            else:
                lines = [
                    "verifyd watch  socket=%s  %d job(s)"
                    % (args.socket, len(rows))
                ]
                for r in rows:
                    key = (r.get("node"), r.get("job"))
                    ratio = float(r.get("progress_ratio") or 0.0)
                    ratios.setdefault(key, []).append(ratio)
                    ratios[key] = ratios[key][-args.width :]
                    eta = r.get("eta_s")
                    lines.append(
                        "  job=%-5s %-11s %s %5.1f%%  %s/%s ops  "
                        "width=%-6s rate=%8.1f/s  eta=%s%s"
                        % (
                            r.get("job"),
                            r.get("engine") or "?",
                            _spark(ratios[key], args.width),
                            100.0 * ratio,
                            r.get("ops_committed"),
                            r.get("total_ops"),
                            r.get("frontier_width"),
                            float(r.get("ops_rate") or 0.0),
                            "%.0fs" % float(eta) if eta is not None else "?",
                            "  node=%s" % r["node"] if r.get("node") else "",
                        )
                    )
                dist = got.get("distributed")
                if dist:
                    lines.append(
                        "  distributed %s  epoch=%s  %d partition(s)"
                        % (
                            str(dist.get("search", ""))[:16],
                            dist.get("epoch"),
                            len(dist.get("partitions") or {}),
                        )
                    )
                    for part, row in sorted(
                        (dist.get("partitions") or {}).items()
                    ):
                        lines.append(
                            "    part %s  node=%s  ops=%s  stalled=%.1fs"
                            % (
                                part,
                                row.get("node"),
                                row.get("ops_committed"),
                                float(row.get("stalled_s") or 0.0),
                            )
                        )
                print("\n".join(lines), flush=True)
            n += 1
            if args.iterations and n >= args.iterations:
                return 0
            time.sleep(max(0.1, args.interval))
    except KeyboardInterrupt:
        return 0


def _cmd_submit(args: argparse.Namespace) -> int:
    from .service.client import (
        VerifydBusy,
        VerifydClient,
        VerifydError,
        VerifydRefused,
        VerifydUnavailable,
    )
    from .service.protocol import EXIT_BUSY, EXIT_PROTOCOL, EXIT_UNAVAILABLE

    if args.file == "-":
        text = sys.stdin.read()
    else:
        try:
            with open(args.file, encoding="utf-8") as f:
                text = f.read()
        except OSError as e:
            log.error("failed to read history: %s", e)
            return USAGE_EXIT
    try:
        client = VerifydClient(args.socket, secret=_read_secret(args))
    except ValueError as e:
        log.error("%s", e)
        return USAGE_EXIT
    try:
        reply = client.submit_with_retry(
            text,
            client=args.client,
            priority=args.priority,
            no_viz=args.no_viz or None,
            timeout=args.timeout,
            retries=args.retries,
            backoff_s=args.backoff,
            deadline_s=args.deadline,
            distributed=args.distributed,
        )
    except VerifydBusy as e:
        log.error(
            "verifyd is at capacity (%s); retry after ~%.1fs",
            e.msg,
            e.retry_after_s,
        )
        return EXIT_BUSY
    except VerifydUnavailable as e:
        log.error("cannot reach verifyd on %s: %s", args.socket, e.msg)
        return EXIT_UNAVAILABLE
    except VerifydRefused as e:
        log.error("verifyd on %s refused: %s", args.socket, e)
        return EXIT_PROTOCOL
    except VerifydError as e:
        if e.cls == "DecodeError":
            log.error("daemon rejected the history: %s", e.msg)
            return USAGE_EXIT
        # The daemon answered — an internal failure is a refusal, not
        # unavailability (exit 76, not 69).
        log.error("submit failed: %s", e)
        return EXIT_PROTOCOL
    except (OSError, TimeoutError) as e:
        log.error("cannot reach verifyd on %s: %s", args.socket, e)
        return EXIT_UNAVAILABLE

    if args.stats:
        import json as _json

        line = {
            "file": args.file,
            "outcome": reply.get("outcome"),
            "backend": reply.get("backend"),
            "wall_s": reply.get("wall_s"),
            "queue_wait_s": reply.get("queue_wait_s"),
            "ops": reply.get("ops"),
            "cached": reply.get("cached", False),
            "shape": reply.get("shape"),
            "trace_id": reply.get("trace_id"),
        }
        print(_json.dumps(line), flush=True)
    art = reply.get("artifact")
    if art:
        log.info("visualization: %s", art)
    if reply.get("trace_id"):
        log.info("trace_id: %s", reply["trace_id"])
    verdict = reply.get("verdict")
    outcome = reply.get("outcome")
    if verdict == 0:
        log.info(
            "history is linearizable (%s, %ss%s)",
            reply.get("backend"),
            reply.get("wall_s"),
            ", cached" if reply.get("cached") else "",
        )
    elif verdict == 1:
        log.error("history is NOT linearizable (%s)", reply.get("backend"))
    else:
        log.error("check inconclusive (outcome %s)", outcome)
    return verdict if verdict in (0, 1, 2) else USAGE_EXIT


def _iter_follow_windows(lines, window_events: int):
    """Cut a JSONL event stream into prefix-closed windows.

    Yields ``(window_lines, dangling)`` chunks: a window is flushed only
    when every call in the buffer has returned (no op spans the cut) and
    at least ``window_events`` lines accumulated.  The final chunk
    carries whatever remains at EOF — ``dangling`` is the set of op ids
    still open there (a truncated tail the daemon would refuse).
    """
    import json as _json

    from .utils import events as ev

    buf: list = []
    open_ops: set = set()
    for raw in lines:
        line = raw.strip()
        if not line:
            continue
        le = ev.decode_obj(_json.loads(line))
        if le.is_start:
            open_ops.add((le.client_id, le.op_id))
        else:
            open_ops.discard((le.client_id, le.op_id))
        buf.append(line)
        if not open_ops and len(buf) >= window_events:
            yield buf, set()
            buf = []
    if buf:
        yield buf, set(open_ops)


def _cmd_follow(args: argparse.Namespace) -> int:
    import json as _json

    from .service.client import (
        VerifydBusy,
        VerifydClient,
        VerifydError,
        VerifydRefused,
        VerifydUnavailable,
    )
    from .service.protocol import (
        ERR_FRONTIER,
        EXIT_BUSY,
        EXIT_PROTOCOL,
        EXIT_UNAVAILABLE,
    )
    from .utils import events as ev

    if args.file == "-":
        source = sys.stdin
        close = False
    else:
        try:
            source = open(args.file, encoding="utf-8")
            close = True
        except OSError as e:
            log.error("failed to read history: %s", e)
            return USAGE_EXIT
    try:
        client = VerifydClient(args.socket, secret=_read_secret(args))
    except ValueError as e:
        log.error("%s", e)
        if close:
            source.close()
        return USAGE_EXIT

    frontier = args.frontier
    committed: list = []  # every line already verified — the resync body
    window = 0
    attempts = 1 + max(0, args.window_retries)
    try:
        for chunk, dangling in _iter_follow_windows(source, args.window):
            if dangling:
                log.warning(
                    "stream tail has %d call(s) with no return — an op "
                    "would span the window cut; skipping the last %d "
                    "line(s)",
                    len(dangling),
                    len(chunk),
                )
                break
            text = "\n".join(chunk) + "\n"
            # A window is only committed once its ops are actually
            # carried into the frontier: an inconclusive verdict (e.g.
            # deadline expiry) or a refused end-of-window snapshot
            # leaves the frontier at the previous cut, and moving on
            # anyway would silently drop this window's ops from the
            # verified lineage — later windows would report OK for a
            # stream-so-far that never included them.  Retry by
            # resyncing (committed + chunk as a fresh lineage); if the
            # window still won't carry, stop with the inconclusive exit
            # code instead of following a broken lineage.
            for attempt in range(attempts):
                resync = attempt > 0
                try:
                    if not resync:
                        try:
                            reply = client.follow(
                                text,
                                stream=args.stream,
                                frontier=frontier,
                                client=args.client,
                                priority=args.priority,
                                timeout=args.timeout,
                                deadline_s=args.deadline,
                            )
                        except VerifydError as e:
                            if e.cls != ERR_FRONTIER:
                                raise
                            # The daemon no longer knows our frontier
                            # (evicted, restarted without state, or a
                            # router moved the stream): resync by
                            # replaying the whole committed stream plus
                            # this window as a fresh lineage.
                            log.warning(
                                "frontier unknown at window %d — "
                                "resyncing with %d committed line(s)",
                                window,
                                len(committed),
                            )
                            resync = True
                    if resync:
                        reply = client.follow(
                            "\n".join(committed + chunk) + "\n",
                            stream=args.stream,
                            frontier=None,
                            client=args.client,
                            priority=args.priority,
                            timeout=args.timeout,
                            deadline_s=args.deadline,
                        )
                except VerifydBusy as e:
                    log.error(
                        "verifyd is at capacity (%s); retry after ~%.1fs",
                        e.msg,
                        e.retry_after_s,
                    )
                    return EXIT_BUSY
                except VerifydUnavailable as e:
                    log.error(
                        "cannot reach verifyd on %s: %s", args.socket, e.msg
                    )
                    return EXIT_UNAVAILABLE
                except VerifydError as e:
                    if e.cls == "DecodeError":
                        log.error("daemon rejected the window: %s", e.msg)
                        return USAGE_EXIT
                    log.error("follow failed: %s", e)
                    return EXIT_PROTOCOL

                verdict = reply.get("verdict")
                if args.stats:
                    print(
                        _json.dumps(
                            {
                                "stream": args.stream,
                                "window": window,
                                "attempt": attempt,
                                "ops": reply.get("ops"),
                                "ops_total": reply.get("ops_total"),
                                "verdict": verdict,
                                "backend": reply.get("backend"),
                                "frontier": reply.get("frontier"),
                                "advanced": reply.get("advanced"),
                                "wall_s": reply.get("wall_s"),
                            }
                        ),
                        flush=True,
                    )
                if verdict == 1:
                    log.error(
                        "stream %s is NOT linearizable at window %d "
                        "(%d ops total)",
                        args.stream,
                        window,
                        reply.get("ops_total") or 0,
                    )
                    return 1
                # Carried: OK with the frontier advanced through this
                # window's ops — or an all-trivial window, which has
                # nothing a frontier could absorb (elided ops cannot
                # change any later verdict).
                if verdict == 0 and (
                    reply.get("advanced") or not reply.get("ops")
                ):
                    break
                log.warning(
                    "window %d not carried (verdict %s, outcome %s, "
                    "advanced=%s)%s",
                    window,
                    verdict,
                    reply.get("outcome"),
                    bool(reply.get("advanced")),
                    "; retrying as a resync" if attempt + 1 < attempts else "",
                )
            else:
                log.error(
                    "window %d never carried into the frontier after %d "
                    "attempt(s) — stopping (%d ops verified so far)",
                    window,
                    attempts,
                    reply.get("ops_total") or len(committed),
                )
                return 2
            log.info(
                "window %d ok: %s ops carried to %s ops total (%s)",
                window,
                reply.get("ops"),
                reply.get("ops_total"),
                reply.get("backend"),
            )
            committed.extend(chunk)
            if reply.get("advanced") and reply.get("frontier"):
                frontier = reply["frontier"]
            window += 1
    except (ev.DecodeError, ValueError) as e:
        log.error("undecodable stream line: %s", e)
        return USAGE_EXIT
    except (OSError, TimeoutError) as e:
        log.error("cannot reach verifyd on %s: %s", args.socket, e)
        return EXIT_UNAVAILABLE
    finally:
        if close:
            source.close()
    if window == 0:
        log.error("stream held no closed window — nothing verified")
        return USAGE_EXIT
    log.info(
        "stream %s: %d window(s) verified, frontier %s",
        args.stream,
        window,
        frontier,
    )
    return 0


def _cmd_soak(args: argparse.Namespace) -> int:
    import json as _json

    from .collector.campaign import get_campaign
    from .service.soak import SoakConfig, SoakRunner, soak_exit_code

    for name in args.campaign or ():
        try:
            get_campaign(name)
        except KeyError as e:
            log.error("%s", e.args[0])
            return USAGE_EXIT
    try:
        secret = _read_secret(args)
    except OSError as e:
        log.error("failed to read secret: %s", e)
        return USAGE_EXIT
    cfg = SoakConfig(
        address=args.socket,
        secret=secret,
        campaigns=tuple(args.campaign or ()),
        seed=args.seed,
        cycles=args.cycles,
        clients=args.num_concurrent_clients,
        ops=args.num_ops_per_client,
        retries=args.retries,
        backoff_s=args.backoff,
        submit_timeout_s=args.timeout,
        deadline_s=args.deadline,
        alert_url=args.alert_url,
        state_dir=args.state_dir,
        mislabel_first=args.mislabel_control,
    )
    runner = SoakRunner(cfg)
    server = None
    if args.metrics_port is not None:
        from .obs.httpd import MetricsServer

        server = MetricsServer(runner.registry, args.metrics_port)
        log.info("soak metrics at %s", server.url)
    try:
        summary = runner.run()
    finally:
        if server is not None:
            server.close()
    code = soak_exit_code(summary)
    if args.json:
        print(_json.dumps(summary, sort_keys=True), flush=True)
    else:
        line = {
            "generated": summary["generated"],
            "submitted": summary["submitted"],
            "ok": summary["ok"],
            "false_verdicts": len(summary["false_verdicts"]),
            "submit_errors": len(summary["submit_errors"]),
            "inconclusive": summary["inconclusive"],
            "unlabeled": summary["unlabeled"],
            "verdict_table": summary["verdict_table"],
            "wall_s": summary["wall_s"],
        }
        print(_json.dumps(line, sort_keys=True), flush=True)
    if code == 0:
        log.info(
            "soak clean: %d/%d verdicts matched ground truth",
            summary["ok"],
            summary["submitted"],
        )
    elif code == 1:
        for fv in summary["false_verdicts"]:
            log.error(
                "false verdict: campaign=%s seed=%d expected=%s actual=%s "
                "fingerprint=%s",
                fv["campaign"],
                fv["seed"],
                fv["expect"],
                fv["actual"],
                fv.get("fingerprint"),
            )
    else:
        log.error(
            "soak inconclusive: %d submit errors, %d UNKNOWN verdicts, "
            "%d unlabeled skips",
            len(summary["submit_errors"]),
            summary["inconclusive"],
            summary["unlabeled"],
        )
    return code


def _lint_root() -> str:
    """The repo root: the directory holding the package directory."""
    return os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _lint_changed_paths(root: str) -> list[str] | None:
    """git-diff-scoped .py paths (worktree + index + untracked), or None
    when git is unavailable — caller falls back to the full tree."""
    import subprocess

    try:
        out = subprocess.run(
            ["git", "status", "--porcelain"],
            cwd=root,
            capture_output=True,
            text=True,
            timeout=10,
            check=True,
        ).stdout
    except (OSError, subprocess.SubprocessError):
        return None
    paths: list[str] = []
    for line in out.splitlines():
        if len(line) < 4:
            continue
        p = line[3:].split(" -> ")[-1].strip().strip('"')
        if p.endswith(".py") and p.startswith("s2_verification_tpu/"):
            if os.path.exists(os.path.join(root, p)):
                paths.append(p)
    return paths


def _cmd_lint(args: argparse.Namespace) -> int:
    import json as _json

    from .analysis import LintEngine, load_baseline, write_baseline
    from .analysis.engine import apply_baseline, discover_files
    from .analysis.event_schema import render_events_md
    from .analysis.engine import TreeContext

    root = _lint_root()
    baseline_path = args.baseline or os.path.join(root, ".verifylint-baseline.json")
    cache_path = None if args.no_cache else os.path.join(root, ".verifylint-cache.json")

    if args.changed:
        rels = _lint_changed_paths(root)
        if rels is None:
            log.warning("lint --changed: git unavailable, scanning the full tree")
            rels = discover_files(root, args.paths or None)
    else:
        rels = discover_files(root, args.paths or None)

    # --events-md / --check-events-md always read the whole package —
    # a partial scan would render a partial registry.
    if args.events_md is not None or args.check_events_md:
        ctx = TreeContext(root, discover_files(root, None))
        rendered = render_events_md(ctx)
        if args.events_md is not None:
            if args.events_md == "-":
                sys.stdout.write(rendered)
            else:
                out_path = (
                    args.events_md
                    if os.path.isabs(args.events_md)
                    else os.path.join(root, args.events_md)
                )
                with open(out_path, "w", encoding="utf-8") as f:
                    f.write(rendered)
                print(f"wrote {out_path}")
        if args.check_events_md:
            committed = os.path.join(root, "docs", "EVENTS.md")
            try:
                with open(committed, encoding="utf-8") as f:
                    on_disk = f.read()
            except OSError:
                on_disk = ""
            if on_disk != rendered:
                log.error(
                    "docs/EVENTS.md is stale — regenerate with "
                    "`lint --events-md docs/EVENTS.md`"
                )
                return 1
            print("docs/EVENTS.md is up to date")
        return 0

    full_tree = not args.changed and not args.paths
    engine = LintEngine(root, cache_path=cache_path)
    res = engine.run(rel_paths=rels)

    if args.write_baseline:
        if not full_tree:
            log.error("--write-baseline needs a full-tree run (no --changed/paths)")
            return USAGE_EXIT
        write_baseline(res.findings, baseline_path)
        print(
            f"wrote {baseline_path} "
            f"({sum(1 for f in res.findings if f.severity == 'error')} errors baselined; "
            "add a justification to every entry)"
        )
        return 0

    baseline = load_baseline(baseline_path)
    ratchet = apply_baseline(res.findings, baseline)

    if args.json:
        doc = {
            "files": res.files,
            "suppressed": res.suppressed,
            "cache_hits": res.cache_hits,
            "findings": [f.to_dict() for f in res.findings],
            "new_errors": [f.to_dict() for f in ratchet.new_errors],
            "baselined": len(ratchet.baselined),
            "stale_baseline_keys": ratchet.stale_keys,
        }
        print(_json.dumps(doc, indent=2))
    else:
        baselined_keys = {f.key for f in ratchet.baselined}
        shown = 0
        for f in res.findings:
            tag = " (baselined)" if f.severity == "error" and f.key in baselined_keys else ""
            print(f"{f.path}:{f.line}: {f.severity}: [{f.rule}] {f.message}{tag}")
            shown += 1
        n_err = sum(1 for f in res.findings if f.severity == "error")
        print(
            f"{shown} finding(s) in {res.files} file(s): {n_err} error(s) "
            f"({len(ratchet.new_errors)} new, {len(ratchet.baselined)} baselined), "
            f"{res.suppressed} suppressed, {res.cache_hits} cache hit(s)"
        )
        if full_tree:
            for key in ratchet.stale_keys:
                print(
                    f"stale baseline entry (debt paid down — shrink with "
                    f"--write-baseline): {key}"
                )
    return 1 if ratchet.new_errors else 0


def build_parser() -> argparse.ArgumentParser:
    p = _Parser(
        prog="s2-verification-tpu",
        description="TPU-native S2 linearizability verification framework",
    )
    p.add_argument(
        "-version", "--version", action="version", version=_version.__version__
    )
    sub = p.add_subparsers(dest="cmd", required=True)

    c = sub.add_parser("check", help="check a JSONL history for linearizability")
    c.add_argument(
        "-file",
        "--file",
        required=True,
        help="history JSONL path, '-' for stdin; a directory or (quoted) "
        "glob checks the whole corpus in one process (compiles amortize "
        "via shape bucketing) — exit code is the worst outcome (ILLEGAL "
        "> unreadable file > UNKNOWN > OK)",
    )
    c.add_argument(
        "-backend",
        "--backend",
        default="auto",
        choices=["oracle", "native", "frontier", "device", "auto"],
    )
    c.add_argument(
        "-time-budget",
        "--time-budget",
        type=float,
        default=None,
        help="CPU-engine time budget in seconds; 0 = run to completion, the "
        "reference's unbounded default (auto backend default: 10)",
    )
    c.add_argument("-out-dir", "--out-dir", default="./porcupine-outputs")
    c.add_argument(
        "-checkpoint",
        "--checkpoint",
        default=None,
        help="snapshot file for long device searches (resume + preemption safety)",
    )
    c.add_argument(
        "-device-rows",
        "--device-rows",
        type=int,
        default=None,
        help="device-resident frontier cap for the device search's "
        "exhaustive phase (default 2^23; the chunked tier engages only "
        "above the 2^20 exhaustive bucket — smaller values, or 0, disable "
        "it)",
    )
    c.add_argument(
        "--prune",
        action="store_true",
        help="verdict-exact search pruning (checker/prune.py): forced "
        "append order, eager commit of inert/passing-filter ops, "
        "tail-pin dead-configuration elimination — same verdicts, "
        "smaller search (parity gated by `make prune`)",
    )
    c.add_argument(
        "--speculate-depth",
        type=int,
        default=0,
        metavar="K",
        help="speculative multi-layer expansion for the device search: "
        "one K-layer dive per launch, wholesale-discarded on "
        "misprediction (0 = off; disabled for witness-carrying runs)",
    )
    c.add_argument(
        "-no-viz", "--no-viz", action="store_true", help="skip the HTML artifact"
    )
    c.add_argument(
        "-stats",
        "--stats",
        action="store_true",
        help="print one machine-readable JSON line (verdict, wall-clock, "
        "search statistics) on stdout",
    )
    c.add_argument(
        "-profile",
        "--profile",
        default=None,
        metavar="OUT.json",
        help="write a search-shape profile JSON (FrontierStats + per-layer "
        "timeline; native backend: per-phase wall attribution) — the same "
        "schema verifyd attaches to its done events",
    )
    c.set_defaults(fn=_cmd_check)

    g = sub.add_parser("collect", help="collect a history against the fake S2")
    g.add_argument(
        "basin",
        nargs="?",
        default="local",
        help="ignored (collection runs against the in-process fake S2)",
    )
    g.add_argument(
        "stream",
        nargs="?",
        default="stream",
        help="ignored (collection runs against the in-process fake S2)",
    )
    g.add_argument(
        "--num-concurrent-clients",
        type=int,
        default=None,
        help="default 5 (or the campaign's own sizing with --campaign)",
    )
    g.add_argument(
        "--num-ops-per-client",
        type=int,
        default=None,
        help="default 100 (or the campaign's own sizing with --campaign)",
    )
    g.add_argument(
        "--workflow",
        default=None,
        choices=["regular", "match-seq-num", "fencing"],
        help="default regular; a --campaign dictates its own workflow",
    )
    g.add_argument("--seed", type=int, default=0)
    g.add_argument(
        "--chaos",
        type=float,
        default=0.2,
        help="fault-injection intensity for the fake S2 (0 disables)",
    )
    g.add_argument(
        "--campaign",
        metavar="NAME",
        help="run one named fault campaign (time-phased faults, optional "
        "deliberate violation) and write a ground-truth "
        "<path>.label.json sidecar (expect=legal|illegal + the injected "
        "violation class) next to the history",
    )
    g.add_argument(
        "--list-campaigns",
        action="store_true",
        help="list the builtin campaign matrix and exit",
    )
    g.add_argument("--out-dir", default="./data")
    g.add_argument(
        "--socket",
        metavar="PATH",
        help="collect over a loopback unix-domain socket at PATH (serves "
        "the fault-injecting stream from another thread) instead of the "
        "in-process call path",
    )
    g.set_defaults(fn=_cmd_collect)

    s = sub.add_parser(
        "serve", help="run verifyd, the resident verification daemon"
    )
    s.add_argument(
        "-socket", "--socket", required=True, help="unix-domain socket path"
    )
    s.add_argument(
        "--queue-depth",
        type=int,
        default=64,
        help="admission-queue bound; a full queue rejects with retry-after "
        "instead of buffering (default 64)",
    )
    s.add_argument("--workers", type=int, default=1)
    s.add_argument(
        "--batch-max",
        type=int,
        default=16,
        help="max jobs per shape group a worker drains back to back",
    )
    s.add_argument(
        "-time-budget",
        "--time-budget",
        type=float,
        default=10.0,
        help="per-job CPU budget in seconds before device escalation; "
        "0 = unbounded CPU, no escalation (default 10)",
    )
    s.add_argument(
        "--device",
        default="supervised",
        choices=["supervised", "inline", "off"],
        help="device escalation: 'supervised' (bounded child + checkpoint "
        "resume; a wedged TPU degrades the job to CPU), 'inline' "
        "(in-process), 'off'",
    )
    s.add_argument("-out-dir", "--out-dir", default="./porcupine-outputs")
    s.add_argument(
        "-no-viz",
        "--no-viz",
        action="store_true",
        help="default jobs to skipping the HTML artifact",
    )
    s.add_argument(
        "--stats-log",
        default="-",
        help="structured per-job event sink: a path, '-' for stderr "
        "(default), or '' to silence",
    )
    s.add_argument(
        "-device-rows",
        "--device-rows",
        type=int,
        default=None,
        help="device-resident frontier cap for escalated jobs",
    )
    s.add_argument(
        "--tcp",
        default=None,
        metavar="HOST:PORT",
        help="also listen on an authenticated TCP address (port 0 = "
        "ephemeral); every frame carries an HMAC under the shared secret "
        "(--secret-file / VERIFYD_SECRET) and unauthenticated frames are "
        "rejected before admission",
    )
    s.add_argument(
        "--secret-file",
        default=None,
        help="file holding the TCP shared secret (whitespace-stripped); "
        "falls back to the VERIFYD_SECRET environment variable",
    )
    s.add_argument(
        "--state-dir",
        default=None,
        help="durable-state directory (verdict-cache segments + admission "
        "journal): a restarted daemon answers previously decided "
        "histories from disk and re-runs jobs that were accepted but "
        "never answered (default: in-memory only)",
    )
    s.add_argument(
        "--fsync",
        action="store_true",
        help="fsync every durable append (survives machine crashes, not "
        "just daemon death; slower)",
    )
    s.add_argument(
        "--metrics-port",
        type=int,
        default=None,
        metavar="PORT",
        help="serve Prometheus text metrics on http://127.0.0.1:PORT/metrics "
        "(0 = ephemeral port, logged at startup; default: off)",
    )
    s.add_argument(
        "--trace-capacity",
        type=int,
        default=8192,
        metavar="SPANS",
        help="in-memory span-ring capacity for the `trace` op (0 disables "
        "tracing; default 8192)",
    )
    s.add_argument(
        "-profile",
        "--profile",
        action="store_true",
        help="attach a per-job search-shape profile (FrontierStats + "
        "per-layer timeline) to every done event and submit reply",
    )
    s.add_argument(
        "-mesh-devices",
        "--mesh-devices",
        default=None,
        metavar="N|auto",
        help="device-pool size for mesh-sharded escalations: escalating "
        "jobs lease a power-of-two chip set sized by job shape and run "
        "the frontier search sharded over exactly those chips, reported "
        "as backend device-mesh[N] ('auto' = every visible device; "
        "default: off — single-chip escalation). Under JAX_PLATFORMS=cpu "
        "a numeric N provisions N virtual devices via XLA_FLAGS.",
    )
    s.add_argument(
        "--log-format",
        default="text",
        choices=["text", "json"],
        help="structured-log line format for daemon diagnostics and the "
        "stats-log '-' fallback: human 'text' (default) or one JSON "
        "object per line for log shippers",
    )
    s.add_argument(
        "--slo-target",
        type=float,
        default=0.99,
        metavar="FRACTION",
        help="SLO availability target driving /healthz and slo_breach "
        "events (default 0.99; 1.0 disables burn-rate math)",
    )
    s.add_argument(
        "--slo-latency-target",
        type=float,
        default=5.0,
        metavar="SECONDS",
        help="end-to-end p95 latency target on the 1m window for "
        "/healthz degradation (default 5.0)",
    )
    s.add_argument(
        "--alert-url",
        default=None,
        metavar="URL",
        help="deliver alertmanager-compatible JSON webhooks (slo_breach, "
        "perf_regression, and --alert-rule matches) to this HTTP URL, "
        "with exponential-backoff retries and per-rule dedup windows "
        "(default: off)",
    )
    s.add_argument(
        "--alert-rule",
        action="append",
        default=None,
        metavar="SPEC",
        help="additional alert rule (repeatable): an event name "
        "('slo_breach'), a field threshold ('done.wall_s>30'), or a "
        "metric threshold ('metric:verifyd_job_errors_total>=5'); "
        "named like a builtin, it overrides that builtin",
    )
    s.add_argument(
        "--alert-dedup",
        type=float,
        default=300.0,
        metavar="SECONDS",
        help="per-rule alert dedup window: repeat fires inside it are "
        "suppressed (counted), not delivered (default 300)",
    )
    s.add_argument(
        "--sentinel-band",
        type=float,
        default=0.75,
        metavar="FRACTION",
        help="perf-regression sentinel drift band: a shape whose wall "
        "time exceeds its EWMA baseline by this fraction on consecutive "
        "jobs emits perf_regression (default 0.75; <=0 disables the "
        "sentinel)",
    )
    s.add_argument(
        "--sentinel-min-samples",
        type=int,
        default=8,
        metavar="N",
        help="jobs per shape before the sentinel judges drift (default 8)",
    )
    s.add_argument(
        "--resource-sample",
        type=float,
        default=1.0,
        metavar="SECONDS",
        help="resource-telemetry sampling interval: host RSS, CPU, fds, "
        "threads, GC pauses into verifyd_resource_* gauges and the "
        "flight recorder (default 1.0; <=0 disables the sampler)",
    )
    s.add_argument(
        "--retrace-storm",
        type=int,
        default=5,
        metavar="N",
        help="emit a latched retrace_storm event when one jit site "
        "recompiles a shape bucket more than N times (default 5; "
        "0 disables)",
    )
    s.add_argument(
        "--dashboard-sample",
        type=float,
        default=2.0,
        metavar="SECONDS",
        help="/dashboard sparkline sampling interval on the metrics "
        "listener (needs --metrics-port; default 2.0; <=0 disables "
        "the dashboard)",
    )
    s.add_argument(
        "--telemetry-dir",
        default=None,
        metavar="DIR",
        help="durable telemetry store root (delta-encoded registry "
        "snapshots at raw/1m/15m resolutions; the tsq command and "
        "sentinel re-seeding read it); default <state-dir>/telemetry, "
        "disabled without a state dir",
    )
    s.add_argument(
        "--telemetry-sample",
        type=float,
        default=2.0,
        metavar="SECONDS",
        help="telemetry sampling interval for the raw ring (the 1m/15m "
        "rings downsample from it; default 2.0; <=0 disables recording)",
    )
    s.add_argument(
        "--drain-timeout",
        type=float,
        default=0.0,
        metavar="SECONDS",
        help="graceful drain budget: on SIGTERM (or a drain-flagged "
        "shutdown op) stop admitting, let queued + in-flight jobs "
        "finish up to this many seconds, close the journal cleanly, "
        "then exit.  0 (default) keeps the immediate-stop behavior; "
        "the router's rolling restart needs this > 0",
    )
    s.add_argument(
        "--max-rss-frac",
        type=float,
        default=0.0,
        metavar="FRACTION",
        help="pressure-aware admission: shed new submits (honest "
        "retry_after, QueueFull) while daemon RSS exceeds this fraction "
        "of MemTotal, and while open fds near RLIMIT_NOFILE "
        "(default 0 = shedding off)",
    )
    s.add_argument(
        "--deadline-grace",
        type=float,
        default=2.0,
        metavar="SECONDS",
        help="SIGTERM-to-SIGKILL grace for supervised children of "
        "cancelled jobs (deadline expiry, client gone, shutdown) "
        "(default 2.0)",
    )
    s.add_argument(
        "--quarantine-threshold",
        type=int,
        default=3,
        metavar="N",
        help="poison-job quarantine: a fingerprint observed in-flight "
        "across this many process deaths or supervised-child kills is "
        "quarantined (definite Quarantined error) instead of replayed; "
        "needs --state-dir (default 3)",
    )
    s.add_argument(
        "--batching",
        action="store_true",
        help="continuous cross-job batching: drain every queued job of a "
        "worker-picked shape group into one mega-launch (late-join at "
        "launch boundaries, per-lane deadlines/cancels honored, per-job "
        "done attribution)",
    )
    s.add_argument(
        "--batch-engine",
        default="auto",
        choices=("auto", "native", "vmap"),
        help="mega-launch engine: native (pre-encoded C lanes, per-lane "
        "early exit) or vmap (one compiled vmapped frontier search per "
        "launch); auto picks native when the C engine is built "
        "(default auto)",
    )
    s.add_argument(
        "--no-fast-admission",
        dest="fast_admission",
        action="store_false",
        default=True,
        help="disable the fused single-pass admission parser and decode "
        "every submission through the layered event decoder",
    )
    s.add_argument(
        "--prune",
        action="store_true",
        help="verdict-exact search pruning on every engine that carries "
        "it: successful appends expand in their forced tail order, inert "
        "ops and state-passing filters commit eagerly, and tail-pinned "
        "dead configurations drop — same verdicts, smaller search "
        "(checker/prune.py; parity gated by `make prune`)",
    )
    s.add_argument(
        "--speculate-depth",
        type=int,
        default=0,
        metavar="K",
        help="speculative multi-layer frontier expansion for device "
        "escalations: one narrow K-layer dive per launch along the "
        "value-ordered beam, accepted only when it reaches a conclusive "
        "accept, wholesale-discarded on misprediction (0 = off; "
        "internally disabled for witness-carrying runs)",
    )
    s.add_argument(
        "--prefix",
        action="store_true",
        help="incremental prefix verification: snapshot the decided "
        "frontier at closed op boundaries of every OK search, keyed by "
        "the chain-hash of the committed prefix, so a resubmission that "
        "extends a verified history resumes at the deepest cached cut "
        "instead of op 0 — and enable the 'follow' op for rolling-window "
        "stream monitoring.  Snapshots persist under --state-dir and "
        "survive restarts",
    )
    s.add_argument(
        "--prefix-capacity",
        type=int,
        default=2048,
        metavar="N",
        help="in-memory prefix-store entries before LRU eviction "
        "(default 2048)",
    )
    s.add_argument(
        "--prefix-min-ops",
        type=int,
        default=4,
        metavar="N",
        help="histories shorter than this skip the prefix probe — the "
        "cold search is cheaper than the bookkeeping (default 4)",
    )
    s.add_argument(
        "--prefix-cuts",
        type=int,
        default=8,
        metavar="N",
        help="snapshot cuts recorded per OK search (deepest boundary "
        "always included; the rest spread evenly) (default 8)",
    )
    s.add_argument(
        "--prefix-max-segments",
        type=int,
        default=8,
        metavar="N",
        help="on-disk prefix log segments before the oldest rotates out "
        "(default 8)",
    )
    s.set_defaults(fn=_cmd_serve, stats=False)

    r = sub.add_parser(
        "route",
        help="verifyd-router: front N verifyd daemons behind one address "
        "(consistent-hash cache affinity, work stealing, circuit-broken "
        "failover, rolling restarts)",
    )
    rsub = r.add_subparsers(dest="route_cmd", required=True)

    rs = rsub.add_parser(
        "serve", help="run the router daemon in the foreground"
    )
    rs.add_argument(
        "--listen",
        required=True,
        metavar="SOCK|HOST:PORT",
        help="router address clients dial: a unix-socket path, or "
        "HOST:PORT for the authenticated TCP transport (needs "
        "--secret-file / VERIFYD_SECRET; port 0 = ephemeral)",
    )
    rs.add_argument(
        "--backend",
        action="append",
        required=True,
        metavar="NAME=ADDR[@HEALTHZ_URL]",
        help="fleet member (repeatable): NAME names the node in metrics "
        "and drain commands; ADDR is its unix socket or HOST:PORT "
        "(TCP backends share the router's secret); the optional "
        "HEALTHZ_URL switches probing from TCP ping to the daemon's "
        "HTTP /healthz (real 200/503 SLO state)",
    )
    rs.add_argument(
        "--secret-file",
        default=None,
        help="file holding the shared secret for the TCP listener and "
        "TCP backends; falls back to VERIFYD_SECRET",
    )
    rs.add_argument(
        "--probe-interval",
        type=float,
        default=1.0,
        metavar="SECONDS",
        help="health-probe period per backend (default 1.0)",
    )
    rs.add_argument(
        "--breaker-failures",
        type=int,
        default=3,
        metavar="N",
        help="consecutive transport failures before a backend's circuit "
        "breaker opens (default 3)",
    )
    rs.add_argument(
        "--breaker-reset",
        type=float,
        default=5.0,
        metavar="SECONDS",
        help="open-breaker wait before admitting one half-open probe "
        "request (default 5.0)",
    )
    rs.add_argument(
        "--steal-depth",
        type=int,
        default=4,
        metavar="N",
        help="router-side in-flight on the home node at which a cold "
        "job is work-stolen to the least loaded healthy node "
        "(default 4)",
    )
    rs.add_argument(
        "--max-failovers",
        type=int,
        default=3,
        metavar="N",
        help="failover hops after the first attempt before answering "
        "NoBackend (default 3)",
    )
    rs.add_argument(
        "--submit-timeout",
        type=float,
        default=None,
        metavar="SECONDS",
        help="per-attempt verdict wait against one backend "
        "(default: wait)",
    )
    rs.add_argument(
        "--ring-replicas",
        type=int,
        default=64,
        metavar="N",
        help="virtual nodes per backend on the consistent-hash ring "
        "(default 64)",
    )
    rs.add_argument(
        "--drain-timeout",
        type=float,
        default=30.0,
        metavar="SECONDS",
        help="default budget a `route drain` waits for in-flight work "
        "(default 30)",
    )
    rs.add_argument(
        "--cache-capacity",
        type=int,
        default=4096,
        metavar="N",
        help="router edge cache: decided verdicts answered at the "
        "router with no backend hop (entries; 0 disables; default 4096)",
    )
    rs.add_argument(
        "--metrics-port",
        type=int,
        default=None,
        metavar="PORT",
        help="router /metrics + /healthz + /slo listener "
        "(0 = ephemeral; default: off)",
    )
    rs.add_argument(
        "--trace-capacity",
        type=int,
        default=4096,
        metavar="SPANS",
        help="router span-ring capacity for the stitched `trace` op "
        "(0 disables; default 4096)",
    )
    rs.add_argument(
        "--slo-target",
        type=float,
        default=0.99,
        metavar="FRACTION",
        help="router availability SLO target for /healthz (default 0.99)",
    )
    rs.add_argument(
        "--slo-latency-target",
        type=float,
        default=5.0,
        metavar="SECONDS",
        help="routed-submit p95 latency target (default 5.0)",
    )
    rs.add_argument(
        "--state-dir",
        default=None,
        metavar="DIR",
        help="router durable state: the distributed-search grant ledger "
        "lands in DIR/distsearch/ (grant-before-ship partition "
        "ownership; replayed at boot to fence a dead coordinator's "
        "epochs and surface orphan ranges).  Default: no ledger",
    )
    rs.add_argument(
        "--distsearch-segments",
        type=int,
        default=3,
        metavar="N",
        help="distributed search: target segment count the coordinator "
        "slices a submitted history into (default 3)",
    )
    rs.add_argument(
        "--distsearch-straggler",
        type=float,
        default=10.0,
        metavar="SECONDS",
        help="distributed search: partition runtime after which an idle "
        "healthy node steals the range under a new epoch (0 disables; "
        "default 10)",
    )
    rs.add_argument(
        "--distsearch-max-regrants",
        type=int,
        default=3,
        metavar="N",
        help="distributed search: re-grants per partition (failover or "
        "inconclusive owner) before the merged verdict degrades to "
        "UNKNOWN (default 3)",
    )
    rs.add_argument(
        "--scrape-interval",
        type=float,
        default=2.0,
        metavar="SECONDS",
        help="fleet-metrics scrape period: every backend's families "
        "polled and merged under a node label onto /fleet/metrics and "
        "the fleet dashboard (default 2.0; <=0 disables the scraper)",
    )
    rs.add_argument(
        "--telemetry-dir",
        default=None,
        metavar="DIR",
        help="durable telemetry store root for the router's registry "
        "(which carries the merged per-node fleet gauges); default "
        "<state-dir>/telemetry, disabled without a state dir",
    )
    rs.add_argument(
        "--telemetry-sample",
        type=float,
        default=2.0,
        metavar="SECONDS",
        help="telemetry sampling interval for the raw ring (default "
        "2.0; <=0 disables recording)",
    )
    rs.set_defaults(fn=_cmd_route_serve)

    def _route_op_parser(name: str, help_text: str):
        rp = rsub.add_parser(name, help=help_text)
        rp.add_argument(
            "-socket",
            "--socket",
            required=True,
            help="the router's unix-socket path or HOST:PORT",
        )
        rp.add_argument(
            "--secret-file",
            default=None,
            help="shared secret for a TCP router address; falls back to "
            "VERIFYD_SECRET",
        )
        return rp

    rd = _route_op_parser(
        "drain",
        "rolling restart, step 1: stop routing to NODE, wait for its "
        "in-flight, then send it a drain-aware shutdown (the restarted "
        "node replays its journal and rejoins via the health probe)",
    )
    rd.add_argument("node", help="backend name (as given to --backend)")
    rd.add_argument(
        "--timeout",
        type=float,
        default=None,
        metavar="SECONDS",
        help="drain budget override (default: the router's "
        "--drain-timeout)",
    )
    rd.set_defaults(fn=_cmd_route_drain)

    ru = _route_op_parser(
        "undrain", "return a drained node to the routable set"
    )
    ru.add_argument("node", help="backend name (as given to --backend)")
    ru.set_defaults(fn=_cmd_route_undrain)

    rf = _route_op_parser(
        "fleet", "show ring membership + per-backend health/drain state"
    )
    rf.add_argument(
        "--json", action="store_true", help="emit the raw fleet JSON"
    )
    rf.set_defaults(fn=_cmd_route_fleet)

    d = sub.add_parser(
        "doctor",
        help="post-mortem a dead verifyd's --state-dir: flight-recorder "
        "tail, orphaned journal entries, open device leases, slowest "
        "spans, and the SLO picture at death",
    )
    d.add_argument(
        "--state-dir",
        required=True,
        help="the dead daemon's durable-state directory",
    )
    d.add_argument(
        "--tail",
        type=int,
        default=20,
        help="flight-recorder records to show (default 20)",
    )
    d.add_argument(
        "--json",
        action="store_true",
        help="emit the full post-mortem as JSON instead of the report",
    )
    d.set_defaults(fn=_cmd_doctor)

    tq = sub.add_parser(
        "tsq",
        help="query durable telemetry history: per-series points with "
        "sparklines, live off a daemon/router socket or cold off a "
        "telemetry directory (same store, same answer)",
    )
    tq.add_argument(
        "-socket",
        "--socket",
        default=None,
        help="live path: a running daemon/router (unix-socket path, or "
        "HOST:PORT with --secret-file / VERIFYD_SECRET)",
    )
    tq.add_argument(
        "--secret-file",
        default=None,
        help="shared-secret file for a TCP --socket",
    )
    tq.add_argument(
        "--telemetry-dir",
        default=None,
        metavar="DIR",
        help="cold path: read the rings straight from a telemetry dir "
        "(works while the daemon is dead)",
    )
    tq.add_argument(
        "--state-dir",
        default=None,
        metavar="DIR",
        help="cold-path shorthand: DIR/telemetry",
    )
    tq.add_argument(
        "--res",
        choices=("raw", "1m", "15m"),
        default="raw",
        help="resolution ring to read (default raw)",
    )
    tq.add_argument(
        "--metric",
        default=None,
        metavar="SUBSTR",
        help="series-name substring filter (e.g. queue_depth)",
    )
    tq.add_argument(
        "--label",
        action="append",
        default=None,
        metavar="KEY=VALUE",
        help="label equality filter (repeatable; all must match)",
    )
    tq.add_argument(
        "--since",
        type=float,
        default=None,
        metavar="EPOCH",
        help="range start (unix seconds)",
    )
    tq.add_argument(
        "--until",
        type=float,
        default=None,
        metavar="EPOCH",
        help="range end (unix seconds)",
    )
    tq.add_argument(
        "--limit",
        type=int,
        default=None,
        metavar="N",
        help="points kept per series, from the tail (default 360 live, "
        "720 cold)",
    )
    tq.add_argument(
        "--rate",
        action="store_true",
        help="render cumulative counters as per-second rates (negative "
        "deltas from restarts clamp to 0)",
    )
    tq.add_argument(
        "--info",
        action="store_true",
        help="ring inventory (records, series, bytes, recovery) instead "
        "of points",
    )
    tq.add_argument(
        "--width",
        type=int,
        default=48,
        metavar="COLS",
        help="sparkline width (default 48)",
    )
    tq.add_argument(
        "--json", action="store_true", help="emit the raw reply JSON"
    )
    tq.add_argument(
        "--csv",
        action="store_true",
        help="emit series,t,value rows instead of the sparkline table",
    )
    tq.set_defaults(fn=_cmd_tsq)

    qp = sub.add_parser(
        "quarantine",
        help="poison-job quarantine: list / inspect / release fingerprints "
        "a live daemon (--socket) or a dead one's --state-dir holds",
    )
    qsub = qp.add_subparsers(dest="quarantine_cmd", required=True)

    def _quarantine_common(p: argparse.ArgumentParser) -> None:
        p.add_argument(
            "-socket",
            "--socket",
            default=None,
            help="a running daemon: unix-socket path, or HOST:PORT for "
            "the authenticated TCP transport (needs --secret-file or "
            "VERIFYD_SECRET)",
        )
        p.add_argument(
            "--state-dir",
            default=None,
            help="cold path: read the quarantine ledger straight from a "
            "state dir (release this way only with the daemon stopped)",
        )
        p.add_argument(
            "--secret-file",
            default=None,
            help="shared-secret file for the TCP transport",
        )
        p.set_defaults(fn=_cmd_quarantine)

    ql = qsub.add_parser("list", help="show quarantined fingerprints")
    _quarantine_common(ql)
    qi = qsub.add_parser(
        "inspect", help="full crash ledger for one fingerprint"
    )
    qi.add_argument("fingerprint", help="fingerprint to inspect")
    _quarantine_common(qi)
    qr = qsub.add_parser(
        "release",
        help="operator override: un-quarantine a fingerprint and reset "
        "its crash count (the next submit runs it again)",
    )
    qr.add_argument("fingerprint", help="fingerprint to release")
    _quarantine_common(qr)

    pr = sub.add_parser(
        "profiles",
        help="query the durable job-profile archive (live --socket or "
        "cold --state-dir): filter, rank by wall time, export CSV/JSONL",
    )
    pr.add_argument(
        "-socket",
        "--socket",
        default=None,
        help="query a running daemon: unix-socket path, or HOST:PORT for "
        "the authenticated TCP transport (needs --secret-file or "
        "VERIFYD_SECRET)",
    )
    pr.add_argument(
        "--state-dir",
        default=None,
        help="read a (dead) daemon's archive cold from its durable-state "
        "directory — no daemon needed",
    )
    pr.add_argument(
        "--secret-file",
        default=None,
        help="file holding the TCP shared secret (whitespace-stripped); "
        "falls back to the VERIFYD_SECRET environment variable",
    )
    pr.add_argument("--shape", default=None, help="exact shape_key match")
    pr.add_argument(
        "--backend",
        default=None,
        help="backend prefix match (e.g. 'device' matches device-mesh[4])",
    )
    pr.add_argument("--client", default=None, help="exact client identity")
    pr.add_argument(
        "--verdict",
        type=int,
        default=None,
        help="verdict exit code (0 linearizable / 1 illegal / 2 unknown)",
    )
    pr.add_argument(
        "--since",
        type=float,
        default=None,
        metavar="EPOCH_S",
        help="records at or after this epoch-seconds timestamp",
    )
    pr.add_argument(
        "--slowest",
        type=int,
        default=None,
        metavar="N",
        help="N slowest by wall time (overrides --limit)",
    )
    pr.add_argument(
        "--limit",
        type=int,
        default=None,
        metavar="N",
        help="newest N records (default 100 when neither --slowest nor "
        "--limit is given)",
    )
    pr.add_argument(
        "--export",
        default=None,
        metavar="FILE",
        help="write matching records to FILE ('-' = stdout) instead of "
        "the table",
    )
    pr.add_argument(
        "--format",
        default="jsonl",
        choices=["jsonl", "csv"],
        help="--export format (default jsonl)",
    )
    pr.set_defaults(fn=_cmd_profiles)

    t = sub.add_parser(
        "trace",
        help="export a running verifyd's span ring as Chrome trace_event "
        "JSON (loads in Perfetto / chrome://tracing)",
    )
    t.add_argument(
        "-socket",
        "--socket",
        required=True,
        help="the daemon's unix-socket path, or HOST:PORT for the "
        "authenticated TCP transport (needs --secret-file or "
        "VERIFYD_SECRET)",
    )
    t.add_argument(
        "--secret-file",
        default=None,
        help="file holding the TCP shared secret (whitespace-stripped); "
        "falls back to the VERIFYD_SECRET environment variable",
    )
    t.add_argument(
        "-out",
        "--out",
        default="-",
        help="output path for the trace JSON ('-' = stdout, the default)",
    )
    t.set_defaults(fn=_cmd_trace)

    da = sub.add_parser(
        "dash",
        help="live terminal dashboard over a running verifyd: sparkline "
        "history of throughput, queue depth, active jobs, RSS, and JIT "
        "compile activity from the stats op (the HTML twin lives at "
        "/dashboard on --metrics-port)",
    )
    da.add_argument(
        "-socket",
        "--socket",
        required=True,
        help="the daemon's unix-socket path, or HOST:PORT for the "
        "authenticated TCP transport (needs --secret-file or "
        "VERIFYD_SECRET)",
    )
    da.add_argument(
        "--secret-file",
        default=None,
        help="file holding the TCP shared secret (whitespace-stripped); "
        "falls back to the VERIFYD_SECRET environment variable",
    )
    da.add_argument(
        "--interval",
        type=float,
        default=2.0,
        metavar="SECONDS",
        help="seconds between polls (default 2.0)",
    )
    da.add_argument(
        "--iterations",
        type=int,
        default=0,
        metavar="N",
        help="stop after N frames (default 0 = run until interrupted)",
    )
    da.add_argument(
        "--width",
        type=int,
        default=32,
        metavar="COLS",
        help="sparkline width in characters (default 32)",
    )
    da.set_defaults(fn=_cmd_dash)

    w = sub.add_parser(
        "watch",
        help="live progress board for running searches: per-job progress "
        "ratio, committed/total ops, frontier width and ETA from the "
        "watch op (point it at a daemon, or at a router to watch the "
        "whole fleet including distributed partitions)",
    )
    w.add_argument(
        "-socket",
        "--socket",
        required=True,
        help="the daemon's (or router's) unix-socket path, or HOST:PORT "
        "for the authenticated TCP transport (needs --secret-file or "
        "VERIFYD_SECRET)",
    )
    w.add_argument(
        "--secret-file",
        default=None,
        help="file holding the TCP shared secret (whitespace-stripped); "
        "falls back to the VERIFYD_SECRET environment variable",
    )
    w.add_argument(
        "--job",
        type=int,
        default=None,
        metavar="ID",
        help="watch one job id (definite UnknownJob when it is not "
        "running; after it was visible, that means it finished)",
    )
    w.add_argument(
        "--fingerprint",
        default=None,
        metavar="FP",
        help="watch by verdict-cache fingerprint (e.g. the ppart:… key "
        "of a distributed partition job)",
    )
    w.add_argument(
        "--search",
        default=None,
        metavar="SEARCH",
        help="watch every partition of a distributed search (the search "
        "id from submit --distributed); against a router this also "
        "shows the coordinator's per-partition aggregate",
    )
    w.add_argument(
        "--part",
        default=None,
        metavar="RANGE",
        help="narrow --search to one partition range",
    )
    w.add_argument(
        "--json",
        action="store_true",
        help="emit one JSON object per poll instead of the board",
    )
    w.add_argument(
        "--interval",
        type=float,
        default=1.0,
        metavar="SECONDS",
        help="seconds between polls (default 1.0)",
    )
    w.add_argument(
        "--iterations",
        type=int,
        default=0,
        metavar="N",
        help="stop after N frames (default 0 = run until the watched "
        "job finishes or interrupted)",
    )
    w.add_argument(
        "--width",
        type=int,
        default=32,
        metavar="COLS",
        help="sparkline width in characters (default 32)",
    )
    w.set_defaults(fn=_cmd_watch)

    u = sub.add_parser("submit", help="submit one history to a running verifyd")
    u.add_argument(
        "-file", "--file", required=True, help="history JSONL path, '-' for stdin"
    )
    u.add_argument(
        "-socket",
        "--socket",
        required=True,
        help="the daemon's unix-socket path, or HOST:PORT for the "
        "authenticated TCP transport (needs --secret-file or "
        "VERIFYD_SECRET)",
    )
    u.add_argument(
        "--secret-file",
        default=None,
        help="file holding the TCP shared secret (whitespace-stripped); "
        "falls back to the VERIFYD_SECRET environment variable",
    )
    u.add_argument("--client", default="cli", help="client identity for the queue")
    u.add_argument(
        "--priority",
        type=int,
        default=10,
        help="admission priority (lower = scheduled sooner; default 10)",
    )
    u.add_argument(
        "--timeout",
        type=float,
        default=None,
        help="seconds to wait for the verdict (default: wait)",
    )
    u.add_argument(
        "--retries",
        type=int,
        default=0,
        help="re-submissions after a transient failure.  Queue-full "
        "rejects sleep the daemon's retry-after hint; connect failures "
        "and transport noise sleep exponential backoff with jitter "
        "(--backoff).  Default 0: fail fast.  Exhausted retries exit "
        "75 (still busy), 69 (no daemon ever answered), or 76 (a "
        "daemon was reached but refused)",
    )
    u.add_argument(
        "--backoff",
        type=float,
        default=0.5,
        metavar="SECONDS",
        help="base of the exponential retry backoff: attempt n sleeps "
        "uniform(0, SECONDS * 2^n), capped at 30s (default 0.5)",
    )
    u.add_argument(
        "--deadline",
        type=float,
        default=None,
        metavar="SECONDS",
        help="total wall-clock budget across all attempts and retry "
        "sleeps: per-attempt timeouts are clamped to what remains, and "
        "a spent budget exits 69 with 'deadline exceeded after N "
        "attempts' — bounds a retry loop against a flapping node "
        "(default: unbounded)",
    )
    u.add_argument(
        "-no-viz", "--no-viz", action="store_true", help="skip the HTML artifact"
    )
    u.add_argument(
        "-distributed",
        "--distributed",
        action="store_true",
        help="ask a verifyd-router to run the search fleet-distributed: "
        "the frontier is partitioned by state-hash range across healthy "
        "backends and the merged verdict carries partition/epoch "
        "telemetry.  Plain daemons (and routers without >= 2 healthy "
        "backends) serve the submit single-node — the flag degrades, "
        "never fails",
    )
    u.add_argument(
        "-stats",
        "--stats",
        action="store_true",
        help="print one machine-readable JSON line (verdict, backend, "
        "queue wait, cache hit) on stdout",
    )
    u.set_defaults(fn=_cmd_submit)

    fo = sub.add_parser(
        "follow",
        help="verify a growing event stream window-by-window against a "
        "--prefix daemon (the decided frontier is carried forward, so "
        "each window costs its own ops)",
    )
    fo.add_argument(
        "-file",
        "--file",
        required=True,
        help="history JSONL path, '-' for stdin (pipe a live collector "
        "into it)",
    )
    fo.add_argument(
        "-socket",
        "--socket",
        required=True,
        help="the daemon's unix-socket path, or HOST:PORT for the "
        "authenticated TCP transport (needs --secret-file or "
        "VERIFYD_SECRET); a router address works — streams route by "
        "stream id",
    )
    fo.add_argument(
        "--secret-file",
        default=None,
        help="file holding the TCP shared secret (whitespace-stripped); "
        "falls back to the VERIFYD_SECRET environment variable",
    )
    fo.add_argument(
        "--stream",
        required=True,
        help="stream identity: scopes the frontier lineage and (behind a "
        "router) pins every window to one backend",
    )
    fo.add_argument(
        "--frontier",
        default=None,
        help="resume from a frontier token printed by an earlier run "
        "(default: start a fresh lineage at window 0)",
    )
    fo.add_argument(
        "--window",
        type=int,
        default=256,
        metavar="EVENTS",
        help="events per window: a window is cut at the first point at "
        "or after this many lines where no call is still open "
        "(default 256)",
    )
    fo.add_argument("--client", default="cli", help="client identity for the queue")
    fo.add_argument(
        "--priority",
        type=int,
        default=10,
        help="admission priority (lower = scheduled sooner; default 10)",
    )
    fo.add_argument(
        "--timeout",
        type=float,
        default=None,
        help="seconds to wait for each window's verdict (default: wait)",
    )
    fo.add_argument(
        "--deadline",
        type=float,
        default=None,
        metavar="SECONDS",
        help="per-window end-to-end deadline forwarded to the daemon "
        "(default: unbounded)",
    )
    fo.add_argument(
        "--window-retries",
        type=int,
        default=2,
        metavar="N",
        help="resync retries for a window whose ops were not carried "
        "into the frontier (inconclusive verdict, refused snapshot) "
        "before exiting 2 — moving on without a carry would silently "
        "drop the window from the verified lineage (default 2)",
    )
    fo.add_argument(
        "-stats",
        "--stats",
        action="store_true",
        help="print one machine-readable JSON line per window (verdict, "
        "backend, frontier token, ops carried) on stdout",
    )
    fo.set_defaults(fn=_cmd_follow)

    k = sub.add_parser(
        "soak",
        help="closed-loop soak: generate labeled fault-campaign histories, "
        "submit them to a live daemon/fleet, and score every verdict "
        "against its ground-truth label",
    )
    k.add_argument(
        "socket",
        help="daemon or router address (unix-socket path, or HOST:PORT "
        "with --secret-file / VERIFYD_SECRET)",
    )
    k.add_argument(
        "--campaign",
        action="append",
        metavar="NAME",
        help="campaign to run (repeatable; default: the full builtin "
        "matrix — see `collect --list-campaigns`)",
    )
    k.add_argument("--seed", type=int, default=0, help="schedule seed base")
    k.add_argument(
        "--cycles",
        type=int,
        default=1,
        help="passes over the campaign list, each with fresh derived seeds",
    )
    k.add_argument(
        "--num-concurrent-clients",
        type=int,
        default=None,
        help="override each campaign's client sizing",
    )
    k.add_argument(
        "--num-ops-per-client",
        type=int,
        default=None,
        help="override each campaign's per-client op count",
    )
    k.add_argument(
        "--retries",
        type=int,
        default=8,
        help="per-history re-submissions riding out fleet failovers "
        "(default 8)",
    )
    k.add_argument("--backoff", type=float, default=0.25, metavar="SECONDS")
    k.add_argument(
        "--timeout",
        type=float,
        default=120.0,
        help="per-attempt verdict wait (default 120s)",
    )
    k.add_argument(
        "--deadline",
        type=float,
        default=None,
        metavar="SECONDS",
        help="total wall-clock budget per submission across retries",
    )
    k.add_argument(
        "--alert-url",
        default=None,
        help="webhook for checker_false_verdict alert delivery",
    )
    k.add_argument(
        "--state-dir",
        default=None,
        help="flight-recorder ring + offending-history dumps land here",
    )
    k.add_argument(
        "--metrics-port",
        type=int,
        default=None,
        help="serve verifyd_soak_* families on /metrics (0 = ephemeral port)",
    )
    k.add_argument(
        "--mislabel-control",
        action="store_true",
        help="deliberately flip the first history's label — a control case "
        "proving the checker_false_verdict alert + nonzero exit fire",
    )
    k.add_argument("--secret-file", default=None)
    k.add_argument(
        "--json",
        action="store_true",
        help="print the full machine-readable summary (default: one "
        "compact summary line)",
    )
    k.set_defaults(fn=_cmd_soak)

    li = sub.add_parser(
        "lint",
        help="run verifylint, the domain-aware static-analysis suite "
        "(jit-hygiene, event-schema, metrics-cardinality, concurrency, "
        "protocol-compat)",
    )
    li.add_argument(
        "paths",
        nargs="*",
        help="files/directories to scan (default: the whole package)",
    )
    li.add_argument(
        "--json", action="store_true", help="machine-readable findings + ratchet state"
    )
    li.add_argument(
        "--baseline",
        default=None,
        metavar="PATH",
        help="baseline ratchet file (default: <repo>/.verifylint-baseline.json)",
    )
    li.add_argument(
        "--write-baseline",
        action="store_true",
        help="rewrite the baseline from this run's error findings "
        "(full-tree runs only; justify every kept entry)",
    )
    li.add_argument(
        "--changed",
        action="store_true",
        help="scan only git-modified/untracked package files (sub-second "
        "incremental gate)",
    )
    li.add_argument(
        "--events-md",
        default=None,
        metavar="PATH",
        help="render the event-schema registry as markdown to PATH "
        "('-' = stdout) and exit",
    )
    li.add_argument(
        "--check-events-md",
        action="store_true",
        help="fail if the committed docs/EVENTS.md is stale",
    )
    li.add_argument(
        "--no-cache", action="store_true", help="ignore and skip the per-file cache"
    )
    li.set_defaults(fn=_cmd_lint)
    return p


def main(argv: list[str] | None = None) -> int:
    logging.basicConfig(
        stream=sys.stderr,
        level=os.environ.get("S2VTPU_LOG", "INFO").upper(),
        format="%(asctime)s %(levelname)s %(name)s %(message)s",
    )
    args = build_parser().parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    raise SystemExit(main())
