"""Runtime introspection: JIT-compile observability + resource telemetry.

Two halves, both always-on and stdlib-only (jax is only touched lazily,
and only when the process already imported it):

**JIT observability** — :func:`observe_jit` wraps a jitted entry point
with a compile tracker.  Each call computes an *abstract signature* of
its arguments (dtype+shape for array-likes, ``repr`` for statics — the
same notion of identity ``jax.jit``'s tracing cache uses), so the first
call under a signature is a compile (timed; a ``jit.compile`` span lands
on the owning job's trace) and every repeat is an executable-cache hit.
Counts are kept per ``(site, shape_key)`` — the daemon's job-shape
bucketing — and exported as the ``verifyd_jit_*`` metric families.  A
shape that recompiles at one site more than ``storm_threshold`` times
trips a **latched** ``retrace_storm`` ServiceStats event (routed through
the alert engine), once per (site, shape).

The tracker is a process-global singleton (:data:`INTROSPECTOR`): the
jit sites in ``checker/device.py`` wrap themselves at import time, the
daemon attaches its registry/stats on boot, and a supervised child
harvests :meth:`JitIntrospector.snapshot_and_reset` into the result JSON
so the parent can :meth:`~JitIntrospector.fold` the child's compile
activity into its own families — the same side channel the child span
ring rides.

**Resource telemetry** — :class:`ResourceSampler`, a low-overhead daemon
thread reading host RSS, CPU time, open fds, thread count, GC pauses
(via ``gc.callbacks``), and per-device memory (best effort, only when
jax is already imported) into the ``verifyd_resource_*`` gauge families,
a bounded in-memory ring, and — when a flight recorder is attached —
``{"k": "res"}`` flight records, so ``doctor`` can show the resource
timeline leading up to a death.
"""

from __future__ import annotations

import gc
import os
import sys
import threading
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Tuple

__all__ = [
    "INTROSPECTOR",
    "JitIntrospector",
    "JobContext",
    "ResourceSampler",
    "get_job_context",
    "job_context",
    "observe_jit",
]

_UNKNOWN_SHAPE = "?"

_local = threading.local()


class JobContext:
    """What the thread is working on: set by the scheduler worker (and
    the supervised child) so jit sites can attribute compiles to a job,
    a shape bucket, a trace id, and a tracer track."""

    __slots__ = ("job", "shape", "trace_id", "tracer")

    def __init__(
        self,
        job: int = 0,
        shape: str = _UNKNOWN_SHAPE,
        trace_id: str = "",
        tracer=None,
    ) -> None:
        self.job = job
        self.shape = shape
        self.trace_id = trace_id
        self.tracer = tracer


_DEFAULT_CONTEXT = JobContext()


def get_job_context() -> JobContext:
    return getattr(_local, "job_context", _DEFAULT_CONTEXT)


class job_context:
    """``with job_context(job=3, shape="64x5x8", trace_id=..., tracer=t):``
    — scoped per-thread attribution for everything the body compiles."""

    def __init__(self, **kw: Any) -> None:
        self._ctx = JobContext(**kw)
        self._prev: Optional[JobContext] = None

    def __enter__(self) -> JobContext:
        self._prev = getattr(_local, "job_context", None)
        _local.job_context = self._ctx
        return self._ctx

    def __exit__(self, *exc) -> None:
        if self._prev is None:
            del _local.job_context
        else:
            _local.job_context = self._prev


def _abstract_sig(obj: Any, depth: int = 0) -> str:
    """Abstract shape signature of one argument: dtype+shape for anything
    array-like (what jit's tracing cache keys on), bounded ``repr`` for
    static values, recursing through the containers jitted signatures
    actually use (tuples/lists/dicts/dataclass-like pytrees)."""
    if depth > 4:
        return "..."
    shape = getattr(obj, "shape", None)
    dtype = getattr(obj, "dtype", None)
    if shape is not None and dtype is not None:
        return f"{dtype}{tuple(shape)}"
    if isinstance(obj, (tuple, list)):
        return "(" + ",".join(_abstract_sig(x, depth + 1) for x in obj) + ")"
    if isinstance(obj, dict):
        return (
            "{"
            + ",".join(
                f"{k}:{_abstract_sig(v, depth + 1)}"
                for k, v in sorted(obj.items(), key=lambda kv: str(kv[0]))
            )
            + "}"
        )
    fields = getattr(obj, "__dataclass_fields__", None)
    if fields is not None:
        return (
            type(obj).__name__
            + "("
            + ",".join(
                f"{name}={_abstract_sig(getattr(obj, name, None), depth + 1)}"
                for name in fields
            )
            + ")"
        )
    return repr(obj)[:64]


class JitIntrospector:
    """Process-global compile tracker behind :func:`observe_jit`.

    Unattached (no registry/stats) it still counts — the numbers a child
    accumulates before harvest are exactly what the parent folds.
    """

    def __init__(self, storm_threshold: int = 5) -> None:
        self._lock = threading.Lock()
        self.storm_threshold = storm_threshold
        self._registry = None
        self._stats = None
        # site -> set of abstract signatures already compiled there
        self._sigs: Dict[str, set] = {}
        # (site, shape) -> count
        self._compiles: Dict[Tuple[str, str], int] = {}
        self._retraces: Dict[Tuple[str, str], int] = {}
        # shape -> count
        self._hits: Dict[str, int] = {}
        self._misses: Dict[str, int] = {}
        # site -> total first-call wall (compile + first dispatch)
        self._compile_wall: Dict[str, float] = {}
        # latched (site, shape) storm trips, with the count at trip time
        self._storms: Dict[Tuple[str, str], int] = {}

    # -- wiring --------------------------------------------------------------

    def attach(
        self, *, registry=None, stats=None, storm_threshold: Optional[int] = None
    ) -> None:
        """Point the tracker at a daemon's registry + event stream.  A
        re-attach (tests boot many daemons per process) replaces both and
        replays accumulated counts into the new registry so /metrics
        never starts behind the tracker."""
        with self._lock:
            self._registry = registry
            self._stats = stats
            if storm_threshold is not None:
                self.storm_threshold = storm_threshold
            if registry is not None:
                self._replay_into_registry()

    def _replay_into_registry(self) -> None:
        # Caller holds the lock.
        for (site, shape), n in self._compiles.items():
            self._metric("verifyd_jit_compiles_total", ("site", "shape")).inc(
                n, site=site, shape=shape
            )
        for (site, shape), n in self._retraces.items():
            self._metric("verifyd_jit_retraces_total", ("site", "shape")).inc(
                n, site=site, shape=shape
            )
        for shape, n in self._hits.items():
            self._metric("verifyd_jit_cache_hits_total", ("shape",)).inc(
                n, shape=shape
            )
        for shape, n in self._misses.items():
            self._metric("verifyd_jit_cache_misses_total", ("shape",)).inc(
                n, shape=shape
            )

    def _metric(self, name: str, labelnames: Tuple[str, ...]):
        # The registry factory is idempotent: ServiceStats pre-registers
        # these families (with HELP text) so headers render even before
        # the first compile; this lookup just returns the same objects.
        return self._registry.counter(name, labelnames=labelnames)

    # -- the hot path --------------------------------------------------------

    def record_call(self, site: str, sig: str) -> bool:
        """Account one call at ``site`` under abstract signature ``sig``;
        returns True when the executable is already cached (a hit)."""
        ctx = get_job_context()
        shape = ctx.shape
        with self._lock:
            seen = self._sigs.setdefault(site, set())
            hit = sig in seen
            if hit:
                self._hits[shape] = self._hits.get(shape, 0) + 1
                if self._registry is not None:
                    self._metric("verifyd_jit_cache_hits_total", ("shape",)).inc(
                        shape=shape
                    )
            else:
                self._misses[shape] = self._misses.get(shape, 0) + 1
                if self._registry is not None:
                    self._metric("verifyd_jit_cache_misses_total", ("shape",)).inc(
                        shape=shape
                    )
        return hit

    def record_compile(self, site: str, sig: str, wall_s: float) -> None:
        """Account the timed first call for a fresh signature; trips the
        latched retrace storm when a shape keeps recompiling one site."""
        ctx = get_job_context()
        shape = ctx.shape
        storm: Optional[Tuple[str, str, int]] = None
        with self._lock:
            seen = self._sigs.setdefault(site, set())
            retrace = bool(seen)  # site already had a compiled signature
            seen.add(sig)
            key = (site, shape)
            self._compiles[key] = self._compiles.get(key, 0) + 1
            self._compile_wall[site] = self._compile_wall.get(site, 0.0) + wall_s
            if self._registry is not None:
                self._metric("verifyd_jit_compiles_total", ("site", "shape")).inc(
                    site=site, shape=shape
                )
                self._registry.histogram(
                    "verifyd_jit_compile_seconds", labelnames=("site",)
                ).observe(wall_s, site=site)
            if retrace:
                self._retraces[key] = self._retraces.get(key, 0) + 1
                if self._registry is not None:
                    self._metric(
                        "verifyd_jit_retraces_total", ("site", "shape")
                    ).inc(site=site, shape=shape)
            if (
                self._compiles[key] > self.storm_threshold
                and key not in self._storms
            ):
                self._storms[key] = self._compiles[key]
                storm = (site, shape, self._compiles[key])
        if storm is not None:
            self._emit_storm(*storm)

    def _emit_storm(self, site: str, shape: str, count: int) -> None:
        stats = self._stats
        if stats is not None:
            ctx = get_job_context()
            stats.emit(
                "retrace_storm",
                site=site,
                shape=shape,
                compiles=count,
                threshold=self.storm_threshold,
                job=ctx.job,
                trace_id=ctx.trace_id,
            )

    # -- harvest / fold ------------------------------------------------------

    def snapshot(self) -> Dict[str, Any]:
        """JSON-serializable view for the stats op and the child harvest.
        Keys are ``site\\tshape`` joins (both are free of tabs)."""
        with self._lock:
            return {
                "compiles": {
                    f"{s}\t{sh}": n for (s, sh), n in self._compiles.items()
                },
                "retraces": {
                    f"{s}\t{sh}": n for (s, sh), n in self._retraces.items()
                },
                "hits": dict(self._hits),
                "misses": dict(self._misses),
                "compile_wall_s": {
                    s: round(w, 6) for s, w in self._compile_wall.items()
                },
                "signatures": {s: len(v) for s, v in self._sigs.items()},
                "storms": [
                    {"site": s, "shape": sh, "compiles": n}
                    for (s, sh), n in self._storms.items()
                ],
                "storm_threshold": self.storm_threshold,
            }

    def snapshot_and_reset(self) -> Dict[str, Any]:
        """Harvest for the child→parent side channel: everything counted
        so far, then a clean slate (a restarted attempt reports only its
        own compiles)."""
        snap = self.snapshot()
        with self._lock:
            self._sigs.clear()
            self._compiles.clear()
            self._retraces.clear()
            self._hits.clear()
            self._misses.clear()
            self._compile_wall.clear()
            self._storms.clear()
        return snap

    def fold(self, snap: Dict[str, Any]) -> None:
        """Merge a child's harvested snapshot into this (parent) tracker:
        counts add, compile wall lands in the histogram as one aggregate
        observation per site, and child storms re-trip the latch here so
        the alert engine sees them exactly once."""
        if not isinstance(snap, dict):
            return
        storms: List[Tuple[str, str, int]] = []

        def _pairs(key: str):
            for joined, n in (snap.get(key) or {}).items():
                site, _, shape = str(joined).partition("\t")
                try:
                    yield site, (shape or _UNKNOWN_SHAPE), int(n)
                except (TypeError, ValueError):
                    continue

        with self._lock:
            for site, shape, n in _pairs("compiles"):
                key = (site, shape)
                self._compiles[key] = self._compiles.get(key, 0) + n
                if self._registry is not None:
                    self._metric(
                        "verifyd_jit_compiles_total", ("site", "shape")
                    ).inc(n, site=site, shape=shape)
                if (
                    self._compiles[key] > self.storm_threshold
                    and key not in self._storms
                ):
                    self._storms[key] = self._compiles[key]
                    storms.append((site, shape, self._compiles[key]))
            for site, shape, n in _pairs("retraces"):
                key = (site, shape)
                self._retraces[key] = self._retraces.get(key, 0) + n
                if self._registry is not None:
                    self._metric(
                        "verifyd_jit_retraces_total", ("site", "shape")
                    ).inc(n, site=site, shape=shape)
            for shape, n in (snap.get("hits") or {}).items():
                self._hits[shape] = self._hits.get(shape, 0) + int(n)
                if self._registry is not None:
                    self._metric("verifyd_jit_cache_hits_total", ("shape",)).inc(
                        int(n), shape=shape
                    )
            for shape, n in (snap.get("misses") or {}).items():
                self._misses[shape] = self._misses.get(shape, 0) + int(n)
                if self._registry is not None:
                    self._metric(
                        "verifyd_jit_cache_misses_total", ("shape",)
                    ).inc(int(n), shape=shape)
            for site, wall in (snap.get("compile_wall_s") or {}).items():
                w = float(wall)
                self._compile_wall[site] = self._compile_wall.get(site, 0.0) + w
                if self._registry is not None and w > 0:
                    self._registry.histogram(
                        "verifyd_jit_compile_seconds", labelnames=("site",)
                    ).observe(w, site=site)
        for storm in storms:
            self._emit_storm(*storm)


#: The process-global tracker every observed jit site reports to.
INTROSPECTOR = JitIntrospector()


def observe_jit(site: str, tracker: Optional[JitIntrospector] = None):
    """Decorator wrapping a jitted callable with the compile tracker.

    The wrapper adds one dict hash + lock on the cache-hit path; a miss
    additionally times the call (compile + first dispatch — the cost a
    fresh shape actually pays) and records a ``jit.compile`` span on the
    job context's tracer.
    """

    def _wrap(fn: Callable) -> Callable:
        intr = tracker if tracker is not None else INTROSPECTOR

        def wrapper(*args, **kwargs):
            sig = _abstract_sig(args) + _abstract_sig(kwargs)
            if intr.record_call(site, sig):
                return fn(*args, **kwargs)
            t0 = time.perf_counter()
            out = fn(*args, **kwargs)
            wall = time.perf_counter() - t0
            intr.record_compile(site, sig, wall)
            ctx = get_job_context()
            tracer = ctx.tracer
            if tracer is not None and getattr(tracer, "enabled", False):
                t1 = tracer.now()
                tracer.add_span(
                    "jit.compile",
                    t1 - wall,
                    t1,
                    tid=ctx.job,
                    cat="jit",
                    args={
                        "site": site,
                        "shape": ctx.shape,
                        "trace_id": ctx.trace_id,
                    },
                )
            return out

        wrapper.__name__ = getattr(fn, "__name__", site)
        wrapper.__doc__ = getattr(fn, "__doc__", None)
        wrapper.__wrapped__ = fn
        return wrapper

    return _wrap


# --------------------------------------------------------------- resources


def _read_rss_bytes() -> int:
    """Resident set size from /proc (Linux); ru_maxrss (high-water, kB on
    Linux) as the portable fallback."""
    try:
        with open("/proc/self/statm", "rb") as f:
            return int(f.read().split()[1]) * (os.sysconf("SC_PAGE_SIZE") or 4096)
    except (OSError, ValueError, IndexError):
        pass
    try:
        import resource

        return resource.getrusage(resource.RUSAGE_SELF).ru_maxrss * 1024
    except Exception:
        return 0


def _read_fds() -> int:
    try:
        return len(os.listdir("/proc/self/fd"))
    except OSError:
        return -1


def _device_memory() -> Dict[str, int]:
    """Per-device bytes in use, best effort: only consults jax when the
    process already imported it (a sampler must never trigger backend
    init), and tolerates backends without memory_stats (CPU)."""
    mod = sys.modules.get("jax")
    if mod is None:
        return {}
    out: Dict[str, int] = {}
    try:
        for d in mod.devices():
            stats = getattr(d, "memory_stats", lambda: None)()
            if isinstance(stats, dict) and "bytes_in_use" in stats:
                out[f"{d.platform}:{d.id}"] = int(stats["bytes_in_use"])
    except Exception:
        return out
    return out


class ResourceSampler:
    """Bounded-ring resource sampler thread feeding gauges + flight."""

    def __init__(
        self,
        registry=None,
        *,
        interval_s: float = 1.0,
        capacity: int = 600,
        recorder=None,
        time_fn: Callable[[], float] = time.time,
    ) -> None:
        self.interval_s = max(0.05, float(interval_s))
        self.recorder = recorder
        self._time = time_fn
        self._ring: deque = deque(maxlen=max(1, int(capacity)))
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._samples = 0
        self._gc_pause_s = 0.0
        self._gc_collections = 0
        self._gc_t0: Optional[float] = None
        self._gc_cb_installed = False

        self._g_rss = self._g_cpu = self._g_fds = None
        self._g_threads = self._g_gc = self._g_dev = None
        if registry is not None:
            self._g_rss = registry.gauge("verifyd_resource_rss_bytes")
            self._g_cpu = registry.gauge("verifyd_resource_cpu_seconds")
            self._g_fds = registry.gauge("verifyd_resource_open_fds")
            self._g_threads = registry.gauge("verifyd_resource_threads")
            self._g_gc = registry.gauge("verifyd_resource_gc_pause_seconds")
            self._g_dev = registry.gauge(
                "verifyd_resource_device_memory_bytes", labelnames=("device",)
            )

    # -- GC pause accounting (gc.callbacks fires around every collection)

    def _gc_callback(self, phase: str, info: Dict[str, Any]) -> None:
        if phase == "start":
            self._gc_t0 = time.perf_counter()
        elif phase == "stop" and self._gc_t0 is not None:
            dt = time.perf_counter() - self._gc_t0
            self._gc_t0 = None
            with self._lock:
                self._gc_pause_s += dt
                self._gc_collections += 1

    # -- sampling ------------------------------------------------------------

    def sample_once(self) -> Dict[str, Any]:
        """Take one sample: update gauges, append to the ring, and feed
        the flight recorder.  Also the test hook (no thread needed)."""
        times = os.times()
        with self._lock:
            gc_pause = self._gc_pause_s
            gc_n = self._gc_collections
        sample: Dict[str, Any] = {
            "t": round(self._time(), 3),
            "rss_bytes": _read_rss_bytes(),
            "cpu_s": round(times[0] + times[1], 3),
            "fds": _read_fds(),
            "threads": threading.active_count(),
            "gc_pause_s": round(gc_pause, 6),
            "gc_collections": gc_n,
        }
        dev = _device_memory()
        if dev:
            sample["devices"] = dev
        if self._g_rss is not None:
            self._g_rss.set(sample["rss_bytes"])
            self._g_cpu.set(sample["cpu_s"])
            self._g_fds.set(sample["fds"])
            self._g_threads.set(sample["threads"])
            self._g_gc.set(sample["gc_pause_s"])
            for name, used in dev.items():
                self._g_dev.set(used, device=name)
        with self._lock:
            self._ring.append(sample)
            self._samples += 1
        if self.recorder is not None:
            self.recorder.record_resource(sample)
        return sample

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.sample_once()
            except Exception:  # telemetry must never take the daemon down
                pass

    def start(self) -> "ResourceSampler":
        if self._thread is None:
            if not self._gc_cb_installed:
                gc.callbacks.append(self._gc_callback)
                self._gc_cb_installed = True
            self.sample_once()  # t=0 point: the ring is never empty while up
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._run, name="verifyd-resources", daemon=True
            )
            self._thread.start()
        return self

    def close(self, timeout: float = 2.0) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=timeout)
            self._thread = None
        if self._gc_cb_installed:
            try:
                gc.callbacks.remove(self._gc_callback)
            except ValueError:
                pass
            self._gc_cb_installed = False

    # -- read side -----------------------------------------------------------

    def ring(self) -> List[Dict[str, Any]]:
        with self._lock:
            return list(self._ring)

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            last = self._ring[-1] if self._ring else None
            return {
                "interval_s": self.interval_s,
                "samples": self._samples,
                "retained": len(self._ring),
                "last": last,
            }
