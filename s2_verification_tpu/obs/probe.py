"""Backend health probing + circuit breaking for the router tier.

Two small, separately testable pieces the fleet router composes per
backend:

- :class:`CircuitBreaker` — the classic closed → open → half-open state
  machine over *request* outcomes.  Consecutive transport failures trip
  the breaker open; after ``reset_s`` one probe request is admitted
  (half-open); its success closes the breaker, its failure re-opens it.
  The clock is injectable (``time_fn``) so the state machine tests
  without sleeping, exactly like :class:`~.health.SLOHealth`.
- :class:`HealthProber` — a polling thread running one boolean probe per
  target (TCP ``ping`` op, or an HTTP ``/healthz`` GET via
  :func:`http_health_probe`) and reporting up/down *transitions* through
  ``on_change``.  Probing is liveness (is the process there at all);
  the breaker is request-path quality — the router routes only where
  both agree.

Both are stdlib-only and own no sockets beyond what the probe callables
dial, so they compose in-process for tests and in the router daemon
unchanged.
"""

from __future__ import annotations

import threading
import time
import urllib.error
import urllib.request
from typing import Callable, Dict, Mapping, Optional

__all__ = ["CircuitBreaker", "HealthProber", "http_health_probe"]


class CircuitBreaker:
    """Closed → open → half-open breaker over request outcomes.

    ``allow()`` answers "may I send this request"; callers must follow
    every allowed request with :meth:`record_success` or
    :meth:`record_failure`.  In half-open exactly one in-flight probe is
    admitted at a time — concurrent callers are refused until the probe
    reports back.
    """

    def __init__(
        self,
        failures: int = 3,
        reset_s: float = 5.0,
        time_fn: Callable[[], float] = time.monotonic,
    ) -> None:
        if failures < 1:
            raise ValueError(f"failure threshold must be >= 1, got {failures}")
        self.failures = failures
        self.reset_s = reset_s
        self._time = time_fn
        self._lock = threading.Lock()
        self._state = "closed"
        self._consecutive = 0
        self._opened_at = 0.0
        self._probing = False

    @property
    def state(self) -> str:
        with self._lock:
            return self._state

    def allow(self) -> bool:
        with self._lock:
            if self._state == "closed":
                return True
            if self._state == "open":
                if self._time() - self._opened_at < self.reset_s:
                    return False
                # Reset window elapsed: admit one probe.
                self._state = "half_open"
                self._probing = True
                return True
            # half_open: one probe at a time.
            if self._probing:
                return False
            self._probing = True
            return True

    def record_success(self) -> None:
        with self._lock:
            self._state = "closed"
            self._consecutive = 0
            self._probing = False

    def record_failure(self) -> None:
        with self._lock:
            self._consecutive += 1
            self._probing = False
            if self._state == "half_open" or self._consecutive >= self.failures:
                self._state = "open"
                self._opened_at = self._time()

    def reset(self) -> None:
        """Force closed (a node verifiably rejoined, e.g. probe up-edge)."""
        self.record_success()


def http_health_probe(url: str, timeout: float = 2.0) -> bool:
    """One ``/healthz`` GET: True only on HTTP 200 — a 503 (degraded SLO)
    or an unreachable listener both read as down."""
    try:
        with urllib.request.urlopen(url, timeout=timeout) as resp:
            return resp.status == 200
    except (urllib.error.URLError, OSError, ValueError):
        return False


class HealthProber:
    """Poll a named set of boolean probes; report up/down transitions.

    ``probes`` maps target name → zero-arg callable returning truthy for
    up (callables bound their own timeouts).  ``on_change(name, up)``
    fires on every transition *and* on the first observation of each
    target, so consumers need no special cold-start handling.  A probe
    that raises reads as down.
    """

    def __init__(
        self,
        probes: Mapping[str, Callable[[], bool]],
        *,
        interval_s: float = 1.0,
        on_change: Optional[Callable[[str, bool], None]] = None,
    ) -> None:
        self._probes = dict(probes)
        self.interval_s = interval_s
        self.on_change = on_change
        #: last observation per target (None = never probed)
        self.status: Dict[str, Optional[bool]] = {n: None for n in self._probes}
        # probe_once is both the poller thread's tick body and a public
        # entry (router failover calls it inline on a routing miss): the
        # transition read-modify-write on ``status`` must not interleave,
        # or both callers observe the same ``prev`` and double-fire
        # on_change for one transition.
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def probe_once(self) -> Dict[str, bool]:
        """Run every probe synchronously (also the thread's tick body)."""
        out: Dict[str, bool] = {}
        for name, fn in self._probes.items():
            try:
                up = bool(fn())
            except Exception:
                up = False
            out[name] = up
            with self._lock:
                prev = self.status.get(name)
                self.status[name] = up
            if up != prev and self.on_change is not None:
                try:
                    self.on_change(name, up)
                except Exception:
                    pass
        return out

    def start(self) -> "HealthProber":
        def _loop() -> None:
            while not self._stop.is_set():
                self.probe_once()
                self._stop.wait(self.interval_s)

        self._thread = threading.Thread(
            target=_loop, name="verifyd-prober", daemon=True
        )
        self._thread.start()
        return self

    def close(self, timeout: float = 2.0) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=timeout)
