"""Durable per-job profile archive: the daemon's recorded-traffic corpus.

The richest exhaust the service produces — per-job profiles, queue and
lease waits, per-shard skew, deciding backend, verdict — used to
evaporate when the JSONL stats sink rotated.  The archive makes it
durable: every ``done`` event becomes one compact record in a
CRC-checked :class:`~..utils.seglog.SegmentLog` under
``<state_dir>/profiles/records/``, and every admitted history's text is
stored once (deduplicated by fingerprint) under
``<state_dir>/profiles/corpus/``.  Together they are a replayable
workload: ``scripts/workload_replay.py`` re-submits the corpus against a
live daemon and checks verdict parity, and the learned-cost-model
ROADMAP item trains directly on the record stream (job features →
observed cost).

Record shape (one JSON object per job)::

    {"t": 1722.5, "job": 3, "client": "loadgen", "fp": "9f3a…",
     "shape": "64x5x8", "backend": "native", "verdict": 0,
     "wall_s": 0.012, "queue_wait_s": 0.003, "lease_wait_s": 0.4,
     "ops": 40, "shape_warm": true, "trace_id": "…",
     "shards": […], "profile": {…}}

Write discipline mirrors the flight recorder: appends are flushed (the
archive survives SIGKILL up to the last OS write) and every failure is
swallowed — archival must never take a job down.  Unlike the flight
ring the record log is *unbounded by default* (it is the training set;
``max_segments`` bounds it when an operator wants a ring).

The read side (:func:`read_archive` / :func:`read_corpus`) is pure —
point it at a dead daemon's ``--state-dir`` and it never creates
directories, which is what the ``profiles`` CLI subcommand, the doctor,
and the replay harness use cold.
"""

from __future__ import annotations

import json
import os
import threading
from typing import Any, Dict, Iterable, List, Optional

from ..utils.seglog import SegmentLog

__all__ = [
    "ARCHIVE_SUBDIR",
    "ProfileArchive",
    "filter_records",
    "read_archive",
    "read_corpus",
]

ARCHIVE_SUBDIR = "profiles"
_RECORDS = "records"
_CORPUS = "corpus"

#: done-event fields copied verbatim into the archived record
_COPY_FIELDS = (
    "t",
    "job",
    "client",
    "shape",
    "backend",
    "verdict",
    "wall_s",
    "queue_wait_s",
    "ops",
    "shape_warm",
    "trace_id",
)


def _records_dir(root: str) -> str:
    return os.path.join(root, _RECORDS)


def _corpus_dir(root: str) -> str:
    return os.path.join(root, _CORPUS)


class ProfileArchive:
    """Write side: lives inside the daemon, fed from the event stream."""

    def __init__(
        self,
        directory: str,
        *,
        fsync: bool = False,
        max_segment_bytes: int = 1 << 20,
        max_segments: Optional[int] = None,
    ) -> None:
        self.dir = directory
        self._lock = threading.Lock()
        self._records_log = SegmentLog(
            _records_dir(directory),
            max_segment_bytes=max_segment_bytes,
            max_segments=max_segments,
            fsync=fsync,
        )
        self._corpus_log = SegmentLog(
            _corpus_dir(directory), max_segment_bytes=4 << 20, fsync=fsync
        )
        # Both logs replay into memory at open: records for the query API,
        # the corpus for fingerprint dedup.  Records are compact (no
        # history text); RAM cost is linear in archived jobs, bounded by
        # max_segments when configured.
        self._records: List[Dict[str, Any]] = _parse_json_records(
            self._records_log.replay()
        )
        self._histories: Dict[str, str] = {}
        for rec in _parse_json_records(self._corpus_log.replay()):
            fp, text = rec.get("fp"), rec.get("history")
            if isinstance(fp, str) and isinstance(text, str):
                self._histories[fp] = text
        #: job id → lease wait, correlated from lease_grant to done
        self._pending_lease: Dict[Any, float] = {}
        self._closed = False
        #: optional service.overload.DegradedWriter: ENOSPC drops records
        #: cheaply (counted, evented) and re-arms when the disk recovers
        self.writer = None

    def _append(self, seg_log: SegmentLog, payload: bytes) -> bool:
        """One append, through the degradation policy when armed."""
        if self.writer is not None:
            _, landed = self.writer.run(lambda: seg_log.append(payload))
            return landed
        try:
            seg_log.append(payload)
            return True
        except (OSError, ValueError, TypeError):
            return False  # archival must never take a job down

    # -- write side ---------------------------------------------------------

    def observe_event(self, ev: Dict[str, Any]) -> None:
        """Absorb one ServiceStats event line (fed outside the sink lock)."""
        name = ev.get("ev") or ev.get("event")
        if name == "lease_grant":
            with self._lock:
                if len(self._pending_lease) < 4096:  # leak guard
                    self._pending_lease[ev.get("job")] = float(
                        ev.get("wait_s", 0.0) or 0.0
                    )
            return
        if name != "done":
            return
        rec: Dict[str, Any] = {
            k: ev[k] for k in _COPY_FIELDS if ev.get(k) is not None
        }
        if ev.get("fingerprint") is not None:
            rec["fp"] = ev["fingerprint"]
        if isinstance(ev.get("profile"), dict):
            rec["profile"] = ev["profile"]
        if ev.get("shards"):
            rec["shards"] = ev["shards"]
        with self._lock:
            lease = self._pending_lease.pop(ev.get("job"), None)
            if lease is not None:
                rec["lease_wait_s"] = lease
            if self._closed:
                return
            try:
                payload = json.dumps(
                    rec, separators=(",", ":"), default=str
                ).encode("utf-8")
            except (ValueError, TypeError):
                return  # archival must never take a job down
            if not self._append(self._records_log, payload):
                return
            self._records.append(rec)

    def add_history(self, fp: str, text: str) -> bool:
        """Store an admitted history once per fingerprint; True when new."""
        with self._lock:
            if self._closed or fp in self._histories:
                return False
            payload = json.dumps(
                {"fp": fp, "history": text}, separators=(",", ":")
            ).encode("utf-8")
            if not self._append(self._corpus_log, payload):
                return False
            self._histories[fp] = text
            return True

    # -- read side ----------------------------------------------------------

    def query(self, **filters: Any) -> List[Dict[str, Any]]:
        """Filtered record copies; see :func:`filter_records` for keys."""
        with self._lock:
            records = list(self._records)
        return filter_records(records, **filters)

    def history(self, fp: str) -> Optional[str]:
        with self._lock:
            return self._histories.get(fp)

    def histories(self) -> Dict[str, str]:
        with self._lock:
            return dict(self._histories)

    def __len__(self) -> int:
        with self._lock:
            return len(self._records)

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "dir": self.dir,
                "records": len(self._records),
                "histories": len(self._histories),
            }

    def close(self) -> None:
        with self._lock:
            if self._closed:
                return
            self._closed = True
            self._records_log.close()
            self._corpus_log.close()


# ------------------------------------------------------------- pure readers


def _parse_json_records(payloads: Iterable[bytes]) -> List[Dict[str, Any]]:
    out: List[Dict[str, Any]] = []
    for payload in payloads:
        try:
            rec = json.loads(payload)
        except ValueError:
            continue
        if isinstance(rec, dict):
            out.append(rec)
    return out


def read_archive(state_dir: str) -> List[Dict[str, Any]]:
    """Replay ``<state_dir>/profiles/records`` cold, oldest first.
    Read-only: tolerates a missing archive (old daemon) by returning []."""
    directory = _records_dir(os.path.join(state_dir, ARCHIVE_SUBDIR))
    if not os.path.isdir(directory):
        return []
    log = SegmentLog(directory)
    try:
        return _parse_json_records(log.replay())
    finally:
        log.close()


def read_corpus(state_dir: str) -> Dict[str, str]:
    """Replay the deduplicated history corpus cold: {fingerprint: text}."""
    directory = _corpus_dir(os.path.join(state_dir, ARCHIVE_SUBDIR))
    if not os.path.isdir(directory):
        return {}
    log = SegmentLog(directory)
    out: Dict[str, str] = {}
    try:
        for rec in _parse_json_records(log.replay()):
            fp, text = rec.get("fp"), rec.get("history")
            if isinstance(fp, str) and isinstance(text, str):
                out[fp] = text
    finally:
        log.close()
    return out


def filter_records(
    records: List[Dict[str, Any]],
    *,
    shape: Optional[str] = None,
    backend: Optional[str] = None,
    verdict: Optional[int] = None,
    client: Optional[str] = None,
    since: Optional[float] = None,
    slowest: Optional[int] = None,
    limit: Optional[int] = None,
) -> List[Dict[str, Any]]:
    """One filter implementation shared by the live ``profiles`` protocol
    op and the cold CLI path.  ``slowest=N`` sorts by wall time
    descending and wins over ``limit`` (which keeps the newest N)."""
    out = records
    if shape is not None:
        out = [r for r in out if r.get("shape") == shape]
    if backend is not None:
        out = [r for r in out if str(r.get("backend", "")).startswith(backend)]
    if verdict is not None:
        out = [r for r in out if r.get("verdict") == verdict]
    if client is not None:
        out = [r for r in out if r.get("client") == client]
    if since is not None:
        out = [r for r in out if float(r.get("t", 0.0) or 0.0) >= since]
    if slowest is not None:
        out = sorted(
            out, key=lambda r: -float(r.get("wall_s", 0.0) or 0.0)
        )[: max(0, slowest)]
    elif limit is not None:
        out = out[-max(0, limit):]
    return [dict(r) for r in out]
