"""Structured logging: JSON or text lines with correlation fields.

Replaces the daemon's ad-hoc ``sys.stderr`` writes with one logger that
every diagnostic goes through.  Two formats, switched by ``serve
--log-format``:

- ``text`` — ``2026-08-05T12:00:00.123Z INFO  msg key=value …`` (the
  human default);
- ``json`` — one JSON object per line (``{"t", "level", "msg", ...}``)
  for log shippers.

Correlation: a logger carries *bound* fields (merged into every line —
e.g. ``component=verifyd``), and call sites pass per-line fields like
``trace_id=…`` / ``job_id=…`` so a grep (or a jq filter) over the log
joins against the trace and the stats stream.  ``bind()`` derives a
child logger with extra bound fields; handy for per-job prefixes.

The module also provides :class:`StructuredHandler`, a
``logging.Handler`` adapter so stdlib ``logging`` emitted by library
code (supervise, resilient, jax itself if enabled) lands in the same
stream with the same format.
"""

from __future__ import annotations

import json
import logging
import sys
import threading
import time
from typing import Any, Dict, IO, Optional

__all__ = ["StructuredLogger", "StructuredHandler", "LEVELS"]

LEVELS = {"debug": 10, "info": 20, "warning": 30, "error": 40}
_NAMES = {v: k for k, v in LEVELS.items()}


class StructuredLogger:
    """Thread-safe leveled line logger, JSON or text, with bound fields."""

    def __init__(
        self,
        stream: Optional[IO[str]] = None,
        *,
        fmt: str = "text",
        level: str = "info",
        **bound: Any,
    ) -> None:
        if fmt not in ("text", "json"):
            raise ValueError("fmt must be 'text' or 'json', got %r" % (fmt,))
        self._stream = stream if stream is not None else sys.stderr
        self.fmt = fmt
        self.level = LEVELS.get(level, 20)
        self._bound: Dict[str, Any] = dict(bound)
        self._lock = threading.Lock()

    def bind(self, **fields: Any) -> "StructuredLogger":
        """A child logger whose lines always carry ``fields`` (e.g.
        ``log.bind(job_id=7, trace_id=tid)``).  Shares the stream+lock."""
        child = StructuredLogger.__new__(StructuredLogger)
        child._stream = self._stream
        child.fmt = self.fmt
        child.level = self.level
        child._bound = {**self._bound, **fields}
        child._lock = self._lock
        return child

    # ------------------------------------------------------------ emit

    def log(self, level: str, msg: str, **fields: Any) -> None:
        lvl = LEVELS.get(level, 20)
        if lvl < self.level:
            return
        merged = {**self._bound, **fields}
        if self.fmt == "json":
            rec: Dict[str, Any] = {
                "t": round(time.time(), 6),
                "level": level,
                "msg": msg,
            }
            rec.update(merged)
            try:
                line = json.dumps(rec, sort_keys=True, default=str)
            except (TypeError, ValueError):
                line = json.dumps(
                    {"t": rec["t"], "level": level, "msg": msg, "unserializable": True}
                )
        else:
            stamp = time.strftime("%Y-%m-%dT%H:%M:%S", time.gmtime())
            extras = " ".join(
                "%s=%s" % (k, _compact(v)) for k, v in sorted(merged.items())
            )
            line = "%sZ %-7s %s" % (stamp, level.upper(), msg)
            if extras:
                line += " " + extras
        try:
            with self._lock:
                self._stream.write(line + "\n")
                self._stream.flush()
        except (OSError, ValueError):
            pass  # a dead log stream must never take the daemon down

    def debug(self, msg: str, **fields: Any) -> None:
        self.log("debug", msg, **fields)

    def info(self, msg: str, **fields: Any) -> None:
        self.log("info", msg, **fields)

    def warning(self, msg: str, **fields: Any) -> None:
        self.log("warning", msg, **fields)

    def error(self, msg: str, **fields: Any) -> None:
        self.log("error", msg, **fields)

    def event(self, name: str, fields: Dict[str, Any]) -> None:
        """Log a ServiceStats event as a structured line (the stats sink
        fallback path: ``stats_log='-'`` routes here instead of raw
        stderr writes)."""
        self.log("info", "event:%s" % name, **fields)


def _compact(value: Any) -> str:
    if isinstance(value, float):
        return "%.6g" % value
    if isinstance(value, str):
        return value if value and " " not in value else json.dumps(value)
    if isinstance(value, (dict, list, tuple)):
        try:
            return json.dumps(value, sort_keys=True, default=str)
        except (TypeError, ValueError):
            return repr(value)
    return str(value)


class StructuredHandler(logging.Handler):
    """stdlib ``logging`` adapter: routes library records (supervise,
    resilient, …) through a StructuredLogger so every diagnostic shares
    one format and one stream."""

    def __init__(self, logger: StructuredLogger) -> None:
        super().__init__()
        self._slog = logger

    def emit(self, record: logging.LogRecord) -> None:
        try:
            level = _NAMES.get(
                min(40, max(10, (record.levelno // 10) * 10)), "info"
            )
            self._slog.log(level, record.getMessage(), logger=record.name)
        except Exception:
            pass
