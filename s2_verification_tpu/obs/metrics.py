"""Counter / gauge / histogram registry with Prometheus text exposition.

Stdlib-only reimplementation of the minimal prometheus_client surface the
daemon needs.  Metrics are created once through the registry (idempotent
per name) and updated from any thread; ``render()`` produces text
exposition format 0.0.4, which Prometheus, VictoriaMetrics, and the
Grafana Agent all scrape natively.

Histograms use *fixed* buckets chosen at creation: cumulative ``le``
bucket semantics (observe(v) lands in every bucket with v <= le, and
``+Inf`` always equals ``_count``), matching the official client so
``histogram_quantile()`` works unmodified in Grafana.

Histogram observations may carry an **exemplar** (the owning request's
``trace_id``): the registry keeps the last exemplar per bucket and
renders it in OpenMetrics exemplar syntax via ``render_openmetrics()``
(served under content negotiation — classic 0.0.4 parsers never see the
``# {...}`` suffix, OpenMetrics scrapers get a bucket→trace link).
"""

from __future__ import annotations

import threading
import time
from bisect import bisect_left
from typing import Any, Dict, Iterable, List, Optional, Sequence, Tuple

__all__ = [
    "Counter",
    "Gauge",
    "Histogram",
    "MetricsRegistry",
    "LATENCY_BUCKETS",
    "LAYER_BUCKETS",
    "OPENMETRICS_CONTENT_TYPE",
]

#: the content type negotiated for ``render_openmetrics()`` output
OPENMETRICS_CONTENT_TYPE = (
    "application/openmetrics-text; version=1.0.0; charset=utf-8"
)

#: Wall/queue latency buckets: sub-ms admission up to the 60s budget ceiling.
LATENCY_BUCKETS: Tuple[float, ...] = (
    0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
    0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0,
)

#: Frontier-depth buckets: BFS layer counts are small integers, power-of-2.
LAYER_BUCKETS: Tuple[float, ...] = (1, 2, 4, 8, 16, 32, 64, 128, 256, 512, 1024)


def _escape_label(value: str) -> str:
    return value.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _escape_help(value: str) -> str:
    return value.replace("\\", "\\\\").replace("\n", "\\n")


def _fmt(v: float) -> str:
    if v == float("inf"):
        return "+Inf"
    if isinstance(v, float) and v.is_integer():
        return str(int(v))
    return repr(v) if isinstance(v, float) else str(v)


def _labelstr(names: Sequence[str], values: Sequence[str], extra: str = "") -> str:
    parts = [f'{n}="{_escape_label(str(v))}"' for n, v in zip(names, values)]
    if extra:
        parts.append(extra)
    return "{" + ",".join(parts) + "}" if parts else ""


class _Metric:
    kind = "untyped"

    def __init__(self, name: str, help: str, labelnames: Sequence[str] = ()) -> None:
        self.name = name
        self.help = help
        self.labelnames = tuple(labelnames)
        self._lock = threading.Lock()
        self._series: Dict[Tuple[str, ...], Any] = {}

    def _key(self, labels: Dict[str, str]) -> Tuple[str, ...]:
        if set(labels) != set(self.labelnames):
            raise ValueError(
                f"{self.name}: expected labels {self.labelnames}, got {tuple(labels)}"
            )
        return tuple(str(labels[n]) for n in self.labelnames)

    def header(self) -> List[str]:
        return [
            f"# HELP {self.name} {_escape_help(self.help)}",
            f"# TYPE {self.name} {self.kind}",
        ]

    def render_om(self) -> List[str]:
        """OpenMetrics lines for this metric; the default matches the
        classic exposition (gauges are identical in both syntaxes)."""
        return self.render()  # type: ignore[attr-defined]


class Counter(_Metric):
    kind = "counter"

    def inc(self, amount: float = 1, **labels: str) -> None:
        if amount < 0:
            raise ValueError(f"{self.name}: counters only go up")
        key = self._key(labels)
        with self._lock:
            self._series[key] = self._series.get(key, 0) + amount

    def value(self, **labels: str) -> float:
        with self._lock:
            return self._series.get(self._key(labels), 0)

    def render(self) -> List[str]:
        out = self.header()
        with self._lock:
            for key in sorted(self._series):
                out.append(
                    f"{self.name}{_labelstr(self.labelnames, key)} "
                    f"{_fmt(self._series[key])}"
                )
        return out

    def snapshot(self) -> Dict[str, float]:
        with self._lock:
            return {
                self.name + _labelstr(self.labelnames, k): v
                for k, v in self._series.items()
            }

    def render_om(self) -> List[str]:
        # OpenMetrics names the *family* without the _total suffix; the
        # samples keep it.  A counter not named *_total renders samples
        # under <family>_total so scrapers still parse the family.
        family = (
            self.name[: -len("_total")]
            if self.name.endswith("_total")
            else self.name
        )
        out = [
            f"# HELP {family} {_escape_help(self.help)}",
            f"# TYPE {family} counter",
        ]
        with self._lock:
            for key in sorted(self._series):
                out.append(
                    f"{family}_total{_labelstr(self.labelnames, key)} "
                    f"{_fmt(self._series[key])}"
                )
        return out


class Gauge(_Metric):
    kind = "gauge"

    def set(self, value: float, **labels: str) -> None:
        key = self._key(labels)
        with self._lock:
            self._series[key] = value

    def inc(self, amount: float = 1, **labels: str) -> None:
        key = self._key(labels)
        with self._lock:
            self._series[key] = self._series.get(key, 0) + amount

    def dec(self, amount: float = 1, **labels: str) -> None:
        self.inc(-amount, **labels)

    def value(self, **labels: str) -> float:
        with self._lock:
            return self._series.get(self._key(labels), 0)

    render = Counter.render
    snapshot = Counter.snapshot


class Histogram(_Metric):
    kind = "histogram"

    def __init__(
        self,
        name: str,
        help: str,
        buckets: Sequence[float] = LATENCY_BUCKETS,
        labelnames: Sequence[str] = (),
    ) -> None:
        super().__init__(name, help, labelnames)
        bs = sorted(float(b) for b in buckets)
        if not bs:
            raise ValueError(f"{name}: histogram needs at least one bucket")
        self.buckets: Tuple[float, ...] = tuple(bs)

    def observe(
        self, value: float, exemplar: Optional[str] = None, **labels: str
    ) -> None:
        key = self._key(labels)
        with self._lock:
            series = self._series.get(key)
            if series is None:
                # [per-bucket counts, sum, count, {bucket_idx: exemplar}];
                # indices 0-2 are load-bearing for counts()/render().
                series = self._series[key] = [
                    [0] * len(self.buckets),
                    0.0,
                    0,
                    {},
                ]
            idx = bisect_left(self.buckets, value)
            if idx < len(self.buckets):
                series[0][idx] += 1
            series[1] += value
            series[2] += 1
            if exemplar:
                # Last exemplar per bucket (+Inf = len(buckets)): one
                # concrete trace_id behind each latency bucket.
                series[3][idx] = (str(exemplar), float(value), time.time())

    def counts(self, **labels: str) -> Tuple[List[int], float, int]:
        """(cumulative bucket counts incl. +Inf, sum, count) for one series."""
        with self._lock:
            series = self._series.get(self._key(labels))
            if series is None:
                return [0] * (len(self.buckets) + 1), 0.0, 0
            cum, acc = [], 0
            for c in series[0]:
                acc += c
                cum.append(acc)
            cum.append(series[2])
            return cum, series[1], series[2]

    def render(self) -> List[str]:
        out = self.header()
        with self._lock:
            for key in sorted(self._series):
                raw, total, count = self._series[key][:3]
                acc = 0
                for le, c in zip(self.buckets, raw):
                    acc += c
                    extra = 'le="%s"' % _fmt(le)
                    out.append(
                        f"{self.name}_bucket"
                        f"{_labelstr(self.labelnames, key, extra)} {acc}"
                    )
                inf = 'le="+Inf"'
                out.append(
                    f"{self.name}_bucket"
                    f"{_labelstr(self.labelnames, key, inf)} {count}"
                )
                out.append(
                    f"{self.name}_sum{_labelstr(self.labelnames, key)} {_fmt(total)}"
                )
                out.append(
                    f"{self.name}_count{_labelstr(self.labelnames, key)} {count}"
                )
        return out

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            out = {}
            for k, v in self._series.items():
                entry: Dict[str, Any] = {"count": v[2], "sum": round(v[1], 6)}
                exemplars = v[3] if len(v) > 3 else {}
                if exemplars:
                    entry["exemplars"] = {
                        _fmt(
                            self.buckets[i] if i < len(self.buckets) else float("inf")
                        ): {"trace_id": ex[0], "value": ex[1], "t": round(ex[2], 3)}
                        for i, ex in sorted(exemplars.items())
                    }
                out[self.name + _labelstr(self.labelnames, k)] = entry
            return out

    def render_om(self) -> List[str]:
        """OpenMetrics exposition with exemplar suffixes on bucket lines:
        ``..._bucket{le="0.25"} 3 # {trace_id="<id>"} 0.18 <ts>``."""
        out = self.header()
        with self._lock:
            for key in sorted(self._series):
                series = self._series[key]
                raw, total, count = series[:3]
                exemplars = series[3] if len(series) > 3 else {}
                acc = 0
                for i, (le, c) in enumerate(zip(self.buckets, raw)):
                    acc += c
                    extra = 'le="%s"' % _fmt(le)
                    line = (
                        f"{self.name}_bucket"
                        f"{_labelstr(self.labelnames, key, extra)} {acc}"
                    )
                    out.append(line + _exemplar_suffix(exemplars.get(i)))
                inf = 'le="+Inf"'
                out.append(
                    f"{self.name}_bucket"
                    f"{_labelstr(self.labelnames, key, inf)} {count}"
                    + _exemplar_suffix(exemplars.get(len(self.buckets)))
                )
                out.append(
                    f"{self.name}_sum{_labelstr(self.labelnames, key)} {_fmt(total)}"
                )
                out.append(
                    f"{self.name}_count{_labelstr(self.labelnames, key)} {count}"
                )
        return out


def _exemplar_suffix(ex: Optional[Tuple[str, float, float]]) -> str:
    """OpenMetrics exemplar clause for one bucket line ('' when absent)."""
    if not ex:
        return ""
    trace_id, value, ts = ex
    return (
        f' # {{trace_id="{_escape_label(trace_id)}"}} {_fmt(value)} {ts:.3f}'
    )


class MetricsRegistry:
    """Idempotent metric factory + renderer (one per daemon)."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._metrics: Dict[str, _Metric] = {}

    def _get_or_create(self, cls, name: str, help: str, **kw) -> Any:
        with self._lock:
            existing = self._metrics.get(name)
            if existing is not None:
                if not isinstance(existing, cls):
                    raise ValueError(
                        f"{name} already registered as {existing.kind}"
                    )
                return existing
            metric = cls(name, help, **kw)
            self._metrics[name] = metric
            return metric

    def counter(self, name: str, help: str = "", labelnames: Sequence[str] = ()) -> Counter:
        return self._get_or_create(Counter, name, help, labelnames=labelnames)

    def gauge(self, name: str, help: str = "", labelnames: Sequence[str] = ()) -> Gauge:
        return self._get_or_create(Gauge, name, help, labelnames=labelnames)

    def histogram(
        self,
        name: str,
        help: str = "",
        buckets: Sequence[float] = LATENCY_BUCKETS,
        labelnames: Sequence[str] = (),
    ) -> Histogram:
        return self._get_or_create(
            Histogram, name, help, buckets=buckets, labelnames=labelnames
        )

    def get(self, name: str) -> Optional[_Metric]:
        """Registered metric by name, or None — read-only lookup for
        consumers (alert threshold rules) that must not create series."""
        with self._lock:
            return self._metrics.get(name)

    def render(self) -> str:
        """Prometheus text exposition format 0.0.4 (trailing newline included).

        The registry lock is held across the whole render: a scrape
        iterating the family dict while a worker thread registers a new
        family must not race the dict (RuntimeError under concurrent
        mutation).  Per-metric locks still serialize series access, and
        registration is rare, so the widened critical section costs a
        scrape nothing measurable.
        """
        with self._lock:
            lines: List[str] = []
            for k in sorted(self._metrics):
                lines.extend(self._metrics[k].render())
        return "\n".join(lines) + "\n"

    def render_openmetrics(self) -> str:
        """OpenMetrics 1.0 exposition: counter families named without the
        ``_total`` suffix, histogram buckets carrying exemplars, and the
        mandatory ``# EOF`` terminator.  Served on /metrics only under
        ``Accept: application/openmetrics-text`` — classic 0.0.4 parsers
        never see exemplar syntax."""
        with self._lock:
            lines = []
            for k in sorted(self._metrics):
                lines.extend(self._metrics[k].render_om())
        lines.append("# EOF")
        return "\n".join(lines) + "\n"

    def snapshot(self) -> Dict[str, Any]:
        """JSON-serializable flat view, merged into the daemon `stats` op."""
        out: Dict[str, Any] = {"counters": {}, "gauges": {}, "histograms": {}}
        with self._lock:
            for k in sorted(self._metrics):
                m = self._metrics[k]
                bucket = {
                    "counter": "counters",
                    "gauge": "gauges",
                    "histogram": "histograms",
                }[m.kind]
                out[bucket].update(m.snapshot())  # type: ignore[attr-defined]
        return out
