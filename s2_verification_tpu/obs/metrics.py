"""Counter / gauge / histogram registry with Prometheus text exposition.

Stdlib-only reimplementation of the minimal prometheus_client surface the
daemon needs.  Metrics are created once through the registry (idempotent
per name) and updated from any thread; ``render()`` produces text
exposition format 0.0.4, which Prometheus, VictoriaMetrics, and the
Grafana Agent all scrape natively.

Histograms use *fixed* buckets chosen at creation: cumulative ``le``
bucket semantics (observe(v) lands in every bucket with v <= le, and
``+Inf`` always equals ``_count``), matching the official client so
``histogram_quantile()`` works unmodified in Grafana.
"""

from __future__ import annotations

import threading
from bisect import bisect_left
from typing import Any, Dict, Iterable, List, Optional, Sequence, Tuple

__all__ = [
    "Counter",
    "Gauge",
    "Histogram",
    "MetricsRegistry",
    "LATENCY_BUCKETS",
    "LAYER_BUCKETS",
]

#: Wall/queue latency buckets: sub-ms admission up to the 60s budget ceiling.
LATENCY_BUCKETS: Tuple[float, ...] = (
    0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
    0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0,
)

#: Frontier-depth buckets: BFS layer counts are small integers, power-of-2.
LAYER_BUCKETS: Tuple[float, ...] = (1, 2, 4, 8, 16, 32, 64, 128, 256, 512, 1024)


def _escape_label(value: str) -> str:
    return value.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _escape_help(value: str) -> str:
    return value.replace("\\", "\\\\").replace("\n", "\\n")


def _fmt(v: float) -> str:
    if v == float("inf"):
        return "+Inf"
    if isinstance(v, float) and v.is_integer():
        return str(int(v))
    return repr(v) if isinstance(v, float) else str(v)


def _labelstr(names: Sequence[str], values: Sequence[str], extra: str = "") -> str:
    parts = [f'{n}="{_escape_label(str(v))}"' for n, v in zip(names, values)]
    if extra:
        parts.append(extra)
    return "{" + ",".join(parts) + "}" if parts else ""


class _Metric:
    kind = "untyped"

    def __init__(self, name: str, help: str, labelnames: Sequence[str] = ()) -> None:
        self.name = name
        self.help = help
        self.labelnames = tuple(labelnames)
        self._lock = threading.Lock()
        self._series: Dict[Tuple[str, ...], Any] = {}

    def _key(self, labels: Dict[str, str]) -> Tuple[str, ...]:
        if set(labels) != set(self.labelnames):
            raise ValueError(
                f"{self.name}: expected labels {self.labelnames}, got {tuple(labels)}"
            )
        return tuple(str(labels[n]) for n in self.labelnames)

    def header(self) -> List[str]:
        return [
            f"# HELP {self.name} {_escape_help(self.help)}",
            f"# TYPE {self.name} {self.kind}",
        ]


class Counter(_Metric):
    kind = "counter"

    def inc(self, amount: float = 1, **labels: str) -> None:
        if amount < 0:
            raise ValueError(f"{self.name}: counters only go up")
        key = self._key(labels)
        with self._lock:
            self._series[key] = self._series.get(key, 0) + amount

    def value(self, **labels: str) -> float:
        with self._lock:
            return self._series.get(self._key(labels), 0)

    def render(self) -> List[str]:
        out = self.header()
        with self._lock:
            for key in sorted(self._series):
                out.append(
                    f"{self.name}{_labelstr(self.labelnames, key)} "
                    f"{_fmt(self._series[key])}"
                )
        return out

    def snapshot(self) -> Dict[str, float]:
        with self._lock:
            return {
                self.name + _labelstr(self.labelnames, k): v
                for k, v in self._series.items()
            }


class Gauge(_Metric):
    kind = "gauge"

    def set(self, value: float, **labels: str) -> None:
        key = self._key(labels)
        with self._lock:
            self._series[key] = value

    def inc(self, amount: float = 1, **labels: str) -> None:
        key = self._key(labels)
        with self._lock:
            self._series[key] = self._series.get(key, 0) + amount

    def dec(self, amount: float = 1, **labels: str) -> None:
        self.inc(-amount, **labels)

    def value(self, **labels: str) -> float:
        with self._lock:
            return self._series.get(self._key(labels), 0)

    render = Counter.render
    snapshot = Counter.snapshot


class Histogram(_Metric):
    kind = "histogram"

    def __init__(
        self,
        name: str,
        help: str,
        buckets: Sequence[float] = LATENCY_BUCKETS,
        labelnames: Sequence[str] = (),
    ) -> None:
        super().__init__(name, help, labelnames)
        bs = sorted(float(b) for b in buckets)
        if not bs:
            raise ValueError(f"{name}: histogram needs at least one bucket")
        self.buckets: Tuple[float, ...] = tuple(bs)

    def observe(self, value: float, **labels: str) -> None:
        key = self._key(labels)
        with self._lock:
            series = self._series.get(key)
            if series is None:
                # [per-bucket counts..., +Inf implicit via count], sum, count
                series = self._series[key] = [[0] * len(self.buckets), 0.0, 0]
            idx = bisect_left(self.buckets, value)
            if idx < len(self.buckets):
                series[0][idx] += 1
            series[1] += value
            series[2] += 1

    def counts(self, **labels: str) -> Tuple[List[int], float, int]:
        """(cumulative bucket counts incl. +Inf, sum, count) for one series."""
        with self._lock:
            series = self._series.get(self._key(labels))
            if series is None:
                return [0] * (len(self.buckets) + 1), 0.0, 0
            cum, acc = [], 0
            for c in series[0]:
                acc += c
                cum.append(acc)
            cum.append(series[2])
            return cum, series[1], series[2]

    def render(self) -> List[str]:
        out = self.header()
        with self._lock:
            for key in sorted(self._series):
                raw, total, count = self._series[key]
                acc = 0
                for le, c in zip(self.buckets, raw):
                    acc += c
                    extra = 'le="%s"' % _fmt(le)
                    out.append(
                        f"{self.name}_bucket"
                        f"{_labelstr(self.labelnames, key, extra)} {acc}"
                    )
                inf = 'le="+Inf"'
                out.append(
                    f"{self.name}_bucket"
                    f"{_labelstr(self.labelnames, key, inf)} {count}"
                )
                out.append(
                    f"{self.name}_sum{_labelstr(self.labelnames, key)} {_fmt(total)}"
                )
                out.append(
                    f"{self.name}_count{_labelstr(self.labelnames, key)} {count}"
                )
        return out

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            return {
                self.name
                + _labelstr(self.labelnames, k): {
                    "count": v[2],
                    "sum": round(v[1], 6),
                }
                for k, v in self._series.items()
            }


class MetricsRegistry:
    """Idempotent metric factory + renderer (one per daemon)."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._metrics: Dict[str, _Metric] = {}

    def _get_or_create(self, cls, name: str, help: str, **kw) -> Any:
        with self._lock:
            existing = self._metrics.get(name)
            if existing is not None:
                if not isinstance(existing, cls):
                    raise ValueError(
                        f"{name} already registered as {existing.kind}"
                    )
                return existing
            metric = cls(name, help, **kw)
            self._metrics[name] = metric
            return metric

    def counter(self, name: str, help: str = "", labelnames: Sequence[str] = ()) -> Counter:
        return self._get_or_create(Counter, name, help, labelnames=labelnames)

    def gauge(self, name: str, help: str = "", labelnames: Sequence[str] = ()) -> Gauge:
        return self._get_or_create(Gauge, name, help, labelnames=labelnames)

    def histogram(
        self,
        name: str,
        help: str = "",
        buckets: Sequence[float] = LATENCY_BUCKETS,
        labelnames: Sequence[str] = (),
    ) -> Histogram:
        return self._get_or_create(
            Histogram, name, help, buckets=buckets, labelnames=labelnames
        )

    def get(self, name: str) -> Optional[_Metric]:
        """Registered metric by name, or None — read-only lookup for
        consumers (alert threshold rules) that must not create series."""
        with self._lock:
            return self._metrics.get(name)

    def render(self) -> str:
        """Prometheus text exposition format 0.0.4 (trailing newline included)."""
        with self._lock:
            metrics = [self._metrics[k] for k in sorted(self._metrics)]
        lines: List[str] = []
        for m in metrics:
            lines.extend(m.render())
        return "\n".join(lines) + "\n"

    def snapshot(self) -> Dict[str, Any]:
        """JSON-serializable flat view, merged into the daemon `stats` op."""
        with self._lock:
            metrics = [self._metrics[k] for k in sorted(self._metrics)]
        out: Dict[str, Any] = {"counters": {}, "gauges": {}, "histograms": {}}
        for m in metrics:
            bucket = {
                "counter": "counters",
                "gauge": "gauges",
                "histogram": "histograms",
            }[m.kind]
            out[bucket].update(m.snapshot())  # type: ignore[attr-defined]
        return out
