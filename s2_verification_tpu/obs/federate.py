"""Federated fleet metrics: every backend's registry, one pane of glass.

The router's ``/metrics`` exports only its *own* registry; each backend
must be scraped separately, so the fleet has no single view and a node
death reads as a missing scrape config rather than a gap in a known
series.  The :class:`FleetScraper` closes that: it polls every
backend's metrics — HTTP ``GET /metrics`` when the backend exposes an
obs httpd (derived from its ``@healthz`` probe URL), the authenticated
``stats`` op's ``metrics`` snapshot as the fallback — and merges the
families under a **closed** ``node`` label whose value set is exactly
the router's static fleet membership (``--backend`` list), the same
bound the verifylint metrics-cardinality pass now proves.

Surfaces (all served by the router's obs httpd):

- ``GET /fleet/metrics`` — the merged exposition.  Every sample line
  from every *live* node, ``node`` injected as the first label.  A node
  whose last scrape is stale contributes **no** samples — a gap, never
  zeros (zeros would read as a real measurement); only the synthetic
  ``verifyd_fleet_node_up`` family keeps reporting it at 0.
- ``GET /fleet/slo`` — the fleet-level SLO rollup: per-node
  availability/burn/health read from the scraped ``verifyd_slo_*``
  gauges, fleet-wide mins/maxes, and summed fleet throughput.
- ``GET /fleet/dashboard`` — a self-contained HTML board (same
  zero-dependency SVG sparklines as the per-daemon dashboard) with one
  retained ring per node.

The scraper also folds the merged view into the router's **own**
registry as bounded ``verifyd_fleet_*`` families (node up, per-node
throughput/queue/RSS, scrape counters, merged-series cardinality) —
which the router's TelemetryStore then records, making fleet history
durable across router restarts.
"""

from __future__ import annotations

import html
import json
import threading
import time
import urllib.error
import urllib.request
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Tuple

from .dashboard import render_sparkline
from .metrics import MetricsRegistry
from .tsdb import flatten_snapshot, parse_series_key

__all__ = ["FleetScraper", "ScrapeTarget", "parse_exposition"]

#: one scraped sample: (metric name, labels, value)
Sample = Tuple[str, Dict[str, str], float]


class ScrapeTarget:
    """How to reach one backend's metrics: an HTTP exposition URL, a
    zero-arg stats callable returning the ``stats`` op snapshot, or both
    (HTTP preferred; the op is the fallback for metrics-portless nodes)."""

    def __init__(
        self,
        metrics_url: Optional[str] = None,
        stats_fn: Optional[Callable[[], Dict[str, Any]]] = None,
    ) -> None:
        self.metrics_url = metrics_url
        self.stats_fn = stats_fn


def parse_exposition(
    text: str,
) -> Tuple[List[Sample], Dict[str, str], Dict[str, str]]:
    """Prometheus text 0.0.4 → (samples, family types, family helps)."""
    samples: List[Sample] = []
    types: Dict[str, str] = {}
    helps: Dict[str, str] = {}
    for line in text.splitlines():
        line = line.strip()
        if not line:
            continue
        if line.startswith("#"):
            parts = line.split(None, 3)
            if len(parts) >= 3 and parts[1] == "TYPE":
                types[parts[2]] = parts[3] if len(parts) > 3 else "untyped"
            elif len(parts) >= 3 and parts[1] == "HELP":
                helps[parts[2]] = parts[3] if len(parts) > 3 else ""
            continue
        key, _, raw = line.rpartition(" ")
        if not key:
            continue
        try:
            value = float(raw)
        except ValueError:
            continue
        name, labels = parse_series_key(key)
        samples.append((name, labels, value))
    return samples, types, helps


def _snapshot_samples(
    snap: Dict[str, Any],
) -> Tuple[List[Sample], Dict[str, str], Dict[str, str]]:
    """``stats`` op ``metrics`` section → the same shape as an HTTP scrape
    (histograms flattened to ``_count``/``_sum``, their family typed)."""
    samples: List[Sample] = []
    types: Dict[str, str] = {}
    for key in (snap.get("counters") or {}):
        types.setdefault(parse_series_key(key)[0], "counter")
    for key in (snap.get("gauges") or {}):
        types.setdefault(parse_series_key(key)[0], "gauge")
    for key in (snap.get("histograms") or {}):
        types.setdefault(parse_series_key(key)[0], "histogram")
    for key, value in flatten_snapshot(snap).items():
        name, labels = parse_series_key(key)
        samples.append((name, labels, value))
    return samples, types, {}


def _find(
    samples: List[Sample], name: str, **labels: str
) -> Optional[float]:
    for n, got, v in samples:
        if n != name:
            continue
        if all(got.get(ln) == lv for ln, lv in labels.items()):
            return v
    return None


def _escape(value: str) -> str:
    return (
        str(value)
        .replace("\\", "\\\\")
        .replace('"', '\\"')
        .replace("\n", "\\n")
    )


class _NodeState:
    __slots__ = (
        "samples",
        "types",
        "helps",
        "last_ok",
        "last_err",
        "scrapes",
        "errors",
        "source",
        "ring",
        "prev_completed",
        "prev_t",
        "build",
    )

    def __init__(self) -> None:
        self.samples: List[Sample] = []
        self.types: Dict[str, str] = {}
        self.helps: Dict[str, str] = {}
        self.last_ok: Optional[float] = None
        self.last_err: Optional[str] = None
        self.scrapes = 0
        self.errors = 0
        self.source: Optional[str] = None
        self.ring: deque = deque(maxlen=240)
        self.prev_completed: Optional[float] = None
        self.prev_t: Optional[float] = None
        self.build: Dict[str, str] = {}


class FleetScraper:
    """Poll every fleet member's metrics; merge, roll up, and re-export."""

    def __init__(
        self,
        registry: MetricsRegistry,
        targets: Dict[str, ScrapeTarget],
        *,
        interval_s: float = 2.0,
        stale_after_s: Optional[float] = None,
        timeout_s: float = 2.0,
        time_fn: Callable[[], float] = time.time,
        title: str = "verifyd fleet",
    ) -> None:
        self.registry = registry
        self.targets = dict(targets)
        #: frozen fleet membership — the closed value set of the ``node``
        #: label (the cardinality pass proves label values fold into it)
        self._nodes = tuple(sorted(self.targets))
        self.interval_s = max(0.2, float(interval_s))
        self.stale_after_s = (
            float(stale_after_s)
            if stale_after_s is not None
            else max(5.0, 3.0 * self.interval_s)
        )
        self.timeout_s = float(timeout_s)
        self.title = title
        self._time = time_fn
        self._lock = threading.Lock()
        self._state: Dict[str, _NodeState] = {
            name: _NodeState() for name in self._nodes
        }
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._m_members = registry.gauge(
            "verifyd_fleet_nodes", "Configured fleet membership size"
        )
        self._m_members.set(float(len(self._nodes)))
        self._m_up = registry.gauge(
            "verifyd_fleet_node_up",
            "1 when the node's last scrape is fresh, 0 when stale/dead",
            labelnames=("node",),
        )
        self._m_scrapes = registry.counter(
            "verifyd_fleet_scrapes_total",
            "Successful federated scrapes, by node",
            labelnames=("node",),
        )
        self._m_errors = registry.counter(
            "verifyd_fleet_scrape_errors_total",
            "Failed federated scrapes, by node",
            labelnames=("node",),
        )
        self._m_series = registry.gauge(
            "verifyd_fleet_series",
            "Merged series count across live nodes (cardinality bound)",
        )
        self._m_jobs = registry.gauge(
            "verifyd_fleet_node_jobs_per_sec",
            "Per-node completed-job rate between scrapes",
            labelnames=("node",),
        )
        self._m_queue = registry.gauge(
            "verifyd_fleet_node_queue_depth",
            "Per-node queue depth at last scrape",
            labelnames=("node",),
        )
        self._m_rss = registry.gauge(
            "verifyd_fleet_node_rss_bytes",
            "Per-node host RSS at last scrape",
            labelnames=("node",),
        )

    # -- scraping ------------------------------------------------------------

    def _fetch(self, target: ScrapeTarget):
        """(samples, types, helps, source) from one backend, HTTP first."""
        if target.metrics_url:
            try:
                with urllib.request.urlopen(
                    target.metrics_url, timeout=self.timeout_s
                ) as resp:
                    text = resp.read().decode("utf-8", "replace")
                return (*parse_exposition(text), "http")
            except (urllib.error.URLError, OSError, ValueError):
                if target.stats_fn is None:
                    raise
        if target.stats_fn is None:
            raise OSError("no scrape path configured")
        snap = target.stats_fn() or {}
        metrics = snap.get("metrics") if isinstance(snap, dict) else None
        if not isinstance(metrics, dict):
            raise OSError("stats reply carried no metrics section")
        return (*_snapshot_samples(metrics), "stats")

    def scrape_once(self) -> Dict[str, bool]:
        """One sweep over the fleet; public for tests and the check
        script.  Returns {node: scrape succeeded}."""
        results: Dict[str, bool] = {}
        for node in self._nodes:
            target = self.targets[node]
            t0 = self._time()
            try:
                samples, types, helps, source = self._fetch(target)
            except Exception as e:  # noqa: BLE001 - any failure is a gap
                results[node] = False
                with self._lock:
                    st = self._state[node]
                    st.errors += 1
                    st.last_err = str(e) or e.__class__.__name__
                if node not in self._nodes:
                    node = "other"
                self._m_errors.inc(node=node)
                continue
            now = self._time()
            with self._lock:
                st = self._state[node]
                st.samples = samples
                st.types = types
                st.helps = helps
                st.last_ok = now
                st.last_err = None
                st.scrapes += 1
                st.source = source
                completed = _find(samples, "verifyd_jobs_completed_total")
                rate = 0.0
                if (
                    completed is not None
                    and st.prev_completed is not None
                    and st.prev_t is not None
                    and now > st.prev_t
                ):
                    rate = max(0.0, completed - st.prev_completed) / (
                        now - st.prev_t
                    )
                if completed is not None:
                    st.prev_completed, st.prev_t = completed, now
                queue = _find(samples, "verifyd_queue_depth") or 0.0
                rss = _find(samples, "verifyd_resource_rss_bytes") or 0.0
                burn = _find(samples, "verifyd_slo_burn_rate", window="fast")
                build = {}
                for n, labels, v in samples:
                    if n == "verifyd_build_info" and v:
                        build = dict(labels)
                        break
                if build:
                    st.build = build
                st.ring.append(
                    {
                        "t": round(now, 3),
                        "throughput": round(rate, 3),
                        "queue_depth": queue,
                        "slo_burn": round(burn or 0.0, 4),
                        "rss_mb": round(rss / (1 << 20), 2),
                        "scrape_s": round(now - t0, 4),
                    }
                )
            results[node] = True
            if node not in self._nodes:
                node = "other"
            self._m_scrapes.inc(node=node)
            self._m_jobs.set(rate, node=node)
            self._m_queue.set(queue, node=node)
            self._m_rss.set(rss, node=node)
        self._refresh_up()
        return results

    def _refresh_up(self) -> None:
        now = self._time()
        live = 0
        series = 0
        with self._lock:
            fresh = {
                node: (
                    st.last_ok is not None
                    and now - st.last_ok <= self.stale_after_s
                )
                for node, st in self._state.items()
            }
            for node, ok in fresh.items():
                if ok:
                    live += 1
                    series += len(self._state[node].samples)
        for node in self._nodes:
            up = 1.0 if fresh.get(node) else 0.0
            if node not in self._nodes:
                node = "other"
            self._m_up.set(up, node=node)
        self._m_series.set(float(series))

    def _live(self) -> Dict[str, _NodeState]:
        """Nodes with a fresh scrape (holding the lock is the caller's job)."""
        now = self._time()
        return {
            node: st
            for node, st in self._state.items()
            if st.last_ok is not None and now - st.last_ok <= self.stale_after_s
        }

    # -- thread --------------------------------------------------------------

    def start(self) -> "FleetScraper":
        if self._thread is None:
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._run, name="verifyd-fleet-scraper", daemon=True
            )
            self._thread.start()
        return self

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.scrape_once()
            except Exception:  # the scraper must never take the router down
                pass

    def close(self, timeout: float = 2.0) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=timeout)
            self._thread = None

    # -- read side -----------------------------------------------------------

    def build_info(self) -> Dict[str, Dict[str, str]]:
        """Last-seen ``verifyd_build_info`` labels per node (route fleet)."""
        with self._lock:
            return {
                node: dict(st.build)
                for node, st in self._state.items()
                if st.build
            }

    def render(self) -> str:
        """The merged ``/fleet/metrics`` exposition, ``node`` label first.

        Families are grouped across live nodes (HELP/TYPE once, first
        node's metadata wins); stale nodes contribute nothing except the
        synthetic ``verifyd_fleet_node_up`` family, which reports every
        configured member so a death reads as ``up 0`` + a gap.
        """
        with self._lock:
            live = self._live()
            families: Dict[str, Tuple[str, str]] = {}
            rows: Dict[str, List[str]] = {}
            up_lines = []
            for node in self._nodes:
                st = self._state[node]
                up_lines.append(
                    f'verifyd_fleet_node_up{{node="{_escape(node)}"}} '
                    f"{1 if node in live else 0}"
                )
                if node not in live:
                    continue
                for name, labels, value in st.samples:
                    family = name
                    for suffix in ("_bucket", "_sum", "_count"):
                        base = name[: -len(suffix)] if name.endswith(suffix) else None
                        if base and st.types.get(base) == "histogram":
                            family = base
                            break
                    if family not in families:
                        families[family] = (
                            st.types.get(family, "untyped"),
                            st.helps.get(family, ""),
                        )
                    parts = [f'node="{_escape(node)}"'] + [
                        f'{ln}="{_escape(lv)}"'
                        for ln, lv in labels.items()
                    ]
                    val = int(value) if float(value).is_integer() else value
                    rows.setdefault(family, []).append(
                        f"{name}{{{','.join(parts)}}} {val}"
                    )
        lines = [
            "# HELP verifyd_fleet_node_up 1 when the node's last scrape "
            "is fresh, 0 when stale/dead",
            "# TYPE verifyd_fleet_node_up gauge",
            *up_lines,
        ]
        for family in sorted(rows):
            kind, help_text = families[family]
            if help_text:
                lines.append(f"# HELP {family} {help_text}")
            lines.append(f"# TYPE {family} {kind}")
            lines.extend(rows[family])
        return "\n".join(lines) + "\n"

    def slo_rollup(self) -> Dict[str, Any]:
        """Fleet-level SLO picture from the scraped ``verifyd_slo_*``
        gauges: per-node availability/burn/health plus fleet extremes
        and summed throughput.  Stale nodes report ``up: False`` with no
        numbers — a gap, not a zero."""
        with self._lock:
            live = self._live()
            nodes: Dict[str, Any] = {}
            avails: List[float] = []
            burns: List[float] = []
            throughput = 0.0
            healthy_nodes = 0
            for node in self._nodes:
                st = self._state[node]
                if node not in live:
                    nodes[node] = {"up": False, "last_error": st.last_err}
                    continue
                avail = _find(
                    st.samples, "verifyd_slo_availability", window="fast"
                )
                burn = _find(
                    st.samples, "verifyd_slo_burn_rate", window="fast"
                )
                healthy = _find(st.samples, "verifyd_slo_healthy")
                rate = st.ring[-1]["throughput"] if st.ring else 0.0
                throughput += rate
                if avail is not None:
                    avails.append(avail)
                if burn is not None:
                    burns.append(burn)
                ok = healthy is None or healthy >= 0.5
                if ok:
                    healthy_nodes += 1
                nodes[node] = {
                    "up": True,
                    "healthy": ok,
                    "availability_fast": avail,
                    "burn_rate_fast": burn,
                    "jobs_per_sec": rate,
                    "source": st.source,
                }
            up = len(live)
        return {
            "title": self.title,
            "nodes": nodes,
            "fleet": {
                "members": len(self._nodes),
                "up": up,
                "healthy_nodes": healthy_nodes,
                "healthy": up > 0 and healthy_nodes == up,
                "availability_min": min(avails) if avails else None,
                "burn_rate_max": max(burns) if burns else None,
                "jobs_per_sec": round(throughput, 3),
            },
        }

    def payload(self) -> Dict[str, Any]:
        """Raw per-node rings (the fleet board's JSON feed, and tests)."""
        with self._lock:
            live = set(self._live())
            return {
                "title": self.title,
                "interval_s": self.interval_s,
                "nodes": {
                    node: {
                        "up": node in live,
                        "scrapes": st.scrapes,
                        "errors": st.errors,
                        "source": st.source,
                        "build": dict(st.build),
                        "samples": [dict(s) for s in st.ring],
                    }
                    for node, st in self._state.items()
                },
            }

    def render_json(self) -> str:
        return json.dumps(self.payload(), sort_keys=True) + "\n"

    def render_html(self) -> str:
        """``/fleet/dashboard``: one self-contained HTML board, one row
        per (node, series) with the same inline-SVG sparklines as the
        per-daemon dashboard."""
        rollup = self.slo_rollup()
        with self._lock:
            live = set(self._live())
            node_rows = []
            for node in self._nodes:
                st = self._state[node]
                samples = list(st.ring)
                status = "up" if node in live else "DOWN"
                build = st.build
                build_txt = (
                    " · ".join(
                        f"{k}={v}" for k, v in sorted(build.items())
                    )
                    if build
                    else ""
                )
                node_rows.append(
                    f'<h2>{html.escape(node)} '
                    f'<span class="unit">{status}'
                    f'{(" · " + html.escape(build_txt)) if build_txt else ""}'
                    "</span></h2>"
                )
                series = (
                    ("throughput", "jobs/s"),
                    ("queue_depth", "jobs"),
                    ("slo_burn", "x"),
                    ("rss_mb", "MiB"),
                )
                rows = []
                for key, unit in series:
                    vals = [float(s.get(key, 0.0)) for s in samples]
                    cur = vals[-1] if vals else 0.0
                    rows.append(
                        "<tr>"
                        f'<td class="name">{html.escape(key)}</td>'
                        f'<td class="val">{cur:g}<span class="unit"> '
                        f"{html.escape(unit)}</span></td>"
                        f"<td>{render_sparkline(vals)}</td>"
                        "</tr>"
                    )
                node_rows.append(f"<table>{''.join(rows)}</table>")
        fleet = rollup["fleet"]
        refresh = max(1, int(round(self.interval_s)))
        when = time.strftime(
            "%Y-%m-%dT%H:%M:%SZ", time.gmtime(self._time())
        )
        return (
            "<!DOCTYPE html>\n"
            '<html><head><meta charset="utf-8">'
            f'<meta http-equiv="refresh" content="{refresh}">'
            f"<title>{html.escape(self.title)}</title>"
            "<style>"
            "body{font:14px/1.4 system-ui,sans-serif;margin:2em;"
            "background:#fbfbfb;color:#222}"
            "table{border-collapse:collapse;margin-bottom:1em}"
            "td{padding:.35em .9em;border-bottom:1px solid #e4e4e4;"
            "vertical-align:middle}"
            "td.name{font-weight:600}"
            "td.val{font-variant-numeric:tabular-nums;text-align:right}"
            ".unit{color:#888;font-size:12px}"
            "svg.spark{display:block}"
            "h1{font-size:18px}h2{font-size:15px}"
            "footer{margin-top:1.5em;color:#888;font-size:12px}"
            "</style></head><body>"
            f"<h1>{html.escape(self.title)} — "
            f"{fleet['up']}/{fleet['members']} up · "
            f"{fleet['jobs_per_sec']:g} jobs/s"
            "</h1>"
            f"{''.join(node_rows)}"
            f"<footer>{self.interval_s:g}s scrape · rendered {when} · "
            "also: <code>/fleet/metrics</code>, <code>/fleet/slo</code>"
            "</footer></body></html>\n"
        )
