"""Perf-regression sentinel: rolling per-shape EWMA baselines that page.

BENCH json lines and BASELINE.json catch regressions *between releases*;
nothing watched the live daemon drift *within* one.  The sentinel rides
the same ServiceStats event stream as every other obs consumer and
keeps, per ``shape_key``, an exponentially-weighted moving average of
verification wall time plus a completion-rate EWMA (from done-event
inter-arrival gaps).  When a shape's wall time sits above its own
baseline by more than the configured band for ``consecutive`` jobs in a
row, the sentinel reports a regression; ServiceStats re-emits it as a
``perf_regression`` event on the stream — which the
:class:`~.alerts.AlertEngine` routes by default, the flight ring
records, and ``verifyd_perf_regressions_total`` counts.

Tuning rationale:

- **cold start**: the first ``min_samples`` jobs per shape only build
  the baseline (first compilation of a shape is legitimately slow);
- **consecutive filter**: one GC pause or noisy-neighbor blip is not a
  regression — the band must hold for several jobs running;
- **contaminated baseline**: out-of-band samples still fold in, but at
  ``alpha/8`` — a genuine persistent shift re-baselines over time
  instead of paging forever, while a transient spike barely moves it;
- **re-arm**: a sample back inside the band resets the streak and
  re-arms the shape, so recovery → regression pages again (edge
  triggering, same discipline as the SLO breach and alert rules);
- **floor**: sub-``floor_s`` walls are scheduler-noise dominated on a
  warm shape and never judged.

Exposed via ``GET /sentinel`` on the obs httpd, the ``stats`` op's
``sentinel`` section, and consumed offline by ``scripts/perf_watch.py``
(the same EWMA-band math applied to BENCH history files).
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass
from typing import Any, Callable, Dict, Optional

from .metrics import MetricsRegistry

__all__ = ["PerfSentinel", "SentinelConfig", "ewma_drift", "seed_from_telemetry"]


@dataclass(frozen=True)
class SentinelConfig:
    alpha: float = 0.25  #: EWMA weight of the newest in-band sample
    band: float = 0.75  #: fire when wall > baseline * (1 + band)
    min_samples: int = 8  #: per-shape cold-start guard
    consecutive: int = 3  #: out-of-band jobs in a row before firing
    floor_s: float = 0.005  #: walls under this are noise, never judged


def ewma_drift(value: float, baseline: float, band: float) -> bool:
    """The one drift predicate, shared with scripts/perf_watch.py."""
    return value > baseline * (1.0 + band)


class _ShapeState:
    __slots__ = (
        "n",
        "ewma_wall",
        "ewma_rate",
        "last_t",
        "last_wall",
        "streak",
        "fired",
        "regressions",
    )

    def __init__(self) -> None:
        self.n = 0
        self.ewma_wall: Optional[float] = None
        self.ewma_rate: Optional[float] = None
        self.last_t: Optional[float] = None
        self.last_wall = 0.0
        self.streak = 0
        self.fired = False
        self.regressions = 0


class PerfSentinel:
    """Per-shape EWMA drift detector over done events."""

    def __init__(
        self,
        config: Optional[SentinelConfig] = None,
        *,
        registry: Optional[MetricsRegistry] = None,
        time_fn: Callable[[], float] = time.time,
    ) -> None:
        self.config = config if config is not None else SentinelConfig()
        self.registry = registry if registry is not None else MetricsRegistry()
        self._time = time_fn
        self._lock = threading.Lock()
        self._shapes: Dict[str, _ShapeState] = {}
        self._m_regressions = self.registry.counter(
            "verifyd_perf_regressions_total",
            "Sentinel wall-time drift trips, by shape",
            labelnames=("shape",),
        )
        self._m_baseline = self.registry.gauge(
            "verifyd_perf_baseline_wall_seconds",
            "Sentinel EWMA wall-time baseline, by shape",
            labelnames=("shape",),
        )
        # Latch state as a gauge so the telemetry store carries it across
        # restarts: a shape that was paging when the process died must not
        # re-page on the first post-boot sample (seed() restores it).
        self._m_fired = self.registry.gauge(
            "verifyd_perf_regression_fired",
            "Sentinel regression latch, by shape (1 = latched)",
            labelnames=("shape",),
        )

    # -- stream side ---------------------------------------------------------

    def observe_event(self, ev: Dict[str, Any]) -> Optional[Dict[str, Any]]:
        """Feed one event line; a regression report means the caller
        (ServiceStats) should emit ``perf_regression`` with it."""
        name = ev.get("ev") or ev.get("event")
        if name != "done":
            return None
        shape = ev.get("shape")
        try:
            wall = float(ev.get("wall_s"))
        except (TypeError, ValueError):
            return None
        if not isinstance(shape, str) or not shape:
            return None
        return self.observe(shape, wall, t=ev.get("t"))

    def observe(
        self, shape: str, wall_s: float, t: Optional[float] = None
    ) -> Optional[Dict[str, Any]]:
        """Core fold, directly unit-testable without event plumbing."""
        cfg = self.config
        now = float(t) if t is not None else self._time()
        with self._lock:
            st = self._shapes.setdefault(shape, _ShapeState())
            st.n += 1
            st.last_wall = wall_s
            if st.last_t is not None and now > st.last_t:
                rate = 1.0 / (now - st.last_t)
                st.ewma_rate = (
                    rate
                    if st.ewma_rate is None
                    else (1 - cfg.alpha) * st.ewma_rate + cfg.alpha * rate
                )
            st.last_t = now

            if st.ewma_wall is None:
                st.ewma_wall = wall_s
                self._m_baseline.set(st.ewma_wall, shape=shape)
                # padding-bucketed like the baseline gauge above
                self._m_fired.set(0.0, shape=shape)  # verifylint: disable=metric-open-label
                return None
            baseline = st.ewma_wall
            judged = (
                st.n > cfg.min_samples
                and wall_s > cfg.floor_s
                and ewma_drift(wall_s, baseline, cfg.band)
            )
            if judged:
                # Out of band: barely move the baseline so a transient
                # spike can't poison it, but a persistent shift still
                # re-baselines eventually.
                st.ewma_wall = (
                    1 - cfg.alpha / 8
                ) * baseline + cfg.alpha / 8 * wall_s
                st.streak += 1
                fire = st.streak >= cfg.consecutive and not st.fired
                if fire:
                    st.fired = True
                    st.regressions += 1
            else:
                st.ewma_wall = (1 - cfg.alpha) * baseline + cfg.alpha * wall_s
                st.streak = 0
                st.fired = False  # recovery re-arms the shape
                fire = False
            self._m_baseline.set(st.ewma_wall, shape=shape)
            # padding-bucketed like the baseline gauge above
            self._m_fired.set(1.0 if st.fired else 0.0, shape=shape)  # verifylint: disable=metric-open-label
            if not fire:
                return None
            self._m_regressions.inc(shape=shape)
            report = {
                "shape": shape,
                "wall_s": round(wall_s, 6),
                "baseline_wall_s": round(baseline, 6),
                "ratio": round(wall_s / baseline, 3) if baseline > 0 else 0.0,
                "band": cfg.band,
                "streak": st.streak,
                "samples": st.n,
            }
            if st.ewma_rate is not None:
                report["jobs_per_sec_ewma"] = round(st.ewma_rate, 3)
            return report

    def seed(self, shape: str, wall_s: float, *, fired: bool = False) -> bool:
        """Restore one shape's baseline from durable history at boot.

        Marks the shape warm (``n = min_samples + 1``): the whole point of
        seeding is that a post-restart slowdown is judged against the
        *pre*-restart baseline immediately, not after a fresh cold start.
        A latched shape stays latched (no re-page on the first sample);
        an in-band sample re-arms it exactly as it would have live.  Live
        samples outrank history: a shape that has already observed real
        traffic this boot is never overwritten.
        """
        cfg = self.config
        if not isinstance(shape, str) or not shape:
            return False
        try:
            wall = float(wall_s)
        except (TypeError, ValueError):
            return False
        if not wall > 0.0:  # rejects zero, negatives, and NaN
            return False
        with self._lock:
            st = self._shapes.get(shape)
            if st is not None and st.n > 0:
                return False
            st = self._shapes.setdefault(shape, _ShapeState())
            st.ewma_wall = wall
            st.n = cfg.min_samples + 1
            st.fired = bool(fired)
            st.streak = cfg.consecutive if st.fired else 0
            # padding-bucketed like the live observe() path
            self._m_baseline.set(wall, shape=shape)  # verifylint: disable=metric-open-label
            self._m_fired.set(1.0 if st.fired else 0.0, shape=shape)  # verifylint: disable=metric-open-label
        return True

    # -- read side ------------------------------------------------------------

    def snapshot(self) -> Dict[str, Any]:
        cfg = self.config
        with self._lock:
            shapes = {
                shape: {
                    "samples": st.n,
                    "baseline_wall_s": (
                        round(st.ewma_wall, 6) if st.ewma_wall is not None else None
                    ),
                    "last_wall_s": round(st.last_wall, 6),
                    "jobs_per_sec_ewma": (
                        round(st.ewma_rate, 3) if st.ewma_rate is not None else None
                    ),
                    "streak": st.streak,
                    "fired": st.fired,
                    "regressions": st.regressions,
                }
                for shape, st in self._shapes.items()
            }
            total = sum(st.regressions for st in self._shapes.values())
        return {
            "config": {
                "alpha": cfg.alpha,
                "band": cfg.band,
                "min_samples": cfg.min_samples,
                "consecutive": cfg.consecutive,
                "floor_s": cfg.floor_s,
            },
            "regressions": total,
            "shapes": shapes,
        }


def seed_from_telemetry(
    sentinel: PerfSentinel, values: Dict[str, float]
) -> int:
    """Seed baselines + latch state from a flattened telemetry snapshot
    (``obs.tsdb.last_values`` / ``TelemetryStore.boot_values``).  Returns
    how many shapes were restored — the ``telemetry_loaded`` event
    reports it."""
    from .tsdb import parse_series_key

    baselines: Dict[str, float] = {}
    latched: Dict[str, bool] = {}
    for key, value in values.items():
        name, labels = parse_series_key(key)
        shape = labels.get("shape")
        if not shape:
            continue
        if name == "verifyd_perf_baseline_wall_seconds":
            baselines[shape] = value
        elif name == "verifyd_perf_regression_fired":
            latched[shape] = value >= 0.5
    seeded = 0
    for shape, wall in sorted(baselines.items()):
        if sentinel.seed(shape, wall, fired=latched.get(shape, False)):
            seeded += 1
    return seeded
