"""Stdlib-only HTTP listener: /metrics, /healthz, /slo, /dashboard.

One ThreadingHTTPServer on a daemon thread per daemon process.  Port 0
binds an ephemeral port (the bound port is readable via ``.port`` — used
by tests and `make obs`).  There is deliberately no write surface here.

Endpoints:

- ``GET /metrics`` — Prometheus text exposition.  When a health engine
  is attached, its gauges are refreshed *before* rendering so a scrape
  never sees stale SLO numbers.  Clients that negotiate
  ``Accept: application/openmetrics-text`` get the OpenMetrics
  exposition instead — same families, plus histogram exemplars
  (bucket → ``trace_id``) and the ``# EOF`` terminator.
- ``GET /healthz`` — a *real* health check: 200 with ``{"status":"ok"}``
  when within SLO, **503** with ``{"status":"degraded","reasons":[…]}``
  when a burn threshold or latency target is blown.  Load balancers key
  off the status code; humans and alerting key off the JSON reasons.
  Without a health engine it degrades to the old static 200 "ok".
- ``GET /slo`` — the full SLO snapshot (all windows, quantiles, burn
  rates, breach history) as JSON.
- ``GET /sentinel`` — the perf-regression sentinel's per-shape EWMA
  baselines and trip counts as JSON (404 without a sentinel).
- ``GET /dashboard`` — a self-contained zero-dependency HTML page with
  server-side SVG sparklines over the retained scrape ring (404 without
  a dashboard); ``GET /dashboard.json`` is the raw series feed.
- ``GET /fleet/metrics`` / ``/fleet/slo`` / ``/fleet/dashboard`` /
  ``/fleet/dashboard.json`` — the federated fleet plane (404 without a
  federator; only the router attaches one): every backend's families
  merged under a ``node`` label, the fleet SLO rollup, and the fleet
  board.
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import TYPE_CHECKING, Optional

from .metrics import OPENMETRICS_CONTENT_TYPE, MetricsRegistry

if TYPE_CHECKING:  # pragma: no cover - import cycle guard (health ← metrics)
    from .dashboard import Dashboard
    from .federate import FleetScraper
    from .health import SLOHealth
    from .sentinel import PerfSentinel

__all__ = ["MetricsServer", "CONTENT_TYPE"]

CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"
_JSON_TYPE = "application/json; charset=utf-8"
_HTML_TYPE = "text/html; charset=utf-8"


class MetricsServer:
    """Background /metrics + /healthz + /slo server bound to ``host:port``."""

    def __init__(
        self,
        registry: MetricsRegistry,
        port: int,
        host: str = "127.0.0.1",
        *,
        health: "Optional[SLOHealth]" = None,
        sentinel: "Optional[PerfSentinel]" = None,
        dashboard: "Optional[Dashboard]" = None,
        federator: "Optional[FleetScraper]" = None,
    ) -> None:
        self.registry = registry
        self.health = health
        self.sentinel = sentinel
        self.dashboard = dashboard
        self.federator = federator

        server = self

        class _Handler(BaseHTTPRequestHandler):
            def _reply(self, code: int, body: bytes, ctype: str) -> None:
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self) -> None:  # noqa: N802 (http.server API)
                path = self.path.split("?", 1)[0]
                if path == "/metrics":
                    if server.health is not None:
                        server.health.refresh()
                    accept = self.headers.get("Accept", "") or ""
                    if "application/openmetrics-text" in accept:
                        self._reply(
                            200,
                            server.registry.render_openmetrics().encode("utf-8"),
                            OPENMETRICS_CONTENT_TYPE,
                        )
                    else:
                        self._reply(
                            200,
                            server.registry.render().encode("utf-8"),
                            CONTENT_TYPE,
                        )
                elif path == "/healthz":
                    if server.health is None:
                        self._reply(200, b"ok\n", "text/plain; charset=utf-8")
                        return
                    healthy, body = server.health.healthz()
                    self._reply(
                        200 if healthy else 503,
                        (json.dumps(body, sort_keys=True) + "\n").encode("utf-8"),
                        _JSON_TYPE,
                    )
                elif path == "/slo" and server.health is not None:
                    snap = server.health.refresh()
                    self._reply(
                        200,
                        (json.dumps(snap, sort_keys=True) + "\n").encode("utf-8"),
                        _JSON_TYPE,
                    )
                elif path == "/sentinel" and server.sentinel is not None:
                    snap = server.sentinel.snapshot()
                    self._reply(
                        200,
                        (json.dumps(snap, sort_keys=True) + "\n").encode("utf-8"),
                        _JSON_TYPE,
                    )
                elif path == "/dashboard" and server.dashboard is not None:
                    self._reply(
                        200,
                        server.dashboard.render_html().encode("utf-8"),
                        _HTML_TYPE,
                    )
                elif path == "/dashboard.json" and server.dashboard is not None:
                    self._reply(
                        200,
                        server.dashboard.render_json().encode("utf-8"),
                        _JSON_TYPE,
                    )
                elif path == "/fleet/metrics" and server.federator is not None:
                    self._reply(
                        200,
                        server.federator.render().encode("utf-8"),
                        CONTENT_TYPE,
                    )
                elif path == "/fleet/slo" and server.federator is not None:
                    snap = server.federator.slo_rollup()
                    self._reply(
                        200,
                        (json.dumps(snap, sort_keys=True) + "\n").encode("utf-8"),
                        _JSON_TYPE,
                    )
                elif (
                    path == "/fleet/dashboard" and server.federator is not None
                ):
                    self._reply(
                        200,
                        server.federator.render_html().encode("utf-8"),
                        _HTML_TYPE,
                    )
                elif (
                    path == "/fleet/dashboard.json"
                    and server.federator is not None
                ):
                    self._reply(
                        200,
                        server.federator.render_json().encode("utf-8"),
                        _JSON_TYPE,
                    )
                else:
                    self.send_error(404)

            def log_message(self, *_args) -> None:  # quiet: stats sink is the log
                pass

        self._httpd = ThreadingHTTPServer((host, port), _Handler)
        self._httpd.daemon_threads = True
        self.host, self.port = self._httpd.server_address[:2]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever,
            kwargs={"poll_interval": 0.25},
            name="verifyd-metrics",
            daemon=True,
        )
        self._thread.start()

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}/metrics"

    def close(self, timeout: Optional[float] = 2.0) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        self._thread.join(timeout=timeout)
