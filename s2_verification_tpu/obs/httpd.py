"""Stdlib-only HTTP listener serving GET /metrics for a MetricsRegistry.

One ThreadingHTTPServer on a daemon thread per daemon process.  Port 0
binds an ephemeral port (the bound port is readable via ``.port`` — used
by tests and `make obs`).  Anything other than GET /metrics (and a
convenience GET /healthz) is a 404; there is deliberately no write
surface here.
"""

from __future__ import annotations

import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

from .metrics import MetricsRegistry

__all__ = ["MetricsServer", "CONTENT_TYPE"]

CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"


class MetricsServer:
    """Background /metrics exposition server bound to ``host:port``."""

    def __init__(
        self,
        registry: MetricsRegistry,
        port: int,
        host: str = "127.0.0.1",
    ) -> None:
        self.registry = registry

        server = self

        class _Handler(BaseHTTPRequestHandler):
            def do_GET(self) -> None:  # noqa: N802 (http.server API)
                path = self.path.split("?", 1)[0]
                if path == "/metrics":
                    body = server.registry.render().encode("utf-8")
                    self.send_response(200)
                    self.send_header("Content-Type", CONTENT_TYPE)
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                elif path == "/healthz":
                    body = b"ok\n"
                    self.send_response(200)
                    self.send_header("Content-Type", "text/plain; charset=utf-8")
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                else:
                    self.send_error(404)

            def log_message(self, *_args) -> None:  # quiet: stats sink is the log
                pass

        self._httpd = ThreadingHTTPServer((host, port), _Handler)
        self._httpd.daemon_threads = True
        self.host, self.port = self._httpd.server_address[:2]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever,
            kwargs={"poll_interval": 0.25},
            name="verifyd-metrics",
            daemon=True,
        )
        self._thread.start()

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}/metrics"

    def close(self, timeout: Optional[float] = 2.0) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        self._thread.join(timeout=timeout)
