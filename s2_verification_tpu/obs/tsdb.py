"""Durable time-series store: the MetricsRegistry, with a memory.

Every telemetry surface before this one — the dashboard sample ring, the
PerfSentinel EWMA baselines, the SLO windows — is in-memory and
per-process: a rolling restart or SIGKILL erases all history, so a
post-deploy regression looks like a cold start and the doctor can only
narrate events, never metric *trajectories*.  The tsdb closes that gap
with the same storage discipline every other durable artifact in this
repo already uses (``utils/seglog.SegmentLog``: CRC-checked records,
flushed appends, torn-tail recovery, bounded retention).

Layout — one log per downsampling ring under the telemetry dir::

    <telemetry-dir>/raw/seg-*.log     every sample      (dashboard cadence)
    <telemetry-dir>/1m/seg-*.log      last sample per 60s bucket
    <telemetry-dir>/15m/seg-*.log     last sample per 900s bucket

Each record is one JSON object ``{"t": wall, "k": "b"|"d", "v": {...}}``
over the *flattened* registry snapshot (counters and gauges by their
rendered series key, histograms as ``<name>_count{...}`` /
``<name>_sum{...}``).  ``"b"`` is a base keyframe carrying every series;
``"d"`` is a delta carrying only the series whose value changed since
the previous record — values are **absolute**, so replay is a cumulative
``dict.update`` and a lost delta can only delay a series, never corrupt
it.  Every boot writes a fresh keyframe (the registry restarts from
zero, so no cross-boot writer state is needed), and a keyframe recurs
every ``keyframe_every`` records so retention eviction of old segments
bounds, rather than breaks, cold reads.

Retention is byte-bounded per ring (``max_segment_bytes`` ×
``max_segments``, oldest segment dropped on rotation); the coarse rings
hold the same byte budget and therefore proportionally longer history —
that multi-resolution exhaust is exactly what the learned-cost-model
ROADMAP item trains on.

The **cold reader** (:func:`query`, :func:`last_values`,
:func:`telemetry_info`) never creates directories and never appends — it
is what ``tsq`` (cold mode), the doctor's telemetry-history section, and
PerfSentinel boot seeding use.  The **live** ``tsq`` op answers by cold-
reading the store's own directory: appends are flushed immediately, so
live and cold agree by construction.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

from ..utils.seglog import Recovery, SegmentLog
from .metrics import MetricsRegistry

__all__ = [
    "RESOLUTIONS",
    "TELEMETRY_SUBDIR",
    "TelemetryStore",
    "default_dir",
    "flatten_snapshot",
    "last_values",
    "parse_series_key",
    "query",
    "telemetry_info",
]

#: the downsampling rings: (name, bucket seconds); 0.0 = every sample
RESOLUTIONS: Tuple[Tuple[str, float], ...] = (
    ("raw", 0.0),
    ("1m", 60.0),
    ("15m", 900.0),
)

#: where the store lives under a daemon ``--state-dir`` by default
TELEMETRY_SUBDIR = "telemetry"


def default_dir(state_dir: str) -> str:
    """The conventional telemetry dir for a state dir — the doctor reads
    here when no explicit ``--telemetry-dir`` is given."""
    return os.path.join(state_dir, TELEMETRY_SUBDIR)


def flatten_snapshot(snap: Dict[str, Any]) -> Dict[str, float]:
    """``MetricsRegistry.snapshot()`` → flat ``{series_key: value}``.

    Counters and gauges keep their rendered key (``name{a="b"}``);
    histograms flatten to the two scrape-visible scalars per series,
    ``<name>_count{...}`` and ``<name>_sum{...}`` (bucket vectors are
    dashboard detail, not history).
    """
    out: Dict[str, float] = {}
    for key, v in (snap.get("counters") or {}).items():
        out[key] = float(v)
    for key, v in (snap.get("gauges") or {}).items():
        out[key] = float(v)
    for key, h in (snap.get("histograms") or {}).items():
        if not isinstance(h, dict):
            continue
        name, brace, rest = key.partition("{")
        suffix = brace + rest
        out[name + "_count" + suffix] = float(h.get("count", 0) or 0)
        out[name + "_sum" + suffix] = float(h.get("sum", 0.0) or 0.0)
    return out


def parse_series_key(key: str) -> Tuple[str, Dict[str, str]]:
    """``name{a="b",c="d"}`` → ``(name, {a: b, c: d})``; unescapes label
    values the way ``obs.metrics`` escaped them."""
    name, brace, rest = key.partition("{")
    if not brace:
        return name, {}
    labels: Dict[str, str] = {}
    i = 0
    n = len(rest)
    while i < n and rest[i] != "}":
        eq = rest.find('="', i)
        if eq < 0:
            break
        lname = rest[i:eq]
        i = eq + 2
        buf: List[str] = []
        while i < n:
            ch = rest[i]
            if ch == "\\" and i + 1 < n:
                nxt = rest[i + 1]
                buf.append("\n" if nxt == "n" else nxt)
                i += 2
                continue
            if ch == '"':
                i += 1
                break
            buf.append(ch)
            i += 1
        labels[lname.strip()] = "".join(buf)
        if i < n and rest[i] == ",":
            i += 1
    return name, labels


def _match(
    key: str, metric: Optional[str], labels: Optional[Dict[str, str]]
) -> bool:
    name, got = parse_series_key(key)
    if metric and metric not in name:
        return False
    for ln, lv in (labels or {}).items():
        if got.get(ln) != lv:
            return False
    return True


class TelemetryStore:
    """Sampler + writer for one process's metric history.

    Construction replays every ring read-only (recovery counts + the
    last cumulative values land in :attr:`recovery` / :meth:`boot_values`
    for the ``telemetry_loaded`` event and sentinel seeding), then arms
    the writer: the next sample per ring is a boot keyframe.
    """

    def __init__(
        self,
        directory: str,
        registry: MetricsRegistry,
        *,
        sample_s: float = 2.0,
        keyframe_every: int = 64,
        max_segment_bytes: int = 256 << 10,
        max_segments: int = 8,
        fsync: bool = False,
        time_fn: Callable[[], float] = time.time,
    ) -> None:
        self.dir = directory
        self.registry = registry
        self.sample_s = max(0.05, float(sample_s))
        self.keyframe_every = max(2, int(keyframe_every))
        self._time = time_fn
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._closed = False
        #: optional service.overload.DegradedWriter — history must never
        #: take the daemon down on a full disk
        self.writer = None
        self._logs: Dict[str, SegmentLog] = {}
        self._prev: Dict[str, Optional[Dict[str, float]]] = {}
        self._count: Dict[str, int] = {}
        self._pending: Dict[str, Optional[Tuple[float, Dict[str, float]]]] = {}
        #: per-resolution Recovery from the boot replay
        self.recovery: Dict[str, Recovery] = {}
        self._boot: Dict[str, Tuple[Optional[float], Dict[str, float]]] = {}
        for res, _step in RESOLUTIONS:
            log = SegmentLog(
                os.path.join(directory, res),
                max_segment_bytes=max_segment_bytes,
                max_segments=max_segments,
                fsync=fsync,
            )
            last_t: Optional[float] = None
            values: Dict[str, float] = {}
            for payload in log.replay():
                rec = _decode(payload)
                if rec is None:
                    continue
                last_t = rec[0]
                values.update(rec[2])
            self.recovery[res] = log.recovery
            self._boot[res] = (last_t, values)
            self._logs[res] = log
            self._prev[res] = None  # forces a boot keyframe
            self._count[res] = 0
            self._pending[res] = None
        self._m_points = registry.counter(
            "verifyd_telemetry_points_total",
            "Telemetry records appended, by resolution ring",
            labelnames=("res",),
        )
        self._m_bytes = registry.counter(
            "verifyd_telemetry_bytes_total",
            "Telemetry payload bytes appended across all rings",
        )
        self._m_store = registry.gauge(
            "verifyd_telemetry_store_bytes",
            "On-disk size of the telemetry store (all rings)",
        )

    # -- boot read side ------------------------------------------------------

    def boot_values(
        self, res: str = "raw"
    ) -> Tuple[Optional[float], Dict[str, float]]:
        """(last sample wall time, cumulative values) found at boot —
        what PerfSentinel seeds from.  ``(None, {})`` on a fresh dir."""
        t, values = self._boot.get(res, (None, {}))
        return t, dict(values)

    def recovery_summary(self) -> Dict[str, Dict[str, Any]]:
        """Per-ring recovery counts for the ``telemetry_loaded`` event."""
        return {
            res: {
                "records": rec.records,
                "segments": rec.segments,
                "torn_tail_bytes": rec.torn_tail_bytes,
                "bad_segments": rec.bad_segments,
            }
            for res, rec in self.recovery.items()
        }

    # -- write side ----------------------------------------------------------

    def sample_once(self) -> None:
        """Flatten the registry and feed every ring; public so tests and
        the shutdown path can force a sample with an injected clock."""
        if self._closed:
            return
        with self._lock:
            # Snapshot under the lock: two racing samplers must append in
            # the same order they observed the registry, or replayed
            # values could regress between adjacent records.
            t = self._time()
            values = flatten_snapshot(self.registry.snapshot())
            self._write("raw", t, values)
            for res, step in RESOLUTIONS:
                if step <= 0.0:
                    continue
                pending = self._pending[res]
                if pending is not None and int(t // step) > int(
                    pending[0] // step
                ):
                    # bucket advanced: the held sample was its bucket's last
                    self._write(res, pending[0], pending[1])
                self._pending[res] = (t, values)
            self._m_store.set(float(self._store_size()))

    def _write(self, res: str, t: float, values: Dict[str, float]) -> None:
        prev = self._prev[res]
        keyframe = prev is None or self._count[res] % self.keyframe_every == 0
        if keyframe:
            body: Dict[str, float] = values
            kind = "b"
        else:
            body = {k: v for k, v in values.items() if prev.get(k) != v}
            kind = "d"
        try:
            payload = json.dumps(
                {"t": round(t, 3), "k": kind, "v": body},
                separators=(",", ":"),
            ).encode("utf-8")
        except (TypeError, ValueError):
            return
        log = self._logs[res]
        try:
            if self.writer is not None:
                self.writer.run(lambda: log.append(payload))
            else:
                log.append(payload)
        except OSError:
            return  # history must never take the daemon down
        # Lock held by construction: _write's only callers are
        # sample_once() and close(), both inside `with self._lock`.
        self._prev[res] = dict(values)  # verifylint: disable=concurrency-unlocked-write
        self._count[res] += 1  # verifylint: disable=concurrency-unlocked-write
        if res not in ("raw", "1m", "15m"):
            res = "raw"
        self._m_points.inc(res=res)
        self._m_bytes.inc(len(payload))

    def _store_size(self) -> int:
        total = 0
        for res, _step in RESOLUTIONS:
            d = os.path.join(self.dir, res)
            try:
                names = os.listdir(d)
            except OSError:
                continue
            for name in names:
                try:
                    total += os.path.getsize(os.path.join(d, name))
                except OSError:
                    pass
        return total

    # -- sampler thread ------------------------------------------------------

    def start(self) -> None:
        if self._thread is not None or self._closed:
            return
        self._thread = threading.Thread(
            target=self._run, name="verifyd-tsdb", daemon=True
        )
        self._thread.start()

    def _run(self) -> None:
        while not self._stop.wait(self.sample_s):
            try:
                self.sample_once()
            except Exception:
                pass  # same contract as the flight ring: never crash

    def close(self) -> None:
        """Final sample, flush held coarse buckets, close the logs."""
        if self._closed:
            return
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None
        try:
            self.sample_once()
        except Exception:
            pass
        with self._lock:
            self._closed = True
            for res, _step in RESOLUTIONS:
                pending = self._pending.get(res)
                if pending is not None:
                    self._write(res, pending[0], pending[1])
                    self._pending[res] = None
            for log in self._logs.values():
                log.close()


# --------------------------------------------------------------- cold reader


def _decode(payload: bytes) -> Optional[Tuple[float, str, Dict[str, float]]]:
    try:
        rec = json.loads(payload)
    except ValueError:
        return None
    if not isinstance(rec, dict):
        return None
    v = rec.get("v")
    if not isinstance(v, dict):
        return None
    try:
        t = float(rec.get("t", 0.0))
    except (TypeError, ValueError):
        return None
    kind = rec.get("k")
    out: Dict[str, float] = {}
    for key, val in v.items():
        try:
            out[str(key)] = float(val)
        except (TypeError, ValueError):
            continue
    return t, ("b" if kind == "b" else "d"), out


def _read_ring(
    telemetry_dir: str, res: str
) -> Tuple[List[Tuple[float, str, Dict[str, float]]], Recovery]:
    directory = os.path.join(telemetry_dir, res)
    if not os.path.isdir(directory):
        return [], Recovery()
    log = SegmentLog(directory)
    records: List[Tuple[float, str, Dict[str, float]]] = []
    try:
        for payload in log.replay():
            rec = _decode(payload)
            if rec is not None:
                records.append(rec)
    finally:
        log.close()
    return records, log.recovery


def query(
    telemetry_dir: str,
    *,
    res: str = "raw",
    metric: Optional[str] = None,
    labels: Optional[Dict[str, str]] = None,
    since: Optional[float] = None,
    until: Optional[float] = None,
    limit: Optional[int] = None,
) -> Dict[str, Any]:
    """Cold range query: dense per-sample points for every matched series.

    ``metric`` is a substring match on the series *name* (before the
    label braces); ``labels`` are exact equality filters; ``since`` /
    ``until`` bound the wall-clock range; ``limit`` keeps the last N
    points per series (default 720).  Records outside the range still
    fold into the cumulative state, so a range query enters with correct
    values even when its window starts on a delta record.
    """
    cap = 720 if limit is None else max(1, int(limit))
    records, recovery = _read_ring(telemetry_dir, res)
    cur: Dict[str, float] = {}
    matched: List[str] = []
    series: Dict[str, List[List[float]]] = {}
    first_t: Optional[float] = None
    last_t: Optional[float] = None
    for t, _kind, v in records:
        for key in v:
            if key not in cur and _match(key, metric, labels):
                matched.append(key)
                series[key] = []
        cur.update(v)
        if since is not None and t < since:
            continue
        if until is not None and t > until:
            continue
        first_t = t if first_t is None else first_t
        last_t = t
        for key in matched:
            pts = series[key]
            pts.append([t, cur[key]])
            if len(pts) > cap:
                del pts[0 : len(pts) - cap]
    points = sum(len(p) for p in series.values())
    return {
        "res": res,
        "series": {k: series[k] for k in sorted(series) if series[k]},
        "points": points,
        "range": [first_t, last_t],
        "recovery": {
            "records": recovery.records,
            "segments": recovery.segments,
            "torn_tail_bytes": recovery.torn_tail_bytes,
            "bad_segments": recovery.bad_segments,
        },
    }


def last_values(
    telemetry_dir: str, res: str = "raw"
) -> Tuple[Optional[float], Dict[str, float]]:
    """(last sample wall time, final cumulative values) — the seed the
    sentinel restores baselines from.  ``(None, {})`` when no history."""
    records, _recovery = _read_ring(telemetry_dir, res)
    last_t: Optional[float] = None
    cur: Dict[str, float] = {}
    for t, _kind, v in records:
        last_t = t
        cur.update(v)
    return last_t, cur


def telemetry_info(telemetry_dir: str) -> Dict[str, Any]:
    """Per-ring shape of a store (doctor / ``tsq --info``): record and
    series counts, recovery verdicts, byte sizes, covered range."""
    rings: Dict[str, Any] = {}
    for res, _step in RESOLUTIONS:
        records, recovery = _read_ring(telemetry_dir, res)
        cur: Dict[str, float] = {}
        for _t, _kind, v in records:
            cur.update(v)
        size = 0
        d = os.path.join(telemetry_dir, res)
        try:
            for name in os.listdir(d):
                try:
                    size += os.path.getsize(os.path.join(d, name))
                except OSError:
                    pass
        except OSError:
            pass
        rings[res] = {
            "records": len(records),
            "series": len(cur),
            "bytes": size,
            "first_t": records[0][0] if records else None,
            "last_t": records[-1][0] if records else None,
            "recovery": {
                "records": recovery.records,
                "segments": recovery.segments,
                "torn_tail_bytes": recovery.torn_tail_bytes,
                "bad_segments": recovery.bad_segments,
            },
        }
    return {"dir": telemetry_dir, "resolutions": rings}


def tsq_request(
    telemetry_dir: str,
    req: Dict[str, Any],
    store: Optional[TelemetryStore] = None,
) -> Tuple[Optional[Dict[str, Any]], Optional[str]]:
    """Shared ``tsq`` op semantics for the daemon and router dispatchers.

    Validates the request's optional selectors and answers from the
    given directory — ``(payload, None)`` on success, ``(None, reason)``
    on a malformed request.  When the live ``store`` is passed, a fresh
    sample is forced first: appends flush as they land, so a cold read
    of the live directory IS the live view — by construction, not copy.
    """
    if store is not None:
        store.sample_once()
    if req.get("info"):
        return telemetry_info(telemetry_dir), None
    res = str(req.get("res") or "raw")
    if res not in {name for name, _step in RESOLUTIONS}:
        return None, "res must be one of raw, 1m, 15m"
    kwargs: Dict[str, Any] = {"res": res}
    if req.get("metric") is not None:
        kwargs["metric"] = str(req["metric"])
    labels = req.get("labels")
    if labels is not None:
        if not isinstance(labels, dict):
            return None, "labels must be an object of {label: value}"
        kwargs["labels"] = {str(k): str(v) for k, v in labels.items()}
    for key in ("since", "until"):
        if req.get(key) is not None:
            try:
                kwargs[key] = float(req[key])
            except (TypeError, ValueError):
                return None, f"{key} must be a number"
    if req.get("limit") is not None:
        try:
            kwargs["limit"] = int(req["limit"])
        except (TypeError, ValueError):
            return None, "limit must be an int"
    else:
        # Bound the reply frame unless the caller chose a cut.
        kwargs["limit"] = 360
    return query(telemetry_dir, **kwargs), None
