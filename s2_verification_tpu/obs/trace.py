"""Bounded in-memory span ring, exportable as Chrome trace_event JSON.

The Tracer records *completed* spans ("X" phase events in the trace_event
format) into a ``collections.deque(maxlen=capacity)``: recording is O(1),
never allocates beyond the ring, and is safe from any thread.  Timestamps
are ``time.monotonic()`` seconds converted to microseconds relative to the
tracer's construction instant, so spans recorded from different threads
share one coherent timeline.

Tracks: the ``tid`` field is a *virtual* track id, not an OS thread id.
verifyd gives every job its own track (``tid = job id``) so the nested
``admit -> queue_wait -> search -> render`` lifecycle of one job reads as
one lane in Perfetto; track 0 is the acceptor ("admission") lane.

The export is a single JSON object ``{"traceEvents": [...],
"displayTimeUnit": "ms"}`` — the JSON Object Format, which both
``chrome://tracing`` and https://ui.perfetto.dev load directly.
"""

from __future__ import annotations

import os
import threading
import time
from collections import deque
from contextlib import contextmanager
from typing import Any, Callable, Dict, Iterator, List, Optional, Sequence, Tuple

from .context import rebase_spans

__all__ = ["Tracer", "NULL_TRACER"]

# A track-name metadata set larger than this is reset wholesale: track ids
# are job ids (unbounded over a daemon's life) and the set exists only to
# dedupe "M" events, so losing it merely re-emits a name.
_MAX_NAMED_TRACKS = 65536


class Tracer:
    """Thread-safe bounded span recorder with Chrome trace_event export."""

    def __init__(self, capacity: int = 8192) -> None:
        self.capacity = max(0, int(capacity))
        self._lock = threading.Lock()
        self._ring: deque = deque(maxlen=self.capacity or 1)
        # Both bases are read at the same instant so wall_base can serve as
        # the cross-process clock-offset handshake: two tracers on the same
        # host rebase each other's spans via their wall_base difference.
        self._base = time.monotonic()
        self.wall_base = time.time()
        self._pid = os.getpid()
        self._named: set = set()
        self._dropped = 0
        #: called with the running drop total each time a span is evicted
        #: (e.g. to bump verifyd_trace_spans_dropped_total); must be cheap
        #: and must not call back into the tracer.
        self.drop_hook: Optional[Callable[[int], None]] = None
        #: called with every completed "X" event dict (e.g. the flight
        #: recorder); invoked outside the ring lock.
        self.span_hook: Optional[Callable[[Dict[str, Any]], None]] = None

    @property
    def enabled(self) -> bool:
        return self.capacity > 0

    @property
    def dropped(self) -> int:
        with self._lock:
            return self._dropped

    def now(self) -> float:
        """A timestamp suitable for add_span (monotonic seconds)."""
        return time.monotonic()

    def us(self, mono: float) -> float:
        """Convert a ``time.monotonic()`` instant to this tracer's
        trace-relative microseconds (the ``ts`` unit of its spans)."""
        return (mono - self._base) * 1e6

    def mono_of_wall(self, wall: float) -> float:
        """Map a wall-clock instant onto this tracer's monotonic timeline.

        Used to place events that only exist as wall time — e.g. the
        client's ``sent_wall`` from the submit frame — onto the daemon's
        span timeline.  Subject to wall-clock skew; callers clamp.
        """
        return self._base + (wall - self.wall_base)

    def __len__(self) -> int:
        with self._lock:
            return len(self._ring)

    def add_span(
        self,
        name: str,
        t0: float,
        t1: float,
        *,
        tid: int = 0,
        cat: str = "verifyd",
        args: Optional[Dict[str, Any]] = None,
    ) -> None:
        """Record a completed span [t0, t1] (``time.monotonic()`` seconds)."""
        if not self.enabled:
            return
        ev: Dict[str, Any] = {
            "name": name,
            "ph": "X",
            "ts": round((t0 - self._base) * 1e6, 3),
            "dur": round(max(0.0, t1 - t0) * 1e6, 3),
            "pid": self._pid,
            "tid": int(tid),
            "cat": cat,
        }
        if args:
            ev["args"] = args
        dropped = None
        with self._lock:
            if len(self._ring) == self._ring.maxlen:
                self._dropped += 1
                dropped = self._dropped
            self._ring.append(ev)
        if dropped is not None and self.drop_hook is not None:
            try:
                self.drop_hook(dropped)
            except Exception:
                pass
        if self.span_hook is not None:
            try:
                self.span_hook(ev)
            except Exception:
                pass

    @contextmanager
    def span(
        self,
        name: str,
        *,
        tid: int = 0,
        cat: str = "verifyd",
        args: Optional[Dict[str, Any]] = None,
    ) -> Iterator[None]:
        """Context manager recording the enclosed block as one span."""
        if not self.enabled:
            yield
            return
        t0 = time.monotonic()
        try:
            yield
        finally:
            self.add_span(name, t0, time.monotonic(), tid=tid, cat=cat, args=args)

    def name_track(self, tid: int, name: str) -> None:
        """Label a virtual track (emits one thread_name "M" event per tid)."""
        if not self.enabled:
            return
        with self._lock:
            if tid in self._named:
                return
            if len(self._named) >= _MAX_NAMED_TRACKS:
                self._named.clear()
            self._named.add(tid)
            self._ring.append(
                {
                    "name": "thread_name",
                    "ph": "M",
                    "ts": 0,
                    "pid": self._pid,
                    "tid": int(tid),
                    "args": {"name": name},
                }
            )

    def merge_child(
        self,
        spans: Sequence[Dict[str, Any]],
        *,
        child_wall_base: float,
        tid: int,
        clamp: Optional[Tuple[float, float]] = None,
        extra_args: Optional[Dict[str, Any]] = None,
    ) -> int:
        """Stitch a child process's span ring onto this tracer's timeline.

        ``child_wall_base`` is the child tracer's ``wall_base`` (shipped
        back in the result JSON) — the clock-offset handshake.  ``clamp``
        is the parent's observed [t0, t1] window for the child in
        ``time.monotonic()`` seconds; rebased spans are pinned inside it
        so clock skew can never produce negative durations or child spans
        outside the escalation that ran them.  Returns how many spans
        were merged.
        """
        if not self.enabled or not spans:
            return 0
        offset_us = (child_wall_base - self.wall_base) * 1e6
        clamp_us = None
        if clamp is not None:
            clamp_us = (self.us(clamp[0]), self.us(clamp[1]))
        merged = rebase_spans(
            spans,
            offset_us=offset_us,
            tid=tid,
            pid=self._pid,
            clamp_us=clamp_us,
            extra_args=extra_args,
        )
        with self._lock:
            for ev in merged:
                if len(self._ring) == self._ring.maxlen:
                    self._dropped += 1
                self._ring.append(ev)
        return len(merged)

    def export(self) -> Dict[str, Any]:
        """Snapshot the ring as a loadable trace_event JSON object."""
        with self._lock:
            events: List[Dict[str, Any]] = list(self._ring)
            dropped = self._dropped
        other: Dict[str, Any] = {
            "producer": "s2-verification-tpu",
            "span_capacity": self.capacity,
            "spans_dropped": dropped,
            "wall_base": round(self.wall_base, 6),
        }
        if dropped:
            other["warning"] = (
                "span ring saturated: %d span(s) dropped; timeline is "
                "truncated — raise --trace-capacity" % dropped
            )
        return {
            "traceEvents": events,
            "displayTimeUnit": "ms",
            "otherData": other,
        }


#: Shared disabled tracer: every record path is a cheap no-op.  Components
#: take ``tracer=NULL_TRACER`` defaults so call sites never None-check.
NULL_TRACER = Tracer(0)
