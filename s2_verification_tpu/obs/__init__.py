"""Zero-dependency observability: spans, metrics, health, and post-mortems.

The obs package is the instrumentation layer threaded through the checker
engines and verifyd hot paths:

- ``trace``   — a thread-safe Tracer recording nested spans into a bounded
                ring, exportable as Chrome trace_event JSON (Perfetto);
                stitches child-process rings via clock rebasing.
- ``context`` — distributed trace ids (W3C-style), protocol-frame
                propagation helpers, and the clock-rebase math.
- ``metrics`` — counter / gauge / histogram registry rendering Prometheus
                text exposition format 0.0.4.
- ``health``  — SLO engine: rolling multi-window availability, latency
                quantiles, and error-budget burn rates over the
                ServiceStats event stream.
- ``httpd``   — stdlib-only HTTP listener serving GET /metrics, a real
                /healthz (200 ok / 503 degraded), and /slo.
- ``log``     — structured logger (JSON or text lines) with trace_id /
                job_id correlation fields.
- ``flight``  — flight recorder: bounded on-disk ring of recent events +
                spans (seglog-backed) and the doctor's post-mortem reader.
- ``alerts``  — rule-driven AlertEngine delivering alertmanager-compatible
                webhooks (backoff + jitter, dedup/re-arm) off the event
                stream.
- ``archive`` — durable per-job profile archive + deduplicated history
                corpus over seglog: the replayable recorded-traffic set.
- ``sentinel``— per-shape EWMA wall-time baselines emitting
                ``perf_regression`` events when drift exceeds the band.
- ``introspect`` — runtime introspection: the JIT-compile tracker
                (compiles / retraces / cache hits per abstract shape,
                ``retrace_storm`` events) wrapped around the device jit
                sites, plus the ResourceSampler (RSS / CPU / fds /
                threads / GC pauses / device memory) feeding gauges and
                the flight recorder.
- ``dashboard`` — live self-contained HTML dashboard (``/dashboard`` on
                the obs httpd): sparkline history sampled straight from
                the metric families.
- ``tsdb``    — durable multi-resolution time-series store over seglog
                (delta-encoded registry snapshots, byte-bounded
                retention, cold reader): telemetry that survives
                restarts, seeds sentinel baselines, and answers ``tsq``.
- ``federate``— the router's FleetScraper: every backend's metrics
                merged under a closed ``node`` label into
                ``/fleet/metrics``, a fleet SLO rollup, and the fleet
                dashboard board.

Everything here is stdlib-only by design: the daemon must stay deployable
on a bare TPU host image with no pip access.
"""

from .alerts import AlertEngine, AlertRule, builtin_rules, parse_rule
from .archive import ProfileArchive, filter_records, read_archive, read_corpus
from .context import new_trace_id, valid_trace_id
from .dashboard import Dashboard
from .federate import FleetScraper, ScrapeTarget
from .flight import FlightRecorder, postmortem, read_flight, render_postmortem
from .health import SLOConfig, SLOHealth
from .introspect import (
    INTROSPECTOR,
    JitIntrospector,
    ResourceSampler,
    get_job_context,
    job_context,
    observe_jit,
)
from .log import StructuredLogger
from .metrics import Counter, Gauge, Histogram, MetricsRegistry
from .sentinel import PerfSentinel, SentinelConfig, seed_from_telemetry
from .trace import Tracer
from .tsdb import TelemetryStore, last_values, query, telemetry_info

__all__ = [
    "AlertEngine",
    "AlertRule",
    "Counter",
    "Dashboard",
    "FleetScraper",
    "FlightRecorder",
    "Gauge",
    "Histogram",
    "INTROSPECTOR",
    "JitIntrospector",
    "MetricsRegistry",
    "PerfSentinel",
    "ProfileArchive",
    "ResourceSampler",
    "SLOConfig",
    "SLOHealth",
    "ScrapeTarget",
    "SentinelConfig",
    "StructuredLogger",
    "TelemetryStore",
    "Tracer",
    "builtin_rules",
    "filter_records",
    "get_job_context",
    "job_context",
    "last_values",
    "new_trace_id",
    "observe_jit",
    "parse_rule",
    "postmortem",
    "query",
    "read_archive",
    "read_corpus",
    "read_flight",
    "render_postmortem",
    "seed_from_telemetry",
    "telemetry_info",
    "valid_trace_id",
]
