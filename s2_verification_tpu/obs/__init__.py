"""Zero-dependency observability: spans, metrics, and a /metrics endpoint.

The obs package is the instrumentation layer threaded through the checker
engines and verifyd hot paths:

- ``trace``   — a thread-safe Tracer recording nested spans into a bounded
                ring, exportable as Chrome trace_event JSON (Perfetto).
- ``metrics`` — counter / gauge / histogram registry rendering Prometheus
                text exposition format 0.0.4.
- ``httpd``   — stdlib-only HTTP listener serving GET /metrics.

Everything here is stdlib-only by design: the daemon must stay deployable
on a bare TPU host image with no pip access.
"""

from .metrics import Counter, Gauge, Histogram, MetricsRegistry
from .trace import Tracer

__all__ = [
    "Counter",
    "Gauge",
    "Histogram",
    "MetricsRegistry",
    "Tracer",
]
