"""Flight recorder: bounded on-disk ring of recent events + spans.

An in-memory span ring and a JSONL stats sink are great while the daemon
is alive — and worthless the moment it is SIGKILLed, OOMed, or wedged.
The flight recorder is the black box: a small, *bounded* on-disk ring
(``utils/seglog.SegmentLog`` with ``max_segments``, the same CRC-checked
storage discipline as the verdict cache) under
``<state_dir>/flight/`` that continuously absorbs

- every ServiceStats event (fed by ServiceStats outside its sink lock),
- every completed tracer span (via ``Tracer.span_hook``),
- every alert the AlertEngine fires (``{"k": "alert"}`` records;
  abandoned deliveries additionally leave an ``alert_failed`` dump
  marker),
- explicit **dump** records on SIGTERM / daemon close / SLO breach,
  carrying a full SLO snapshot at that instant,
- periodic resource samples from the ResourceSampler (``{"k": "res"}``
  records: RSS, CPU seconds, fds, threads, GC pauses), so the doctor can
  show the resource timeline *before* a death — an OOM kill reads as a
  climbing RSS line ending mid-flight.

Each record is one JSON object ``{"k": "ev"|"span"|"alert"|"dump"|"res",
"t": wall, ...}``.  Because every append is flushed, the tail survives SIGKILL up
to the last OS write — exactly the property the doctor needs.

:func:`postmortem` is the read side: point it at a dead daemon's
``--state-dir`` and it reconstructs the story — last events, orphaned
journal entries, device-pool leases still open at death, slowest spans,
the SLO picture (replayed from recorded events, which carry their own
timestamps), and whether the death looks clean (last record is a
shutdown dump) or not.  The ``doctor`` CLI subcommand is a thin wrapper
over it.
"""

from __future__ import annotations

import json
import os
import time
from typing import Any, Dict, List, Optional

from ..utils.seglog import SegmentLog
from .health import SLOConfig, SLOHealth

__all__ = ["FlightRecorder", "read_flight", "postmortem", "render_postmortem"]

FLIGHT_SUBDIR = "flight"


class FlightRecorder:
    """Continuously-flushed bounded ring of observability records."""

    def __init__(
        self,
        directory: str,
        *,
        max_segment_bytes: int = 256 << 10,
        max_segments: int = 8,
        fsync: bool = False,
    ) -> None:
        self._log = SegmentLog(
            directory,
            max_segment_bytes=max_segment_bytes,
            max_segments=max_segments,
            fsync=fsync,
        )
        self._closed = False
        #: optional service.overload.DegradedWriter: on ENOSPC the ring
        #: drops records cheaply (counted, evented) and re-arms when the
        #: disk recovers, instead of paying a failing syscall per record
        self.writer = None

    def _append(self, rec: Dict[str, Any]) -> None:
        if self._closed:
            return
        try:
            payload = json.dumps(rec, separators=(",", ":"), default=str).encode(
                "utf-8"
            )
            if self.writer is not None:
                self.writer.run(lambda: self._log.append(payload))
                return
            self._log.append(payload)
        except (OSError, ValueError, TypeError):
            pass  # the black box must never take the plane down

    def record_event(self, ev: Dict[str, Any]) -> None:
        """Absorb one ServiceStats event line (already has ``t``/``event``)."""
        self._append({"k": "ev", **ev})

    def record_span(self, span: Dict[str, Any]) -> None:
        """Absorb one completed tracer span (Tracer.span_hook target)."""
        if span.get("ph") != "X":
            return
        self._append({"k": "span", "t": round(time.time(), 6), **span})

    def record_alert(self, alert: Dict[str, Any]) -> None:
        """Absorb one fired alert (AlertEngine target); delivery failures
        arrive separately as ``alert_failed`` dump markers."""
        self._append({"k": "alert", "t": round(time.time(), 6), **alert})

    def record_resource(self, sample: Dict[str, Any]) -> None:
        """Absorb one ResourceSampler sample (already has ``t``)."""
        self._append({"k": "res", **sample})

    def dump(self, reason: str, **extra: Any) -> None:
        """Write a marker record (shutdown / sigterm / slo_breach) with
        whatever context the caller attaches (usually ``slo=snapshot``)."""
        self._append({"k": "dump", "t": round(time.time(), 6), "reason": reason, **extra})

    def close(self) -> None:
        if not self._closed:
            self._closed = True
            self._log.close()


# --------------------------------------------------------------- read side


def read_flight(state_dir: str) -> List[Dict[str, Any]]:
    """Replay a state dir's flight ring → record dicts, oldest first.
    Read-only: tolerates a missing ring (old daemon) by returning []."""
    directory = os.path.join(state_dir, FLIGHT_SUBDIR)
    if not os.path.isdir(directory):
        return []
    log = SegmentLog(directory)
    out: List[Dict[str, Any]] = []
    try:
        for payload in log.replay():
            try:
                rec = json.loads(payload)
            except ValueError:
                continue
            if isinstance(rec, dict):
                out.append(rec)
    finally:
        log.close()
    return out


def _journal_orphans(state_dir: str) -> List[Dict[str, Any]]:
    journal_dir = os.path.join(state_dir, "journal")
    if not os.path.isdir(journal_dir):
        return []
    # local import: journal pulls in the service package; doctor must not
    # need a running daemon's deps beyond stdlib + seglog
    from ..service.journal import JobJournal

    j = JobJournal(journal_dir)
    try:
        return j.orphans()
    finally:
        j.close()


def postmortem(
    state_dir: str,
    *,
    tail: int = 40,
    slow: int = 10,
    slo_config: Optional[SLOConfig] = None,
) -> Dict[str, Any]:
    """Reconstruct a dead daemon's last moments from its state dir.

    Pure read: never creates directories, never appends.  Returns a dict
    with the flight tail, orphaned journal entries, open leases, slowest
    spans, breach dumps, the replayed SLO picture at death, and a
    clean/unclean verdict.
    """
    records = read_flight(state_dir)
    events = [r for r in records if r.get("k") == "ev"]
    spans = [r for r in records if r.get("k") == "span"]
    dumps = [r for r in records if r.get("k") == "dump"]
    alerts = [r for r in records if r.get("k") == "alert"]
    resources = [r for r in records if r.get("k") == "res"]

    # Open leases: grants never matched by a release/timeout of the same job.
    open_leases: Dict[Any, Dict[str, Any]] = {}
    # Degraded writers: writer_degraded events not healed by a later
    # writer_recovered for the same writer.  Cancellations: counts by
    # reason, so "deadline ×12" reads at a glance.
    degraded_writers: Dict[str, Dict[str, Any]] = {}
    cancellations: Dict[str, int] = {}
    for ev in events:
        name = ev.get("ev") or ev.get("event")
        if name == "lease_grant":
            open_leases[ev.get("job")] = ev
        elif name in ("lease_release", "lease_timeout"):
            open_leases.pop(ev.get("job"), None)
        elif name == "writer_degraded":
            degraded_writers[str(ev.get("writer", "?"))] = ev
        elif name == "writer_recovered":
            degraded_writers.pop(str(ev.get("writer", "?")), None)
        elif name == "job_cancelled":
            reason = str(ev.get("reason", "other"))
            cancellations[reason] = cancellations.get(reason, 0) + 1

    # SLO at death: replay recorded request-outcome events (each carries
    # its own wall ``t``) into a fresh engine, evaluated at the last
    # recorded instant so the windows reflect the moment of death rather
    # than "now".
    last_t = max((float(r.get("t", 0.0)) for r in records), default=time.time())
    engine = SLOHealth(slo_config, time_fn=lambda: last_t)
    for ev in events:
        engine.observe_event(ev)
    slo_at_death = engine.snapshot()

    slowest = sorted(
        (s for s in spans if isinstance(s.get("dur"), (int, float))),
        key=lambda s: -float(s["dur"]),
    )[:slow]

    breaches = [d for d in dumps if d.get("reason") == "slo_breach"]
    alert_failures = [d for d in dumps if d.get("reason") == "alert_failed"]

    # Slowest archived jobs: the profile archive (PR 6) shares the state
    # dir; a pre-archive daemon simply has none.
    from .archive import filter_records, read_archive

    slowest_jobs = filter_records(read_archive(state_dir), slowest=slow)

    last = records[-1] if records else None
    clean = bool(
        last
        and last.get("k") == "dump"
        and last.get("reason") in ("shutdown", "sigterm", "sigint")
    )

    # Quarantine ledger: cold read of the store file — the dead daemon's
    # poison history is part of the story (a crash loop often IS a poison
    # job the threshold never caught).
    quarantine: Dict[str, Any] = {}
    qpath = os.path.join(state_dir, "quarantine", "quarantine.json")
    try:
        with open(qpath, encoding="utf-8") as f:
            qdata = json.load(f)
        if isinstance(qdata, dict):
            quarantine = {
                "quarantined": qdata.get("quarantined", {}) or {},
                "crashes": qdata.get("crashes", {}) or {},
            }
    except (OSError, ValueError):
        pass

    # Prefix store: cold replay of the chain-hash frontier log, plus the
    # hit/miss picture from the flight events — together they answer
    # "was incremental verification pulling its weight when it died?".
    from ..service.prefixstore import read_cold as read_prefix_cold

    prefix_store = read_prefix_cold(state_dir)

    # Distributed-search grant ledger (a router's state dir): partition
    # ownership open at death, per-search epochs, and the last delta per
    # range — the post-mortem of a coordinator killed mid-search.
    from ..service.journal import read_grants_cold

    distsearch = read_grants_cold(state_dir)

    # Telemetry history (obs/tsdb.py): cold read of the durable metric
    # rings under <state_dir>/telemetry — the trajectory of the load
    # picture in the daemon's final stretch, plus the sentinel baselines
    # the *next* boot will seed from.  Pre-telemetry state dirs simply
    # have no rings.
    from .tsdb import default_dir as _telemetry_default_dir
    from .tsdb import last_values as _telemetry_last
    from .tsdb import query as _telemetry_query
    from .tsdb import telemetry_info as _telemetry_info

    telemetry: Optional[Dict[str, Any]] = None
    tdir = _telemetry_default_dir(state_dir)
    if os.path.isdir(tdir):
        info = _telemetry_info(tdir)
        tel_last_t, finals = _telemetry_last(tdir)
        kept = {
            key: val
            for key, val in finals.items()
            if key.startswith(
                (
                    "verifyd_jobs_completed_total",
                    "verifyd_queue_depth",
                    "verifyd_resource_rss_bytes",
                    "verifyd_perf_baseline_wall_seconds",
                    "verifyd_perf_regression_fired",
                    "verifyd_slo_healthy",
                )
            )
        }
        trajectories: Dict[str, Any] = {}
        for metric in ("verifyd_queue_depth", "verifyd_resource_rss_bytes"):
            q = _telemetry_query(tdir, metric=metric, limit=tail)
            trajectories.update(q["series"])
        telemetry = {
            "dir": tdir,
            "resolutions": info["resolutions"],
            "last_t": tel_last_t,
            "final_values": kept,
            "trajectories": trajectories,
        }

    prefix_activity: Dict[str, int] = {}
    for ev in events:
        name = ev.get("ev") or ev.get("event")
        if name in (
            "prefix_hit",
            "prefix_miss",
            "prefix_snapshot",
            "prefix_refused",
            "window_done",
        ):
            prefix_activity[name] = prefix_activity.get(name, 0) + 1

    # Search progress: the last heartbeat per job from the ring.  A job
    # with a heartbeat but no later terminal event (done / job_error /
    # job_cancelled) was mid-search when the daemon died — its last
    # reported ratio and ETA are the honest "how far did it get".
    progress_last: Dict[Any, Dict[str, Any]] = {}
    progress_beats = 0
    progress_finished: set = set()
    for ev in events:
        name = ev.get("ev") or ev.get("event")
        if name == "search_progress":
            progress_beats += 1
            progress_last[ev.get("job")] = ev
        elif name in ("done", "job_error", "job_cancelled"):
            progress_finished.add(ev.get("job"))
    at_death = [
        {
            "job": job,
            "engine": ev.get("engine"),
            "ops_committed": ev.get("ops_committed"),
            "total_ops": ev.get("total_ops"),
            "progress_ratio": ev.get("progress_ratio"),
            "eta_s": ev.get("eta_s"),
            "fingerprint": ev.get("fingerprint"),
            "t": ev.get("t"),
        }
        for job, ev in sorted(
            progress_last.items(), key=lambda kv: str(kv[0])
        )
        if job not in progress_finished
    ]
    search_progress = {
        "heartbeats": progress_beats,
        "jobs": len(progress_last),
        "in_flight_at_death": at_death,
    }

    return {
        "state_dir": state_dir,
        "records": len(records),
        "events": len(events),
        "spans": len(spans),
        "dumps": dumps,
        "breaches": breaches,
        "alerts": alerts,
        "alert_failures": alert_failures,
        "slowest_jobs": slowest_jobs,
        "clean_shutdown": clean,
        "last_record": last,
        "tail": records[-tail:],
        "orphans": _journal_orphans(state_dir),
        "open_leases": list(open_leases.values()),
        "quarantine": quarantine,
        "degraded_writers": list(degraded_writers.values()),
        "cancellations": cancellations,
        "slowest_spans": slowest,
        "slo_at_death": slo_at_death,
        "prefix_store": prefix_store,
        "prefix_activity": prefix_activity,
        "telemetry": telemetry,
        "search_progress": search_progress,
        "distsearch": distsearch,
        # Resource timeline before death: keep the tail — the interesting
        # part of an OOM story is the last few minutes, not the first.
        "resources": resources[-tail:],
        "resource_samples": len(resources),
    }


def _fmt_t(t: Any) -> str:
    try:
        return time.strftime("%Y-%m-%dT%H:%M:%S", time.gmtime(float(t)))
    except (TypeError, ValueError, OverflowError):
        return "?"


def render_postmortem(pm: Dict[str, Any], *, tail: int = 20) -> str:
    """Human-readable doctor report (the --json flag skips this)."""
    lines: List[str] = []
    add = lines.append
    add("== verifyd doctor: %s ==" % pm["state_dir"])
    add(
        "flight ring: %d records (%d events, %d spans, %d dumps)"
        % (pm["records"], pm["events"], pm["spans"], len(pm["dumps"]))
    )
    verdict = "clean shutdown" if pm["clean_shutdown"] else "UNCLEAN DEATH"
    last = pm["last_record"]
    if last is not None:
        add(
            "last record: %s %s at %s  -> %s"
            % (
                last.get("k"),
                last.get("reason") or last.get("ev") or last.get("name", ""),
                _fmt_t(last.get("t")),
                verdict,
            )
        )
    else:
        add("last record: none (empty or missing flight ring) -> %s" % verdict)

    if pm["breaches"]:
        add("")
        add("-- SLO breaches recorded (%d) --" % len(pm["breaches"]))
        for b in pm["breaches"][-5:]:
            reasons = b.get("breach", {}).get("reasons") or b.get("reasons") or []
            why = "; ".join(
                "%s burn=%.1f on %s"
                % (r.get("kind"), r.get("burn_rate", 0.0), r.get("window"))
                for r in reasons
            )
            add("  %s  %s" % (_fmt_t(b.get("t")), why or "(no detail)"))

    if pm.get("alerts"):
        add("")
        add("-- alerts fired (last %d) --" % min(10, len(pm["alerts"])))
        for a in pm["alerts"][-10:]:
            add(
                "  %s  %-16s rule=%s severity=%s"
                % (
                    _fmt_t(a.get("t")),
                    a.get("event", "?"),
                    a.get("rule"),
                    a.get("severity", "?"),
                )
            )

    if pm.get("alert_failures"):
        add("")
        add(
            "-- alert deliveries abandoned: %d --" % len(pm["alert_failures"])
        )
        for d in pm["alert_failures"][-5:]:
            add(
                "  %s  rule=%s attempts=%s error=%s"
                % (
                    _fmt_t(d.get("t")),
                    d.get("rule"),
                    d.get("attempts"),
                    d.get("error"),
                )
            )

    slo = pm["slo_at_death"]
    add("")
    add(
        "-- SLO at death (target %.3f) --  %s"
        % (
            slo["availability_target"],
            "healthy" if slo["healthy"] else "DEGRADED: %s" % json.dumps(slo["reasons"]),
        )
    )
    for wname, w in slo["windows"].items():
        add(
            "  %-4s avail=%.4f burn=%.1f good=%d bad=%d p95=%s"
            % (
                wname,
                w["availability"],
                w["burn_rate"],
                w["good"],
                w["bad"],
                w["latency"].get("p95"),
            )
        )

    if pm["orphans"]:
        add("")
        add("-- orphaned journal entries (accepted, never closed): %d --" % len(pm["orphans"]))
        for rec in pm["orphans"][:10]:
            add(
                "  job=%s fp=%s client=%s"
                % (rec.get("job"), str(rec.get("fp", ""))[:16], rec.get("client"))
            )

    q = pm.get("quarantine") or {}
    if q.get("quarantined") or q.get("crashes"):
        add("")
        add(
            "-- quarantine: %d fingerprint(s) held, %d with crash history --"
            % (len(q.get("quarantined", {})), len(q.get("crashes", {})))
        )
        for fp, ent in sorted(q.get("quarantined", {}).items())[:10]:
            add(
                "  HELD %s  crashes=%s kinds=%s since=%s"
                % (
                    fp[:16],
                    ent.get("crashes"),
                    json.dumps(ent.get("kinds", {}), sort_keys=True),
                    _fmt_t(ent.get("since")),
                )
            )
        for fp, ent in sorted(q.get("crashes", {}).items())[:10]:
            if fp not in q.get("quarantined", {}):
                add("  warm %s  crashes=%s" % (fp[:16], ent.get("count")))

    if pm.get("degraded_writers"):
        add("")
        add(
            "-- writers degraded at death: %d --" % len(pm["degraded_writers"])
        )
        for ev in pm["degraded_writers"]:
            add(
                "  %s  writer=%s error=%s"
                % (_fmt_t(ev.get("t")), ev.get("writer"), ev.get("error"))
            )

    if pm.get("cancellations"):
        add("")
        add(
            "-- cancellations --  "
            + "  ".join(
                "%s=%d" % (r, n)
                for r, n in sorted(pm["cancellations"].items())
            )
        )

    if pm["open_leases"]:
        add("")
        add("-- device-pool leases open at death: %d --" % len(pm["open_leases"]))
        for ev in pm["open_leases"]:
            add(
                "  job=%s devices=%s granted at %s"
                % (ev.get("job"), ev.get("devices"), _fmt_t(ev.get("t")))
            )

    if pm["slowest_spans"]:
        add("")
        add("-- slowest spans --")
        for s in pm["slowest_spans"]:
            add(
                "  %8.1f ms  %-20s tid=%s %s"
                % (
                    float(s.get("dur", 0.0)) / 1000.0,
                    s.get("name"),
                    s.get("tid"),
                    json.dumps(s.get("args", {}), sort_keys=True) if s.get("args") else "",
                )
            )

    if pm.get("slowest_jobs"):
        add("")
        add("-- slowest archived jobs --")
        for r in pm["slowest_jobs"]:
            add(
                "  %8.1f ms  job=%s shape=%s backend=%s verdict=%s client=%s"
                % (
                    float(r.get("wall_s", 0.0) or 0.0) * 1000.0,
                    r.get("job"),
                    r.get("shape"),
                    r.get("backend"),
                    r.get("verdict"),
                    r.get("client"),
                )
            )

    ps = pm.get("prefix_store")
    activity = pm.get("prefix_activity") or {}
    if ps is not None or activity:
        add("")
        if ps is None:
            add("-- prefix store: no on-disk log (in-memory only) --")
        else:
            rec = ps.get("recovery") or {}
            add(
                "-- prefix store: %d frontier(s), %d bytes, deepest %d ops --"
                % (ps.get("entries", 0), ps.get("bytes", 0), ps.get("deepest_ops", 0))
            )
            add(
                "  log: %s segment(s), %s record(s) replayed, "
                "torn tail %sB, %s bad segment(s)"
                % (
                    rec.get("segments", "?"),
                    rec.get("records", "?"),
                    rec.get("torn_tail_bytes", "?"),
                    rec.get("bad_segments", "?"),
                )
            )
            for stream, info in sorted(ps.get("streams", {}).items())[:10]:
                add(
                    "  stream %-20s frontier at %d ops (window %s, %d events)"
                    % (
                        stream,
                        info.get("ops", 0),
                        info.get("window", "?"),
                        info.get("events", 0),
                    )
                )
        hits = activity.get("prefix_hit", 0)
        misses = activity.get("prefix_miss", 0)
        if hits or misses:
            add(
                "  probes: %d hit / %d miss (%.0f%% warm), %d snapshot(s), "
                "%d refused, %d window(s)"
                % (
                    hits,
                    misses,
                    100.0 * hits / (hits + misses),
                    activity.get("prefix_snapshot", 0),
                    activity.get("prefix_refused", 0),
                    activity.get("window_done", 0),
                )
            )
        elif activity:
            add(
                "  probes: none recorded; %d snapshot(s), %d refused, "
                "%d window(s)"
                % (
                    activity.get("prefix_snapshot", 0),
                    activity.get("prefix_refused", 0),
                    activity.get("window_done", 0),
                )
            )

    sp = pm.get("search_progress") or {}
    if sp.get("heartbeats"):
        add("")
        add(
            "-- search progress: %d heartbeat(s) across %d job(s) --"
            % (sp.get("heartbeats", 0), sp.get("jobs", 0))
        )
        stuck = sp.get("in_flight_at_death") or []
        if not stuck:
            add("  every heartbeating job reached a terminal event")
        for row in stuck[:10]:
            ratio = row.get("progress_ratio")
            eta = row.get("eta_s")
            add(
                "  MID-SEARCH job=%s engine=%s %s/%s ops (%s) eta %s  "
                "fp=%s  last beat %s"
                % (
                    row.get("job"),
                    row.get("engine"),
                    row.get("ops_committed"),
                    row.get("total_ops"),
                    "%.0f%%" % (100.0 * float(ratio))
                    if ratio is not None
                    else "?",
                    "%.1fs" % float(eta) if eta is not None else "?",
                    str(row.get("fingerprint") or "")[:20],
                    _fmt_t(row.get("t")),
                )
            )

    ds = pm.get("distsearch")
    if ds is not None:
        rec = ds.get("recovery") or {}
        add("")
        add(
            "-- distributed search: %d search(es), %d grant(s) open at "
            "death --"
            % (len(ds.get("searches", {})), ds.get("open_total", 0))
        )
        add(
            "  ledger: %s record(s) in %s segment(s), torn tail %sB, "
            "%s bad segment(s)"
            % (
                rec.get("records", "?"),
                rec.get("segments", "?"),
                rec.get("torn_tail_bytes", "?"),
                rec.get("bad_segments", "?"),
            )
        )
        for search, info in sorted(ds.get("searches", {}).items())[:10]:
            verdict = info.get("verdict")
            add(
                "  search %s  %s  segs=%s parts=%s max_epoch=%s fences=%s"
                % (
                    search[:16],
                    (
                        "UNDECIDED AT DEATH"
                        if verdict is None
                        else "verdict=%s (%s)" % (verdict, info.get("outcome"))
                    ),
                    info.get("segs", "?"),
                    info.get("parts", "?"),
                    info.get("max_epoch", 0),
                    info.get("fences", 0),
                )
            )
            for g in (info.get("open_grants") or [])[:8]:
                add(
                    "    OPEN range %s  node=%s epoch=%s (%s) seg=%s"
                    % (
                        g.get("part"),
                        g.get("node"),
                        g.get("epoch"),
                        g.get("reason"),
                        str(g.get("seg", ""))[:20],
                    )
                )
            for part, d in sorted((info.get("last_delta") or {}).items())[:8]:
                add(
                    "    last delta range %s  node=%s epoch=%s verdict=%s "
                    "states=%s bytes=%s"
                    % (
                        part,
                        d.get("node"),
                        d.get("epoch"),
                        d.get("verdict"),
                        d.get("states"),
                        d.get("bytes"),
                    )
                )

    tel = pm.get("telemetry")
    if tel is not None:
        add("")
        add("-- telemetry history: %s --" % tel.get("dir"))
        for res, info in sorted((tel.get("resolutions") or {}).items()):
            rec = info.get("recovery") or {}
            add(
                "  %-3s %6s record(s) %4s series %9sB  last %s  "
                "torn tail %sB, %s bad segment(s)"
                % (
                    res,
                    info.get("records", 0),
                    info.get("series", 0),
                    info.get("bytes", 0),
                    _fmt_t(info.get("last_t")),
                    rec.get("torn_tail_bytes", "?"),
                    rec.get("bad_segments", "?"),
                )
            )
        finals = tel.get("final_values") or {}
        baselines = sorted(
            k
            for k in finals
            if k.startswith("verifyd_perf_baseline_wall_seconds")
        )
        if baselines:
            add("  sentinel baselines at death (the next boot seeds these):")
            for k in baselines[:10]:
                fired_key = k.replace(
                    "verifyd_perf_baseline_wall_seconds",
                    "verifyd_perf_regression_fired",
                )
                add(
                    "    %s = %.4fs%s"
                    % (
                        k,
                        finals[k],
                        "  LATCHED"
                        if finals.get(fired_key, 0.0) >= 0.5
                        else "",
                    )
                )
        for key, pts in sorted((tel.get("trajectories") or {}).items()):
            if not pts:
                continue
            vals = [p[1] for p in pts]
            add(
                "  %s: last %d point(s)  min=%.1f max=%.1f final=%.1f"
                % (key, len(pts), min(vals), max(vals), vals[-1])
            )

    if pm.get("resources"):
        add("")
        add(
            "-- resource timeline (last %d of %d samples) --"
            % (len(pm["resources"]), pm.get("resource_samples", len(pm["resources"])))
        )
        for r in pm["resources"]:
            add(
                "  %s  rss=%7.1fMiB cpu=%8.1fs fds=%-4s threads=%-3s gc=%.3fs"
                % (
                    _fmt_t(r.get("t")),
                    float(r.get("rss_bytes", 0) or 0) / (1 << 20),
                    float(r.get("cpu_s", 0.0) or 0.0),
                    r.get("fds", "?"),
                    r.get("threads", "?"),
                    float(r.get("gc_pause_s", 0.0) or 0.0),
                )
            )

    if pm["tail"]:
        add("")
        add("-- flight tail (last %d of %d) --" % (min(tail, len(pm["tail"])), pm["records"]))
        for rec in pm["tail"][-tail:]:
            kind = rec.get("k")
            if kind == "ev":
                body = rec.get("ev") or rec.get("event") or "?"
                detail = {
                    k: v
                    for k, v in rec.items()
                    if k not in ("k", "t", "ev", "event")
                    and not isinstance(v, (dict, list))
                }
                add(
                    "  %s ev   %-14s %s"
                    % (_fmt_t(rec.get("t")), body, json.dumps(detail, sort_keys=True, default=str))
                )
            elif kind == "span":
                add(
                    "  %s span %-14s dur=%.1fms tid=%s"
                    % (
                        _fmt_t(rec.get("t")),
                        rec.get("name", "?"),
                        float(rec.get("dur", 0.0)) / 1000.0,
                        rec.get("tid"),
                    )
                )
            elif kind == "alert":
                add(
                    "  %s ALRT %-14s rule=%s"
                    % (
                        _fmt_t(rec.get("t")),
                        rec.get("event", "?"),
                        rec.get("rule"),
                    )
                )
            elif kind == "res":
                add(
                    "  %s res  rss=%.1fMiB threads=%s"
                    % (
                        _fmt_t(rec.get("t")),
                        float(rec.get("rss_bytes", 0) or 0) / (1 << 20),
                        rec.get("threads", "?"),
                    )
                )
            else:
                add(
                    "  %s DUMP %s"
                    % (_fmt_t(rec.get("t")), rec.get("reason", "?"))
                )
    return "\n".join(lines) + "\n"
