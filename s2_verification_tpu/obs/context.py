"""Distributed trace context: ids, frame propagation, and clock rebasing.

A served job crosses three process boundaries — submit client → TCP/unix
daemon → supervised (possibly mesh-sharded) child — and each hop keeps
its own monotonic clock.  This module is the glue that lets one
``trace_id`` follow the job across all three and lets the daemon stitch
the children's span rings into its own timeline:

- **Trace ids** are W3C trace-context style: 16 random bytes as 32 lower
  hex chars, never all-zero (the W3C invalid value).  The submit client
  mints one per request; an old client that sends none gets a
  daemon-minted id, so every job has exactly one.
- **Frame propagation**: the id rides the submit frame in an *optional*
  ``"trace"`` field (:data:`TRACE_FIELD`) together with the client's
  wall-clock send instant — old daemons ignore the field, old clients
  simply never send it, and the HMAC covers it like any other field, so
  the protocol stays backward-compatible in both directions.
- **Child propagation**: supervised children receive the id via a
  ``trace=<id>`` argv extra (:data:`ENV_TRACE` is the env fallback) and
  ship their own span ring back inside the result JSON.
- **Clock rebasing**: span timestamps are microseconds relative to each
  tracer's construction instant.  Every :class:`~.trace.Tracer` records
  the wall-clock time of that instant (``wall_base``), so two rings on
  the same host rebase with ``offset_us = (child.wall_base -
  parent.wall_base) * 1e6`` — the clock-offset handshake.  Residual skew
  (NTP steps, coarse wall clocks) is killed by clamping the rebased
  spans into the parent's observed escalation window, which is what
  guarantees *no negative durations and no child span outside its
  parent* regardless of what the clocks did.
"""

from __future__ import annotations

import os
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

__all__ = [
    "TRACE_FIELD",
    "ENV_TRACE",
    "new_trace_id",
    "valid_trace_id",
    "trace_frame",
    "parse_trace_frame",
    "rebase_spans",
]

#: optional submit-frame field carrying ``{"trace_id", "sent_wall"}``
TRACE_FIELD = "trace"

#: environment fallback for child trace-id propagation (argv wins)
ENV_TRACE = "S2VTPU_TRACE"


def new_trace_id() -> str:
    """A fresh W3C-style trace id: 32 lower hex chars, never all-zero."""
    while True:
        tid = os.urandom(16).hex()
        if any(c != "0" for c in tid):
            return tid


def valid_trace_id(value: Any) -> bool:
    return (
        isinstance(value, str)
        and len(value) == 32
        and all(c in "0123456789abcdef" for c in value)
        and any(c != "0" for c in value)
    )


def trace_frame(trace_id: str) -> Dict[str, Any]:
    """The submit-frame ``trace`` field a client sends: the id plus the
    wall-clock send instant (lets the daemon reconstruct client wait)."""
    return {"trace_id": trace_id, "sent_wall": round(time.time(), 6)}


def parse_trace_frame(obj: Any) -> Tuple[Optional[str], Optional[float]]:
    """Validate an incoming ``trace`` field → ``(trace_id, sent_wall)``.

    Both come back ``None``-able: a malformed id is treated as absent
    (the daemon mints its own) rather than an error — trace context is
    best-effort metadata, never a reason to refuse a job.
    """
    if not isinstance(obj, dict):
        return None, None
    tid = obj.get("trace_id")
    if not valid_trace_id(tid):
        tid = None
    wall = obj.get("sent_wall")
    try:
        wall = float(wall) if wall is not None else None
    except (TypeError, ValueError):
        wall = None
    return tid, wall


def rebase_spans(
    spans: Sequence[Dict[str, Any]],
    *,
    offset_us: float,
    tid: int,
    pid: int,
    clamp_us: Optional[Tuple[float, float]] = None,
    extra_args: Optional[Dict[str, Any]] = None,
) -> List[Dict[str, Any]]:
    """Rebase a foreign span ring onto a parent timeline.

    ``offset_us`` shifts every timestamp from the child's tracer-relative
    microseconds to the parent's.  ``clamp_us = (lo, hi)`` then pins each
    span inside the parent's observed window for the child (spans that
    drifted outside are shrunk to the boundary and tagged
    ``args.clamped``), which is what makes the merged timeline immune to
    inter-process clock skew: durations can never go negative and a
    child span can never escape the escalation span that contains it.
    Non-"X" events (track-name metadata) are dropped — the parent track
    already has a name.
    """
    out: List[Dict[str, Any]] = []
    for e in spans:
        if not isinstance(e, dict) or e.get("ph") != "X":
            continue
        try:
            ts = float(e.get("ts", 0.0)) + offset_us
            dur = max(0.0, float(e.get("dur", 0.0)))
        except (TypeError, ValueError):
            continue
        end = ts + dur
        clamped = False
        if clamp_us is not None:
            lo, hi = clamp_us
            new_ts = min(max(ts, lo), hi)
            new_end = min(max(end, lo), hi)
            clamped = abs(new_ts - ts) > 0.5 or abs(new_end - end) > 0.5
            ts, end = new_ts, new_end
        args = dict(e.get("args") or {})
        if extra_args:
            args.update(extra_args)
        if clamped:
            args["clamped"] = True
        out.append(
            {
                "name": str(e.get("name", "span")),
                "ph": "X",
                "ts": round(ts, 3),
                "dur": round(max(0.0, end - ts), 3),
                "pid": int(pid),
                "tid": int(tid),
                "cat": str(e.get("cat", "child")),
                "args": args,
            }
        )
    return out
