"""SLO health engine: rolling availability, latency quantiles, burn rates.

Consumes the single ServiceStats event stream (``done`` / ``cache_hit``
are good requests, ``job_error`` / ``reject`` are bad) and maintains
per-second buckets merged on demand into rolling windows (1m/5m/30m by
default).  From each window it derives:

- **availability** — good / (good + bad);
- **latency quantiles** — p50/p95/p99 estimated from the fixed
  ``LATENCY_BUCKETS`` histogram by linear interpolation within the
  bucket (the classic Prometheus ``histogram_quantile``), over
  end-to-end job wall (queue wait + execution);
- **error-budget burn rate** — ``error_rate / (1 - target)``: how many
  times faster than sustainable the budget is being spent.  Burn 1.0
  exactly exhausts a 30-day budget in 30 days; the standard
  multiwindow alerting pair is a *fast* burn (~14.4 on the short
  window: budget gone in ~2 days) and a *slow* burn (~6 on the long
  window: gone in ~5 days).

The engine is passive — no threads.  ``observe_event`` is fed by
ServiceStats (outside its sink lock), and readers (``/healthz``,
``/slo``, the ``stats`` op, gauge refresh before each ``/metrics``
scrape) recompute windows on demand.  Breach detection is
edge-triggered: ``check_breach`` reports a burn trip only on the
not-breached → breached transition, which is what gates the
``slo_breach`` ServiceStats event and the flight-recorder dump.

Everything is stdlib-only and injectable-clock (``time_fn``) so the
window math is testable on a synthetic stream without sleeping.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple

from .metrics import LATENCY_BUCKETS, MetricsRegistry

__all__ = ["SLOConfig", "SLOHealth"]

#: events that count as a served request, successfully
_GOOD_EVENTS = ("done", "cache_hit")
#: events that count as a served request, failed (burns budget)
_BAD_EVENTS = ("job_error", "reject")


@dataclass(frozen=True)
class SLOConfig:
    """Targets + window geometry for the health engine."""

    availability_target: float = 0.99
    latency_target_s: float = 5.0
    latency_quantile: float = 0.95
    #: rolling windows in seconds, shortest first
    windows: Tuple[int, ...] = (60, 300, 1800)
    quantiles: Tuple[float, ...] = (0.5, 0.95, 0.99)
    #: burn-rate trip levels: fast on windows[0], slow on windows[-1]
    fast_burn_threshold: float = 14.4
    slow_burn_threshold: float = 6.0
    #: a window with fewer total events than this never trips (cold-start
    #: guard: one early failure must not read as burn 100)
    min_events: int = 10


_WINDOW_NAMES = {60: "1m", 300: "5m", 1800: "30m"}


def window_name(seconds: int) -> str:
    return _WINDOW_NAMES.get(seconds, "%ds" % seconds)


@dataclass
class _Bucket:
    """One second of aggregated events."""

    ok: int = 0
    err: int = 0
    lat: List[int] = field(default_factory=lambda: [0] * (len(LATENCY_BUCKETS) + 1))


def _quantile_from_buckets(counts: List[int], q: float) -> Optional[float]:
    """Estimate a quantile from cumulative-less bucket counts by linear
    interpolation inside the owning bucket (the +Inf bucket answers with
    the largest finite boundary — the estimate saturates, as Prometheus's
    does)."""
    total = sum(counts)
    if total <= 0:
        return None
    rank = q * total
    seen = 0.0
    for i, c in enumerate(counts):
        if c <= 0:
            continue
        if seen + c >= rank:
            if i >= len(LATENCY_BUCKETS):
                return LATENCY_BUCKETS[-1]
            lo = LATENCY_BUCKETS[i - 1] if i > 0 else 0.0
            hi = LATENCY_BUCKETS[i]
            frac = (rank - seen) / c
            return lo + (hi - lo) * min(1.0, max(0.0, frac))
        seen += c
    return LATENCY_BUCKETS[-1]


class SLOHealth:
    """Rolling multi-window SLO state over the ServiceStats event stream."""

    def __init__(
        self,
        config: Optional[SLOConfig] = None,
        *,
        registry: Optional[MetricsRegistry] = None,
        time_fn: Callable[[], float] = time.time,
    ) -> None:
        self.config = config or SLOConfig()
        self._time = time_fn
        self._lock = threading.Lock()
        self._buckets: Dict[int, _Bucket] = {}
        self._horizon = max(self.config.windows) + 2
        self._breached = False
        self._last_breach: Optional[Dict[str, Any]] = None
        self._breach_count = 0
        #: operational degradations reported from outside the SLO math
        #: (e.g. a journal writer on a full disk): key → detail dict.
        #: Any entry forces /healthz to degraded with a "degraded" reason.
        self._degraded_reasons: Dict[str, Dict[str, Any]] = {}
        self._m_avail = self._m_burn = self._m_lat = None
        self._m_healthy = self._m_breaches = None
        if registry is not None:
            self._m_avail = registry.gauge(
                "verifyd_slo_availability",
                "Rolling availability (good/(good+bad)) per window.",
                labelnames=("window",),
            )
            self._m_burn = registry.gauge(
                "verifyd_slo_burn_rate",
                "Error-budget burn rate (error_rate/(1-target)) per window.",
                labelnames=("window",),
            )
            self._m_lat = registry.gauge(
                "verifyd_slo_latency_seconds",
                "Rolling end-to-end latency quantiles per window.",
                labelnames=("window", "quantile"),
            )
            self._m_healthy = registry.gauge(
                "verifyd_slo_healthy",
                "1 when within SLO, 0 when degraded (mirrors /healthz).",
            )
            self._m_breaches = registry.counter(
                "verifyd_slo_breaches_total",
                "Edge-triggered SLO burn-rate breaches.",
            )
            self._m_healthy.set(1)
            self._m_breaches.inc(0)

    # ------------------------------------------------------------- ingest

    def observe_event(self, ev: Dict[str, Any]) -> None:
        """Feed one ServiceStats event line (already-serialized dict).

        Only request-outcome events count; everything else — including
        ``slo_breach`` itself, which would otherwise feed back — is
        ignored.  The event's own ``t`` field wins over the engine clock
        so post-mortem replay (doctor) reconstructs the same windows.
        """
        # ServiceStats lines carry the name under "ev"; synthetic test
        # streams may use "event" — accept both.
        name = ev.get("ev") or ev.get("event")
        if name in _GOOD_EVENTS:
            ok, err = 1, 0
        elif name in _BAD_EVENTS:
            ok, err = 0, 1
        else:
            return
        try:
            t = float(ev.get("t", self._time()))
        except (TypeError, ValueError):
            t = self._time()
        latency = None
        if ok:
            try:
                latency = float(ev.get("wall_s", 0.0)) + float(
                    ev.get("queue_wait_s", 0.0)
                )
            except (TypeError, ValueError):
                latency = None
        sec = int(t)
        with self._lock:
            b = self._buckets.get(sec)
            if b is None:
                b = self._buckets[sec] = _Bucket()
                self._gc_locked(sec)
            b.ok += ok
            b.err += err
            if latency is not None:
                b.lat[self._lat_index(latency)] += 1

    @staticmethod
    def _lat_index(latency: float) -> int:
        for i, edge in enumerate(LATENCY_BUCKETS):
            if latency <= edge:
                return i
        return len(LATENCY_BUCKETS)

    def _gc_locked(self, now_sec: int) -> None:
        if len(self._buckets) <= self._horizon:
            return
        cutoff = now_sec - self._horizon
        for sec in [s for s in self._buckets if s < cutoff]:
            del self._buckets[sec]

    # ------------------------------------------------------------ windows

    def _window_locked(self, seconds: int, now: float) -> Tuple[int, int, List[int]]:
        lo = int(now) - seconds
        hi = int(now)
        ok = err = 0
        lat = [0] * (len(LATENCY_BUCKETS) + 1)
        for sec, b in self._buckets.items():
            if lo < sec <= hi:
                ok += b.ok
                err += b.err
                for i, c in enumerate(b.lat):
                    lat[i] += c
        return ok, err, lat

    def snapshot(self) -> Dict[str, Any]:
        """Full SLO picture: per-window availability/burn/quantiles plus
        the health verdict.  Shape is shared by ``/slo``, the ``stats``
        op ``slo`` section, and the flight recorder."""
        cfg = self.config
        now = self._time()
        windows: Dict[str, Any] = {}
        with self._lock:
            for w in cfg.windows:
                ok, err, lat = self._window_locked(w, now)
                total = ok + err
                avail = (ok / total) if total else 1.0
                burn = (
                    ((err / total) / (1.0 - cfg.availability_target))
                    if total and cfg.availability_target < 1.0
                    else 0.0
                )
                quantiles = {
                    ("p%g" % (q * 100)): _quantile_from_buckets(lat, q)
                    for q in cfg.quantiles
                }
                windows[window_name(w)] = {
                    "seconds": w,
                    "good": ok,
                    "bad": err,
                    "availability": round(avail, 6),
                    "burn_rate": round(burn, 4),
                    "latency": {
                        k: (round(v, 6) if v is not None else None)
                        for k, v in quantiles.items()
                    },
                }
            breached = self._breached
            last_breach = self._last_breach
            breach_count = self._breach_count
            degraded = {k: dict(v) for k, v in self._degraded_reasons.items()}
        healthy, reasons = self._verdict(windows)
        for key, detail in sorted(degraded.items()):
            reasons.append({"kind": "degraded", "what": key, **detail})
            healthy = False
        return {
            "healthy": healthy,
            "reasons": reasons,
            "availability_target": cfg.availability_target,
            "latency_target_s": cfg.latency_target_s,
            "windows": windows,
            "breached": breached,
            "breaches": breach_count,
            "last_breach": last_breach,
        }

    def _verdict(self, windows: Dict[str, Any]) -> Tuple[bool, List[Dict[str, Any]]]:
        """Degraded when a burn threshold trips (with enough events) or the
        target latency quantile blows through its target on the short
        window."""
        cfg = self.config
        reasons: List[Dict[str, Any]] = []
        checks = (
            (window_name(cfg.windows[0]), cfg.fast_burn_threshold, "fast_burn"),
            (window_name(cfg.windows[-1]), cfg.slow_burn_threshold, "slow_burn"),
        )
        for wname, threshold, kind in checks:
            w = windows.get(wname)
            if not w or (w["good"] + w["bad"]) < cfg.min_events:
                continue
            if w["burn_rate"] >= threshold:
                reasons.append(
                    {
                        "kind": kind,
                        "window": wname,
                        "burn_rate": w["burn_rate"],
                        "threshold": threshold,
                        "availability": w["availability"],
                    }
                )
        short = windows.get(window_name(cfg.windows[0]))
        if short and (short["good"] + short["bad"]) >= cfg.min_events:
            qkey = "p%g" % (cfg.latency_quantile * 100)
            lat = short["latency"].get(qkey)
            if lat is not None and lat > cfg.latency_target_s:
                reasons.append(
                    {
                        "kind": "latency",
                        "window": window_name(cfg.windows[0]),
                        "quantile": qkey,
                        "latency_s": lat,
                        "target_s": cfg.latency_target_s,
                    }
                )
        return (not reasons), reasons

    # ------------------------------------------------------------ surface

    def set_degraded(self, key: str, **detail: Any) -> None:
        """Mark an operational degradation (journal on a full disk, …):
        /healthz answers 503 with a ``degraded`` reason naming ``key``
        until :meth:`clear_degraded` re-arms it."""
        with self._lock:
            self._degraded_reasons[key] = dict(detail)

    def clear_degraded(self, key: str) -> None:
        with self._lock:
            self._degraded_reasons.pop(key, None)

    def healthz(self) -> Tuple[bool, Dict[str, Any]]:
        """The /healthz verdict: (healthy, body).  Body is small and
        machine-readable either way — a degraded 503 carries reasons."""
        snap = self.snapshot()
        body = {
            "status": "ok" if snap["healthy"] else "degraded",
            "reasons": snap["reasons"],
            "breaches": snap["breaches"],
        }
        return snap["healthy"], body

    def check_breach(self) -> Optional[Dict[str, Any]]:
        """Edge-triggered breach detection.

        Returns a breach description exactly once per not-breached →
        breached transition (None otherwise); recovery re-arms it.  The
        caller (ServiceStats) turns the description into an
        ``slo_breach`` event + flight-recorder dump.
        """
        snap = self.snapshot()
        burning = [r for r in snap["reasons"] if r["kind"].endswith("_burn")]
        with self._lock:
            if burning and not self._breached:
                self._breached = True
                self._breach_count += 1
                breach = {
                    "reasons": burning,
                    "availability": {
                        k: w["availability"] for k, w in snap["windows"].items()
                    },
                }
                self._last_breach = breach
                if self._m_breaches is not None:
                    self._m_breaches.inc()
                return breach
            if not burning and self._breached:
                self._breached = False
            return None

    def refresh(self) -> Dict[str, Any]:
        """Recompute windows and push them into the metric gauges (called
        before each /metrics render so scrapes are never stale).  Returns
        the snapshot so callers can reuse it."""
        snap = self.snapshot()
        if self._m_avail is not None:
            for wname, w in snap["windows"].items():
                self._m_avail.set(w["availability"], window=wname)
                self._m_burn.set(w["burn_rate"], window=wname)
                for qkey, v in w["latency"].items():
                    if v is not None:
                        self._m_lat.set(v, window=wname, quantile=qkey)
            self._m_healthy.set(1 if snap["healthy"] else 0)
        return snap
