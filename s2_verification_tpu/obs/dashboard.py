"""Live dashboard: a retained scrape ring rendered as a zero-dependency
HTML page (``GET /dashboard`` on the obs httpd) and a JSON series feed
(``GET /dashboard.json``).

A small sampler thread reads the daemon's own metric families — the same
numbers a Prometheus scrape would see — into a bounded ring, so the page
needs no external TSDB: sparklines are server-side inline SVG, the page
is one self-contained document (no scripts, no fetches, works through an
SSH port forward), and a ``<meta http-equiv="refresh">`` keeps it live.

Series retained per tick: throughput (completed jobs/s over the tick),
queue depth, SLO fast-window burn rate, device-lease occupancy, JIT
compile activity (compiles/tick), and host RSS.
"""

from __future__ import annotations

import html
import json
import threading
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Sequence

__all__ = ["Dashboard", "render_sparkline", "SERIES"]

#: retained series, in display order: (key, title, unit)
SERIES = (
    ("throughput", "throughput", "jobs/s"),
    ("queue_depth", "queue depth", "jobs"),
    ("slo_burn", "SLO burn (fast window)", "x"),
    ("leases", "devices leased", "devices"),
    ("compiles", "JIT compiles", "per tick"),
    ("rss_mb", "host RSS", "MiB"),
)


def _counter_total(registry, name: str) -> float:
    """Sum of all series of a counter family (0.0 when unregistered)."""
    m = registry.get(name)
    if m is None:
        return 0.0
    try:
        return float(sum(m.snapshot().values()))
    except (TypeError, ValueError, AttributeError):
        return 0.0


def _gauge_value(registry, name: str) -> float:
    m = registry.get(name)
    if m is None:
        return 0.0
    try:
        return float(m.value())
    except (TypeError, ValueError, AttributeError):
        return 0.0


def render_sparkline(
    values: Sequence[float], *, width: int = 280, height: int = 48
) -> str:
    """One series as an inline SVG polyline (self-contained, no scripts)."""
    vals = [float(v) for v in values]
    if not vals:
        return (
            f'<svg class="spark" width="{width}" height="{height}" '
            f'viewBox="0 0 {width} {height}"></svg>'
        )
    lo, hi = min(vals), max(vals)
    span = (hi - lo) or 1.0
    n = len(vals)
    step = width / max(1, n - 1)
    pts = []
    for i, v in enumerate(vals):
        x = 0.0 if n == 1 else i * step
        y = height - 4 - (v - lo) / span * (height - 8)
        pts.append(f"{x:.1f},{y:.1f}")
    return (
        f'<svg class="spark" width="{width}" height="{height}" '
        f'viewBox="0 0 {width} {height}" preserveAspectRatio="none">'
        f'<polyline fill="none" stroke="#2a7ae2" stroke-width="1.5" '
        f'points="{" ".join(pts)}"/></svg>'
    )


class Dashboard:
    """Retained scrape ring + HTML/JSON renderers.

    ``start_thread=False`` leaves sampling to the caller (tests call
    :meth:`sample_once` directly; the daemon runs the thread).
    """

    def __init__(
        self,
        registry,
        *,
        health=None,
        sampler=None,
        interval_s: float = 2.0,
        capacity: int = 240,
        time_fn: Callable[[], float] = time.time,
        title: str = "verifyd",
        progress_fn: Optional[Callable[[], List[dict]]] = None,
    ) -> None:
        self.registry = registry
        self.health = health
        self.sampler = sampler
        #: zero-arg callable returning per-active-job progress rows
        #: (service/progress.py JobProgress.rows); sampled on the SAME
        #: tick as the metric series — no extra thread for the panel
        self.progress_fn = progress_fn
        self.interval_s = max(0.2, float(interval_s))
        self.title = title
        self._time = time_fn
        self._lock = threading.Lock()
        self._ring: deque = deque(maxlen=max(2, int(capacity)))
        self._prev_completed: Optional[float] = None
        self._prev_compiles: Optional[float] = None
        self._prev_t: Optional[float] = None
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- sampling ------------------------------------------------------------

    def sample_once(self) -> Dict[str, float]:
        now = self._time()
        completed = _counter_total(self.registry, "verifyd_jobs_completed_total")
        compiles = _counter_total(self.registry, "verifyd_jit_compiles_total")
        # sample_once is both the sampler thread's tick body and a public
        # entry (the /dashboard handler samples inline when the ring is
        # cold): the prev_* delta baseline is a read-then-write, so an
        # interleaved pair of calls would both diff against the same
        # baseline and double-count the interval's throughput.
        with self._lock:
            dt = (now - self._prev_t) if self._prev_t is not None else None
            throughput = 0.0
            compile_rate = 0.0
            if dt and dt > 0:
                throughput = max(0.0, completed - (self._prev_completed or 0.0)) / dt
                compile_rate = max(0.0, compiles - (self._prev_compiles or 0.0))
            self._prev_t, self._prev_completed, self._prev_compiles = (
                now,
                completed,
                compiles,
            )
        burn = 0.0
        if self.health is not None:
            try:
                snap = self.health.snapshot()
                windows = snap.get("windows") or {}
                if windows:
                    first = sorted(
                        windows.items(), key=lambda kv: kv[1].get("seconds", 0)
                    )[0][1]
                    burn = float(first.get("burn_rate", 0.0))
            except Exception:
                burn = 0.0
        rss = _gauge_value(self.registry, "verifyd_resource_rss_bytes")
        sample = {
            "t": round(now, 3),
            "throughput": round(throughput, 3),
            "queue_depth": _gauge_value(self.registry, "verifyd_queue_depth"),
            "slo_burn": round(burn, 4),
            "leases": _gauge_value(self.registry, "verifyd_devices_leased"),
            "compiles": compile_rate,
            "rss_mb": round(rss / (1 << 20), 2),
        }
        if self.progress_fn is not None:
            try:
                rows = self.progress_fn() or []
            except Exception:
                rows = []
            sample["progress"] = [
                {
                    "job": r.get("job"),
                    "engine": r.get("engine"),
                    "ratio": float(r.get("progress_ratio") or 0.0),
                    "eta_s": r.get("eta_s"),
                }
                for r in rows[:16]
                if isinstance(r, dict) and not r.get("done")
            ]
        with self._lock:
            self._ring.append(sample)
        return sample

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.sample_once()
            except Exception:  # the dashboard must never take verifyd down
                pass

    def start(self) -> "Dashboard":
        if self._thread is None:
            self.sample_once()
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._run, name="verifyd-dashboard", daemon=True
            )
            self._thread.start()
        return self

    def close(self, timeout: float = 2.0) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=timeout)
            self._thread = None

    # -- read side -----------------------------------------------------------

    @staticmethod
    def _progress_series(
        samples: List[dict], cap: int = 8
    ) -> List[Dict[str, Any]]:
        """Per-job progress-ratio series across the retained ring.

        Jobs come from the newest sample that carried progress rows
        (the currently active set); each job's series is its ratio at
        every retained tick it appeared in, so a long-running search
        draws a climbing sparkline while short jobs show as blips.
        """
        latest: List[dict] = []
        for s in reversed(samples):
            if s.get("progress"):
                latest = s["progress"]
                break
        out = []
        for row in latest[:cap]:
            job = row.get("job")
            series = [
                p["ratio"]
                for s in samples
                for p in (s.get("progress") or ())
                if p.get("job") == job
            ]
            out.append(
                {
                    "job": job,
                    "engine": row.get("engine"),
                    "ratio": row.get("ratio", 0.0),
                    "eta_s": row.get("eta_s"),
                    "series": series,
                }
            )
        return out

    def payload(self) -> Dict[str, Any]:
        """The /dashboard.json body: retained series, oldest first."""
        with self._lock:
            samples = list(self._ring)
        return {
            "title": self.title,
            "interval_s": self.interval_s,
            "retained": len(samples),
            "t": [s["t"] for s in samples],
            "series": {
                key: [s.get(key, 0.0) for s in samples] for key, _, _ in SERIES
            },
            "progress": self._progress_series(samples),
        }

    def render_json(self) -> str:
        return json.dumps(self.payload(), sort_keys=True) + "\n"

    def render_html(self) -> str:
        """The /dashboard body: one self-contained HTML document."""
        with self._lock:
            samples = list(self._ring)
        refresh = max(1, int(round(self.interval_s)))
        rows = []
        for key, label, unit in SERIES:
            vals = [float(s.get(key, 0.0)) for s in samples]
            cur = vals[-1] if vals else 0.0
            hi = max(vals) if vals else 0.0
            rows.append(
                "<tr>"
                f"<td class=\"name\">{html.escape(label)}</td>"
                f"<td class=\"val\">{cur:g}<span class=\"unit\"> "
                f"{html.escape(unit)}</span></td>"
                f"<td class=\"peak\">peak {hi:g}</td>"
                f"<td data-series=\"{html.escape(key)}\">"
                f"{render_sparkline(vals)}</td>"
                "</tr>"
            )
        progress_rows = []
        for p in self._progress_series(samples):
            eta = p.get("eta_s")
            eta_txt = f"{float(eta):.0f}s left" if eta is not None else "—"
            progress_rows.append(
                "<tr>"
                f"<td class=\"name\">job {html.escape(str(p['job']))}"
                f"<span class=\"unit\"> {html.escape(str(p.get('engine') or ''))}"
                "</span></td>"
                f"<td class=\"val\">{100.0 * float(p.get('ratio') or 0.0):.1f}"
                "<span class=\"unit\"> %</span></td>"
                f"<td class=\"peak\">{html.escape(eta_txt)}</td>"
                "<td data-series=\"progress\">"
                f"{render_sparkline(p.get('series') or [])}</td>"
                "</tr>"
            )
        progress_html = ""
        if progress_rows:
            progress_html = (
                "<h1>active searches</h1>"
                f"<table>{''.join(progress_rows)}</table>"
            )
        when = time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime(self._time()))
        return (
            "<!DOCTYPE html>\n"
            "<html><head><meta charset=\"utf-8\">"
            f"<meta http-equiv=\"refresh\" content=\"{refresh}\">"
            f"<title>{html.escape(self.title)} dashboard</title>"
            "<style>"
            "body{font:14px/1.4 system-ui,sans-serif;margin:2em;"
            "background:#fbfbfb;color:#222}"
            "table{border-collapse:collapse}"
            "td{padding:.35em .9em;border-bottom:1px solid #e4e4e4;"
            "vertical-align:middle}"
            "td.name{font-weight:600}"
            "td.val{font-variant-numeric:tabular-nums;text-align:right}"
            "td.peak{color:#888;font-size:12px}"
            ".unit{color:#888;font-size:12px}"
            "svg.spark{display:block}"
            "h1{font-size:18px}footer{margin-top:1.5em;color:#888;"
            "font-size:12px}"
            "</style></head><body>"
            f"<h1>{html.escape(self.title)} — live dashboard</h1>"
            f"<table>{''.join(rows)}</table>"
            f"{progress_html}"
            f"<footer>{len(samples)} samples retained · "
            f"{self.interval_s:g}s tick · rendered {when} · "
            "also: <code>/dashboard.json</code>, <code>/metrics</code>, "
            "<code>/slo</code></footer>"
            "</body></html>\n"
        )
