"""Rule-driven alert delivery: the first obs consumer that *acts*.

PRs 3 and 5 made the daemon visible — metrics, traces, SLO burn rates,
an edge-triggered ``slo_breach`` event — but every signal dead-ended in
the stats stream.  The :class:`AlertEngine` closes the loop: it
subscribes to the same ServiceStats event stream everything else rides
(fed outside the sink lock, like the flight recorder), matches each
event against a small rule set, and delivers alertmanager-compatible
JSON to an operator-configured URL (``serve --alert-url``) over stdlib
HTTP.

Rule grammar (``serve --alert-rule``, repeatable)::

    slo_breach                      fire whenever the event occurs
    done.wall_s>30                  event-field threshold (edge-triggered)
    reject.queue_depth>=48          ops: > >= < <=
    metric:verifyd_job_errors_total>100
                                    registry counter/gauge threshold,
                                    evaluated on every event (edge-triggered)

``slo_breach`` and ``perf_regression`` rules are built in — an alert URL
with no explicit rules still pages on the two signals that matter.

Delivery discipline (everything injected for tests):

- one background daemon thread drains a bounded queue, so a dead
  webhook endpoint can never stall the emit path a job passes through;
- exponential backoff with full jitter between attempts; a 4xx other
  than 408/429 is definite (the payload will never be accepted) and is
  not retried;
- per-rule dedup window (default 300 s): a flapping signal produces one
  delivery per window, the rest are counted as suppressed;
- field/metric threshold rules are *edge-triggered*: they fire on the
  crossing and re-arm only after a sample back inside the band, so a
  saturated gauge pages once, not per event.

Metric families: ``verifyd_alerts_sent_total`` /
``verifyd_alerts_failed_total`` / ``verifyd_alerts_suppressed_total``
(all by rule) and the ``verifyd_alert_delivery_seconds`` histogram.
Fired alerts land in the flight ring as ``{"k": "alert"}`` records and
exhausted deliveries as ``alert_failed`` dump markers, so the doctor can
report both cold.
"""

from __future__ import annotations

import collections
import json
import os
import random
import threading
import time
import urllib.error
import urllib.request
from dataclasses import dataclass
from typing import TYPE_CHECKING, Any, Callable, Dict, Iterable, List, Optional, Tuple

from .metrics import LATENCY_BUCKETS, MetricsRegistry

if TYPE_CHECKING:  # pragma: no cover
    from .flight import FlightRecorder

__all__ = ["AlertEngine", "AlertRule", "builtin_rules", "parse_rule"]

_OPS: Dict[str, Callable[[float, float], bool]] = {
    ">": lambda a, b: a > b,
    ">=": lambda a, b: a >= b,
    "<": lambda a, b: a < b,
    "<=": lambda a, b: a <= b,
}

#: 4xx statuses worth retrying (timeout / throttle); every other 4xx is
#: a definite refusal of this payload.
_RETRYABLE_4XX = (408, 429)


@dataclass(frozen=True)
class AlertRule:
    """One compiled rule.  ``kind`` is ``event`` (fire on occurrence),
    ``field`` (event-field threshold) or ``metric`` (registry value
    threshold, checked whenever any event arrives)."""

    name: str  #: the spec string; doubles as the alertname label
    kind: str
    event: str = ""
    field: str = ""
    metric: str = ""
    op: str = ">"
    threshold: float = 0.0
    severity: str = "page"

    def describe(self) -> str:
        if self.kind == "event":
            return f"event {self.event}"
        if self.kind == "field":
            return f"{self.event}.{self.field} {self.op} {self.threshold:g}"
        return f"metric {self.metric} {self.op} {self.threshold:g}"


def _split_threshold(expr: str) -> Tuple[str, str, float]:
    """``"name>=5"`` → (name, op, 5.0); longest operator wins."""
    for op in (">=", "<=", ">", "<"):
        if op in expr:
            lhs, rhs = expr.split(op, 1)
            lhs, rhs = lhs.strip(), rhs.strip()
            if not lhs or not rhs:
                break
            try:
                return lhs, op, float(rhs)
            except ValueError:
                break
    raise ValueError(f"bad alert threshold expression: {expr!r}")


def parse_rule(spec: str) -> AlertRule:
    """Compile one ``--alert-rule`` spec; raises ValueError on nonsense."""
    spec = spec.strip()
    if not spec:
        raise ValueError("empty alert rule")
    if spec.startswith("metric:"):
        name, op, thr = _split_threshold(spec[len("metric:") :])
        return AlertRule(
            name=spec, kind="metric", metric=name, op=op, threshold=thr,
            severity="warn",
        )
    if any(op in spec for op in _OPS):
        lhs, op, thr = _split_threshold(spec)
        if "." not in lhs:
            raise ValueError(
                f"field rule needs EVENT.FIELD on the left: {spec!r}"
            )
        event, fname = lhs.split(".", 1)
        if not event or not fname:
            raise ValueError(f"field rule needs EVENT.FIELD: {spec!r}")
        return AlertRule(
            name=spec, kind="field", event=event, field=fname, op=op,
            threshold=thr, severity="warn",
        )
    if not spec.replace("_", "").isalnum():
        raise ValueError(f"bad event name in alert rule: {spec!r}")
    return AlertRule(name=spec, kind="event", event=spec)


def builtin_rules() -> Tuple[AlertRule, ...]:
    """The signals every deployment should page on: SLO burn, perf
    regressions, retrace storms, a poison job entering quarantine, a
    durable writer degrading (journal on a full disk), and the soak loop
    catching the checker contradicting a ground-truth label."""
    return (
        AlertRule(name="slo_breach", kind="event", event="slo_breach"),
        AlertRule(name="perf_regression", kind="event", event="perf_regression"),
        AlertRule(name="retrace_storm", kind="event", event="retrace_storm"),
        AlertRule(name="job_quarantined", kind="event", event="job_quarantined"),
        AlertRule(name="writer_degraded", kind="event", event="writer_degraded"),
        AlertRule(
            name="checker_false_verdict",
            kind="event",
            event="checker_false_verdict",
        ),
    )


@dataclass
class _RuleState:
    armed: bool = True  #: threshold rules: re-armed by an in-band sample
    last_fired: Optional[float] = None
    fired: int = 0
    suppressed: int = 0


def _rfc3339(t: float) -> str:
    return time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime(t))


class AlertEngine:
    """Matches the ServiceStats stream against rules and delivers webhooks.

    ``observe_event`` (the hot path) only does rule matching and a deque
    append; all HTTP happens on the drain thread.  ``time_fn`` /
    ``sleep_fn`` / ``rng`` are injectable so tests cover backoff and
    dedup without real clocks.
    """

    def __init__(
        self,
        url: str,
        rules: Iterable[AlertRule] = (),
        *,
        registry: Optional[MetricsRegistry] = None,
        recorder: "Optional[FlightRecorder]" = None,
        retries: int = 4,
        backoff_s: float = 0.5,
        max_backoff_s: float = 30.0,
        dedup_s: float = 300.0,
        timeout_s: float = 5.0,
        queue_cap: int = 256,
        time_fn: Callable[[], float] = time.time,
        sleep_fn: Callable[[float], None] = time.sleep,
        rng: Optional[random.Random] = None,
    ) -> None:
        self.url = url
        self.rules: Tuple[AlertRule, ...] = tuple(rules) or builtin_rules()
        self.recorder = recorder
        self.registry = registry if registry is not None else MetricsRegistry()
        self.retries = retries
        self.backoff_s = backoff_s
        self.max_backoff_s = max_backoff_s
        self.dedup_s = dedup_s
        self.timeout_s = timeout_s
        self.queue_cap = queue_cap
        self._time = time_fn
        self._sleep = sleep_fn
        self._rng = rng if rng is not None else random.Random()

        r = self.registry
        self._m_sent = r.counter(
            "verifyd_alerts_sent_total",
            "Alert webhooks delivered (2xx)",
            labelnames=("rule",),
        )
        self._m_failed = r.counter(
            "verifyd_alerts_failed_total",
            "Alert deliveries abandoned after retries (or queue overflow)",
            labelnames=("rule",),
        )
        self._m_suppressed = r.counter(
            "verifyd_alerts_suppressed_total",
            "Alerts swallowed by the per-rule dedup window",
            labelnames=("rule",),
        )
        self._m_latency = r.histogram(
            "verifyd_alert_delivery_seconds",
            "Wall time from firing to 2xx, retries included",
            buckets=LATENCY_BUCKETS,
        )

        self._cv = threading.Condition()
        self._queue: collections.deque = collections.deque()
        self._state: Dict[str, _RuleState] = {
            rule.name: _RuleState() for rule in self.rules
        }
        self._inflight = 0
        self._closed = False
        self._worker = threading.Thread(
            target=self._drain, name="verifyd-alerts", daemon=True
        )
        self._worker.start()

    # -- hot path: rule matching --------------------------------------------

    def observe_event(self, ev: Dict[str, Any]) -> None:
        """Feed one event line; fired rules enqueue for async delivery."""
        name = ev.get("ev") or ev.get("event")
        if not name:
            return
        now = self._time()
        for rule in self.rules:
            if self._matches(rule, name, ev):
                self._fire(rule, name, ev, now)

    def _matches(self, rule: AlertRule, name: str, ev: Dict[str, Any]) -> bool:
        state = self._state[rule.name]
        if rule.kind == "event":
            return name == rule.event
        if rule.kind == "field":
            if name != rule.event or rule.field not in ev:
                return False
            try:
                value = float(ev[rule.field])
            except (TypeError, ValueError):
                return False
        else:  # metric
            value = self._metric_value(rule.metric)
            if value is None:
                return False
        crossed = _OPS[rule.op](value, rule.threshold)
        if not crossed:
            state.armed = True  # back in band: re-arm
            return False
        if not state.armed:
            return False  # still over threshold since the last firing
        state.armed = False
        return True

    def _metric_value(self, name: str) -> Optional[float]:
        metric = self.registry.get(name)
        if metric is None or not hasattr(metric, "value"):
            return None
        try:
            if not getattr(metric, "labelnames", ()):
                return float(metric.value())
            # Labeled counter/gauge: threshold the sum over all series.
            return float(sum(metric.snapshot().values()))
        except (TypeError, ValueError, AttributeError):
            return None

    def _fire(
        self, rule: AlertRule, event: str, ev: Dict[str, Any], now: float
    ) -> None:
        state = self._state[rule.name]
        if state.last_fired is not None and now - state.last_fired < self.dedup_s:
            state.suppressed += 1
            self._m_suppressed.inc(rule=rule.name)
            return
        state.last_fired = now
        state.fired += 1
        if self.recorder is not None:
            self.recorder.record_alert(
                {"rule": rule.name, "event": event, "severity": rule.severity}
            )
        alert = {"rule": rule, "event": event, "ev": dict(ev), "t": now}
        with self._cv:
            if self._closed:
                return
            if len(self._queue) >= self.queue_cap:
                # Shed the oldest undelivered alert, accounted as failed:
                # recency wins when the endpoint is this far behind.
                dropped = self._queue.popleft()
                self._m_failed.inc(rule=dropped["rule"].name)
            self._queue.append(alert)
            self._cv.notify()

    # -- delivery thread ----------------------------------------------------

    def _drain(self) -> None:
        while True:
            with self._cv:
                while not self._queue and not self._closed:
                    self._cv.wait(timeout=0.5)
                if not self._queue:
                    if self._closed:
                        return
                    continue
                alert = self._queue.popleft()
                self._inflight += 1
            try:
                self._deliver(alert)
            finally:
                with self._cv:
                    self._inflight -= 1
                    self._cv.notify_all()

    def _payload(self, alert: Dict[str, Any]) -> List[Dict[str, Any]]:
        """Alertmanager v1 shape: a JSON list of alert objects."""
        rule: AlertRule = alert["rule"]
        ev = alert["ev"]
        labels = {
            "alertname": rule.name,
            "service": "verifyd",
            "severity": rule.severity,
            "event": alert["event"],
        }
        for key in ("shape", "backend", "client"):
            if ev.get(key) is not None:
                labels[key] = str(ev[key])
        # Drop bulky nested payloads (profiles, SLO snapshots) from the
        # annotation; the flight ring keeps the full record.
        detail = {
            k: v for k, v in ev.items() if not isinstance(v, (dict, list))
        }
        return [
            {
                "labels": labels,
                "annotations": {
                    "summary": f"verifyd {alert['event']}: {rule.describe()}",
                    "detail": json.dumps(detail, sort_keys=True, default=str),
                },
                "startsAt": _rfc3339(alert["t"]),
                "generatorURL": f"verifyd://{os.uname().nodename}/{os.getpid()}",
            }
        ]

    def _post_once(self, body: bytes) -> Tuple[bool, bool, str]:
        """One POST → (delivered, retryable, error-detail)."""
        req = urllib.request.Request(
            self.url,
            data=body,
            headers={"Content-Type": "application/json"},
            method="POST",
        )
        try:
            with urllib.request.urlopen(req, timeout=self.timeout_s) as resp:
                resp.read()
                return True, False, ""
        except urllib.error.HTTPError as e:
            retryable = e.code >= 500 or e.code in _RETRYABLE_4XX
            return False, retryable, f"HTTP {e.code}"
        except (urllib.error.URLError, OSError, TimeoutError) as e:
            return False, True, str(e)

    def _deliver(self, alert: Dict[str, Any]) -> None:
        rule: AlertRule = alert["rule"]
        body = json.dumps(self._payload(alert), default=str).encode("utf-8")
        t0 = self._time()
        error = ""
        attempts = 0
        for attempt in range(self.retries + 1):
            attempts = attempt + 1
            delivered, retryable, error = self._post_once(body)
            if delivered:
                self._m_sent.inc(rule=rule.name)
                self._m_latency.observe(max(0.0, self._time() - t0))
                return
            if not retryable:
                break
            if attempt < self.retries:
                # Exponential backoff with full jitter, capped.
                cap = min(self.max_backoff_s, self.backoff_s * (2**attempt))
                self._sleep(self._rng.uniform(0.0, cap))
        self._m_failed.inc(rule=rule.name)
        if self.recorder is not None:
            self.recorder.dump(
                "alert_failed",
                rule=rule.name,
                url=self.url,
                error=error,
                attempts=attempts,
            )

    # -- lifecycle / introspection ------------------------------------------

    def flush(self, timeout: float = 10.0) -> bool:
        """Block until the queue drains (tests, shutdown); False on timeout."""
        deadline = time.monotonic() + timeout
        with self._cv:
            while self._queue or self._inflight:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return False
                self._cv.wait(timeout=remaining)
        return True

    def close(self, timeout: float = 5.0) -> None:
        self.flush(timeout=timeout)
        with self._cv:
            self._closed = True
            self._cv.notify_all()
        self._worker.join(timeout=2.0)

    def snapshot(self) -> Dict[str, Any]:
        with self._cv:
            pending = len(self._queue) + self._inflight
        return {
            "url": self.url,
            "dedup_s": self.dedup_s,
            "pending": pending,
            "rules": {
                rule.name: {
                    "kind": rule.kind,
                    "fired": self._state[rule.name].fired,
                    "suppressed": self._state[rule.name].suppressed,
                    "armed": self._state[rule.name].armed,
                }
                for rule in self.rules
            },
        }
