from . import events, hashing

__all__ = ["events", "hashing"]
