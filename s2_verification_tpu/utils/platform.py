"""Platform pinning that survives the axon sitecustomize hook.

The TPU (axon) PJRT plugin registers itself from a sitecustomize module at
interpreter start and overrides ``JAX_PLATFORMS``, so exporting
``JAX_PLATFORMS=cpu`` alone does not keep jax off the TPU — and when the
TPU tunnel is down, backend init *hangs* rather than errors.  Re-pinning
through the config API before first device use restores the documented
env-var semantics.  Call this before touching jax in any entry point that
honors ``JAX_PLATFORMS`` (the CLI device backend, benchmark scripts).
"""

from __future__ import annotations

import os

__all__ = ["pin_platform"]


def pin_platform() -> None:
    """Make ``JAX_PLATFORMS`` mean what it says (no-op when unset)."""
    plat = os.environ.get("JAX_PLATFORMS")
    if plat:
        import jax

        jax.config.update("jax_platforms", plat)
