"""Platform pinning that survives the axon sitecustomize hook.

The TPU (axon) PJRT plugin registers itself from a sitecustomize module at
interpreter start and overrides ``JAX_PLATFORMS``, so exporting
``JAX_PLATFORMS=cpu`` alone does not keep jax off the TPU — and when the
TPU tunnel is down, backend init *hangs* rather than errors.  Re-pinning
through the config API before first device use restores the documented
env-var semantics.  Call this before touching jax in any entry point that
honors ``JAX_PLATFORMS`` (the CLI device backend, benchmark scripts).
"""

from __future__ import annotations

import os

__all__ = ["pin_platform", "ensure_host_device_count"]


def pin_platform() -> None:
    """Make ``JAX_PLATFORMS`` mean what it says (no-op when unset)."""
    plat = os.environ.get("JAX_PLATFORMS")
    if plat:
        import jax

        jax.config.update("jax_platforms", plat)


def ensure_host_device_count(n: int) -> None:
    """Provision ``n`` virtual CPU devices via ``XLA_FLAGS`` (env mutation,
    so escalation children inherit the same topology).  Call before first
    jax use in this process — the flag is read at backend init.

    When the host has fewer cores than devices, XLA's per-device Eigen
    thread pools oversubscribe the machine badly; pin them to one thread
    each in that case (same guard as tests/conftest.py and
    ``__graft_entry__.dryrun_multichip``).
    """
    flags = [
        f
        for f in os.environ.get("XLA_FLAGS", "").split()
        if "xla_force_host_platform_device_count" not in f
    ]
    flags.append(f"--xla_force_host_platform_device_count={int(n)}")
    if (os.cpu_count() or 1) < n and not any(
        "xla_cpu_multi_thread_eigen" in f for f in flags
    ):
        flags.append("--xla_cpu_multi_thread_eigen=false")
        flags.append("intra_op_parallelism_threads=1")
    os.environ["XLA_FLAGS"] = " ".join(flags)
