"""Chained xxh3 stream-hash protocol (host-side reference implementation).

The cumulative hash over a stream is the left fold of :func:`chain_hash` over
the xxh3-64 of every record body, in sequence order, starting from 0 for the
empty stream.  Each value commits to the entire stream prefix, which lets the
linearizability model keep a constant-size state instead of the stream
contents.

Wire/protocol parity with the reference implementation:
  - rust/s2-verification/src/history.rs:43-45 (``chain_hash``)
  - golang/s2-porcupine/main.go:232-244 (``chainHash`` / ``foldRecordHashes``)
Pinned cross-language test vectors: history.rs:687-696, main_test.go:15-32.

The JAX/TPU implementation of the same function lives in
``s2_verification_tpu.ops.xxh3`` and is differential-tested against this one.
"""

from __future__ import annotations

import struct
from collections.abc import Iterable

import xxhash

__all__ = ["record_hash", "chain_hash", "fold_record_hashes", "stream_hash_of_bodies"]

_U64 = struct.Struct("<Q")


def record_hash(body: bytes) -> int:
    """xxh3-64 (no seed) of one record body."""
    return xxhash.xxh3_64_intdigest(body)


def chain_hash(stream_hash: int, rec_hash: int) -> int:
    """Fold one record-body hash into a cumulative stream hash.

    Defined as ``xxh3_64(le_bytes(rec_hash), seed=stream_hash)``.
    """
    return xxhash.xxh3_64_intdigest(_U64.pack(rec_hash & 0xFFFFFFFFFFFFFFFF), seed=stream_hash)


def fold_record_hashes(stream_hash: int, rec_hashes: Iterable[int]) -> int:
    """Left-fold :func:`chain_hash` over a batch of record hashes."""
    acc = stream_hash
    for rh in rec_hashes:
        acc = chain_hash(acc, rh)
    return acc


def stream_hash_of_bodies(bodies: Iterable[bytes]) -> int:
    """Cumulative hash of an entire stream given every record body in order."""
    return fold_record_hashes(0, (record_hash(b) for b in bodies))
