"""Persistent XLA compilation cache.

The reference binaries start in O(ms); a fresh CLI invocation of the device
engine used to pay the full XLA compile (minutes on hard histories) on
every run.  Enabling JAX's persistent compilation cache makes repeat
invocations of the same search shapes skip compilation entirely.

Controlled by ``S2VTPU_COMPILE_CACHE``: unset → ``~/.cache/s2vtpu/xla``;
set to a path → that path; set to empty → disabled.
"""

from __future__ import annotations

import os

__all__ = ["enable_persistent_cache"]

_DEFAULT = os.path.join("~", ".cache", "s2vtpu", "xla")
_enabled: str | None = None


def enable_persistent_cache() -> str | None:
    """Idempotently point JAX at the on-disk compile cache.

    Must run before the first compilation to take effect for it (later
    compiles still benefit).  Returns the cache dir, or None if disabled
    or unavailable.
    """
    global _enabled
    if _enabled is not None:
        return _enabled or None
    path = os.environ.get("S2VTPU_COMPILE_CACHE")
    if path is None:
        path = os.path.expanduser(_DEFAULT)
    if not path:
        _enabled = ""
        return None
    try:
        import jax

        os.makedirs(path, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", path)
        # Cache everything that takes noticeable time; the default 1s
        # floor would skip the many small helper jits.
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.2)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
    except Exception:  # pragma: no cover - best-effort: cache is optional
        _enabled = ""
        return None
    _enabled = path
    return path
