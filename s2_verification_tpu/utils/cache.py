"""Persistent XLA compilation cache.

The reference binaries start in O(ms); a fresh CLI invocation of the device
engine used to pay the full XLA compile (minutes on hard histories) on
every run.  Enabling JAX's persistent compilation cache makes repeat
invocations of the same search shapes skip compilation entirely.

Controlled by ``S2VTPU_COMPILE_CACHE``: unset → ``~/.cache/s2vtpu/xla-<host>``;
set to a path → that path; set to empty → disabled.

The default directory is namespaced by a host-CPU fingerprint: XLA:CPU
AOT executables embed the compile machine's feature set, so entries
written on one host generation can mis-load (or SIGILL) on another.  A
per-host namespace starts a clean cache on a box change instead of
loading foreign executables.  (Note: cpu_aot_loader prints
machine-feature warnings even for same-host entries — XLA appends
synthetic `prefer-no-scatter/gather` options to the compile-time
feature list that host detection never reports — so the warnings alone
do not indicate a host change.)
"""

from __future__ import annotations

import hashlib
import os

__all__ = ["enable_persistent_cache"]

_enabled: str | None = None


def _host_fingerprint() -> str:
    """Short stable id of this host's CPU feature set.

    x86 /proc/cpuinfo exposes ``flags``, aarch64 exposes ``Features``;
    either line captures the AOT-relevant feature set.  The fallback
    hashes the full uname + machine string rather than
    ``platform.processor()`` (empty on most Linux), so two different
    host types never silently share a namespace just because the
    fingerprint degenerated to a constant.
    """
    try:
        with open("/proc/cpuinfo") as f:
            for line in f:
                if line.startswith(("flags", "Features")):
                    return hashlib.sha1(line.encode()).hexdigest()[:10]
    except OSError:
        pass
    import platform

    ident = "|".join([platform.machine(), platform.platform(), platform.processor()])
    return hashlib.sha1(ident.encode()).hexdigest()[:10]


def _default_dir() -> str:
    return os.path.expanduser(
        os.path.join("~", ".cache", "s2vtpu", f"xla-{_host_fingerprint()}")
    )


def enable_persistent_cache() -> str | None:
    """Idempotently point JAX at the on-disk compile cache.

    Must run before the first compilation to take effect for it (later
    compiles still benefit).  Returns the cache dir, or None if disabled
    or unavailable.
    """
    global _enabled
    if _enabled is not None:
        return _enabled or None
    path = os.environ.get("S2VTPU_COMPILE_CACHE")
    if path is None:
        path = _default_dir()
    if not path:
        _enabled = ""
        return None
    try:
        import jax

        # One-time cleanup of the pre-namespacing default — but only when
        # running with the fingerprinted default itself (env unset): a
        # user-configured dir must never trigger deletion of anything,
        # least of all a cache they pointed at or under the legacy path.
        if "S2VTPU_COMPILE_CACHE" not in os.environ:
            legacy = os.path.expanduser(
                os.path.join("~", ".cache", "s2vtpu", "xla")
            )
            if os.path.isdir(legacy):
                import shutil

                shutil.rmtree(legacy, ignore_errors=True)

        os.makedirs(path, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", path)
        # Cache everything that takes noticeable time; the default 1s
        # floor would skip the many small helper jits.
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.2)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
    except Exception:  # pragma: no cover - best-effort: cache is optional
        _enabled = ""
        return None
    _enabled = path
    return path
