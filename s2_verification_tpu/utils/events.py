"""History event vocabulary and its JSONL wire format.

The on-disk history format is line-oriented JSON records, wire-compatible with
the reference collector's serde encoding (rust/s2-verification/src/history.rs:84-137)
and the reference checker's decoder (golang/s2-porcupine/main.go:18-194):

  - unit enum variants encode as bare strings: ``{"event":{"Start":"Read"},...}``
  - struct variants encode as single-key objects:
    ``{"event":{"Start":{"Append":{"num_records":...,...}}},...}``
  - every record carries ``client_id`` and ``op_id``.

Decoding follows Go's ``json.Decoder`` semantics (a stream of concatenated
JSON values, not a line scanner), so arbitrarily large records are fine
(golang/s2-porcupine/main_test.go:34-101).  Validation matches the reference:
an ``Append`` start must carry exactly ``num_records`` record hashes
(main.go:62-64) and each record must hold exactly one of Start/Finish
(main.go:184-186).
"""

from __future__ import annotations

import io
import json
from dataclasses import dataclass, field
from typing import Iterator, Union

__all__ = [
    "AppendStart",
    "ReadStart",
    "CheckTailStart",
    "AppendSuccess",
    "AppendDefiniteFailure",
    "AppendIndefiniteFailure",
    "ReadSuccess",
    "ReadFailure",
    "CheckTailSuccess",
    "CheckTailFailure",
    "Start",
    "Finish",
    "LabeledEvent",
    "DecodeError",
    "encode_event",
    "event_to_obj",
    "decode_obj",
    "iter_history",
    "read_history",
    "write_history",
]


class DecodeError(ValueError):
    """A history record failed to decode or validate."""


# --------------------------------------------------------------------------
# Call-start variants
# --------------------------------------------------------------------------


@dataclass(frozen=True)
class AppendStart:
    num_records: int
    #: xxh3 of each record body in the batch, in order; the model folds these
    #: onto its cumulative stream hash.
    record_hashes: tuple[int, ...] = ()
    set_fencing_token: str | None = None
    fencing_token: str | None = None
    match_seq_num: int | None = None

    def __post_init__(self) -> None:
        if len(self.record_hashes) != self.num_records:
            raise ValueError(
                f"append has {len(self.record_hashes)} record_hashes "
                f"but {self.num_records} records"
            )


@dataclass(frozen=True)
class ReadStart:
    pass


@dataclass(frozen=True)
class CheckTailStart:
    pass


Start = Union[AppendStart, ReadStart, CheckTailStart]


# --------------------------------------------------------------------------
# Call-finish variants
# --------------------------------------------------------------------------


@dataclass(frozen=True)
class AppendSuccess:
    tail: int


@dataclass(frozen=True)
class AppendDefiniteFailure:
    pass


@dataclass(frozen=True)
class AppendIndefiniteFailure:
    pass


@dataclass(frozen=True)
class ReadSuccess:
    tail: int
    #: Cumulative chain hash over every record body from the head of the
    #: stream through the tail.
    stream_hash: int


@dataclass(frozen=True)
class ReadFailure:
    pass


@dataclass(frozen=True)
class CheckTailSuccess:
    tail: int


@dataclass(frozen=True)
class CheckTailFailure:
    pass


Finish = Union[
    AppendSuccess,
    AppendDefiniteFailure,
    AppendIndefiniteFailure,
    ReadSuccess,
    ReadFailure,
    CheckTailSuccess,
    CheckTailFailure,
]

_START_TYPES = (AppendStart, ReadStart, CheckTailStart)


@dataclass(frozen=True)
class LabeledEvent:
    """One history record: a call start or finish, tagged with identity.

    ``client_id`` scopes real-time ordering (ops within a client are
    sequential); ``op_id`` pairs a start with its finish.
    """

    event: Start | Finish
    client_id: int
    op_id: int

    @property
    def is_start(self) -> bool:
        return isinstance(self.event, _START_TYPES)


# --------------------------------------------------------------------------
# Encoding
# --------------------------------------------------------------------------

_UNIT_VARIANTS: dict[type, str] = {
    ReadStart: "Read",
    CheckTailStart: "CheckTail",
    AppendDefiniteFailure: "AppendDefiniteFailure",
    AppendIndefiniteFailure: "AppendIndefiniteFailure",
    ReadFailure: "ReadFailure",
    CheckTailFailure: "CheckTailFailure",
}


def _payload_to_obj(ev: Start | Finish) -> object:
    name = _UNIT_VARIANTS.get(type(ev))
    if name is not None:
        return name
    if isinstance(ev, AppendStart):
        return {
            "Append": {
                "num_records": ev.num_records,
                "record_hashes": list(ev.record_hashes),
                "set_fencing_token": ev.set_fencing_token,
                "fencing_token": ev.fencing_token,
                "match_seq_num": ev.match_seq_num,
            }
        }
    if isinstance(ev, AppendSuccess):
        return {"AppendSuccess": {"tail": ev.tail}}
    if isinstance(ev, ReadSuccess):
        return {"ReadSuccess": {"tail": ev.tail, "stream_hash": ev.stream_hash}}
    if isinstance(ev, CheckTailSuccess):
        return {"CheckTailSuccess": {"tail": ev.tail}}
    raise TypeError(f"unknown event payload: {ev!r}")


def event_to_obj(le: LabeledEvent) -> dict:
    side = "Start" if le.is_start else "Finish"
    return {
        "event": {side: _payload_to_obj(le.event)},
        "client_id": le.client_id,
        "op_id": le.op_id,
    }


def encode_event(le: LabeledEvent) -> str:
    """One JSONL line (no trailing newline)."""
    return json.dumps(event_to_obj(le), separators=(",", ":"))


# --------------------------------------------------------------------------
# Decoding
# --------------------------------------------------------------------------


_U64_MAX = (1 << 64) - 1
#: Tails, match_seq_num and num_records are u32 in the model
#: (golang/s2-porcupine/main.go:196-225).  The Go checker decodes them as
#: ``int`` and then converts with ``uint32(...)`` (main.go:428-520), which
#: silently *wraps* out-of-range values — a wrapped tail would change a
#: verdict without any diagnostic.  A verification tool must not guess, so
#: values outside u32 are rejected at decode instead.
_U32_MAX = (1 << 32) - 1


def _require_int(
    obj: object, key: str, ctx: str, u64: bool = False, u32: bool = False
) -> int:
    if not isinstance(obj, dict):
        raise DecodeError(f"{ctx}: expected an object body, got {obj!r}")
    v = obj.get(key)
    if not isinstance(v, int) or isinstance(v, bool):
        raise DecodeError(f"{ctx}: expected integer {key!r}, got {v!r}")
    if v < 0 or (u64 and v > _U64_MAX) or (u32 and v > _U32_MAX):
        raise DecodeError(f"{ctx}: {key!r} out of range: {v}")
    return v


def _opt_int(
    obj: dict, key: str, ctx: str, u64: bool = False, u32: bool = False
) -> int | None:
    if obj.get(key) is None:
        return None
    return _require_int(obj, key, ctx, u64=u64, u32=u32)


def _opt_str(obj: dict, key: str, ctx: str) -> str | None:
    v = obj.get(key)
    if v is None or isinstance(v, str):
        return v
    raise DecodeError(f"{ctx}: expected string-or-null {key!r}, got {v!r}")


def _decode_start(data: object) -> Start:
    if isinstance(data, str):
        if data == "Read":
            return ReadStart()
        if data == "CheckTail":
            return CheckTailStart()
        raise DecodeError(f"unknown string start event: {data}")
    if isinstance(data, dict):
        if "Append" in data:
            args = data["Append"]
            if not isinstance(args, dict):
                raise DecodeError("Append args must be an object")
            hashes = args.get("record_hashes")
            if hashes is None:
                hashes = []
            if not isinstance(hashes, list) or not all(
                isinstance(h, int) and not isinstance(h, bool) and 0 <= h <= _U64_MAX
                for h in hashes
            ):
                raise DecodeError("record_hashes must be a list of u64 integers")
            num = _require_int(args, "num_records", "Append", u32=True)
            match = _opt_int(args, "match_seq_num", "Append", u32=True)
            try:
                return AppendStart(
                    num_records=num,
                    record_hashes=tuple(hashes),
                    set_fencing_token=_opt_str(args, "set_fencing_token", "Append"),
                    fencing_token=_opt_str(args, "fencing_token", "Append"),
                    match_seq_num=match,
                )
            except ValueError as e:
                raise DecodeError(str(e)) from None
    raise DecodeError("unknown start event format")


def _decode_finish(data: object) -> Finish:
    if isinstance(data, str):
        unit = {
            "AppendDefiniteFailure": AppendDefiniteFailure,
            "AppendIndefiniteFailure": AppendIndefiniteFailure,
            "ReadFailure": ReadFailure,
            "CheckTailFailure": CheckTailFailure,
        }.get(data)
        if unit is None:
            raise DecodeError(f"unknown string finish event: {data}")
        return unit()
    if isinstance(data, dict):
        if "AppendSuccess" in data:
            body = data["AppendSuccess"]
            return AppendSuccess(tail=_require_int(body, "tail", "AppendSuccess", u32=True))
        if "ReadSuccess" in data:
            body = data["ReadSuccess"]
            return ReadSuccess(
                tail=_require_int(body, "tail", "ReadSuccess", u32=True),
                stream_hash=_require_int(body, "stream_hash", "ReadSuccess", u64=True),
            )
        if "CheckTailSuccess" in data:
            body = data["CheckTailSuccess"]
            return CheckTailSuccess(
                tail=_require_int(body, "tail", "CheckTailSuccess", u32=True)
            )
    raise DecodeError("unknown finish event format")


def decode_obj(obj: object) -> LabeledEvent:
    """Decode one parsed JSON record into a :class:`LabeledEvent`."""
    if not isinstance(obj, dict):
        raise DecodeError(f"history record must be an object, got {type(obj).__name__}")
    ev = obj.get("event")
    if not isinstance(ev, dict):
        raise DecodeError("missing 'event' object")
    has_start = "Start" in ev
    has_finish = "Finish" in ev
    if has_start == has_finish:
        raise DecodeError(
            f"expected exactly one of Start/Finish, got Start={has_start} Finish={has_finish}"
        )
    payload: Start | Finish
    if has_start:
        payload = _decode_start(ev["Start"])
    else:
        payload = _decode_finish(ev["Finish"])
    return LabeledEvent(
        event=payload,
        client_id=_require_int(obj, "client_id", "record"),
        op_id=_require_int(obj, "op_id", "record"),
    )


def iter_history(stream: io.TextIOBase | str) -> Iterator[LabeledEvent]:
    """Decode a stream of concatenated JSON records (JSONL or denser).

    Mirrors Go ``json.Decoder`` semantics: values may span or share lines and
    may be arbitrarily large.  Raises :class:`DecodeError` with the character
    offset (into the decoded text) of the first malformed value.
    """
    if isinstance(stream, str):
        stream = io.StringIO(stream)
    decoder = json.JSONDecoder()
    buf = ""
    pos = 0  # cursor into buf
    consumed = 0  # chars consumed before buf[0]
    read_size = 1 << 20
    eof = False
    while True:
        while pos < len(buf) and buf[pos].isspace():
            pos += 1
        if pos < len(buf):
            try:
                obj, end = decoder.raw_decode(buf, pos)
                read_size = 1 << 20
            except json.JSONDecodeError as je:
                # A value truncated at the chunk boundary fails either inside
                # an unterminated string or within the last partial token;
                # errors anywhere else are corruption and raised immediately.
                truncated = je.pos >= len(buf) - 32 or je.msg.startswith(
                    "Unterminated string"
                )
                if truncated and not eof:
                    # Read exponentially larger chunks so re-parsing a giant
                    # value costs amortized linear time overall.
                    buf = buf[pos:]
                    consumed += pos
                    pos = 0
                    chunk = stream.read(read_size)
                    read_size = min(read_size * 2, 1 << 28)
                    if chunk:
                        buf += chunk
                    else:
                        eof = True
                    continue
                raise DecodeError(
                    f"decode record at char offset {consumed + pos}: malformed JSON "
                    f"({je.msg} at {consumed + je.pos})"
                ) from None
            try:
                yield decode_obj(obj)
            except DecodeError as e:
                raise DecodeError(
                    f"decode record at char offset {consumed + pos}: {e}"
                ) from None
            pos = end
            continue
        if eof:
            return
        buf = ""
        consumed += pos
        pos = 0
        chunk = stream.read(1 << 20)
        if not chunk:
            eof = True
        else:
            buf = chunk


def read_history(path: str) -> list[LabeledEvent]:
    with open(path, "r", encoding="utf-8") as f:
        return list(iter_history(f))


def write_history(events: list[LabeledEvent], stream: io.TextIOBase) -> None:
    for le in events:
        stream.write(encode_event(le))
        stream.write("\n")
