"""CRC-checked append-only segment log: verifyd's durable-state primitive.

Both pieces of daemon state that must survive a crash — the verdict cache
(``service/cache.py``) and the admission journal (``service/journal.py``)
— are streams of small records with identical failure semantics, so they
share one storage discipline.  A log is a directory of numbered segment
files (``seg-00000001.log`` ...); each record is

    <u32 payload length> <u32 crc32(payload)> <payload bytes>

appended and flushed immediately (a flush survives SIGKILL of the
process; ``fsync=True`` additionally survives the machine).

Recovery mirrors the definite/indefinite taxonomy the collector applies
to the network (collect-history.rs:70-94): a record either replays
**definitely intact** (length and CRC agree) or it — and everything after
it in that segment, which is unframed garbage once one header is wrong —
is dropped and *counted*:

- **torn write**: the process died mid-append, so the final segment ends
  in a partial record.  Replay keeps the valid prefix and reports the
  dropped tail bytes.
- **corrupted segment**: a CRC mismatch mid-file (bit rot, concurrent
  writer).  Replay keeps that segment's valid prefix, skips its remainder,
  and continues with the *next* segment — one bad segment never poisons
  the others.

A writer never appends to a damaged segment (appending after garbage
would be unreadable forever): it rotates to a fresh one and leaves the
damaged file for replay's prefix recovery.  One process per log
(single-writer; the daemon holds it for its lifetime).
"""

from __future__ import annotations

import contextlib
import dataclasses
import os
import re
import struct
import threading
import zlib
from typing import Iterable, Iterator

__all__ = ["Recovery", "SegmentLog"]

_HDR = struct.Struct("<II")  # payload length, crc32(payload)
_SEG_RE = re.compile(r"^seg-(\d{8})\.log$")
_MAX_RECORD = 64 << 20  # a length field past this is corruption, not data


@dataclasses.dataclass
class Recovery:
    """What the last :meth:`SegmentLog.replay` found on disk."""

    records: int = 0  #: intact records yielded
    segments: int = 0  #: segment files scanned
    torn_tail_bytes: int = 0  #: bytes dropped after the final segment's last intact record
    bad_segments: int = 0  #: segments with a mid-file CRC/header failure
    dropped_records_possible: bool = False  #: any bytes at all were skipped


def _seg_name(index: int) -> str:
    return f"seg-{index:08d}.log"


def _scan(path: str) -> tuple[list[bytes], int, int]:
    """Read one segment: (intact payloads, valid-prefix end offset, file size)."""
    payloads: list[bytes] = []
    offset = 0
    with open(path, "rb") as f:
        data = f.read()
    size = len(data)
    while offset + _HDR.size <= size:
        length, crc = _HDR.unpack_from(data, offset)
        if length > _MAX_RECORD or offset + _HDR.size + length > size:
            break  # torn header/payload (or a corrupt length field)
        payload = data[offset + _HDR.size : offset + _HDR.size + length]
        if zlib.crc32(payload) != crc:
            break  # corruption: everything after is unframed
        payloads.append(payload)
        offset += _HDR.size + length
    return payloads, offset, size


class SegmentLog:
    def __init__(
        self,
        directory: str,
        *,
        max_segment_bytes: int = 4 << 20,
        max_segments: int | None = None,
        fsync: bool = False,
    ) -> None:
        self.dir = directory
        self.max_segment_bytes = max_segment_bytes
        #: cap on retained segments (oldest dropped on rotation) — bounded
        #: disk for cache-like logs; ``None`` keeps everything (journals
        #: compact explicitly instead).
        self.max_segments = max_segments
        self.fsync = fsync
        self.recovery = Recovery()
        self._lock = threading.Lock()
        self._fh = None  # type: ignore[assignment]
        self._fh_index = 0
        self._fh_size = 0
        os.makedirs(directory, exist_ok=True)

    # -- reading ------------------------------------------------------------

    def _segment_indices(self) -> list[int]:
        out = []
        for name in os.listdir(self.dir):
            m = _SEG_RE.match(name)
            if m:
                out.append(int(m.group(1)))
        return sorted(out)

    def replay(self) -> Iterator[bytes]:
        """Yield every intact payload in write order; sets :attr:`recovery`."""
        rec = Recovery()
        indices = self._segment_indices()
        rec.segments = len(indices)
        for pos, idx in enumerate(indices):
            payloads, valid_end, size = _scan(os.path.join(self.dir, _seg_name(idx)))
            rec.records += len(payloads)
            if valid_end < size:
                rec.dropped_records_possible = True
                if pos == len(indices) - 1:
                    rec.torn_tail_bytes += size - valid_end
                else:
                    rec.bad_segments += 1
            yield from payloads
        self.recovery = rec

    def replay_all(self) -> list[bytes]:
        return list(self.replay())

    # -- writing ------------------------------------------------------------

    def _open_tail(self) -> None:
        """Position the writer: append to the last segment when it is
        intact and under the size cap, otherwise rotate to a fresh one."""
        indices = self._segment_indices()
        last = indices[-1] if indices else 0
        if last:
            path = os.path.join(self.dir, _seg_name(last))
            _, valid_end, size = _scan(path)
            if valid_end == size and size < self.max_segment_bytes:
                self._fh = open(path, "ab")
                self._fh_index, self._fh_size = last, size
                return
        self._start_segment(last + 1)

    def _start_segment(self, index: int) -> None:
        self._fh = open(os.path.join(self.dir, _seg_name(index)), "ab")
        self._fh_index, self._fh_size = index, 0
        if self.max_segments is not None:
            for idx in self._segment_indices()[: -self.max_segments]:
                with contextlib.suppress(OSError):
                    os.remove(os.path.join(self.dir, _seg_name(idx)))

    def append(self, payload: bytes) -> None:
        with self._lock:
            if self._fh is None:
                self._open_tail()
            elif self._fh_size >= self.max_segment_bytes:
                self._fh.close()
                self._start_segment(self._fh_index + 1)
            blob = _HDR.pack(len(payload), zlib.crc32(payload)) + payload
            self._fh.write(blob)
            self._fh.flush()
            if self.fsync:
                os.fsync(self._fh.fileno())
            self._fh_size += len(blob)

    def rewrite(self, payloads: Iterable[bytes]) -> None:
        """Compact: replace every segment with one fresh segment holding
        ``payloads``.  Crash-ordered — the new segment is fsynced and
        renamed into place before the old ones are removed, so an
        interrupted compaction leaves duplicates (at-least-once), never a
        hole."""
        with self._lock:
            if self._fh is not None:
                self._fh.close()
                self._fh = None
            old = self._segment_indices()
            new_index = (old[-1] if old else 0) + 1
            final = os.path.join(self.dir, _seg_name(new_index))
            tmp = f"{final}.tmp.{os.getpid()}"
            with open(tmp, "wb") as f:
                for payload in payloads:
                    f.write(_HDR.pack(len(payload), zlib.crc32(payload)) + payload)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, final)
            for idx in old:
                with contextlib.suppress(OSError):
                    os.remove(os.path.join(self.dir, _seg_name(idx)))

    def close(self) -> None:
        with self._lock:
            if self._fh is not None:
                self._fh.close()
                self._fh = None
