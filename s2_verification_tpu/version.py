"""Version of the framework.

The reference stamps its checker binary from ``golang/VERSION`` (v0.4.0) via
ldflags (Makefile:5,9); we keep the version in one importable place instead.
"""

__version__ = "0.2.0"
