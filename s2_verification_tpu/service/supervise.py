"""Supervised device escalation: bounded child + checkpoint resume.

The axon TPU worker dies (not errors) on HBM exhaustion and *hangs* (not
errors) when the tunnel drops — failure shapes that would take a resident
daemon down with the job.  A device escalation therefore runs in the
bounded-child/checkpoint-resume machinery the repo already trusts for
long measurements (``checker/resilient.drive`` + ``checker/checkpoint``):

- the search runs in a child with a hard attempt timeout (crash → nonzero
  rc, hang → process-group kill);
- each relaunch resumes from the search checkpoint, so a worker crash
  costs one segment, not the search;
- when the restart budget is exhausted (or the backend never answers its
  probe again), the caller degrades *that job* to the CPU engines — the
  daemon itself never touches the device in-process.

This module is also the child's entry point
(``python -m s2_verification_tpu.service.supervise HIST CKPT OUT``): pin
the platform through the config API (the axon sitecustomize hook
overrides the env var), check with the device portfolio, write the result
JSON atomically to OUT.
"""

from __future__ import annotations

import json
import os
import sys

from ..checker.oracle import CheckOutcome, CheckResult

__all__ = ["supervised_device_check"]


def _result_to_json(res: CheckResult) -> dict:
    out = {
        "outcome": res.outcome.value,
        "linearization": res.linearization,
        "deepest": list(res.deepest),
        "steps": res.steps,
    }
    st = getattr(res, "stats", None)
    if st is not None:
        # Search stats (incl. the per-shard summary and profile timeline of
        # a mesh run) cross the process boundary with the verdict: the
        # parent's metrics/tracer/viz must see what the child measured.
        import dataclasses

        out["stats"] = dataclasses.asdict(st)
    return out


def _result_from_json(obj: dict) -> CheckResult:
    res = CheckResult(
        CheckOutcome(obj["outcome"]),
        linearization=obj.get("linearization"),
        deepest=list(obj.get("deepest") or []),
        steps=int(obj.get("steps") or 0),
    )
    st = obj.get("stats")
    if isinstance(st, dict):
        import dataclasses

        from ..checker.frontier import FrontierStats

        known = {f.name for f in dataclasses.fields(FrontierStats)}
        res.stats = FrontierStats(  # type: ignore[attr-defined]
            **{k: v for k, v in st.items() if k in known}
        )
    # The child's own span ring (``{"trace_id", "pid", "wall_base",
    # "spans", "dropped"}``) rides the result JSON home; the scheduler
    # stitches it onto the job's trace track via clock rebasing.
    trace = obj.get("trace")
    if isinstance(trace, dict):
        res.child_trace = trace  # type: ignore[attr-defined]
    # Likewise the child's harvested JIT-compile snapshot: the scheduler
    # folds it into the daemon's introspector (verifyd_jit_* families).
    jit = obj.get("jit")
    if isinstance(jit, dict):
        res.child_jit = jit  # type: ignore[attr-defined]
    return res


def supervised_device_check(
    events: list,
    *,
    spool_dir: str,
    job_id: int,
    attempt_timeout_s: float = 900.0,
    max_restarts: int = 2,
    device_rows: int | None = None,
    devices: tuple[int, ...] | list[int] | None = None,
    profile: bool = False,
    trace_id: str = "",
    probe: bool | None = None,
    log=None,
    tracer=None,
    cancel=None,
    grace_s: float = 5.0,
    progress=None,
    prune: bool = False,
    speculate_depth: int = 0,
) -> CheckResult | None:
    """Run the device search for ``events`` under supervision.

    Returns the device verdict, or ``None`` when the device never produced
    one (restart budget exhausted, backend dead) — the caller's signal to
    degrade the job to CPU.  ``probe`` gates between-attempt backend
    probing; default: only when the environment is not pinned to CPU
    (probing a CPU "backend" is pointless and slow).  ``tracer`` (a
    :class:`~..obs.Tracer`) records the driver's attempt/probe spans on
    the job's trace track.

    ``devices`` (a :class:`~.devicepool.DevicePool` grant): offsets into
    the child's ``jax.devices()`` list; the child builds a frontier mesh
    over exactly those chips and runs the search sharded, collecting the
    per-shard stats the parent's metrics need.  Indices travel as argv —
    the supervising daemon never resolves device objects itself (a dead
    backend hangs init; ``checker/resilient.py``).  Because the child
    re-places the checkpointed frontier onto whatever mesh its argv
    names, a restart after a re-grant onto a *different* chip set resumes
    the same snapshot.  ``profile=True`` makes the child record the
    per-segment timeline (rides back in the result JSON).

    ``cancel`` (a ``() -> reason | None`` callable, the job's
    CancelToken poll) is threaded into the driver: a cancelled job
    SIGTERMs the child, waits ``grace_s`` for a clean exit, SIGKILLs it
    otherwise, and returns ``None`` with no relaunch — the lease
    releases through the scheduler's normal ``finally``.

    ``progress`` (a :class:`~..checker.progress.ProgressSink`) crosses
    the process boundary over the same spool-file seam as the history and
    result: the child overwrites ``jobN.progress.json`` atomically with
    its latest heartbeat, and the parent reads it from inside the
    driver's existing cancel poll (no extra thread) and re-offers it to
    the job's sink — so a supervised search is as watchable as an inline
    one, and the spooled file survives a SIGKILL for the flight
    recorder's post-mortem.

    ``prune``/``speculate_depth`` are the search-accelerator knobs
    (``checker/device.check_device_auto``): verdict-exact order pruning
    and the speculative multi-layer dive.  They ride to the child as
    argv extras, so a restarted attempt keeps the same configuration.
    """
    from ..checker.resilient import default_probe_cmd, drive
    from ..obs.trace import NULL_TRACER
    from ..utils import events as ev

    os.makedirs(spool_dir, exist_ok=True)
    hist_path = os.path.join(spool_dir, f"job{job_id}.jsonl")
    ckpt_path = os.path.join(spool_dir, f"job{job_id}.ckpt.npz")
    out_path = os.path.join(spool_dir, f"job{job_id}.result.json")
    progress_path = os.path.join(spool_dir, f"job{job_id}.progress.json")
    with open(hist_path, "w", encoding="utf-8") as f:
        ev.write_history(events, f)

    if probe is None:
        probe = os.environ.get("JAX_PLATFORMS", "").strip().lower() != "cpu"
    cmd = [
        sys.executable,
        "-m",
        "s2_verification_tpu.service.supervise",
        hist_path,
        ckpt_path,
        out_path,
    ]
    if device_rows is not None:
        cmd.append(str(device_rows))
    if devices is not None:
        cmd.append("devices=" + ",".join(str(int(i)) for i in devices))
    if profile:
        cmd.append("profile=1")
    if prune:
        cmd.append("prune=1")
    if speculate_depth:
        cmd.append(f"spec={int(speculate_depth)}")
    if trace_id:
        # Distributed-trace propagation: the child runs its own Tracer
        # under this id and ships its span ring back in the result JSON.
        cmd.append("trace=" + trace_id)
    if progress is not None:
        cmd.append("progress=" + progress_path)
        cancel = _progress_poll(cancel, progress, progress_path)
    try:
        outcome = drive(
            cmd,
            done=lambda: os.path.exists(out_path),
            attempt_timeout_s=attempt_timeout_s,
            max_restarts=max_restarts,
            probe_cmd=default_probe_cmd() if probe else None,
            log=log,
            tracer=tracer if tracer is not None else NULL_TRACER,
            trace_tid=job_id,
            cancel=cancel,
            grace_s=grace_s,
        )
        if not outcome.ok:
            return None
        with open(out_path, encoding="utf-8") as f:
            return _result_from_json(json.load(f))
    except (OSError, ValueError, KeyError):
        return None
    finally:
        for p in (hist_path, ckpt_path, out_path, progress_path):
            try:
                os.remove(p)
            except OSError:
                pass


def _progress_poll(cancel, sink, path, min_interval_s: float = 0.5):
    """Wrap the driver's cancel poll to also drain the child's spooled
    heartbeat.  The driver already polls cancel every ~0.25s while it
    waits on the child; reading one small JSON file at bounded cadence
    rides that loop for free (no babysitter thread)."""
    import time as _time

    state = {"next": 0.0, "stamp": None}

    def poll():
        now = _time.monotonic()
        if now >= state["next"]:
            state["next"] = now + min_interval_s
            try:
                with open(path, encoding="utf-8") as f:
                    rec = json.load(f)
                stamp = (rec.get("ops_committed"), rec.get("layer"))
                if stamp != state["stamp"]:
                    state["stamp"] = stamp
                    sink.update(
                        ops_committed=int(rec.get("ops_committed", 0)),
                        total_ops=int(rec.get("total_ops", 0)),
                        frontier_width=int(rec.get("frontier_width", 0)),
                        states_expanded=int(rec.get("states_expanded", 0)),
                        layer=rec.get("layer"),
                        engine=str(rec.get("engine", "device")),
                    )
            except (OSError, ValueError, TypeError):
                pass
        return cancel() if cancel is not None else None

    return poll


def _child_main(argv: list[str]) -> int:
    hist_path, ckpt_path, out_path = argv[:3]
    # Trailing argv: a bare int is the legacy device_rows cap; `key=value`
    # extras carry the mesh grant, the profile flag, and the trace id.
    device_rows: int | None = None
    devices: list[int] | None = None
    profile = False
    trace_id = ""
    progress_path = ""
    prune = False
    spec_depth = 0
    for extra in argv[3:]:
        if extra.startswith("devices="):
            devices = [int(s) for s in extra[len("devices=") :].split(",") if s]
        elif extra.startswith("profile="):
            profile = extra[len("profile=") :] == "1"
        elif extra.startswith("prune="):
            prune = extra[len("prune=") :] == "1"
        elif extra.startswith("spec="):
            spec_depth = int(extra[len("spec=") :])
        elif extra.startswith("trace="):
            trace_id = extra[len("trace=") :]
        elif extra.startswith("progress="):
            progress_path = extra[len("progress=") :]
        else:
            device_rows = int(extra)
    if not trace_id:
        from ..obs.context import ENV_TRACE

        trace_id = os.environ.get(ENV_TRACE, "")

    # Same pin discipline as checker/resilient._PROBE_CODE: the axon
    # sitecustomize hook overrides JAX_PLATFORMS, so re-pin via config API.
    plat = os.environ.get("JAX_PLATFORMS")
    if plat:
        import jax

        jax.config.update("jax_platforms", plat)

    from ..checker.device import check_device_auto
    from ..checker.entries import prepare
    from ..obs.introspect import INTROSPECTOR, job_context
    from ..obs.trace import Tracer
    from ..utils import events as ev
    from .scheduler import shape_key

    # The child's own span ring: a small Tracer whose wall_base rides the
    # result JSON back so the parent can rebase these spans onto its
    # timeline (the clock-offset handshake).  tid is irrelevant here —
    # the parent re-homes merged spans onto the job's track.
    tracer = Tracer(512)
    with tracer.span("child_prepare", cat="child", args={"trace_id": trace_id}):
        hist = prepare(ev.read_history(hist_path))
    kw: dict = {} if device_rows is None else {"device_rows_cap": device_rows}
    if profile:
        kw["profile"] = True
    if prune:
        kw["prune"] = True
    if spec_depth:
        kw["speculate_depth"] = spec_depth
    if progress_path:
        # The latest heartbeat overwrites the spool file atomically: the
        # parent samples it from its cancel poll, and whatever survives a
        # SIGKILL tells the post-mortem how far the search got.
        from ..checker.progress import ProgressSink

        def _spool(rec, _path=progress_path):
            tmp = f"{_path}.tmp.{os.getpid()}"
            try:
                with open(tmp, "w", encoding="utf-8") as f:
                    json.dump(rec, f)
                os.replace(tmp, _path)
            except OSError:
                pass

        kw["progress"] = ProgressSink(_spool)
    if devices is not None:
        import jax

        from ..parallel.distributed import frontier_mesh

        ds = jax.devices()
        missing = [i for i in devices if i >= len(ds)]
        if missing:
            raise SystemExit(
                f"device grant {devices} exceeds the {len(ds)} visible "
                "devices (check XLA_FLAGS / the platform pin)"
            )
        # Mesh runs always collect stats: the parent's per-shard metric
        # families are fed from the result JSON, profile or not.
        kw["mesh"] = frontier_mesh(devices=[ds[i] for i in devices])
        kw["collect_stats"] = True
    # Job context for the observed jit sites: compiles in this child are
    # attributed to the job's shape bucket, and jit.compile spans land on
    # the child tracer (merged home with everything else).
    with job_context(
        shape=shape_key(hist), trace_id=trace_id, tracer=tracer
    ), tracer.span(
        "child_search",
        cat="child",
        args={"trace_id": trace_id, "devices": devices or []},
    ):
        res = check_device_auto(hist, checkpoint_path=ckpt_path, **kw)
    out = _result_to_json(res)
    # Harvest-and-reset: a restarted attempt reports only its own
    # compiles, so the parent's fold never double-counts.
    out["jit"] = INTROSPECTOR.snapshot_and_reset()
    out["trace"] = {
        "trace_id": trace_id,
        "pid": os.getpid(),
        "wall_base": round(tracer.wall_base, 6),
        "spans": tracer.export()["traceEvents"],
        "dropped": tracer.dropped,
    }
    tmp = f"{out_path}.tmp.{os.getpid()}"
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump(out, f)
    os.replace(tmp, out_path)
    return 0


if __name__ == "__main__":
    raise SystemExit(_child_main(sys.argv[1:]))
