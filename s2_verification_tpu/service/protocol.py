"""verifyd wire protocol: newline-delimited JSON frames over a unix
socket or an authenticated TCP connection.

Same framing discipline as the collector's loopback transport
(``collector/socket_s2.py``): one JSON object per line, request → one JSON
reply, one connection per request.  ``submit`` replies are *deferred* —
the connection stays open until the verdict is ready (or the admission
queue rejects the job immediately).

Requests are ``{"op": <name>, ...}``; replies are ``{"ok": {...}}`` or
``{"err": {"class": <name>, "msg": <text>, ...}}``.

Ops:

``ping``      → ``{"ok": {"server": "verifyd", "version", "pid", "protocol"}}``
``stats``     → ``{"ok": {<counter snapshot>}}``
``submit``    → history JSONL text in ``history``; optional ``client``
                (string identity), ``priority`` (int, lower = sooner),
                ``no_viz``, ``deadline`` — remaining end-to-end budget
                in seconds; the daemon refuses a spent budget with the
                **definite** ``DeadlineExceeded`` and cooperatively
                cancels queued/running work when it expires (like
                ``trace`` below, the field is optional, ignored by old
                daemons, and HMAC-covered) — and ``trace`` — a
                distributed-trace context
                ``{"trace_id": <32 hex>, "sent_wall": <epoch s>}``
                (obs/context.py).  The field is *optional and ignored by
                old daemons* (unknown keys pass through untouched, and
                the HMAC covers whatever keys are present), so new
                clients interoperate with old daemons and vice versa; a
                daemon that understands it threads the id through every
                span and echoes it as ``trace_id`` in the reply.  Reply
                carries the ``check`` verdict (``verdict`` = the CLI
                exit code 0/1/2, ``outcome``), the HTML artifact path,
                the backend that decided, queue wait, and ``cached``
                (answered from the verdict cache).
``follow``    → one rolling window of a continuously monitored stream
                (requires the daemon's prefix store, ``serve --prefix``).
                Same history payload fields as ``submit`` (``history`` /
                ``records``, ``client``, ``priority``, ``deadline``,
                ``trace``) plus a required ``stream`` id and an optional
                ``frontier`` — the store key of the previous window's
                committed cut, echoed by the previous reply.  The reply
                is **window-scoped** (``scope="window"``): the verdict
                covers the stream-so-far given the committed prefix, not
                the window as a standalone history, so it is never
                cached.  It carries ``window`` (ordinal), ``ops`` /
                ``ops_total``, ``advanced`` (whether the frontier moved)
                and the new ``frontier`` token.  An unknown/evicted
                ``frontier`` is refused with the **definite**
                ``UnknownFrontier`` — the client resyncs by resubmitting
                the full history (or restarting the follow from scratch).
``trace``     → ``{"ok": {"traceEvents": [...], ...}}`` — the daemon's
                in-memory span ring in Chrome trace_event JSON (Object
                Format); loads directly in Perfetto / chrome://tracing.
``profiles``  → query the durable per-job profile archive (requires
                ``--state-dir``).  Optional filters: ``shape``,
                ``backend`` (prefix match), ``client``, ``verdict``
                (int), ``since`` (epoch s), ``slowest`` (N by wall
                time), ``limit`` (newest N; defaults to 100 when no
                other cut is given).  Reply:
                ``{"ok": {"records": [...], "total": <archived>}}``.
``quarantine``→ poison-job ledger ops (requires ``--state-dir``):
                ``action`` = ``list`` (every quarantined fingerprint),
                ``inspect`` (one entry + live crash count, needs
                ``fingerprint``), or ``release`` (operator override:
                un-quarantine + forgive crashes).  Submitting a
                quarantined history is answered with the **definite**
                ``Quarantined`` error before admission.
``shutdown``  → acks, then stops the daemon.  Optional ``drain``
                (bool) + ``timeout`` (seconds): stop admitting, let
                in-flight jobs finish up to the deadline, close the
                journal cleanly, then stop — the router's rolling
                restart sends this.

Distributed-search ops (coordinator → backend; ``service/distsearch.py``
orchestrates, ``service/router.py`` hosts the coordinator):

``grant``     → ownership handshake for one frontier partition:
                ``search`` (the job fingerprint), ``seg`` (segment cut
                key), ``part`` (digest range id), ``epoch`` (monotone
                fencing counter).  The backend records the grant and
                refuses any frame for the pair carrying an *older* epoch
                with the **definite** ``EpochFenced`` — the coordinator
                journals the grant before sending, so an unclean death
                leaves a re-grantable record, never a lost range.
``delta``     → run one partition of a segment: the segment history
                (``history``/``records``) plus ``carry`` — the partition's
                share of the frontier union in the prefix-carry payload
                shape (checker/prefix.py).  Deferred reply like
                ``submit``; the backend checks the grant epoch both at
                admission and again when the verdict is ready (a
                revocation landing mid-search turns the reply into
                ``EpochFenced`` instead of a zombie delta).  Reply carries
                ``verdict``/``outcome`` and, on OK, ``states`` — the
                end-of-segment union the coordinator merges.
``partition_done`` → close or revoke a grant (``reason`` = ``done`` /
                ``revoked``): the backend drops the grant entry when the
                epoch is current-or-newer and cancels any in-flight
                partition search for the pair.

Router ops (``service/router.py`` speaks this same protocol and adds):

``fleet``     → ``{"ok": {"ring": {...}, "backends": [...]}}`` —
                per-backend up/draining/breaker/in-flight state.
``drain``     → ``{"node": <name>, "timeout": <s>}``: stop routing new
                work to the node, wait for router-side in-flight, then
                send it a drain-aware ``shutdown``.
``undrain``   → put a drained node back in the routable set.

A router ``submit`` may also fail with ``NoBackend`` (transient: every
routable backend was tried and none answered — retry like
``ShuttingDown``); successes carry ``node`` (which backend answered)
and ``stolen`` when work-stealing rerouted a cold job.

Frame bounds: the daemon reads at most ``MAX_FRAME_BYTES`` per frame
(configurable) and answers an oversized frame with the **definite**
protocol error ``FrameTooLarge`` before closing the connection — a
garbled client cannot balloon daemon memory through an unbounded read.
``FrameError`` (transport-level malformation: not JSON, not an object)
is distinct from ``DecodeError`` (a well-formed frame whose *history*
does not decode): the first is retryable line noise, the second is the
client's bug.

Authentication (TCP only; the unix socket is filesystem-permissioned and
carries no auth field): every frame carries ``"auth"``, the hex
HMAC-SHA256 of the frame's canonical JSON (sorted keys, compact
separators, ``auth`` excluded) under the shared secret.  The daemon
verifies before dispatch — a wrong or missing secret is rejected with
``AuthError`` before anything touches the admission queue — and signs
its replies so the client can verify them back.

Backpressure: a full admission queue answers ``submit`` immediately with
``{"err": {"class": "QueueFull", "retry_after_s": <hint>}}`` — the
documented reject-with-retry-after reply; the daemon never buffers beyond
its configured depth.

Exit-code conventions for the ``submit`` CLI: verdicts map to the
``check`` exit codes (0 linearizable / 1 not / 2 inconclusive, 64 decode
errors); ``EXIT_BUSY`` (75, EX_TEMPFAIL) for a backpressure reject after
retries; ``EXIT_UNAVAILABLE`` (69, EX_UNAVAILABLE) when no daemon ever
answered a connect; ``EXIT_PROTOCOL`` (76, EX_PROTOCOL) when a daemon
*was* reached but refused after retries (bad secret, persistent frame
errors, connection lost mid-call).
"""

from __future__ import annotations

import hashlib
import hmac as _hmac
import json

__all__ = [
    "PROTOCOL_VERSION",
    "MAX_FRAME_BYTES",
    "ERR_QUEUE_FULL",
    "ERR_DECODE",
    "ERR_FRAME",
    "ERR_TOO_LARGE",
    "ERR_AUTH",
    "ERR_INTERNAL",
    "ERR_SHUTTING_DOWN",
    "ERR_NO_BACKEND",
    "ERR_DEADLINE",
    "ERR_EPOCH",
    "ERR_FRONTIER",
    "ERR_QUARANTINED",
    "ERR_CANCELLED",
    "ERR_UNKNOWN_JOB",
    "EXIT_BUSY",
    "EXIT_UNAVAILABLE",
    "EXIT_PROTOCOL",
    "VERDICT_EXIT",
    "encode_frame",
    "decode_frame",
    "sign_frame",
    "verify_frame",
    "parse_hostport",
    "ok",
    "err",
]

PROTOCOL_VERSION = 1

#: Default per-frame read bound.  A submitted history rides inside one
#: frame, so this also caps history size (~8 MiB JSONL ≈ 10^5 events —
#: far past what any engine decides); the old implicit bound was
#: asyncio's 64 KiB stream limit, which *rejected* legal large histories.
MAX_FRAME_BYTES = 8 << 20

ERR_QUEUE_FULL = "QueueFull"
ERR_DECODE = "DecodeError"
ERR_FRAME = "FrameError"
ERR_TOO_LARGE = "FrameTooLarge"
ERR_AUTH = "AuthError"
ERR_INTERNAL = "InternalError"
ERR_SHUTTING_DOWN = "ShuttingDown"
#: Definite: the job's end-to-end deadline passed (at admission, in the
#: queue, or mid-search).  Retrying without a larger deadline is
#: pointless, so clients treat it like a semantic refusal, and the
#: router forwards it instead of failing over.
ERR_DEADLINE = "DeadlineExceeded"
#: Definite: the history's fingerprint is quarantined after repeated
#: process/child deaths.  Answered before admission; an operator
#: releases it with the ``quarantine`` op.
ERR_QUARANTINED = "Quarantined"
#: Definite: the job was cancelled for a non-deadline reason
#: (``client_gone``, ``shutdown``) after admission.
ERR_CANCELLED = "Cancelled"
#: Definite: a ``follow`` frame named a frontier token the prefix store
#: does not hold (evicted, never durable, or minted by another node).
#: Retrying the same token is pointless — the client resyncs with a full
#: ``submit`` and starts a fresh follow lineage.
ERR_FRONTIER = "UnknownFrontier"
#: Router-only: every routable backend was tried (or none existed) and
#: the submit could not be placed.  Transient — clients retry like
#: :data:`ERR_SHUTTING_DOWN`.
ERR_NO_BACKEND = "NoBackend"
#: Definite: a distributed-search frame (``grant``/``delta``) carried an
#: epoch older than the one this node holds for the partition, or named a
#: grant that was revoked underneath the sender.  The fencing answer of
#: the distsearch protocol: a zombie owner that missed its own revocation
#: gets this instead of an accepted delta, and the coordinator applies
#: the same check once more at merge time — retrying the stale epoch is
#: pointless, the partition already belongs to a newer grant.
ERR_EPOCH = "EpochFenced"
#: Definite: a ``watch`` frame named a job (or fingerprint / search) this
#: node is not running and does not remember finishing.  Retrying the
#: same selector on the same node is pointless; the router treats it as a
#: semantic answer, not a reason to fail over.
ERR_UNKNOWN_JOB = "UnknownJob"

#: check-CLI exit code per outcome value (cli.py docstring contract).
VERDICT_EXIT = {"ok": 0, "illegal": 1, "unknown": 2}

EXIT_BUSY = 75  # EX_TEMPFAIL: queue full, retry after the hint
EXIT_UNAVAILABLE = 69  # EX_UNAVAILABLE: no daemon ever answered a connect
EXIT_PROTOCOL = 76  # EX_PROTOCOL: daemon reached but refused after retries


#: Request-frame field table: op -> {field: "required" | "optional"}.
#: ``op`` itself and the fields in :data:`UNSIGNED_FIELDS` ride on every
#: frame implicitly.  This is the wire contract the static protocol-compat
#: lint pass checks construction sites (client.py) and parse sites
#: (daemon.py/router.py) against: a field added here must be optional (old
#: peers must keep interoperating — senders may omit it, parsers must
#: ``.get`` it with a default), and because only :data:`UNSIGNED_FIELDS`
#: escape the MAC, every new field is HMAC-covered by construction.
FRAME_FIELDS = {
    "ping": {},
    "stats": {},
    "trace": {},
    "fleet": {},
    "submit": {
        # Exactly one of history (JSONL string) / records (JSON array of
        # event objects) — the daemon enforces the one-of; both are
        # optional at the frame layer so either wire form interoperates.
        "history": "optional",
        "records": "optional",
        "client": "optional",
        "priority": "optional",
        "no_viz": "optional",
        "deadline": "optional",
        "trace": "optional",
        # Route the submit through the fleet-distributed frontier search
        # (router only; service/distsearch.py).  Optional and ignored by
        # plain daemons, so old peers keep interoperating.
        "distributed": "optional",
    },
    "follow": {
        # Same one-of history/records contract as submit, plus the
        # stream identity and the carried frontier token.
        "history": "optional",
        "records": "optional",
        "client": "optional",
        "priority": "optional",
        "deadline": "optional",
        "trace": "optional",
        "stream": "optional",
        "frontier": "optional",
    },
    "profiles": {
        "shape": "optional",
        "backend": "optional",
        "client": "optional",
        "verdict": "optional",
        "since": "optional",
        "slowest": "optional",
        "limit": "optional",
    },
    "shutdown": {"drain": "optional", "timeout": "optional"},
    "quarantine": {"action": "optional", "fingerprint": "optional"},
    # Live progress snapshot of running searches.  All selectors optional
    # (old-peer interop): no selector = every active job on the node;
    # ``job`` = one job id; ``fingerprint`` = jobs keyed by verdict-cache
    # fingerprint (how a coordinator polls its ``ppart:`` partition jobs);
    # ``search``(+``part``) = a distributed search's partitions, resolved
    # by the router against its live coordinator or fanned out.
    "watch": {
        "job": "optional",
        "fingerprint": "optional",
        "search": "optional",
        "part": "optional",
    },
    # Durable-telemetry history query (obs/tsdb.py).  All selectors
    # optional (old-peer interop): no selector = the raw ring's recent
    # tail; ``info`` = ring inventory instead of points.
    "tsq": {
        "res": "optional",
        "metric": "optional",
        "labels": "optional",
        "since": "optional",
        "until": "optional",
        "limit": "optional",
        "info": "optional",
    },
    "drain": {"node": "required", "timeout": "optional"},
    "undrain": {"node": "required"},
    # Distributed-search ops (coordinator → backend; service/distsearch.py).
    # All fields optional at the frame layer for old-peer interop; the
    # daemon enforces the semantic requirements (search/part/epoch) itself.
    "grant": {
        "search": "optional",
        "seg": "optional",
        "part": "optional",
        "epoch": "optional",
        "trace": "optional",
    },
    "delta": {
        # Same one-of history/records payload contract as submit, plus the
        # partition identity and the carried frontier union.
        "history": "optional",
        "records": "optional",
        "client": "optional",
        "deadline": "optional",
        "trace": "optional",
        "search": "optional",
        "seg": "optional",
        "part": "optional",
        "epoch": "optional",
        "carry": "optional",
        "union": "optional",
    },
    "partition_done": {
        "search": "optional",
        "part": "optional",
        "epoch": "optional",
        "reason": "optional",
        "trace": "optional",
    },
}

#: The only fields excluded from the HMAC canonicalization — the MAC
#: itself.  Everything else in a frame is authenticated; extending this
#: tuple widens the unauthenticated surface and fails the protocol-compat
#: lint unless :func:`_frame_mac` agrees.
UNSIGNED_FIELDS = ("auth",)


def encode_frame(obj: dict) -> bytes:
    """One wire frame: compact JSON + newline (history text rides inside a
    JSON string, so embedded newlines are escaped and framing holds)."""
    return json.dumps(obj, separators=(",", ":")).encode("utf-8") + b"\n"


def decode_frame(line: bytes) -> dict:
    obj = json.loads(line)
    if not isinstance(obj, dict):
        raise ValueError(f"frame must be a JSON object, got {type(obj).__name__}")
    return obj


def _frame_mac(obj: dict, secret: bytes) -> str:
    """HMAC-SHA256 over the canonical serialization of ``obj`` minus
    :data:`UNSIGNED_FIELDS`.  Canonical = sorted keys + compact separators,
    so both ends derive identical bytes regardless of insertion order."""
    body = {k: v for k, v in obj.items() if k not in UNSIGNED_FIELDS}
    canon = json.dumps(body, sort_keys=True, separators=(",", ":")).encode("utf-8")
    return _hmac.new(secret, canon, hashlib.sha256).hexdigest()


def sign_frame(obj: dict, secret: bytes) -> dict:
    return {**obj, "auth": _frame_mac(obj, secret)}


def verify_frame(obj: dict, secret: bytes) -> bool:
    mac = obj.get("auth")
    return isinstance(mac, str) and _hmac.compare_digest(
        mac, _frame_mac(obj, secret)
    )


def parse_hostport(addr: str) -> tuple[str, int]:
    """``host:port`` → (host, port); bare ``:port`` binds all interfaces."""
    host, sep, port = addr.rpartition(":")
    if not sep or not port.isdigit():
        raise ValueError(f"expected HOST:PORT, got {addr!r}")
    return host or "0.0.0.0", int(port)


def ok(payload: dict) -> dict:
    return {"ok": payload}


def err(cls: str, msg: str, **extra) -> dict:
    e = {"class": cls, "msg": msg}
    e.update(extra)
    return {"err": e}
