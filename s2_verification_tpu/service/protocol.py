"""verifyd wire protocol: newline-delimited JSON frames over a unix socket.

Same framing discipline as the collector's loopback transport
(``collector/socket_s2.py``): one JSON object per line, request → one JSON
reply, one connection per request.  ``submit`` replies are *deferred* —
the connection stays open until the verdict is ready (or the admission
queue rejects the job immediately).

Requests are ``{"op": <name>, ...}``; replies are ``{"ok": {...}}`` or
``{"err": {"class": <name>, "msg": <text>, ...}}``.

Ops:

``ping``      → ``{"ok": {"server": "verifyd", "version", "pid", "protocol"}}``
``stats``     → ``{"ok": {<counter snapshot>}}``
``submit``    → history JSONL text in ``history``; optional ``client``
                (string identity), ``priority`` (int, lower = sooner),
                ``no_viz``.  Reply carries the ``check`` verdict
                (``verdict`` = the CLI exit code 0/1/2, ``outcome``), the
                HTML artifact path, the backend that decided, queue wait,
                and ``cached`` (answered from the verdict cache).
``shutdown``  → acks, then stops the daemon.

Backpressure: a full admission queue answers ``submit`` immediately with
``{"err": {"class": "QueueFull", "retry_after_s": <hint>}}`` — the
documented reject-with-retry-after reply; the daemon never buffers beyond
its configured depth.

Exit-code conventions for the ``submit`` CLI: verdicts map to the
``check`` exit codes (0 linearizable / 1 not / 2 inconclusive, 64 decode
errors); ``EXIT_BUSY`` (75, EX_TEMPFAIL) for a backpressure reject and
``EXIT_UNAVAILABLE`` (69, EX_UNAVAILABLE) when no daemon answers on the
socket.
"""

from __future__ import annotations

import json

__all__ = [
    "PROTOCOL_VERSION",
    "ERR_QUEUE_FULL",
    "ERR_DECODE",
    "ERR_INTERNAL",
    "ERR_SHUTTING_DOWN",
    "EXIT_BUSY",
    "EXIT_UNAVAILABLE",
    "VERDICT_EXIT",
    "encode_frame",
    "decode_frame",
    "ok",
    "err",
]

PROTOCOL_VERSION = 1

ERR_QUEUE_FULL = "QueueFull"
ERR_DECODE = "DecodeError"
ERR_INTERNAL = "InternalError"
ERR_SHUTTING_DOWN = "ShuttingDown"

#: check-CLI exit code per outcome value (cli.py docstring contract).
VERDICT_EXIT = {"ok": 0, "illegal": 1, "unknown": 2}

EXIT_BUSY = 75  # EX_TEMPFAIL: queue full, retry after the hint
EXIT_UNAVAILABLE = 69  # EX_UNAVAILABLE: no daemon on the socket


def encode_frame(obj: dict) -> bytes:
    """One wire frame: compact JSON + newline (history text rides inside a
    JSON string, so embedded newlines are escaped and framing holds)."""
    return json.dumps(obj, separators=(",", ":")).encode("utf-8") + b"\n"


def decode_frame(line: bytes) -> dict:
    obj = json.loads(line)
    if not isinstance(obj, dict):
        raise ValueError(f"frame must be a JSON object, got {type(obj).__name__}")
    return obj


def ok(payload: dict) -> dict:
    return {"ok": payload}


def err(cls: str, msg: str, **extra) -> dict:
    e = {"class": cls, "msg": msg}
    e.update(extra)
    return {"err": e}
