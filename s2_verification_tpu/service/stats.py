"""Structured per-job service events + aggregate counters.

Every notable daemon event becomes one JSON line on the configured sink
(a file-like object; ``None`` silences the stream but keeps counters):

    {"ev": "done", "t": <epoch>, "job": 3, "client": "loadgen",
     "backend": "native", "wall_s": 0.012, "queue_wait_s": 0.003,
     "verdict": 0, "shape": "64x5x8", "shape_warm": true}

Event names: ``serve_start``, ``admit``, ``reject``, ``cache_hit``,
``start``, ``done``, ``decode_error``, ``degrade`` (supervised device job
fell back to CPU), ``serve_stop``; durability and remote-transport
events: ``cache_loaded`` (persistent verdict segments replayed at boot),
``orphan`` (journal replay re-admitted an accepted-but-unanswered job),
``orphan_dropped`` / ``orphan_invalid`` (reported, not silently lost),
``auth_reject`` (TCP frame failed HMAC verification — rejected before
admission), ``frame_error`` (oversized or malformed frame).
``shape_warm`` marks a job whose
padded search shape was already run by this daemon — the observable for
"jitted executables reused instead of recompiled".

Counters aggregate the same stream for the ``stats`` protocol op and for
the backpressure retry-after hint (average decided-job wall time).
"""

from __future__ import annotations

import json
import threading
import time
from typing import IO

__all__ = ["ServiceStats"]


class ServiceStats:
    def __init__(self, sink: IO[str] | None = None) -> None:
        self._sink = sink
        self._lock = threading.Lock()
        self._t0 = time.time()
        self._counters: dict[str, int] = {
            "submitted": 0,
            "admitted": 0,
            "rejected": 0,
            "completed": 0,
            "cache_hits": 0,
            "decode_errors": 0,
            "degraded": 0,
            "verdict_ok": 0,
            "verdict_illegal": 0,
            "verdict_unknown": 0,
            "auth_rejects": 0,
            "frame_errors": 0,
            "orphans_recovered": 0,
            "cache_loaded": 0,
        }
        self._wall_total_s = 0.0
        self._shapes_seen: set[str] = set()

    # -- event stream -------------------------------------------------------

    def emit(self, event: str, **fields) -> None:
        with self._lock:
            self._count(event, fields)
            if self._sink is not None:
                line = {"ev": event, "t": round(time.time(), 3)}
                line.update(fields)
                try:
                    self._sink.write(json.dumps(line, separators=(",", ":")) + "\n")
                    self._sink.flush()
                except (OSError, ValueError):
                    # A closed/broken stats sink must never take a job down.
                    self._sink = None

    def _count(self, event: str, fields: dict) -> None:
        if event == "admit":
            self._counters["submitted"] += 1
            self._counters["admitted"] += 1
        elif event == "reject":
            self._counters["submitted"] += 1
            self._counters["rejected"] += 1
        elif event == "cache_hit":
            self._counters["submitted"] += 1
            self._counters["cache_hits"] += 1
        elif event == "decode_error":
            self._counters["submitted"] += 1
            self._counters["decode_errors"] += 1
        elif event == "degrade":
            self._counters["degraded"] += 1
        elif event == "auth_reject":
            self._counters["auth_rejects"] += 1
        elif event == "frame_error":
            self._counters["frame_errors"] += 1
        elif event == "orphan":
            self._counters["orphans_recovered"] += 1
        elif event == "cache_loaded":
            self._counters["cache_loaded"] = int(fields.get("entries", 0))
        elif event == "done":
            self._counters["completed"] += 1
            self._wall_total_s += float(fields.get("wall_s", 0.0))
            v = {0: "verdict_ok", 1: "verdict_illegal", 2: "verdict_unknown"}.get(
                fields.get("verdict")
            )
            if v is not None:
                self._counters[v] += 1

    # -- shape warmth -------------------------------------------------------

    def note_shape(self, shape: str) -> bool:
        """Record a shape about to run; returns True when this daemon has
        already run it (compiled executables are warm)."""
        with self._lock:
            warm = shape in self._shapes_seen
            self._shapes_seen.add(shape)
            return warm

    # -- aggregates ---------------------------------------------------------

    def snapshot(self) -> dict:
        with self._lock:
            snap = dict(self._counters)
            snap["uptime_s"] = round(time.time() - self._t0, 3)
            snap["shapes_run"] = len(self._shapes_seen)
            done = self._counters["completed"]
            snap["avg_wall_s"] = round(self._wall_total_s / done, 4) if done else 0.0
            return snap

    def retry_after_hint(self, queue_depth: int) -> float:
        """Backpressure hint: roughly how long until the queue has room —
        depth × average decided-job wall time, clamped to [0.5, 30] s (a
        cold daemon has no average yet; never tell a client "0")."""
        with self._lock:
            done = self._counters["completed"]
            avg = (self._wall_total_s / done) if done else 1.0
        return round(min(30.0, max(0.5, queue_depth * avg)), 2)
