"""Structured per-job service events + aggregate counters + metrics.

Every notable daemon event becomes one JSON line on the configured sink
(a file-like object; ``None`` silences the stream but keeps counters):

    {"ev": "done", "t": <epoch>, "job": 3, "client": "loadgen",
     "backend": "native", "wall_s": 0.012, "queue_wait_s": 0.003,
     "verdict": 0, "shape": "64x5x8", "shape_warm": true}

Event names: ``serve_start``, ``admit``, ``reject``, ``cache_hit``,
``start``, ``done``, ``job_error`` (worker raised; job answered with an
internal error), ``decode_error``, ``degrade`` (supervised device job
fell back to CPU), ``serve_stop``; durability and remote-transport
events: ``cache_loaded`` (persistent verdict segments replayed at boot),
``orphan`` (journal replay re-admitted an accepted-but-unanswered job),
``orphan_dropped`` / ``orphan_invalid`` (reported, not silently lost),
``auth_reject`` (TCP frame failed HMAC verification — rejected before
admission), ``frame_error`` (oversized or malformed frame),
``stats_sink_lost`` (the event sink broke twice; counters survive);
``slo_breach`` (the SLO engine's edge-triggered burn-rate trip — emitted
back onto this same stream so sinks, the flight recorder, and counters
all see it); ``perf_regression`` (the sentinel's per-shape EWMA
wall-time drift trip — also re-emitted onto the stream, where the alert
engine routes it); ``retrace_storm`` (the JIT introspector's latched
per-(site, shape) recompile trip — emitted by
:data:`~..obs.introspect.INTROSPECTOR` onto this stream, where the
alert engine routes it like any other signal).  Overload-protection
events (ISSUE 10): ``job_cancelled`` (cooperative cancellation fired:
``reason`` = deadline / client_gone / shutdown), ``admission_shed``
(the AdmissionController refused before queueing: ``reason`` = rss /
fds / deadline), ``client_gone`` (a submit's TCP peer vanished
mid-wait), ``job_quarantined`` / ``quarantine_release`` /
``quarantine_reject`` (poison-job ledger transitions), and
``writer_degraded`` / ``writer_recovered`` (a durable writer hit
ENOSPC/OSError and dropped to memory-only / re-armed).  Continuous
batching (ISSUE 15): ``batch_launch`` — one mega-launch of a shape group
(``engine`` = batch-native / batch-vmap, ``lanes``, ``decided``,
``early_exits``, ``occupancy`` = lanes over ``batch_max``, ``late_join``,
``wall_s`` = the launch wall; per-job attribution stays on each lane's
own ``done`` event).
Incremental prefix verification (ISSUE 16): ``prefix_loaded``
(persisted frontier snapshots replayed at boot), ``prefix_hit`` /
``prefix_miss`` (an admission probe found / missed a cached prefix;
hits carry ``resume_ops`` and ``depth_frac`` = resumed fraction of the
history), ``prefix_snapshot`` (a worker persisted one cut's carried
frontier; carries the store's ``entries``/``bytes`` after the put),
``prefix_refused`` (a snapshot or frontier advance was refused:
``reason`` = open_ops / unknown_frontier), and ``window_done`` (one
``follow`` window answered: ``stream``, ``window`` ordinal,
``verdict``, ``advanced``, cumulative ``ops_total``).
Search acceleration (ISSUE 19): ``prune_applied`` — verdict-exact
order pruning contributed to a decided job (``commits`` eager-closed
ops, ``dead`` tail-pinned configurations, ``ranked`` rank-gated
candidates), and ``speculation_rollback`` — one or more speculative
multi-layer dives were discarded on misprediction (``rollbacks``,
cumulative speculated ``layers``, ``launches``, ``accepts``); both ride
the verdict-exact guarantee, so they are rate signals, never
correctness ones.
``shape_warm`` marks a job whose
padded search shape was already run by this daemon — the observable for
"jitted executables reused instead of recompiled".

Counters aggregate the same stream for the ``stats`` protocol op and for
the backpressure retry-after hint (average decided-job wall time).  The
same hooks also drive a Prometheus :class:`~..obs.MetricsRegistry`
(scraped via ``serve --metrics-port``), so the JSONL stream, the ``stats``
op, and /metrics can never disagree about what happened.
"""

from __future__ import annotations

import json
import threading
import time
from typing import IO, TYPE_CHECKING, Optional

from ..obs.metrics import LATENCY_BUCKETS, LAYER_BUCKETS, MetricsRegistry

if TYPE_CHECKING:  # pragma: no cover
    from ..obs.alerts import AlertEngine
    from ..obs.archive import ProfileArchive
    from ..obs.flight import FlightRecorder
    from ..obs.health import SLOHealth
    from ..obs.log import StructuredLogger
    from ..obs.sentinel import PerfSentinel

__all__ = ["ServiceStats"]

_VERDICT_LABEL = {0: "ok", 1: "illegal", 2: "unknown"}

#: Lanes-per-launch histogram buckets: powers of two up to the largest
#: supported ``batch_max`` — launch sizes are pow2-bucketed anyway.
_LANE_BUCKETS = (1.0, 2.0, 4.0, 8.0, 16.0, 32.0, 64.0)


class ServiceStats:
    def __init__(
        self,
        sink: IO[str] | None = None,
        registry: Optional[MetricsRegistry] = None,
        *,
        health: "Optional[SLOHealth]" = None,
        recorder: "Optional[FlightRecorder]" = None,
        logger: "Optional[StructuredLogger]" = None,
        alerts: "Optional[AlertEngine]" = None,
        archive: "Optional[ProfileArchive]" = None,
        sentinel: "Optional[PerfSentinel]" = None,
    ) -> None:
        self._sink = sink
        #: SLO engine fed every event (outside the sink lock); its breach
        #: edge re-enters emit() as an ``slo_breach`` event.
        self.health = health
        #: flight recorder absorbing every event line for post-mortems
        self.recorder = recorder
        #: structured logger; when set and no sink is configured, events
        #: flow through it instead of a raw stderr stream
        self.logger = logger
        #: alert engine matching every event line against delivery rules
        self.alerts = alerts
        #: durable profile archive absorbing done events (+ lease waits)
        self.archive = archive
        #: perf sentinel; its drift trip re-enters emit() as
        #: ``perf_regression``
        self.sentinel = sentinel
        self._in_breach_emit = False
        self._in_regression_emit = False
        self._lock = threading.Lock()
        self._t0 = time.time()
        self._counters: dict[str, int] = {
            "submitted": 0,
            "admitted": 0,
            "rejected": 0,
            "completed": 0,
            "cache_hits": 0,
            "decode_errors": 0,
            "degraded": 0,
            "verdict_ok": 0,
            "verdict_illegal": 0,
            "verdict_unknown": 0,
            "auth_rejects": 0,
            "frame_errors": 0,
            "orphans_recovered": 0,
            "cache_loaded": 0,
            "job_errors": 0,
            "stats_sink_lost": 0,
            "leases_granted": 0,
            "lease_timeouts": 0,
            "slo_breaches": 0,
            "perf_regressions": 0,
            "retrace_storms": 0,
            "cancelled": 0,
            "admission_shed": 0,
            "quarantined": 0,
            "quarantine_rejects": 0,
            "writer_degraded_events": 0,
            "client_gone": 0,
            "batch_launches": 0,
            "batch_lanes": 0,
            "batch_early_exits": 0,
            "prefix_hits": 0,
            "prefix_misses": 0,
            "prefix_snapshots": 0,
            "prefix_refused": 0,
            "prefix_loaded": 0,
            "windows_done": 0,
            "partitions_granted": 0,
            "partition_deltas": 0,
            "partitions_done": 0,
            "epoch_fences": 0,
            "search_progress": 0,
            "prune_applied": 0,
            "speculation_rollbacks": 0,
        }
        self._wall_total_s = 0.0
        self._active = 0  # jobs handed to a worker, not yet answered
        self._shapes_seen: set[str] = set()
        #: per-shape EWMA of decided wall time (AdmissionController's
        #: deadline-feasibility input)
        self._shape_wall: dict[str, float] = {}
        #: EWMA of device-lease hold time (retry-after's lease-wait term)
        self._lease_hold_avg = 0.0
        #: DevicePool the daemon arms so retry_after_hint can fold
        #: lease-wait estimates in (None when escalation is off)
        self.device_pool = None

        self.registry = registry if registry is not None else MetricsRegistry()
        r = self.registry
        self._m_submitted = r.counter(
            "verifyd_jobs_submitted_total", "Submit requests received (any outcome)"
        )
        self._m_rejected = r.counter(
            "verifyd_jobs_rejected_total", "Submits rejected by admission control"
        )
        self._m_completed = r.counter(
            "verifyd_jobs_completed_total",
            "Jobs answered with a verdict",
            labelnames=("verdict",),
        )
        self._m_cache_hits = r.counter(
            "verifyd_cache_hits_total", "Verdicts answered from the cache"
        )
        self._m_decode_errors = r.counter(
            "verifyd_decode_errors_total", "Submits with undecodable histories"
        )
        self._m_degraded = r.counter(
            "verifyd_degraded_total", "Device escalations that fell back to CPU"
        )
        self._m_job_errors = r.counter(
            "verifyd_job_errors_total", "Jobs answered with an internal error"
        )
        self._m_auth_rejects = r.counter(
            "verifyd_auth_rejects_total", "TCP frames failing HMAC verification"
        )
        self._m_frame_errors = r.counter(
            "verifyd_frame_errors_total", "Oversized or malformed frames"
        )
        self._m_orphans = r.counter(
            "verifyd_orphans_recovered_total", "Journal orphans re-admitted at boot"
        )
        self._m_cache_loaded = r.counter(
            "verifyd_cache_loaded_total", "Persisted verdicts replayed at boot"
        )
        self._m_sink_lost = r.counter(
            "verifyd_stats_sink_lost_total", "Stats sinks dropped after a retry"
        )
        self._m_active = r.gauge(
            "verifyd_active_jobs", "Jobs currently executing on a worker"
        )
        self._m_queue_depth = r.gauge(
            "verifyd_queue_depth", "Jobs waiting in the admission queue"
        )
        self._m_queue_wait = r.histogram(
            "verifyd_queue_wait_seconds",
            "Admission-to-worker-pickup latency",
            buckets=LATENCY_BUCKETS,
        )
        self._m_wall = r.histogram(
            "verifyd_wall_seconds",
            "Verification wall time by deciding backend",
            buckets=LATENCY_BUCKETS,
            labelnames=("backend",),
        )
        self._m_layers = r.histogram(
            "verifyd_frontier_layers",
            "BFS layers searched per profiled job",
            buckets=LAYER_BUCKETS,
        )
        # Device-pool lease accounting (service/devicepool.py events).
        self._m_leases_granted = r.counter(
            "verifyd_leases_granted_total",
            "Device leases granted to escalating jobs",
        )
        self._m_lease_timeouts = r.counter(
            "verifyd_lease_timeouts_total",
            "Lease requests that timed out under contention",
        )
        self._m_devices_leased = r.gauge(
            "verifyd_devices_leased", "Devices currently under lease"
        )
        self._m_lease_wait = r.histogram(
            "verifyd_lease_wait_seconds",
            "Time escalating jobs waited for a device lease",
            buckets=LATENCY_BUCKETS,
        )
        # Per-shard mesh search metrics, labeled by shard index; label
        # cardinality is bounded by the pool size (≤ device count).
        self._m_shard_occ = r.gauge(
            "verifyd_shard_frontier_occupancy",
            "Peak live frontier rows on each mesh shard (last sharded job)",
            labelnames=("shard",),
        )
        self._m_shard_collective = r.histogram(
            "verifyd_shard_collective_seconds",
            "Cross-shard sync wall per sharded job, by shard",
            buckets=LATENCY_BUCKETS,
            labelnames=("shard",),
        )
        self._m_shard_skew = r.gauge(
            "verifyd_shard_skew",
            "Shard peak occupancy over mesh mean (1.0 = balanced)",
            labelnames=("shard",),
        )
        # JIT-compile observability (obs/introspect.py increments these
        # through the same registry — registering them here, with HELP
        # text, makes the family headers render from the first scrape).
        self._m_jit_compiles = r.counter(
            "verifyd_jit_compiles_total",
            "XLA compiles at an observed jit site, by site and job shape",
            labelnames=("site", "shape"),
        )
        self._m_jit_retraces = r.counter(
            "verifyd_jit_retraces_total",
            "Recompiles at a site that already held an executable "
            "(fresh abstract shape signature)",
            labelnames=("site", "shape"),
        )
        self._m_jit_cache_hits = r.counter(
            "verifyd_jit_cache_hits_total",
            "Observed-jit calls answered by an already-compiled executable",
            labelnames=("shape",),
        )
        self._m_jit_cache_misses = r.counter(
            "verifyd_jit_cache_misses_total",
            "Observed-jit calls that had to trace and compile",
            labelnames=("shape",),
        )
        self._m_jit_compile_wall = r.histogram(
            "verifyd_jit_compile_seconds",
            "First-call wall time per fresh signature (compile + first "
            "dispatch), by site",
            buckets=LATENCY_BUCKETS,
            labelnames=("site",),
        )
        self._m_retrace_storms = r.counter(
            "verifyd_retrace_storms_total",
            "Latched retrace-storm trips (a shape recompiling one site "
            "past the threshold)",
        )
        # Overload protection (ISSUE 10).  Label sets are bounded by
        # construction: reasons come from fixed vocabularies, writer
        # names from the five durable writers.
        self._m_cancelled = r.counter(
            "verifyd_jobs_cancelled_total",
            "Jobs cooperatively cancelled after admission, by reason",
            labelnames=("reason",),
        )
        self._m_shed = r.counter(
            "verifyd_admission_shed_total",
            "Submits shed before queueing by the admission controller",
            labelnames=("reason",),
        )
        self._m_quarantine_size = r.gauge(
            "verifyd_quarantine_size",
            "Fingerprints currently quarantined as poison jobs",
        )
        self._m_quarantine_size.set(0)
        self._m_writer_degraded = r.gauge(
            "verifyd_writer_degraded",
            "1 while the named durable writer is degraded to memory-only",
            labelnames=("writer",),
        )
        # Continuous cross-job batching (ISSUE 15).  Engine label is the
        # closed {batch-native, batch-vmap} set (anything else folds to
        # "other"), so cardinality is bounded by construction.
        self._m_batch_lanes = r.histogram(
            "verifyd_batch_launch_lanes",
            "Live lanes per mega-launch, by batch engine",
            buckets=_LANE_BUCKETS,
            labelnames=("engine",),
        )
        self._m_batch_early = r.counter(
            "verifyd_batch_early_exits_total",
            "Lanes whose verdict latched while other lanes kept searching",
        )
        self._m_batch_occupancy = r.gauge(
            "verifyd_batch_launch_occupancy_ratio",
            "Lanes over batch_max for the most recent mega-launch",
        )
        # Incremental prefix verification (ISSUE 16).  The refused-reason
        # label is the closed {open_ops, unknown_frontier} vocabulary.
        self._m_prefix_hits = r.counter(
            "verifyd_prefix_hits_total",
            "Admission probes that found a cached prefix to resume from",
        )
        self._m_prefix_misses = r.counter(
            "verifyd_prefix_misses_total",
            "Admission probes that found no cached prefix (cold search)",
        )
        self._m_prefix_snapshots = r.counter(
            "verifyd_prefix_snapshots_total",
            "Frontier snapshots persisted at prefix-closed cuts",
        )
        self._m_prefix_refused = r.counter(
            "verifyd_prefix_refused_total",
            "Snapshots or frontier advances refused for soundness",
            labelnames=("reason",),
        )
        self._m_prefix_entries = r.gauge(
            "verifyd_prefix_store_entries", "Frontier snapshots held in the store"
        )
        self._m_prefix_bytes = r.gauge(
            "verifyd_prefix_store_bytes",
            "Serialized bytes of the in-memory prefix store",
        )
        self._m_prefix_depth = r.histogram(
            "verifyd_prefix_resume_depth_ratio",
            "Resumed fraction of the history on a prefix hit (1.0 = the "
            "whole committed prefix was cached)",
            buckets=(0.1, 0.25, 0.5, 0.75, 0.9, 0.95, 1.0),
        )
        self._m_windows = r.counter(
            "verifyd_follow_windows_total",
            "Follow windows answered with a window-scoped verdict",
        )
        # Distributed search, backend side (service/daemon.py _ds_* ops;
        # the coordinator's own families live on the router registry).
        self._m_ds_granted = r.counter(
            "verifyd_distsearch_partitions_granted_total",
            "Partition ownership grants accepted by this backend",
        )
        self._m_ds_deltas = r.counter(
            "verifyd_distsearch_deltas_total",
            "Frontier deltas answered, by partition verdict",
            labelnames=("verdict",),
        )
        self._m_ds_delta_bytes = r.counter(
            "verifyd_distsearch_delta_bytes_total",
            "Serialized end-of-segment state-union bytes shipped back",
        )
        self._m_ds_done = r.counter(
            "verifyd_distsearch_partitions_done_total",
            "Partition grants closed (done, revoked or failed)",
        )
        self._m_ds_fences = r.counter(
            "verifyd_distsearch_epoch_fences_total",
            "Stale-epoch frames refused, by op",
            labelnames=("op",),
        )
        # Resource telemetry (obs/introspect.ResourceSampler sets these).
        self._m_res_rss = r.gauge(
            "verifyd_resource_rss_bytes", "Daemon resident set size"
        )
        self._m_res_cpu = r.gauge(
            "verifyd_resource_cpu_seconds",
            "Cumulative process CPU time (user+system)",
        )
        self._m_res_fds = r.gauge(
            "verifyd_resource_open_fds", "Open file descriptors"
        )
        self._m_res_threads = r.gauge(
            "verifyd_resource_threads", "Live Python threads"
        )
        self._m_res_gc = r.gauge(
            "verifyd_resource_gc_pause_seconds",
            "Cumulative GC pause time observed via gc callbacks",
        )
        self._m_res_devmem = r.gauge(
            "verifyd_resource_device_memory_bytes",
            "Per-device bytes in use (when the backend reports memory stats)",
            labelnames=("device",),
        )
        # Live search progress (service/progress.JobProgress heartbeats):
        # last-heartbeat values per engine family — a watch surface, not a
        # per-job timeseries (job ids would be unbounded labels).
        self._m_progress_ratio = r.gauge(
            "verifyd_search_progress_ratio",
            "Committed fraction of the search (last heartbeat), by engine",
            labelnames=("engine",),
        )
        self._m_frontier_width = r.gauge(
            "verifyd_search_frontier_width",
            "Live frontier width of the search (last heartbeat), by engine",
            labelnames=("engine",),
        )
        self._m_layer_rate = r.gauge(
            "verifyd_search_layer_rate",
            "EWMA search layers per second (last heartbeat), by engine",
            labelnames=("engine",),
        )
        # Search acceleration (ISSUE 19): verdict-exact pruning and
        # speculative expansion counters, fed per decided job from the
        # scheduler's prune_applied / speculation_rollback events.
        self._m_prune_commits = r.counter(
            "verifyd_search_prune_commits_total",
            "Ops eagerly committed by the verdict-exact prune "
            "(inert ops and state-passing filters closed without search)",
        )
        self._m_prune_dead = r.counter(
            "verifyd_search_prune_dead_total",
            "Configurations dropped by the tail-pin dead-row rule",
        )
        self._m_prune_ranked = r.counter(
            "verifyd_search_prune_ranked_total",
            "Expansion candidates skipped by the append rank-order gate",
        )
        self._m_spec_layers = r.counter(
            "verifyd_search_spec_layers_total",
            "Search layers expanded inside speculative multi-layer dives",
        )
        self._m_spec_rollbacks = r.counter(
            "verifyd_search_spec_rollbacks_total",
            "Speculative dives discarded on misprediction (exact loop "
            "re-searches from the pre-dive frontier)",
        )

    # -- event stream -------------------------------------------------------

    def emit(self, event: str, **fields) -> None:
        line = {"ev": event, "t": round(time.time(), 3)}
        line.update(fields)
        with self._lock:
            self._count(event, fields)
            if self._sink is not None:
                payload = json.dumps(line, separators=(",", ":"), default=str) + "\n"
                # A broken stats sink must never take a job down — but a
                # single transient OSError (EINTR, brief ENOSPC) must not
                # silence the stream forever either: retry once, then drop
                # the sink with an accounted stats_sink_lost increment.
                for attempt in (0, 1):
                    try:
                        self._sink.write(payload)
                        self._sink.flush()
                        break
                    except ValueError:
                        # Closed file object: no point retrying.
                        self._drop_sink()
                        break
                    except OSError:
                        if attempt:
                            self._drop_sink()
            elif self.logger is not None:
                self.logger.event(event, fields)
        # Observability consumers run outside the sink lock: neither the
        # flight recorder's disk flush, the archive append, nor the SLO
        # window math may extend the emit critical section every job
        # passes through.
        if self.recorder is not None:
            self.recorder.record_event(line)
        if self.archive is not None:
            self.archive.observe_event(line)
        if self.sentinel is not None and not self._in_regression_emit:
            regression = self.sentinel.observe_event(line)
            if regression is not None:
                # Re-entrant emit, same discipline as slo_breach: the
                # regression rides the stream (sink, recorder, alert
                # engine, counters).  The guard stops a regression from
                # judging itself; the sentinel also only folds done
                # events, so no feedback.
                self._in_regression_emit = True
                try:
                    self.emit("perf_regression", **regression)
                finally:
                    self._in_regression_emit = False
        if self.alerts is not None:
            self.alerts.observe_event(line)
        if self.health is not None and not self._in_breach_emit:
            self.health.observe_event(line)
            breach = self.health.check_breach()
            if breach is not None:
                # Re-entrant emit: slo_breach rides the same stream as
                # everything else (sink, recorder, logger, counters).  The
                # guard only stops a breach from evaluating itself; the
                # engine also ignores non-outcome events, so no feedback.
                self._in_breach_emit = True
                try:
                    self.emit("slo_breach", **breach)
                finally:
                    self._in_breach_emit = False
                if self.recorder is not None:
                    self.recorder.dump("slo_breach", breach=breach, slo=self.health.snapshot())

    def _drop_sink(self) -> None:
        # Caller holds self._lock.
        self._sink = None
        self._counters["stats_sink_lost"] += 1
        self._m_sink_lost.inc()

    def _count(self, event: str, fields: dict) -> None:
        if event == "admit":
            self._counters["submitted"] += 1
            self._counters["admitted"] += 1
            self._m_submitted.inc()
        elif event == "reject":
            self._counters["submitted"] += 1
            self._counters["rejected"] += 1
            self._m_submitted.inc()
            self._m_rejected.inc()
        elif event == "cache_hit":
            self._counters["submitted"] += 1
            self._counters["cache_hits"] += 1
            self._m_submitted.inc()
            self._m_cache_hits.inc()
            if "queue_wait_s" in fields:
                self._m_queue_wait.observe(
                    float(fields["queue_wait_s"]),
                    exemplar=fields.get("trace_id"),
                )
        elif event == "decode_error":
            self._counters["submitted"] += 1
            self._counters["decode_errors"] += 1
            self._m_submitted.inc()
            self._m_decode_errors.inc()
        elif event == "degrade":
            self._counters["degraded"] += 1
            self._m_degraded.inc()
        elif event == "lease_grant":
            self._counters["leases_granted"] += 1
            self._m_leases_granted.inc()
            self._m_devices_leased.set(int(fields.get("in_use", 0)))
            if "wait_s" in fields:
                self._m_lease_wait.observe(float(fields["wait_s"]))
        elif event == "lease_release":
            self._m_devices_leased.set(int(fields.get("in_use", 0)))
            if "held_s" in fields:
                held = float(fields["held_s"])
                prev = self._lease_hold_avg
                self._lease_hold_avg = held if prev <= 0 else 0.7 * prev + 0.3 * held
        elif event == "lease_timeout":
            self._counters["lease_timeouts"] += 1
            self._m_lease_timeouts.inc()
        elif event == "slo_breach":
            self._counters["slo_breaches"] += 1
        elif event == "perf_regression":
            self._counters["perf_regressions"] += 1
        elif event == "retrace_storm":
            self._counters["retrace_storms"] += 1
            self._m_retrace_storms.inc()
        elif event == "auth_reject":
            self._counters["auth_rejects"] += 1
            self._m_auth_rejects.inc()
        elif event == "frame_error":
            self._counters["frame_errors"] += 1
            self._m_frame_errors.inc()
        elif event == "orphan":
            self._counters["orphans_recovered"] += 1
            self._m_orphans.inc()
        elif event == "cache_loaded":
            # Additive: one boot can replay several segments (and a long
            # daemon life can reload more than once); each event reports
            # the entries *it* replayed.
            n = int(fields.get("entries", 0))
            self._counters["cache_loaded"] += n
            self._m_cache_loaded.inc(n)
        elif event == "start":
            self._active += 1
            self._m_active.set(self._active)
            if "queue_wait_s" in fields:
                # Exemplar: the event's trace_id rides the observation so
                # an OpenMetrics scrape links the bucket to a timeline.
                self._m_queue_wait.observe(
                    float(fields["queue_wait_s"]),
                    exemplar=fields.get("trace_id"),
                )
        elif event == "job_cancelled":
            self._counters["cancelled"] += 1
            reason = str(fields.get("reason", "other"))
            if reason not in ("deadline", "client_gone", "shutdown"):
                reason = "other"
            self._m_cancelled.inc(reason=reason)
            # Only a job that actually started (emitted `start`) holds a
            # slot in the active gauge; queue-expiry cancels never did.
            if fields.get("started"):
                self._active = max(0, self._active - 1)
                self._m_active.set(self._active)
        elif event == "admission_shed":
            self._counters["submitted"] += 1
            self._counters["admission_shed"] += 1
            self._m_submitted.inc()
            reason = str(fields.get("reason", "other"))
            if reason not in ("rss", "fds", "deadline"):
                reason = "other"
            self._m_shed.inc(reason=reason)
        elif event == "job_quarantined":
            self._counters["quarantined"] += 1
            self._m_quarantine_size.set(int(fields.get("size", 0)))
        elif event == "quarantine_release":
            self._m_quarantine_size.set(int(fields.get("size", 0)))
        elif event == "quarantine_reject":
            self._counters["submitted"] += 1
            self._counters["quarantine_rejects"] += 1
            self._m_submitted.inc()
        elif event == "writer_degraded":
            self._counters["writer_degraded_events"] += 1
            writer = str(fields.get("writer", "?"))
            if writer not in (
                "flight", "archive", "journal", "cache", "telemetry"
            ):
                writer = "other"
            self._m_writer_degraded.set(1, writer=writer)
        elif event == "writer_recovered":
            writer = str(fields.get("writer", "?"))
            if writer not in (
                "flight", "archive", "journal", "cache", "telemetry"
            ):
                writer = "other"
            self._m_writer_degraded.set(0, writer=writer)
        elif event == "client_gone":
            self._counters["client_gone"] += 1
        elif event == "batch_launch":
            lanes = int(fields.get("lanes", 0))
            early = int(fields.get("early_exits", 0))
            self._counters["batch_launches"] += 1
            self._counters["batch_lanes"] += lanes
            self._counters["batch_early_exits"] += early
            engine = str(fields.get("engine", "other"))
            if engine not in ("batch-native", "batch-vmap"):
                engine = "other"
            self._m_batch_lanes.observe(float(lanes), engine=engine)
            if early:
                self._m_batch_early.inc(early)
            self._m_batch_occupancy.set(float(fields.get("occupancy", 0.0)))
        elif event == "prefix_loaded":
            n = int(fields.get("entries", 0))
            self._counters["prefix_loaded"] += n
            self._m_prefix_entries.set(n)
            self._m_prefix_bytes.set(int(fields.get("bytes", 0)))
        elif event == "prefix_hit":
            self._counters["prefix_hits"] += 1
            self._m_prefix_hits.inc()
            if "depth_frac" in fields:
                self._m_prefix_depth.observe(
                    float(fields["depth_frac"]),
                    exemplar=fields.get("trace_id"),
                )
        elif event == "prefix_miss":
            self._counters["prefix_misses"] += 1
            self._m_prefix_misses.inc()
        elif event == "prefix_snapshot":
            self._counters["prefix_snapshots"] += 1
            self._m_prefix_snapshots.inc()
            self._m_prefix_entries.set(int(fields.get("entries", 0)))
            self._m_prefix_bytes.set(int(fields.get("bytes", 0)))
        elif event == "prefix_refused":
            self._counters["prefix_refused"] += 1
            reason = str(fields.get("reason", "other"))
            if reason not in ("open_ops", "unknown_frontier"):
                reason = "other"
            self._m_prefix_refused.inc(reason=reason)
        elif event == "window_done":
            self._counters["windows_done"] += 1
            self._m_windows.inc()
        elif event == "partition_granted":
            self._counters["partitions_granted"] += 1
            self._m_ds_granted.inc()
        elif event == "partition_delta":
            self._counters["partition_deltas"] += 1
            try:
                v = int(fields.get("verdict", 2))
            except (TypeError, ValueError):
                v = 2
            self._m_ds_deltas.inc(verdict=_VERDICT_LABEL.get(v, "unknown"))
            try:
                self._m_ds_delta_bytes.inc(int(fields.get("bytes", 0)))
            except (TypeError, ValueError):
                pass
        elif event == "partition_done":
            self._counters["partitions_done"] += 1
            self._m_ds_done.inc()
        elif event == "epoch_fence":
            self._counters["epoch_fences"] += 1
            op = str(fields.get("op", "other"))
            if op not in ("grant", "delta", "delta_reply", "done"):
                op = "other"
            self._m_ds_fences.inc(op=op)
        elif event == "prune_applied":
            self._counters["prune_applied"] += 1
            self._m_prune_commits.inc(int(fields.get("commits", 0)))
            self._m_prune_dead.inc(int(fields.get("dead", 0)))
            self._m_prune_ranked.inc(int(fields.get("ranked", 0)))
        elif event == "speculation_rollback":
            n = int(fields.get("rollbacks", 1))
            self._counters["speculation_rollbacks"] += n
            self._m_spec_rollbacks.inc(n)
            self._m_spec_layers.inc(int(fields.get("layers", 0)))
        elif event == "search_progress":
            self._counters["search_progress"] += 1
            engine = str(fields.get("engine", "other"))
            if engine not in (
                "native",
                "oracle",
                "frontier",
                "device",
                "device-mesh",
                "batch-native",
                "batch-vmap",
            ):
                engine = "other"
            self._m_progress_ratio.set(
                float(fields.get("progress_ratio", 0.0)), engine=engine
            )
            self._m_frontier_width.set(
                float(fields.get("frontier_width", 0)), engine=engine
            )
            self._m_layer_rate.set(
                float(fields.get("layer_rate", 0.0)), engine=engine
            )
        elif event == "job_error":
            self._counters["job_errors"] += 1
            self._active = max(0, self._active - 1)
            self._m_job_errors.inc()
            self._m_active.set(self._active)
        elif event == "done":
            self._counters["completed"] += 1
            self._active = max(0, self._active - 1)
            self._m_active.set(self._active)
            wall = float(fields.get("wall_s", 0.0))
            self._wall_total_s += wall
            shape = fields.get("shape")
            if shape:
                prev = self._shape_wall.get(str(shape))
                self._shape_wall[str(shape)] = (
                    wall if prev is None else 0.7 * prev + 0.3 * wall
                )
            v = fields.get("verdict")
            name = {0: "verdict_ok", 1: "verdict_illegal", 2: "verdict_unknown"}.get(v)
            if name is not None:
                self._counters[name] += 1
            self._m_completed.inc(verdict=_VERDICT_LABEL.get(v, "unknown"))
            # The event field carries sized values ("device-mesh[4]",
            # "device-3"): fold to the engine family before it becomes a
            # label, or every mesh size / device ordinal mints a new
            # timeseries.
            backend = str(fields.get("backend", "unknown"))
            if backend.startswith("device-mesh"):
                backend = "device-mesh"
            elif backend.startswith("device"):
                backend = "device"
            elif backend.startswith("frontier"):
                # frontier-cold / frontier-resume / frontier-unbounded:
                # one engine family, one timeseries.
                backend = "frontier"
            if backend not in (
                "native",
                "oracle",
                "frontier",
                "device",
                "device-mesh",
                "auto",
                "unknown",
                "batch-native",
                "batch-vmap",
            ):
                backend = "other"
            self._m_wall.observe(
                wall,
                exemplar=fields.get("trace_id"),
                backend=backend,
            )
            profile = fields.get("profile")
            if isinstance(profile, dict) and "layers" in profile:
                self._m_layers.observe(float(profile["layers"]))
            for s in fields.get("shards") or []:
                if not isinstance(s, dict):
                    continue
                # shard ordinals are bounded by the device-pool size (≤8
                # mesh devices), not by traffic — closed in practice, just
                # not provable from a literal set.
                shard = str(s.get("shard", "?"))
                self._m_shard_occ.set(
                    float(s.get("peak_occupancy", 0)),
                    shard=shard,  # verifylint: disable=metric-open-label
                )
                self._m_shard_collective.observe(
                    float(s.get("collective_wall_s", 0.0)),
                    shard=shard,  # verifylint: disable=metric-open-label
                )
                self._m_shard_skew.set(
                    float(s.get("skew", 1.0)),
                    shard=shard,  # verifylint: disable=metric-open-label
                )

    def set_quarantine_size(self, size: int) -> None:
        """Boot-time (re)sync of the quarantine gauge with the persisted
        ledger; live transitions ride the event stream."""
        self._m_quarantine_size.set(int(size))

    def set_queue_depth(self, depth: int) -> None:
        """Point-in-time admission-queue depth (daemon after put, workers
        after a batch pull)."""
        self._m_queue_depth.set(depth)

    # -- shape warmth -------------------------------------------------------

    def note_shape(self, shape: str) -> bool:
        """Record a shape about to run; returns True when this daemon has
        already run it (compiled executables are warm)."""
        with self._lock:
            warm = shape in self._shapes_seen
            self._shapes_seen.add(shape)
            return warm

    # -- aggregates ---------------------------------------------------------

    def snapshot(self) -> dict:
        with self._lock:
            snap = dict(self._counters)
            snap["uptime_s"] = round(time.time() - self._t0, 3)
            snap["shapes_run"] = len(self._shapes_seen)
            snap["active"] = self._active
            done = self._counters["completed"]
            snap["avg_wall_s"] = round(self._wall_total_s / done, 4) if done else 0.0
        snap["metrics"] = self.registry.snapshot()
        if self.health is not None:
            snap["slo"] = self.health.snapshot()
        if self.sentinel is not None:
            snap["sentinel"] = self.sentinel.snapshot()
        if self.alerts is not None:
            snap["alerts"] = self.alerts.snapshot()
        if self.archive is not None:
            snap["archive"] = self.archive.snapshot()
        return snap

    @property
    def active(self) -> int:
        """Jobs handed to a worker and not yet answered (drain poller)."""
        with self._lock:
            return self._active

    def predicted_wall_s(self, shape: str) -> float:
        """EWMA of decided wall time for ``shape`` (0.0 = never seen) —
        the AdmissionController's deadline-feasibility input."""
        with self._lock:
            return self._shape_wall.get(str(shape), 0.0)

    def retry_after_hint(self, queue_depth: int) -> float:
        """Backpressure hint: roughly how long until the queue has room —
        (queued + in-flight jobs) × average decided-job wall time, plus
        the device pool's lease-wait backlog (waiters × EWMA lease hold:
        jobs parked in supervised escalation drain no faster than leases
        turn over, and a hint that ignored them taught clients to
        dogpile a wedged mesh), clamped to [0.5, 30] s (a cold daemon
        has no average yet; never tell a client "0")."""
        with self._lock:
            done = self._counters["completed"]
            avg = (self._wall_total_s / done) if done else 1.0
            pending = queue_depth + self._active
            hold = self._lease_hold_avg
        extra = 0.0
        if self.device_pool is not None and hold > 0:
            try:
                extra = self.device_pool.snapshot().get("waiters", 0) * hold
            except Exception:
                extra = 0.0
        return round(min(30.0, max(0.5, pending * avg + extra)), 2)
