"""Overload protection: deadlines, quarantine, shedding, degradation.

Four cooperating pieces, one per failure mode a production verifyd must
survive (ISSUE 10):

``CancelToken``
    One per job, threaded from admission through the scheduler into the
    supervised child.  Set-once with a reason (``deadline`` /
    ``client_gone`` / ``shutdown``); searches poll :meth:`check` at
    layer boundaries instead of being preempted, so cancellation is
    cooperative and leases release through the normal ``finally`` path.

``QuarantineStore``
    Persistent per-fingerprint crash ledger under ``--state-dir``.  A
    fingerprint observed in-flight across >= threshold process deaths
    (or supervised-child kills) is quarantined: boot-time orphan replay
    skips it and fresh submits are answered with the **definite**
    ``Quarantined`` error until an operator releases it.  This turns
    the poison-job crash loop — die, replay the orphan, die again —
    into a non-event.

``AdmissionController``
    Pre-admission shedding on host pressure: RSS against a
    ``--max-rss-frac`` watermark, fd headroom against ``RLIMIT_NOFILE``,
    and deadline feasibility against per-shape observed wall time.
    Sheds answer immediately with an honest ``retry_after`` instead of
    queueing work the host cannot finish.  Resource reads are cached
    for a short interval so the hot submit path stays cheap.

``DegradedWriter``
    One ENOSPC/OSError policy for every durable writer (journal, cache
    seglog, archive, flight recorder): the first failure flips the
    writer into a degraded memory-only mode (counted, evented, surfaced
    on /healthz for the journal), subsequent appends are dropped
    cheaply, and a periodic re-probe re-arms the writer when space
    returns.  The ``VERIFYD_FAULT_ENOSPC_FILE`` environment shim lets
    the chaos harness inject ENOSPC deterministically without filling a
    real filesystem.
"""

from __future__ import annotations

import errno
import json
import os
import resource
import threading
import time
from typing import Callable, Optional

__all__ = [
    "CancelToken",
    "QuarantineStore",
    "AdmissionController",
    "DegradedWriter",
    "FAULT_ENOSPC_ENV",
]

#: While the file this variable points at exists — and is empty or holds
#: the writer's name — DegradedWriter.run raises a synthetic ENOSPC
#: instead of calling through.  Fault injection for `make overload`;
#: zero overhead when the variable is unset.
FAULT_ENOSPC_ENV = "VERIFYD_FAULT_ENOSPC_FILE"


class CancelToken:
    """Set-once cooperative cancellation flag with an optional deadline.

    Thread-safe: the submit path arms it, scheduler workers and the
    supervised-child babysitter poll it, and the acceptor's client-gone
    watcher may fire it — all from different threads.  First reason
    wins; a deadline expiry observed by :meth:`check` self-cancels with
    reason ``"deadline"``.
    """

    __slots__ = ("_lock", "_reason", "deadline_at")

    def __init__(self, deadline_at: Optional[float] = None) -> None:
        self._lock = threading.Lock()
        self._reason: Optional[str] = None
        #: absolute time.monotonic() deadline, or None for unbounded
        self.deadline_at = deadline_at

    def cancel(self, reason: str) -> bool:
        """Arm the token; returns True if this call set it first."""
        with self._lock:
            if self._reason is None:
                self._reason = reason
                return True
            return False

    def check(self) -> Optional[str]:
        """Reason if cancelled (auto-cancelling on a passed deadline)."""
        with self._lock:
            if self._reason is None and self.deadline_at is not None:
                if time.monotonic() >= self.deadline_at:
                    self._reason = "deadline"
            return self._reason

    def remaining(self) -> Optional[float]:
        """Seconds until the deadline (None = unbounded, 0.0 = passed)."""
        if self.deadline_at is None:
            return None
        return max(0.0, self.deadline_at - time.monotonic())

    @property
    def reason(self) -> Optional[str]:
        with self._lock:
            return self._reason


class QuarantineStore:
    """Persistent poison-job ledger: crash counts and quarantined set.

    One JSON file (atomic tmp+rename rewrite — the set is operator-scale
    small) under ``<state_dir>/quarantine/``.  ``note_crash`` is called
    once per fingerprint per observed death: at boot for every journal
    orphan that had *started* running when the process died, and live
    when a supervised child dies inconclusively.  Reaching the threshold
    moves the fingerprint to the quarantined set; ``note_success``
    forgives accumulated crashes on any conclusive verdict.
    """

    def __init__(
        self,
        dir_path: str,
        *,
        threshold: int = 3,
        stats=None,
    ) -> None:
        self.dir = dir_path
        self.path = os.path.join(dir_path, "quarantine.json")
        self.threshold = max(1, int(threshold))
        self.stats = stats
        self._lock = threading.Lock()
        self._crashes: dict[str, dict] = {}
        self._quarantined: dict[str, dict] = {}
        self._load()

    # -- persistence ----------------------------------------------------

    def _load(self) -> None:
        try:
            with open(self.path, encoding="utf-8") as f:
                data = json.load(f)
            self._crashes = dict(data.get("crashes", {}))
            self._quarantined = dict(data.get("quarantined", {}))
        except (OSError, ValueError, TypeError):
            pass

    def _persist_locked(self) -> None:
        """Atomic rewrite; an unwritable disk loses only counter deltas —
        the ledger itself degrades gracefully like every other writer."""
        try:
            os.makedirs(self.dir, exist_ok=True)
            tmp = self.path + ".tmp"
            with open(tmp, "w", encoding="utf-8") as f:
                json.dump(
                    {
                        "crashes": self._crashes,
                        "quarantined": self._quarantined,
                    },
                    f,
                    sort_keys=True,
                )
            os.replace(tmp, self.path)
        except OSError:
            pass

    # -- mutation -------------------------------------------------------

    def note_crash(self, fingerprint: str, kind: str = "boot") -> int:
        """Record one death coinciding with ``fingerprint``; quarantines
        at the threshold.  Returns the accumulated crash count."""
        if not fingerprint:
            return 0
        emit = None
        with self._lock:
            ent = self._crashes.setdefault(
                fingerprint, {"count": 0, "kinds": {}}
            )
            ent["count"] = int(ent.get("count", 0)) + 1
            kinds = ent.setdefault("kinds", {})
            kinds[kind] = int(kinds.get(kind, 0)) + 1
            count = ent["count"]
            if (
                count >= self.threshold
                and fingerprint not in self._quarantined
            ):
                self._quarantined[fingerprint] = {
                    "crashes": count,
                    "kinds": dict(kinds),
                    "since": time.time(),
                }
                emit = ("job_quarantined", count, kind)
            self._persist_locked()
            size = len(self._quarantined)
        if emit is not None and self.stats is not None:
            self.stats.emit(
                "job_quarantined",
                fingerprint=fingerprint,
                crashes=emit[1],
                kind=emit[2],
                size=size,
            )
        return count

    def note_success(self, fingerprint: str) -> None:
        """A conclusive verdict forgives accumulated crashes."""
        if not fingerprint:
            return
        with self._lock:
            if self._crashes.pop(fingerprint, None) is not None:
                self._persist_locked()

    def release(self, fingerprint: str) -> bool:
        """Operator override: un-quarantine and reset the crash count."""
        with self._lock:
            ent = self._quarantined.pop(fingerprint, None)
            self._crashes.pop(fingerprint, None)
            if ent is None:
                return False
            self._persist_locked()
            size = len(self._quarantined)
        if self.stats is not None:
            self.stats.emit(
                "quarantine_release", fingerprint=fingerprint, size=size
            )
        return True

    # -- queries --------------------------------------------------------

    def is_quarantined(self, fingerprint: str) -> bool:
        with self._lock:
            return fingerprint in self._quarantined

    def get(self, fingerprint: str) -> Optional[dict]:
        with self._lock:
            ent = self._quarantined.get(fingerprint)
            return dict(ent, fingerprint=fingerprint) if ent else None

    def crash_count(self, fingerprint: str) -> int:
        with self._lock:
            ent = self._crashes.get(fingerprint)
            return int(ent.get("count", 0)) if ent else 0

    def list(self) -> list[dict]:
        with self._lock:
            return [
                dict(ent, fingerprint=fp)
                for fp, ent in sorted(self._quarantined.items())
            ]

    def __len__(self) -> int:
        with self._lock:
            return len(self._quarantined)


def _read_meminfo_total() -> int:
    try:
        with open("/proc/meminfo", encoding="ascii") as f:
            for line in f:
                if line.startswith("MemTotal:"):
                    return int(line.split()[1]) * 1024
    except (OSError, ValueError, IndexError):
        pass
    return 0


def _read_self_rss() -> int:
    try:
        with open("/proc/self/statm", encoding="ascii") as f:
            return int(f.read().split()[1]) * (os.sysconf("SC_PAGE_SIZE"))
    except (OSError, ValueError, IndexError):
        return 0


def _count_open_fds() -> int:
    try:
        return len(os.listdir("/proc/self/fd"))
    except OSError:
        return 0


class AdmissionController:
    """Shed-before-queue decisions on host pressure and deadline math.

    ``decide`` returns ``None`` to admit or a shed reason from the
    bounded set ``{"rss", "fds", "deadline"}``.  Resource probes are
    cached for ``cache_s`` so a 300+ jobs/s submit stream does not churn
    /proc; the sampler's last sample is preferred when one is armed.
    """

    #: shed when open fds pass this fraction of RLIMIT_NOFILE
    FD_FRAC = 0.9

    def __init__(
        self,
        stats=None,
        *,
        max_rss_frac: float = 0.0,
        sampler=None,
        cache_s: float = 0.25,
        rss_fn: Callable[[], int] = _read_self_rss,
        fds_fn: Callable[[], int] = _count_open_fds,
    ) -> None:
        self.stats = stats
        self.max_rss_frac = float(max_rss_frac or 0.0)
        self.sampler = sampler
        self.cache_s = cache_s
        self._rss_fn = rss_fn
        self._fds_fn = fds_fn
        self._mem_total = _read_meminfo_total()
        try:
            self._fd_limit = resource.getrlimit(resource.RLIMIT_NOFILE)[0]
        except (OSError, ValueError):
            self._fd_limit = 0
        self._lock = threading.Lock()
        self._probed_at = 0.0
        self._rss = 0
        self._fds = 0

    def _probe(self) -> tuple[int, int]:
        now = time.monotonic()
        with self._lock:
            if now - self._probed_at < self.cache_s:
                return self._rss, self._fds
            self._probed_at = now
        rss = fds = 0
        sample = None
        if self.sampler is not None:
            try:
                sample = self.sampler.snapshot().get("last")
            except Exception:
                sample = None
        if isinstance(sample, dict):
            rss = int(sample.get("rss_bytes") or 0)
            fds = int(sample.get("fds") or 0)
        if not rss:
            rss = self._rss_fn()
        if not fds:
            fds = self._fds_fn()
        with self._lock:
            self._rss, self._fds = rss, fds
        return rss, fds

    def decide(
        self,
        *,
        queue_depth: int = 0,
        deadline_s: Optional[float] = None,
        shape: Optional[str] = None,
    ) -> Optional[str]:
        """None = admit; else the shed reason (bounded cardinality)."""
        if self.max_rss_frac > 0 and self._mem_total > 0:
            rss, fds = self._probe()
            if rss > self.max_rss_frac * self._mem_total:
                return "rss"
            if (
                self._fd_limit
                and self._fd_limit != resource.RLIM_INFINITY
                and fds > self.FD_FRAC * self._fd_limit
            ):
                return "fds"
        if deadline_s is not None and self.stats is not None and shape:
            try:
                wall = self.stats.predicted_wall_s(shape)
            except Exception:
                wall = 0.0
            if wall > 0:
                # Queue ETA + this job's own predicted wall: a deadline
                # the host has never met for this shape is shed honestly
                # at the door rather than cancelled after queueing.
                eta = queue_depth * wall + wall
                if eta > deadline_s:
                    return "deadline"
        return None


class DegradedWriter:
    """One degrade/recover policy for a durable append path.

    ``run(fn)`` calls through while armed.  The first ``OSError`` (or
    injected ENOSPC) flips the writer degraded: the failure is counted
    and evented (``writer_degraded``), ``on_degrade`` fires (the journal
    uses it to mark /healthz), and subsequent appends are *dropped*
    without touching the disk except for one re-probe attempt every
    ``reprobe_s``.  A successful re-probe re-arms the writer and events
    ``writer_recovered`` with the number of drops.
    """

    def __init__(
        self,
        name: str,
        stats=None,
        *,
        reprobe_s: float = 5.0,
        on_degrade: Optional[Callable[[str], None]] = None,
        on_recover: Optional[Callable[[], None]] = None,
    ) -> None:
        self.name = name
        self.stats = stats
        self.reprobe_s = reprobe_s
        self.on_degrade = on_degrade
        self.on_recover = on_recover
        self._lock = threading.Lock()
        self._degraded = False
        self._degraded_at = 0.0
        self._last_probe = 0.0
        self._drops = 0
        self._error = ""

    @property
    def degraded(self) -> bool:
        with self._lock:
            return self._degraded

    @property
    def drops(self) -> int:
        with self._lock:
            return self._drops

    def _maybe_inject_fault(self) -> None:
        path = os.environ.get(FAULT_ENOSPC_ENV)
        if not path:
            return
        try:
            with open(path, encoding="utf-8") as f:
                targets = f.read().split()
        except OSError:
            return  # file absent → fault disarmed
        if not targets or self.name in targets:
            raise OSError(errno.ENOSPC, "injected: no space left on device")

    def run(self, fn: Callable[[], object], default=None):
        """Returns ``(value, ok)``: ``fn()``'s result and whether the
        append actually landed.  Degraded calls return ``(default,
        False)`` without invoking ``fn`` except on re-probe ticks."""
        now = time.monotonic()
        with self._lock:
            if self._degraded and now - self._last_probe < self.reprobe_s:
                self._drops += 1
                return default, False
            self._last_probe = now
            was_degraded = self._degraded
        try:
            self._maybe_inject_fault()
            value = fn()
        except OSError as e:
            self._note_failure(e, was_degraded)
            return default, False
        if was_degraded:
            self._note_recovery()
        return value, True

    def _note_failure(self, e: OSError, was_degraded: bool) -> None:
        with self._lock:
            self._error = f"{e.__class__.__name__}: {e}"
            if self._degraded:
                self._drops += 1
                return
            self._degraded = True
            self._degraded_at = time.time()
            self._drops = 1
        if self.stats is not None:
            self.stats.emit(
                "writer_degraded", writer=self.name, error=str(e)
            )
        if self.on_degrade is not None:
            try:
                self.on_degrade(str(e))
            except Exception:
                pass

    def _note_recovery(self) -> None:
        with self._lock:
            self._degraded = False
            drops, self._drops = self._drops, 0
            self._error = ""
        if self.stats is not None:
            self.stats.emit(
                "writer_recovered", writer=self.name, drops=drops
            )
        if self.on_recover is not None:
            try:
                self.on_recover()
            except Exception:
                pass

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "writer": self.name,
                "degraded": self._degraded,
                "drops": self._drops,
                "error": self._error,
                "degraded_at": self._degraded_at or None,
            }
