"""Durable chain-hash-keyed prefix store: resume searches instead of re-running.

The verdict cache (service/cache.py) only helps on byte-identical
histories; this store memoizes the *search itself* at prefix-closed op
boundaries (checker/prefix.py) so window N+1 of a live stream resumes
from window N's decided frontier.  The same chain-hash fold that names
full histories names prefixes: the key of cut K is the fingerprint fold's
intermediate accumulator after K ops::

    p{version}:{acc:016x}:{K}

``acc`` commits to every op of the prefix (canon, order, real-time
window), so two histories share a key exactly when they prepare to the
same first K ops — extensions of a stream probe with their own fold's
intermediates and hit whatever some earlier job snapshotted.  Keys from
``follow`` windows are computed with each window's ops re-based to
absolute event indices (the window's offset is carried in the entry), so
a follow lineage's keys coincide with the keys a one-shot submit of the
concatenated history would compute — warm state is shared across both
paths, across jobs, and (the store being node-local) across boots.

Persistence mirrors the verdict cache: an in-memory LRU spilled to a
CRC-checked segment log (utils/seglog.py) under ``<state_dir>/prefix/``,
replayed at boot (torn tails and corrupt segments recover to a valid
prefix — a lost snapshot costs a cold search, never a wrong verdict),
disk bounded by segment rotation so old prefixes age out with their
segment.

Soundness: an entry is only ever written from a completed snapshot cut of
an OK search (checker/frontier.py refuses cuts touched by pruning;
checker/prefix.py refuses boundaries crossed by in-flight ops), and
:meth:`PrefixStore.put` re-validates the shape.  Resuming from an entry
is then verdict-equivalent to the cold search — the differential gate in
scripts/prefix_check.py proves warm-vs-cold parity end to end.
"""

from __future__ import annotations

import dataclasses
import json
import logging
import os
import threading
from collections import OrderedDict
from dataclasses import dataclass, field
from typing import Sequence

from ..checker.entries import History
from ..checker.prefix import (
    PrefixCarry,
    boundary_counts,
    choose_cuts,
    closed_boundaries,
    has_open_ops,
)
from ..utils.hashing import chain_hash
from ..utils.seglog import Recovery, SegmentLog
from .cache import _FP_VERSION, _op_digest

__all__ = [
    "PrefixPlan",
    "PrefixStore",
    "affinity_key",
    "parse_prefix_key",
    "plan_for_submit",
    "plan_for_window",
    "prefix_accumulators",
    "prefix_key",
    "read_cold",
]

log = logging.getLogger("s2_verification_tpu.verifyd")

#: subdirectory of ``--state-dir`` holding the segment log
PREFIX_SUBDIR = "prefix"


def prefix_key(acc: int, ops: int) -> str:
    """Wire/store key of the cut after ``ops`` cumulative ops.

    The ``p`` prefix keeps the namespace disjoint from verdict-cache
    fingerprints (``v2:...``) — a window job's "fingerprint" is its cut
    key, and it must never collide with a real full-history fingerprint.
    """
    return f"p{_FP_VERSION}:{acc & 0xFFFFFFFFFFFFFFFF:016x}:{ops}"


def parse_prefix_key(key: str) -> tuple[int, int]:
    """(accumulator, cumulative ops) of a store key; raises ValueError."""
    ver, acc, ops = key.split(":")
    if ver != f"p{_FP_VERSION}":
        raise ValueError(f"prefix key version mismatch: {key!r}")
    return int(acc, 16), int(ops)


def prefix_accumulators(
    hist: History,
    cuts: Sequence[int] | None = None,
    *,
    acc: int = 0,
    ops_base: int = 0,
    event_offset: int = 0,
) -> dict[int, str]:
    """Fold the fingerprint canon over ``hist.ops``; return ``{local cut K
    -> store key}`` for each requested cut (default: every closed
    boundary).

    ``acc``/``ops_base``/``event_offset`` continue a follow lineage: the
    fold starts from the previous window's accumulator and each op's
    call/ret are re-based to absolute event indices, so the resulting keys
    equal the ones a cold fold over the concatenated history would
    produce.
    """
    want = set(cuts) if cuts is not None else set(closed_boundaries(hist))
    out: dict[int, str] = {}
    if not want:
        return out
    top = max(want)
    for i, op in enumerate(hist.ops):
        if i >= top:
            break
        if event_offset:
            op = dataclasses.replace(
                op, call=op.call + event_offset, ret=op.ret + event_offset
            )
        acc = chain_hash(acc, _op_digest(op))
        k = i + 1
        if k in want:
            out[k] = prefix_key(acc, ops_base + k)
    return out


def affinity_key(hist: History, fingerprint: str) -> str:
    """Ring placement key for a prepared history.

    The verdict fingerprint changes whenever a single op is appended, so
    fingerprint-keyed placement scatters a growing stream's
    resubmissions across the fleet — every extension lands cold, away
    from the node holding its prefix snapshots.  Keying the ring by the
    chain-hash accumulator at the history's *first* closed boundary is
    stable under extension (appended ops only deepen the suffix), so the
    whole lineage — and its ``follow`` windows, which reuse the same
    chain-hash namespace — homes on one node.  Identical texts still
    collide (same first boundary), preserving verdict-cache affinity.
    Histories with no closed boundary short of the end fall back to the
    fingerprint.

    This is the router's placement function (``VerifydRouter``
    delegates here); anything predicting a job's home node — e.g. the
    fleet gate's fresh-history picks — must use it too, never the raw
    fingerprint.
    """
    bounds = closed_boundaries(hist)
    cuts = [k for k in bounds if k < len(hist.ops)]
    if not cuts:
        return fingerprint
    keys = prefix_accumulators(hist, [cuts[0]])
    return keys.get(cuts[0], fingerprint)


def make_entry(
    carry: PrefixCarry,
    *,
    events: int,
    stream: str | None = None,
    window: int | None = None,
) -> dict:
    """Store-entry payload for one snapshot cut.

    ``events`` is the absolute event horizon of the cut — the offset the
    next follow window folds from.  ``stream``/``window`` label follow
    lineages for doctor post-mortems; submit-lineage entries omit them.
    """
    entry = dict(carry.to_payload())
    entry["e"] = int(events)
    if stream is not None:
        entry["stream"] = stream
    if window is not None:
        entry["w"] = int(window)
    return entry


@dataclass
class PrefixPlan:
    """Everything the scheduler needs to run one prefix-aware search.

    ``kind`` is ``"extend"`` (a full history that probed the store; the
    search covers all of ``hist.ops`` and may resume at ``carry.ops``) or
    ``"window"`` (a follow delta; the search history is the standalone
    suffix, counts start at zero, and the verdict is window-scoped — it
    must never enter the verdict cache or the router edge cache).
    """

    kind: str
    carry: PrefixCarry | None = None
    #: per-chain counts at the resume cut, within the search history
    #: (``"extend"`` only; window searches start every chain at zero)
    resume_counts: tuple[int, ...] | None = None
    #: local cut K (within the search history) -> store key to write on OK
    snap_keys: dict[int, str] = field(default_factory=dict)
    #: cumulative ops committed before this search's op 0 (window lineage)
    base_ops: int = 0
    #: absolute event horizon before this search's event 0
    base_events: int = 0
    #: events in this search's own history (set at admission; the horizon
    #: of the final cut, where ``ops[K].call`` does not exist)
    total_events: int = 0
    stream: str | None = None
    window: int | None = None
    #: closed boundaries probed (diagnostics for the prefix_{hit,miss} events)
    probed: int = 0
    #: why snapshotting was refused, when it was (e.g. ``"open_ops"``)
    refused: str | None = None

    @property
    def resume_ops(self) -> int:
        return self.carry.ops if self.carry is not None else 0


def plan_for_submit(
    store: "PrefixStore | None",
    hist: History,
    *,
    max_cuts: int = 8,
    min_ops: int = 4,
) -> PrefixPlan | None:
    """Probe the store for the longest cached prefix of a full history and
    pick the cuts worth snapshotting past it.  Returns ``None`` when the
    history is too small to bother (the plan itself routes the job onto
    the host frontier path, so tiny histories stay on the native engine).
    """
    if store is None or len(hist.ops) < min_ops:
        return None
    keys = prefix_accumulators(hist)
    if not keys:
        return None
    open_ops = has_open_ops(hist)
    if open_ops:
        # The K = num_ops boundary is only *geometrically* closed when ops
        # are pending; their outcome is undecided and must not be carried.
        keys.pop(len(hist.ops), None)
        if not keys:
            return None
    plan = PrefixPlan(kind="extend", probed=len(keys))
    hit_k = 0
    by_depth = sorted(keys, reverse=True)
    entry = store.probe([keys[k] for k in by_depth])
    if entry is not None:
        key, payload = entry
        try:
            carry = PrefixCarry.from_payload(payload)
        except (ValueError, KeyError, TypeError):
            carry = None  # foreign/corrupt entry: treat as a miss
        if carry is not None and keys.get(carry.ops) == key:
            hit_k = carry.ops
            plan.carry = carry
            plan.resume_counts = boundary_counts(hist, hit_k)
    snap_cuts = [k for k in choose_cuts(hist, max_cuts) if k > hit_k and k in keys]
    plan.snap_keys = {k: keys[k] for k in snap_cuts}
    if open_ops:
        plan.refused = "open_ops"
    return plan


def plan_for_window(
    hist: History,
    *,
    token: str | None,
    entry: dict | None,
    stream: str,
) -> PrefixPlan:
    """Build the plan for one follow window (the standalone suffix).

    ``token``/``entry`` are the previous window's store key and payload
    (both ``None`` for the first window).  The only snapshot cut is the
    window's end; it is refused when the window has in-flight ops — the
    client must resend those events once their finishes arrive.
    """
    acc, base_ops, base_events, window = 0, 0, 0, 0
    carry = None
    if token is not None:
        acc, base_ops = parse_prefix_key(token)
        assert entry is not None
        carry = PrefixCarry.from_payload(entry)
        if carry.ops != base_ops:
            raise ValueError("frontier token does not match its entry")
        base_events = int(entry.get("e", 0))
        window = int(entry.get("w", -1)) + 1
    plan = PrefixPlan(
        kind="window",
        carry=carry,
        base_ops=base_ops,
        base_events=base_events,
        stream=stream,
        window=window,
    )
    n = len(hist.ops)
    if has_open_ops(hist):
        plan.refused = "open_ops"
    elif n > 0:
        keys = prefix_accumulators(
            hist, [n], acc=acc, ops_base=base_ops, event_offset=base_events
        )
        plan.snap_keys = {n: keys[n]}
    else:
        # An all-trivial window: nothing to search, but the event horizon
        # still advances — re-key the carry at the same cut.
        plan.snap_keys = {0: token} if token is not None else {}
    return plan


class PrefixStore:
    """Thread-safe LRU of cut key → carried frontier state, spilled to a
    segment log so restarts resume from the last durable snapshot."""

    def __init__(
        self,
        capacity: int = 2048,
        persist_dir: str | None = None,
        *,
        fsync: bool = False,
        max_segments: int = 8,
        writer=None,
    ) -> None:
        if capacity < 1:
            raise ValueError(f"prefix capacity must be >= 1, got {capacity}")
        self.capacity = capacity
        self.writer = writer
        self._lock = threading.Lock()
        self._entries: OrderedDict[str, dict] = OrderedDict()
        self._sizes: dict[str, int] = {}
        self._bytes = 0
        self._log: SegmentLog | None = None
        self.hits = 0
        self.misses = 0
        self.loaded = 0  #: entries replayed from disk at construction
        self.recovery: Recovery | None = None
        if persist_dir is not None:
            self._log = SegmentLog(
                persist_dir, fsync=fsync, max_segments=max_segments
            )
            for payload in self._log.replay():
                try:
                    rec = json.loads(payload)
                    key, value = rec["k"], rec["p"]
                except (ValueError, KeyError, TypeError):
                    continue  # CRC-intact but foreign: skip, never crash
                if isinstance(key, str) and isinstance(value, dict):
                    self._set(key, value, len(payload))
            while len(self._entries) > self.capacity:
                self._evict_oldest()
            self.loaded = len(self._entries)
            self.recovery = self._log.recovery

    def _set(self, key: str, value: dict, size: int) -> None:
        self._bytes -= self._sizes.get(key, 0)
        self._entries[key] = value
        self._entries.move_to_end(key)
        self._sizes[key] = size
        self._bytes += size

    def _evict_oldest(self) -> None:
        key, _ = self._entries.popitem(last=False)
        self._bytes -= self._sizes.pop(key, 0)

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    def __contains__(self, key: str) -> bool:
        with self._lock:
            return key in self._entries

    @property
    def bytes(self) -> int:
        with self._lock:
            return self._bytes

    def get(self, key: str) -> dict | None:
        """One entry, LRU-touched and hit/miss-counted."""
        with self._lock:
            value = self._entries.get(key)
            if value is None:
                self.misses += 1
                return None
            self.hits += 1
            self._entries.move_to_end(key)
            return dict(value)

    def probe(self, keys: Sequence[str]) -> tuple[str, dict] | None:
        """First hit among ``keys`` (callers order deepest-first); counted
        as a single hit or miss regardless of how many cuts were probed."""
        with self._lock:
            for key in keys:
                value = self._entries.get(key)
                if value is not None:
                    self.hits += 1
                    self._entries.move_to_end(key)
                    return key, dict(value)
            self.misses += 1
            return None

    def put(self, key: str, entry: dict) -> None:
        """Record one snapshot cut; validates the carried shape first."""
        if not entry.get("s") or int(entry.get("n", 0)) < 0:
            raise ValueError(f"refusing malformed prefix entry for {key!r}")
        record = json.dumps({"k": key, "p": entry}, separators=(",", ":")).encode(
            "utf-8"
        )
        with self._lock:
            self._set(key, dict(entry), len(record))
            while len(self._entries) > self.capacity:
                self._evict_oldest()
            if self._log is not None:
                if self.writer is not None:
                    try:
                        self.writer.run(lambda: self._log.append(record))
                    except ValueError:
                        log.exception("prefix-store spill failed; disabling")
                        self._log = None
                    return
                try:
                    self._log.append(record)
                except (OSError, ValueError):
                    # Spill is best-effort: a full disk must not fail jobs.
                    log.exception("prefix-store spill failed; disabling")
                    self._log = None

    def stats(self) -> dict:
        with self._lock:
            return {
                "entries": len(self._entries),
                "bytes": self._bytes,
                "hits": self.hits,
                "misses": self.misses,
                "loaded": self.loaded,
            }

    def close(self) -> None:
        if self._log is not None:
            self._log.close()


def read_cold(state_dir: str) -> dict | None:
    """Post-mortem view of a dead daemon's prefix store (doctor).

    Replays the segment log without opening it for writing; returns
    ``None`` when the directory has no prefix log at all.
    """
    directory = os.path.join(state_dir, PREFIX_SUBDIR)
    if not os.path.isdir(directory):
        return None
    slog = SegmentLog(directory)
    entries: dict[str, dict] = {}
    sizes: dict[str, int] = {}
    for payload in slog.replay():
        try:
            rec = json.loads(payload)
            key, value = rec["k"], rec["p"]
        except (ValueError, KeyError, TypeError):
            continue
        if isinstance(key, str) and isinstance(value, dict):
            entries[key] = value
            sizes[key] = len(payload)
    total = sum(sizes.values())
    streams: dict[str, dict] = {}
    deepest = 0
    for value in entries.values():
        deepest = max(deepest, int(value.get("n", 0)))
        stream = value.get("stream")
        if isinstance(stream, str):
            cur = streams.get(stream)
            if cur is None or int(value.get("n", 0)) >= cur["ops"]:
                streams[stream] = {
                    "ops": int(value.get("n", 0)),
                    "window": value.get("w"),
                    "events": int(value.get("e", 0)),
                }
    rec = slog.recovery
    return {
        "entries": len(entries),
        "bytes": total,
        "deepest_ops": deepest,
        "streams": streams,
        "recovery": {
            "records": rec.records,
            "segments": rec.segments,
            "torn_tail_bytes": rec.torn_tail_bytes,
            "bad_segments": rec.bad_segments,
        },
    }
