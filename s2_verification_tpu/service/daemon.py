"""verifyd: the resident verification daemon.

Serves the :mod:`.protocol` over a unix-domain socket.  Like the
collector's loopback S2 server (``collector/socket_s2.py``), the asyncio
acceptor runs a private event loop on a daemon thread, so the daemon
composes as a context manager in tests and as a foreground process under
``s2-verification-tpu serve``.  Checking itself never runs on the event
loop: the acceptor only decodes, consults the verdict cache, and admits
into the bounded queue; :class:`~.scheduler.Scheduler` worker threads do
the searching and resolve each submit's deferred reply through
``call_soon_threadsafe``.
"""

from __future__ import annotations

import asyncio
import contextlib
import itertools
import logging
import os
import sys
import threading
from dataclasses import dataclass, field

from .. import version as _version
from ..checker.entries import prepare
from ..utils import events as ev
from .cache import VerdictCache, history_fingerprint
from .protocol import (
    ERR_DECODE,
    ERR_INTERNAL,
    ERR_QUEUE_FULL,
    ERR_SHUTTING_DOWN,
    PROTOCOL_VERSION,
    decode_frame,
    encode_frame,
    err,
    ok,
)
from .queue import AdmissionQueue, Job, QueueFull
from .scheduler import Scheduler, shape_key
from .stats import ServiceStats

__all__ = ["VerifydConfig", "Verifyd"]

log = logging.getLogger("s2_verification_tpu.verifyd")


@dataclass
class VerifydConfig:
    socket_path: str
    queue_depth: int = 64
    workers: int = 1  # 0 = admission only (test hook: nothing drains)
    batch_max: int = 16
    time_budget_s: float | None = 10.0  # per-job CPU budget; 0 = unbounded CPU
    device: str = "supervised"  # supervised | inline | off
    unbounded_close: bool = False
    out_dir: str = "./porcupine-outputs"
    no_viz: bool = False
    cache_capacity: int = 4096
    spool_dir: str | None = None
    device_rows: int | None = None
    attempt_timeout_s: float = 900.0
    max_restarts: int = 2
    #: structured-events sink: a path, "-" for stderr, or None (silent)
    stats_log: str | None = None
    extra: dict = field(default_factory=dict)


class Verifyd:
    """The daemon.  ``with Verifyd(cfg) as d: ...`` for tests;
    :meth:`serve_forever` for the foreground CLI."""

    def __init__(self, config: VerifydConfig) -> None:
        self.cfg = config
        self._stats_file = None
        sink = None
        if config.stats_log == "-":
            sink = sys.stderr
        elif config.stats_log:
            self._stats_file = open(config.stats_log, "a", encoding="utf-8")
            sink = self._stats_file
        self.stats = ServiceStats(sink)
        self.cache = VerdictCache(config.cache_capacity)
        self.queue = AdmissionQueue(
            config.queue_depth, retry_hint=self.stats.retry_after_hint
        )
        self.scheduler = Scheduler(
            self.queue,
            self.cache,
            self.stats,
            time_budget_s=config.time_budget_s,
            device=config.device,
            unbounded_close=config.unbounded_close,
            batch_max=config.batch_max,
            out_dir=config.out_dir,
            spool_dir=config.spool_dir,
            device_rows=config.device_rows,
            attempt_timeout_s=config.attempt_timeout_s,
            max_restarts=config.max_restarts,
        )
        self._job_ids = itertools.count(1)
        self._thread: threading.Thread | None = None
        self._loop: asyncio.AbstractEventLoop | None = None
        self._started = threading.Event()
        self._stopped = threading.Event()
        self._stop: asyncio.Future | None = None
        self._startup_error: BaseException | None = None

    # -- lifecycle ----------------------------------------------------------

    def __enter__(self) -> "Verifyd":
        self.scheduler.start(self.cfg.workers)
        self.stats.emit(
            "serve_start",
            socket=self.cfg.socket_path,
            workers=self.cfg.workers,
            queue_depth=self.cfg.queue_depth,
            pid=os.getpid(),
        )
        self._thread = threading.Thread(
            target=self._run, name="verifyd-accept", daemon=True
        )
        self._thread.start()
        if not self._started.wait(timeout=10):
            raise RuntimeError(
                f"verifyd failed to start on {self.cfg.socket_path}"
            )
        if self._startup_error is not None:
            raise RuntimeError(
                f"verifyd failed to start on {self.cfg.socket_path}"
            ) from self._startup_error
        return self

    def __exit__(self, *exc) -> None:
        self.request_stop()
        if self._thread is not None:
            self._thread.join(timeout=10)
        self.scheduler.stop()
        self.stats.emit("serve_stop", **self.stats.snapshot())
        if self._stats_file is not None:
            with contextlib.suppress(OSError):
                self._stats_file.close()
        with contextlib.suppress(FileNotFoundError):
            os.remove(self.cfg.socket_path)

    def request_stop(self) -> None:
        """Thread-safe stop trigger (shutdown op, signal handler)."""
        self._stopped.set()
        if self._loop is not None and self._stop is not None:
            def _finish() -> None:
                if not self._stop.done():
                    self._stop.set_result(None)

            with contextlib.suppress(RuntimeError):
                self._loop.call_soon_threadsafe(_finish)

    def wait(self) -> None:
        """Block until a shutdown request (or KeyboardInterrupt)."""
        try:
            self._stopped.wait()
        except KeyboardInterrupt:
            pass

    def serve_forever(self) -> int:
        with self:
            log.info(
                "verifyd listening on %s (queue depth %d, %d workers, "
                "device=%s)",
                self.cfg.socket_path,
                self.cfg.queue_depth,
                self.cfg.workers,
                self.cfg.device,
            )
            self.wait()
        return 0

    def _run(self) -> None:
        try:
            asyncio.run(self._main())
        except BaseException as e:
            self._startup_error = e
        finally:
            self._started.set()
            self._stopped.set()

    async def _main(self) -> None:
        self._loop = asyncio.get_running_loop()
        self._stop = self._loop.create_future()
        server = await asyncio.start_unix_server(
            self._handle, path=self.cfg.socket_path
        )
        self._started.set()
        try:
            await self._stop
        finally:
            server.close()
            await server.wait_closed()

    # -- connection handling ------------------------------------------------

    async def _handle(
        self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter
    ) -> None:
        try:
            while line := await reader.readline():
                try:
                    req = decode_frame(line)
                except ValueError as e:
                    resp = err(ERR_DECODE, f"malformed frame: {e}")
                else:
                    resp = await self._dispatch(req)
                writer.write(encode_frame(resp))
                await writer.drain()
        except (ConnectionResetError, BrokenPipeError):
            pass
        finally:
            writer.close()
            with contextlib.suppress(Exception):
                await writer.wait_closed()

    async def _dispatch(self, req: dict) -> dict:
        op = req.get("op")
        try:
            if op == "ping":
                return ok(
                    {
                        "server": "verifyd",
                        "version": _version.__version__,
                        "protocol": PROTOCOL_VERSION,
                        "pid": os.getpid(),
                    }
                )
            if op == "stats":
                snap = self.stats.snapshot()
                snap["queue_depth_now"] = len(self.queue)
                snap["cache_entries"] = len(self.cache)
                return ok(snap)
            if op == "shutdown":
                self.request_stop()
                return ok({"stopping": True})
            if op == "submit":
                return await self._submit(req)
            return err(ERR_DECODE, f"unknown op {op!r}")
        except Exception as e:  # protocol handler must never kill the loop
            log.exception("dispatch failed for op %r", op)
            return err(ERR_INTERNAL, repr(e))

    async def _submit(self, req: dict) -> dict:
        text = req.get("history")
        if not isinstance(text, str) or not text.strip():
            self.stats.emit("decode_error", reason="missing history")
            return err(ERR_DECODE, "submit needs a non-empty 'history' JSONL string")
        client = str(req.get("client") or "anon")
        try:
            priority = int(req.get("priority", 10))
        except (TypeError, ValueError):
            return err(ERR_DECODE, f"priority must be an int, got {req.get('priority')!r}")
        no_viz = bool(req.get("no_viz", self.cfg.no_viz))

        try:
            events = list(ev.iter_history(text))
            hist = prepare(events, elide_trivial=True)
        except (ev.DecodeError, ValueError) as e:
            self.stats.emit("decode_error", client=client, reason=str(e)[:200])
            return err(ERR_DECODE, str(e))

        fingerprint = history_fingerprint(hist)
        cached = self.cache.get(fingerprint)
        if cached is not None:
            self.stats.emit(
                "cache_hit", stage="admission", client=client, fingerprint=fingerprint
            )
            cached.update(cached=True, queue_wait_s=0.0)
            return ok(cached)

        job = Job(
            id=next(self._job_ids),
            client=client,
            priority=priority,
            shape=shape_key(hist),
            fingerprint=fingerprint,
            events=events,
            hist=hist,
            no_viz=no_viz,
        )
        fut: asyncio.Future = self._loop.create_future()

        def _resolve(reply: dict) -> None:
            def _finish() -> None:
                if not fut.done():
                    fut.set_result(reply)

            with contextlib.suppress(RuntimeError):  # loop already closed
                self._loop.call_soon_threadsafe(_finish)

        job.resolve = _resolve
        try:
            depth = self.queue.put(job)
        except QueueFull as e:
            self.stats.emit(
                "reject",
                client=client,
                priority=priority,
                depth=e.depth,
                retry_after_s=e.retry_after_s,
            )
            return err(
                ERR_QUEUE_FULL,
                str(e),
                retry_after_s=e.retry_after_s,
                depth=e.depth,
            )
        except RuntimeError as e:  # queue closed: daemon is stopping
            return err(ERR_SHUTTING_DOWN, str(e))
        self.stats.emit(
            "admit",
            job=job.id,
            client=client,
            priority=priority,
            shape=job.shape,
            depth=depth,
        )
        return await fut
