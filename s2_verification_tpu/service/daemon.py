"""verifyd: the resident verification daemon.

Serves the :mod:`.protocol` over a unix-domain socket and, optionally, an
HMAC-authenticated TCP listener (``VerifydConfig.tcp`` + ``secret``) so
collectors on other machines can submit.  Like the collector's loopback
S2 server (``collector/socket_s2.py``), the asyncio acceptor runs a
private event loop on a daemon thread, so the daemon composes as a
context manager in tests and as a foreground process under
``s2-verification-tpu serve``.  Checking itself never runs on the event
loop: the acceptor only decodes, consults the verdict cache, and admits
into the bounded queue; :class:`~.scheduler.Scheduler` worker threads do
the searching and resolve each submit's deferred reply through
``call_soon_threadsafe``.

Durability (``VerifydConfig.state_dir``): the verdict cache spills to
CRC-checked segment files (``<state_dir>/verdicts/``) and admission
write-ahead records to a journal (``<state_dir>/journal/``).  Startup
replays both — previously decided fingerprints answer warm without a
checker, and accepted-but-unanswered jobs from a crashed run are
re-admitted (``orphan`` stats events) instead of silently dropped.  This
is the crash→bounded-child→resume discipline ``checker/resilient.py``
applies to the TPU worker, extended to the daemon's own state.
"""

from __future__ import annotations

import asyncio
import contextlib
import functools
import itertools
import json
import logging
import os
import platform
import sys
import threading
import time
from dataclasses import dataclass, field

from .. import version as _version
from ..checker.entries import prepare
from ..obs.alerts import AlertEngine, builtin_rules, parse_rule
from ..obs.archive import ARCHIVE_SUBDIR, ProfileArchive
from ..obs.context import TRACE_FIELD, new_trace_id, parse_trace_frame
from ..obs.dashboard import Dashboard
from ..obs.flight import FLIGHT_SUBDIR, FlightRecorder
from ..obs.health import SLOConfig, SLOHealth
from ..obs.httpd import MetricsServer
from ..obs.introspect import INTROSPECTOR, ResourceSampler
from ..obs.log import StructuredLogger
from ..obs.metrics import MetricsRegistry
from ..obs.sentinel import PerfSentinel, SentinelConfig, seed_from_telemetry
from ..obs.trace import Tracer
from ..obs.tsdb import TelemetryStore
from ..obs.tsdb import default_dir as telemetry_default_dir
from ..obs.tsdb import tsq_request
from ..utils import events as ev
from .cache import VerdictCache, history_fingerprint
from .distsearch import pack_states
from .fastprep import FastPrepFallback, fast_prepare
from .journal import JobJournal
from .overload import (
    AdmissionController,
    CancelToken,
    DegradedWriter,
    QuarantineStore,
)
from ..checker.prefix import PrefixCarry
from .prefixstore import (
    PREFIX_SUBDIR,
    PrefixPlan,
    PrefixStore,
    make_entry,
    plan_for_submit,
    plan_for_window,
)
from .protocol import (
    ERR_AUTH,
    ERR_DEADLINE,
    ERR_DECODE,
    ERR_EPOCH,
    ERR_FRAME,
    ERR_FRONTIER,
    ERR_INTERNAL,
    ERR_QUARANTINED,
    ERR_QUEUE_FULL,
    ERR_SHUTTING_DOWN,
    ERR_TOO_LARGE,
    ERR_UNKNOWN_JOB,
    MAX_FRAME_BYTES,
    PROTOCOL_VERSION,
    decode_frame,
    encode_frame,
    err,
    ok,
    parse_hostport,
    sign_frame,
    verify_frame,
)
from .queue import AdmissionQueue, Job, QueueFull
from .scheduler import Scheduler, shape_key
from .stats import ServiceStats

__all__ = ["VerifydConfig", "Verifyd"]

log = logging.getLogger("s2_verification_tpu.verifyd")


@dataclass
class VerifydConfig:
    socket_path: str
    queue_depth: int = 64
    workers: int = 1  # 0 = admission only (test hook: nothing drains)
    batch_max: int = 16
    time_budget_s: float | None = 10.0  # per-job CPU budget; 0 = unbounded CPU
    device: str = "supervised"  # supervised | inline | off
    unbounded_close: bool = False
    out_dir: str = "./porcupine-outputs"
    no_viz: bool = False
    cache_capacity: int = 4096
    spool_dir: str | None = None
    device_rows: int | None = None
    attempt_timeout_s: float = 900.0
    max_restarts: int = 2
    #: structured-events sink: a path, "-" for stderr, or None (silent)
    stats_log: str | None = None
    #: "host:port" for the authenticated TCP listener (port 0 = ephemeral,
    #: bound port on :attr:`Verifyd.tcp_port`); requires ``secret``
    tcp: str | None = None
    #: shared secret for TCP frame HMACs; the unix socket never needs it
    secret: bytes | None = None
    #: per-frame read bound; oversized frames get a definite FrameTooLarge
    frame_max_bytes: int = MAX_FRAME_BYTES
    #: TCP per-frame *read* deadline (slowloris bound) — the deferred
    #: submit reply is bounded by the scheduler's budgets, not this
    conn_deadline_s: float = 30.0
    #: graceful-drain budget (``serve --drain-timeout``): on SIGTERM or a
    #: drain-flagged shutdown op, stop admitting, let queued + in-flight
    #: jobs finish up to this many seconds, close the journal cleanly,
    #: then stop.  0 keeps the historical behavior (immediate stop) —
    #: the router's rolling restart needs this > 0
    drain_timeout_s: float = 0.0
    #: durable-state root (verdict segments + admission journal); None =
    #: in-memory only, the pre-durability behavior
    state_dir: str | None = None
    #: fsync every durable append (survives machine crash, not just
    #: process death); off by default — SIGKILL safety needs only flush
    fsync: bool = False
    #: Prometheus /metrics HTTP listener port; None = no listener, 0 =
    #: ephemeral (bound port on :attr:`Verifyd.metrics_port`)
    metrics_port: int | None = None
    #: span-ring capacity for the in-process tracer (`trace` op / CLI
    #: export); 0 disables tracing entirely
    trace_capacity: int = 8192
    #: attach per-job search profiles (FrontierStats timeline, native
    #: phase attribution) to `done` events and submit replies
    profile: bool = False
    #: size of the device pool for mesh-sharded escalations; None = the
    #: single-chip path (no pool).  The daemon only tracks abstract slot
    #: indices — device objects are resolved by escalation children
    mesh_devices: int | None = None
    #: how long an escalation waits for a lease before running unsharded
    lease_timeout_s: float = 120.0
    #: structured-log line format for daemon diagnostics (and the
    #: stats_log="-" fallback): "text" or "json"
    log_format: str = "text"
    #: SLO availability target driving /healthz and the burn-rate breach
    #: events; 1.0 disables burn math (never degraded by errors)
    slo_target: float = 0.99
    #: end-to-end latency target (p95 on the short window) for /healthz
    slo_latency_target_s: float = 5.0
    #: alert webhook URL (alertmanager-compatible POST target); None
    #: disables the alert engine entirely
    alert_url: str | None = None
    #: extra --alert-rule specs (see obs/alerts.parse_rule); the
    #: slo_breach + perf_regression built-ins always apply
    alert_rules: tuple = ()
    #: per-rule alert dedup window (a flapping signal pages once per
    #: window; the rest count as suppressed)
    alert_dedup_s: float = 300.0
    #: delivery retries after the first attempt (exponential backoff
    #: with full jitter between them)
    alert_retries: int = 4
    alert_backoff_s: float = 0.5
    #: perf sentinel drift band: fire when a shape's wall time exceeds
    #: its EWMA baseline by this fraction for consecutive jobs; <= 0
    #: disables the sentinel
    sentinel_band: float = 0.75
    #: sentinel cold-start guard: per-shape jobs folded before judging
    sentinel_min_samples: int = 8
    #: resource-telemetry sampling interval (RSS, CPU, fds, threads, GC
    #: pauses, device memory → gauges + flight ring); <= 0 disables
    resource_sample_s: float = 1.0
    #: retained resource samples in the in-memory ring
    resource_capacity: int = 600
    #: latched retrace_storm trip point: a shape recompiling one jit
    #: site more than this many times emits the event once
    retrace_storm_threshold: int = 5
    #: /dashboard scrape-ring tick (sparkline resolution); <= 0 disables
    #: the dashboard even when the metrics listener is up
    dashboard_sample_s: float = 2.0
    #: retained dashboard ticks (sparkline history length)
    dashboard_capacity: int = 240
    #: durable telemetry store root; None = <state_dir>/telemetry when a
    #: state dir is set, else disabled.  Registry snapshots are
    #: delta-encoded into multi-resolution seglog rings that survive
    #: restarts (the ``tsq`` op / CLI and sentinel re-seeding read them)
    telemetry_dir: str | None = None
    #: telemetry sampling cadence (raw ring tick; the 1m/15m rings
    #: downsample from it); <= 0 disables recording entirely
    telemetry_sample_s: float = 2.0
    #: RSS watermark for the admission controller, as a fraction of
    #: MemTotal: submits arriving past it are shed with an honest
    #: retry_after instead of queued; <= 0 disables pressure shedding
    max_rss_frac: float = 0.0
    #: SIGTERM→SIGKILL grace for cancelled supervised children (also the
    #: slack a 2 s-deadline job gets to actually free its worker)
    deadline_grace_s: float = 2.0
    #: process deaths / child kills per fingerprint before quarantine
    quarantine_threshold: int = 3
    #: fused single-pass admission (service/fastprep.py): parse, pair,
    #: validate and prepare in one walk, falling back to the layered
    #: decode path on any anomaly (identical errors, just slower)
    fast_admission: bool = True
    #: continuous cross-job batching: shape groups run as mega-launches
    #: (service/batcher.py) with late-join and per-lane attribution
    batching: bool = False
    #: lane engine for mega-launches: auto | native | vmap
    batch_engine: str = "auto"
    #: incremental prefix verification (service/prefixstore.py): probe
    #: incoming histories for cached prefixes, snapshot OK searches at
    #: closed op boundaries, and serve the ``follow`` op.  Opt-in
    #: (``serve --prefix``): planned jobs run the resumable host-frontier
    #: engine instead of the native/oracle portfolio.
    prefix_enabled: bool = False
    #: in-memory prefix-store entries (LRU); disk is bounded separately
    #: by segment rotation
    prefix_capacity: int = 2048
    #: histories below this many ops never probe or snapshot (the cold
    #: engines already answer them faster than a store round-trip)
    prefix_min_ops: int = 4
    #: snapshot cuts collected per OK search (probes still check every
    #: closed boundary — lookups are cheap, snapshots are not)
    prefix_cuts: int = 8
    #: prefix-store segment-rotation bound under <state_dir>/prefix/
    prefix_max_segments: int = 8
    #: progress-heartbeat cadence per job (checker/progress.ProgressSink
    #: time gate, the `watch` op's data source); <= 0 disables heartbeats
    #: entirely — engines then run exactly the pre-progress code path
    progress_interval_s: float = 0.5
    #: verdict-exact search pruning (``serve --prune``): append
    #: rank-order, eager-commit and tail-pin rules on every engine that
    #: carries them (checker/prune.py); never changes a verdict
    prune: bool = False
    #: speculative multi-layer frontier expansion depth for device
    #: escalations (``serve --speculate-depth``); 0 = off
    speculate_depth: int = 0
    extra: dict = field(default_factory=dict)


class Verifyd:
    """The daemon.  ``with Verifyd(cfg) as d: ...`` for tests;
    :meth:`serve_forever` for the foreground CLI."""

    def __init__(self, config: VerifydConfig) -> None:
        self.cfg = config
        if config.tcp is not None and not config.secret:
            raise ValueError(
                "a TCP listener requires a shared secret (VerifydConfig.secret)"
            )
        self.logger = StructuredLogger(
            sys.stderr, fmt=config.log_format, component="verifyd"
        )
        self._stats_file = None
        sink = None
        stats_logger = None
        if config.stats_log == "-":
            # The old ad-hoc raw-stderr sink: events now flow through the
            # structured logger so they share format + stream with every
            # other daemon diagnostic.
            stats_logger = self.logger
        elif config.stats_log:
            self._stats_file = open(config.stats_log, "a", encoding="utf-8")
            sink = self._stats_file
        self.registry = MetricsRegistry()
        self.tracer = Tracer(config.trace_capacity)
        self.tracer.name_track(0, "admission")
        self._m_trace_dropped = self.registry.counter(
            "verifyd_trace_spans_dropped_total",
            "Spans evicted from the saturated trace ring (timelines truncated)",
        )
        self._m_trace_dropped.inc(0)
        self.tracer.drop_hook = lambda _total: self._m_trace_dropped.inc()
        # Info-style gauge (constant 1): build identity rides the label
        # set, so scrapes and the fleet plane can tell nodes apart.
        self.registry.gauge(
            "verifyd_build_info",
            "Build identity (value is always 1; the labels carry it)",
            labelnames=("version", "backend", "python"),
        ).set(
            1.0,
            version=_version.__version__,
            backend=config.device,
            python=platform.python_version(),
        )
        self.health = SLOHealth(
            SLOConfig(
                availability_target=config.slo_target,
                latency_target_s=config.slo_latency_target_s,
            ),
            registry=self.registry,
        )
        self.flight = None
        self.archive = None
        if config.state_dir:
            self.flight = FlightRecorder(
                os.path.join(config.state_dir, FLIGHT_SUBDIR), fsync=config.fsync
            )
            self.tracer.span_hook = self.flight.record_span
            self.archive = ProfileArchive(
                os.path.join(config.state_dir, ARCHIVE_SUBDIR),
                fsync=config.fsync,
            )
        self.sentinel = None
        if config.sentinel_band > 0:
            self.sentinel = PerfSentinel(
                SentinelConfig(
                    band=config.sentinel_band,
                    min_samples=config.sentinel_min_samples,
                ),
                registry=self.registry,
            )
        self.alerts = None
        if config.alert_url:
            # User rules extend (never replace) the built-ins; a repeated
            # spec keeps one state slot.
            rules = {r.name: r for r in builtin_rules()}
            for spec in config.alert_rules:
                rule = parse_rule(spec)
                rules[rule.name] = rule
            self.alerts = AlertEngine(
                config.alert_url,
                rules.values(),
                registry=self.registry,
                recorder=self.flight,
                retries=config.alert_retries,
                backoff_s=config.alert_backoff_s,
                dedup_s=config.alert_dedup_s,
            )
        self.stats = ServiceStats(
            sink,
            registry=self.registry,
            health=self.health,
            recorder=self.flight,
            logger=stats_logger,
            alerts=self.alerts,
            archive=self.archive,
            sentinel=self.sentinel,
        )
        # Runtime introspection: point the process-global JIT tracker at
        # this daemon's registry + event stream (retrace_storm rides the
        # stream into the alert engine like every other signal), and arm
        # the resource sampler feeding gauges + the flight ring.
        INTROSPECTOR.attach(
            registry=self.registry,
            stats=self.stats,
            storm_threshold=config.retrace_storm_threshold,
        )
        # Disk-full degradation: every persistent writer routes its appends
        # through a DegradedWriter, so ENOSPC degrades the feature (dropped
        # flight/archive records, memory-only cache, non-durable journal)
        # instead of taking the daemon down.  flight/archive are built
        # before stats exists, so their writers attach post-hoc.
        if self.flight is not None:
            self.flight.writer = DegradedWriter("flight", self.stats)
        if self.archive is not None:
            self.archive.writer = DegradedWriter("archive", self.stats)
        self.sampler = None
        if config.resource_sample_s > 0:
            self.sampler = ResourceSampler(
                self.registry,
                interval_s=config.resource_sample_s,
                capacity=config.resource_capacity,
                recorder=self.flight,
            )
        self.dashboard = None
        # Durable telemetry: periodic registry snapshots delta-encoded
        # into multi-resolution seglog rings.  Built after stats (the
        # degraded-writer + telemetry_loaded sinks) and after the
        # sentinel, whose baselines the previous run's history re-seeds —
        # a slowdown across a restart still fires perf_regression.
        self.telemetry = None
        self._telemetry_dir = None
        if config.telemetry_sample_s > 0:
            tdir = config.telemetry_dir or (
                telemetry_default_dir(config.state_dir)
                if config.state_dir
                else None
            )
            if tdir:
                self._telemetry_dir = tdir
                self.telemetry = TelemetryStore(
                    tdir,
                    self.registry,
                    sample_s=config.telemetry_sample_s,
                    fsync=config.fsync,
                )
                self.telemetry.writer = DegradedWriter("telemetry", self.stats)
                seeded = 0
                if self.sentinel is not None:
                    _boot_t, boot_values = self.telemetry.boot_values()
                    seeded = seed_from_telemetry(self.sentinel, boot_values)
                recs = self.telemetry.recovery_summary().values()
                self.stats.emit(
                    "telemetry_loaded",
                    dir=tdir,
                    records=sum(r["records"] for r in recs),
                    segments=sum(r["segments"] for r in recs),
                    torn_tail_bytes=sum(r["torn_tail_bytes"] for r in recs),
                    bad_segments=sum(r["bad_segments"] for r in recs),
                    baselines_seeded=seeded,
                )
        verdict_dir = (
            os.path.join(config.state_dir, "verdicts") if config.state_dir else None
        )
        self._cache_writer = (
            DegradedWriter("cache", self.stats) if verdict_dir is not None else None
        )
        self.cache = VerdictCache(
            config.cache_capacity,
            verdict_dir,
            fsync=config.fsync,
            writer=self._cache_writer,
        )
        if verdict_dir is not None:
            rec = self.cache.recovery
            self.stats.emit(
                "cache_loaded",
                entries=self.cache.loaded,
                segments=rec.segments if rec else 0,
                torn_tail_bytes=rec.torn_tail_bytes if rec else 0,
                bad_segments=rec.bad_segments if rec else 0,
            )
        self.prefix = None
        self._prefix_writer = None
        if config.prefix_enabled:
            prefix_dir = (
                os.path.join(config.state_dir, PREFIX_SUBDIR)
                if config.state_dir
                else None
            )
            self._prefix_writer = (
                DegradedWriter("prefix", self.stats)
                if prefix_dir is not None
                else None
            )
            self.prefix = PrefixStore(
                config.prefix_capacity,
                prefix_dir,
                fsync=config.fsync,
                max_segments=config.prefix_max_segments,
                writer=self._prefix_writer,
            )
            if prefix_dir is not None:
                rec = self.prefix.recovery
                self.stats.emit(
                    "prefix_loaded",
                    entries=self.prefix.loaded,
                    bytes=self.prefix.bytes,
                    segments=rec.segments if rec else 0,
                    torn_tail_bytes=rec.torn_tail_bytes if rec else 0,
                    bad_segments=rec.bad_segments if rec else 0,
                )
        self.journal = (
            JobJournal(os.path.join(config.state_dir, "journal"), fsync=config.fsync)
            if config.state_dir
            else None
        )
        self._journal_writer = None
        if self.journal is not None:
            # Journal ENOSPC is the one degradation the client must *see*:
            # replies carry durable=false, /healthz goes unhealthy with the
            # reason, and the writer_degraded alert fires.  Recovery (disk
            # freed, reprobe write succeeds) re-arms durability and clears
            # the health reason.
            self._journal_writer = DegradedWriter(
                "journal",
                self.stats,
                on_degrade=lambda e: self.health.set_degraded("journal", error=e),
                on_recover=lambda: self.health.clear_degraded("journal"),
            )
        self.quarantine = None
        if config.state_dir:
            self.quarantine = QuarantineStore(
                os.path.join(config.state_dir, "quarantine"),
                threshold=config.quarantine_threshold,
                stats=self.stats,
            )
            self.stats.set_quarantine_size(len(self.quarantine))
        self.queue = AdmissionQueue(
            config.queue_depth, retry_hint=self.stats.retry_after_hint
        )
        self.device_pool = None
        if config.mesh_devices and config.device != "off":
            from .devicepool import DevicePool

            self.device_pool = DevicePool(
                config.mesh_devices, stats=self.stats
            )
        # retry_after hints fold supervised lease-wait estimates in: the
        # stats object reads pool waiters straight off this snapshot.
        self.stats.device_pool = self.device_pool
        self.admission = AdmissionController(
            self.stats,
            max_rss_frac=config.max_rss_frac,
            sampler=self.sampler,
        )
        self.progress = None
        if config.progress_interval_s > 0:
            from .progress import JobProgress

            self.progress = JobProgress(
                interval_s=config.progress_interval_s,
                on_heartbeat=self._emit_progress,
            )
        self.scheduler = Scheduler(
            self.queue,
            self.cache,
            self.stats,
            time_budget_s=config.time_budget_s,
            device=config.device,
            unbounded_close=config.unbounded_close,
            batch_max=config.batch_max,
            out_dir=config.out_dir,
            spool_dir=config.spool_dir,
            device_rows=config.device_rows,
            attempt_timeout_s=config.attempt_timeout_s,
            max_restarts=config.max_restarts,
            journal=self.journal,
            tracer=self.tracer,
            profile=config.profile,
            device_pool=self.device_pool,
            lease_timeout_s=config.lease_timeout_s,
            journal_writer=self._journal_writer,
            quarantine=self.quarantine,
            cancel_grace_s=config.deadline_grace_s,
            batching=config.batching,
            batch_engine=config.batch_engine,
            prefix_store=self.prefix,
            progress=self.progress,
            prune=config.prune,
            speculate_depth=config.speculate_depth,
        )
        self._job_ids = itertools.count(1)
        #: distributed-search partition grants: (search, part) -> epoch.
        #: Bounded (oldest evicted) — a coordinator that never closes its
        #: grants must not leak backend memory.  Loop-thread owned.
        self._grants: dict[tuple[str, str], int] = {}
        #: in-flight partition jobs by (search, part), for revocation
        self._part_jobs: dict[tuple[str, str], CancelToken] = {}
        #: submits between dispatch and reply-written (loop thread owns
        #: the writes; the drain poller only reads)
        self._inflight = 0
        self._drain_lock = threading.Lock()
        self._draining = False
        self._drain_thread: threading.Thread | None = None
        self._thread: threading.Thread | None = None
        self._loop: asyncio.AbstractEventLoop | None = None
        self._started = threading.Event()
        self._stopped = threading.Event()
        self._stop: asyncio.Future | None = None
        self._startup_error: BaseException | None = None
        #: bound port of the TCP listener (set before __enter__ returns)
        self.tcp_port: int | None = None
        #: bound port of the /metrics listener (set in __enter__)
        self.metrics_port: int | None = None
        self._metrics_server: MetricsServer | None = None

    # -- lifecycle ----------------------------------------------------------

    def __enter__(self) -> "Verifyd":
        if self.sampler is not None:
            self.sampler.start()
        if self.telemetry is not None:
            self.telemetry.start()
        if self.cfg.metrics_port is not None:
            if self.cfg.dashboard_sample_s > 0:
                self.dashboard = Dashboard(
                    self.registry,
                    health=self.health,
                    sampler=self.sampler,
                    interval_s=self.cfg.dashboard_sample_s,
                    capacity=self.cfg.dashboard_capacity,
                    progress_fn=(
                        self.progress.rows
                        if self.progress is not None
                        else None
                    ),
                ).start()
            self._metrics_server = MetricsServer(
                self.registry,
                self.cfg.metrics_port,
                health=self.health,
                sentinel=self.sentinel,
                dashboard=self.dashboard,
            )
            self.metrics_port = self._metrics_server.port
        self._recover_orphans()
        self.scheduler.start(self.cfg.workers)
        self.stats.emit(
            "serve_start",
            socket=self.cfg.socket_path,
            tcp=self.cfg.tcp,
            workers=self.cfg.workers,
            queue_depth=self.cfg.queue_depth,
            pid=os.getpid(),
            mesh_devices=self.cfg.mesh_devices,
        )
        self._thread = threading.Thread(
            target=self._run, name="verifyd-accept", daemon=True
        )
        self._thread.start()
        if not self._started.wait(timeout=10):
            raise RuntimeError(
                f"verifyd failed to start on {self.cfg.socket_path}"
            )
        if self._startup_error is not None:
            raise RuntimeError(
                f"verifyd failed to start on {self.cfg.socket_path}"
            ) from self._startup_error
        return self

    def __exit__(self, *exc) -> None:
        self.request_stop()
        if self._thread is not None:
            self._thread.join(timeout=10)
        self.scheduler.stop()
        if self._metrics_server is not None:
            self._metrics_server.close()
        if self.dashboard is not None:
            self.dashboard.close()
        if self.sampler is not None:
            # Final sample first: the flight ring's last resource record
            # should reflect the moment of shutdown, not a second before.
            with contextlib.suppress(Exception):
                self.sampler.sample_once()
            self.sampler.close()
        if self.telemetry is not None:
            # Close takes a final sample and flushes the pending coarse
            # buckets, so the history's last point is the shutdown state.
            with contextlib.suppress(Exception):
                self.telemetry.close()
        self.stats.emit("serve_stop", **self.stats.snapshot())
        self.dump_flight("shutdown")
        if self.alerts is not None:
            # Drain pending deliveries while the flight ring can still
            # absorb alert_failed markers.
            self.alerts.close()
        if self.flight is not None:
            self.flight.close()
        if self.archive is not None:
            self.archive.close()
        self.cache.close()
        if self.prefix is not None:
            self.prefix.close()
        if self.journal is not None:
            self.journal.close()
        if self._stats_file is not None:
            with contextlib.suppress(OSError):
                self._stats_file.close()
        with contextlib.suppress(FileNotFoundError):
            os.remove(self.cfg.socket_path)

    def _recover_orphans(self) -> None:
        """Journal replay: re-admit jobs a previous run accepted but never
        answered.  Runs before the acceptor and the workers start, so
        recovered jobs are first in line; their verdicts land in the
        (durable) cache, which is what answers the submitter's retry."""
        if self.journal is None:
            return
        for rec in self.journal.orphans():
            text = rec.get("history", "")
            fp = str(rec.get("fp") or "")
            if self.quarantine is not None and fp:
                # Poison accounting BEFORE re-admission: an orphan a worker
                # had *started* (journal "run" record) when the process
                # died is one crash against its fingerprint.  Queued-only
                # orphans are innocent bystanders — replayed for free.
                if rec.get("started"):
                    self.quarantine.note_crash(fp, kind="boot")
                if self.quarantine.is_quarantined(fp):
                    self.stats.emit(
                        "orphan_quarantined",
                        fingerprint=fp,
                        client=rec.get("client"),
                        crashes=self.quarantine.crash_count(fp),
                    )
                    continue  # compact() below drops the accept for good
            try:
                events = list(ev.iter_history(text))
                hist = prepare(events, elide_trivial=True)
            except (ev.DecodeError, ValueError) as e:
                self.stats.emit(
                    "orphan_invalid",
                    fingerprint=rec.get("fp"),
                    client=rec.get("client"),
                    reason=str(e)[:200],
                )
                continue
            job = Job(
                id=next(self._job_ids),
                client=str(rec.get("client") or "anon"),
                priority=int(rec.get("priority") or 10),
                shape=shape_key(hist),
                fingerprint=history_fingerprint(hist),
                events=events,
                hist=hist,
                no_viz=True,  # the submitter is gone; re-run for the verdict
                trace_id=new_trace_id(),
            )
            self.journal.accept(
                job=job.id,
                fingerprint=job.fingerprint,
                client=job.client,
                priority=job.priority,
                history=text,
            )
            if self.archive is not None:
                self.archive.add_history(job.fingerprint, text)
            job.enqueued_at = self.tracer.now()
            try:
                self.queue.put(job)
            except QueueFull:
                # Reported, not silent — and the journal still holds the
                # accept, so the *next* restart retries the re-admission.
                self.stats.emit(
                    "orphan_dropped", job=job.id, fingerprint=job.fingerprint
                )
                continue
            self.stats.emit(
                "orphan",
                job=job.id,
                fingerprint=job.fingerprint,
                client=job.client,
                from_boot=rec.get("boot"),
            )
        self.journal.compact()

    def dump_flight(self, reason: str) -> None:
        """Write a flight-recorder marker with the SLO picture at this
        instant (shutdown path, SIGTERM handler).  Safe without a state
        dir (no-op) and safe to call more than once."""
        if self.flight is not None:
            self.flight.dump(reason, slo=self.health.snapshot())

    def request_drain(self, timeout_s: float | None = None) -> float:
        """Graceful drain, then stop (drain-flagged shutdown op, SIGTERM
        under ``serve --drain-timeout``).  Thread-safe and idempotent.

        Closes the admission queue immediately — new submits answer
        ``ShuttingDown``, workers finish what is queued — then a
        background thread waits until every dispatched submit has its
        reply written, the queue is empty, and no worker holds an active
        job (or the budget runs out), and finally triggers the normal
        stop path, which closes the journal and verdict segments
        cleanly.  Cache hits keep answering throughout: they touch no
        queue slot and cost nothing.  Returns the effective budget.
        """
        t = float(
            timeout_s
            if timeout_s is not None
            else (self.cfg.drain_timeout_s or 30.0)
        )
        with self._drain_lock:
            if self._draining:
                return t
            self._draining = True

        def _drain() -> None:
            self.queue.close()
            self.stats.emit(
                "drain_start",
                queued=len(self.queue),
                inflight=self._inflight,
                active=self.stats.active,
                timeout_s=t,
            )
            t0 = time.monotonic()
            while time.monotonic() - t0 < t:
                if (
                    self._inflight == 0
                    and len(self.queue) == 0
                    and self.stats.active == 0
                ):
                    break
                time.sleep(0.05)
            waited = time.monotonic() - t0
            clean = (
                self._inflight == 0
                and len(self.queue) == 0
                and self.stats.active == 0
            )
            self.stats.emit(
                "drain_done", waited_s=round(waited, 3), clean=clean
            )
            self.dump_flight("drain")
            self.request_stop()

        self._drain_thread = threading.Thread(
            target=_drain, name="verifyd-drain", daemon=True
        )
        self._drain_thread.start()
        return t

    def request_stop(self) -> None:
        """Thread-safe stop trigger (shutdown op, signal handler)."""
        self._stopped.set()
        if self._loop is not None and self._stop is not None:
            def _finish() -> None:
                if not self._stop.done():
                    self._stop.set_result(None)

            with contextlib.suppress(RuntimeError):
                self._loop.call_soon_threadsafe(_finish)

    def wait(self) -> None:
        """Block until a shutdown request (or KeyboardInterrupt)."""
        try:
            self._stopped.wait()
        except KeyboardInterrupt:
            pass

    def serve_forever(self) -> int:
        with self:
            log.info(
                "verifyd listening on %s%s (queue depth %d, %d workers, "
                "device=%s)",
                self.cfg.socket_path,
                f" + tcp {self.cfg.tcp} (port {self.tcp_port})"
                if self.cfg.tcp
                else "",
                self.cfg.queue_depth,
                self.cfg.workers,
                self.cfg.device,
            )
            self.wait()
        return 0

    def _run(self) -> None:
        try:
            asyncio.run(self._main())
        except BaseException as e:
            self._startup_error = e
        finally:
            self._started.set()
            self._stopped.set()

    async def _main(self) -> None:
        self._loop = asyncio.get_running_loop()
        self._stop = self._loop.create_future()
        # The stream limit IS the frame bound: readuntil past it raises
        # LimitOverrunError, answered as a definite FrameTooLarge.
        server = await asyncio.start_unix_server(
            functools.partial(self._handle, secret=None, deadline_s=None),
            path=self.cfg.socket_path,
            limit=self.cfg.frame_max_bytes,
        )
        tcp_server = None
        if self.cfg.tcp is not None:
            host, port = parse_hostport(self.cfg.tcp)
            tcp_server = await asyncio.start_server(
                functools.partial(
                    self._handle,
                    secret=self.cfg.secret,
                    deadline_s=self.cfg.conn_deadline_s,
                ),
                host=host,
                port=port,
                limit=self.cfg.frame_max_bytes,
            )
            self.tcp_port = tcp_server.sockets[0].getsockname()[1]
        self._started.set()
        try:
            await self._stop
        finally:
            server.close()
            await server.wait_closed()
            if tcp_server is not None:
                tcp_server.close()
                await tcp_server.wait_closed()

    # -- connection handling ------------------------------------------------

    async def _read_frame(
        self, reader: asyncio.StreamReader, deadline_s: float | None
    ) -> bytes | None:
        """One frame, bounded in size (stream limit) and, on TCP, in read
        time.  Returns None on clean EOF; raises the caller's per-frame
        protocol failures as marker exceptions."""
        fut = reader.readuntil(b"\n")
        if deadline_s is not None:
            fut = asyncio.wait_for(fut, timeout=deadline_s)
        try:
            return await fut
        except asyncio.IncompleteReadError as e:
            return e.partial or None  # truncated final frame or clean EOF

    async def _handle(
        self,
        reader: asyncio.StreamReader,
        writer: asyncio.StreamWriter,
        *,
        secret: bytes | None,
        deadline_s: float | None,
    ) -> None:
        try:
            while True:
                try:
                    line = await self._read_frame(reader, deadline_s)
                except (asyncio.LimitOverrunError, ValueError):
                    # Oversized frame: a definite protocol error, then
                    # close — the stream cannot be resynced past it.
                    self.stats.emit("frame_error", reason="oversized")
                    resp = err(
                        ERR_TOO_LARGE,
                        f"frame exceeds {self.cfg.frame_max_bytes} bytes",
                    )
                    await self._reply(writer, resp, secret)
                    break
                except asyncio.TimeoutError:
                    self.stats.emit("frame_error", reason="read_deadline")
                    break
                if not line:
                    break
                close_after = False
                inflight = False
                try:
                    try:
                        req = decode_frame(line)
                    except ValueError as e:
                        self.stats.emit("frame_error", reason="decode")
                        resp = err(ERR_FRAME, f"malformed frame: {e}")
                    else:
                        if secret is not None and not verify_frame(req, secret):
                            # Rejected before admission: nothing below the
                            # transport ever sees an unauthenticated frame.
                            peer = writer.get_extra_info("peername")
                            self.stats.emit(
                                "auth_reject", op=req.get("op"), peer=str(peer)
                            )
                            resp = err(ERR_AUTH, "missing or invalid frame auth")
                            close_after = True
                        else:
                            if req.get("op") in ("submit", "follow", "delta"):
                                # Drain counts a submit (or follow window)
                                # until its reply is *written* — an accepted
                                # job whose verdict never reached the
                                # client is a lost job.
                                inflight = True
                                # Single-threaded by construction: every
                                # _handle coroutine runs on the accept
                                # loop's event loop, so +=/-= never
                                # interleave; the drain poller thread only
                                # reads the counter (a stale read just
                                # re-polls).
                                self._inflight += 1  # verifylint: disable=concurrency-unlocked-write
                            resp = await self._dispatch(req, reader)
                    await self._reply(writer, resp, secret)
                finally:
                    if inflight:
                        self._inflight -= 1
                if close_after:
                    break
        except (ConnectionResetError, BrokenPipeError):
            pass
        finally:
            writer.close()
            with contextlib.suppress(Exception):
                await writer.wait_closed()

    async def _reply(
        self, writer: asyncio.StreamWriter, resp: dict, secret: bytes | None
    ) -> None:
        if secret is not None:
            resp = sign_frame(resp, secret)
        writer.write(encode_frame(resp))
        await writer.drain()

    async def _dispatch(
        self, req: dict, reader: asyncio.StreamReader | None = None
    ) -> dict:
        op = req.get("op")
        try:
            if op == "ping":
                return ok(
                    {
                        "server": "verifyd",
                        "version": _version.__version__,
                        "protocol": PROTOCOL_VERSION,
                        "pid": os.getpid(),
                    }
                )
            if op == "stats":
                snap = self.stats.snapshot()
                snap["queue_depth_now"] = len(self.queue)
                snap["cache_entries"] = len(self.cache)
                if self.metrics_port is not None:
                    snap["metrics_port"] = self.metrics_port
                if self.device_pool is not None:
                    snap["device_pool"] = self.device_pool.snapshot()
                introspection: dict = {"jit": INTROSPECTOR.snapshot()}
                if self.sampler is not None:
                    introspection["resources"] = self.sampler.snapshot()
                snap["introspection"] = introspection
                if self.progress is not None:
                    snap["progress"] = self.progress.rows()
                if self.telemetry is not None:
                    snap["telemetry"] = {
                        "dir": self._telemetry_dir,
                        "sample_s": self.cfg.telemetry_sample_s,
                        "recovery": self.telemetry.recovery_summary(),
                    }
                return ok(snap)
            if op == "tsq":
                if self._telemetry_dir is None:
                    return err(
                        ERR_DECODE,
                        "no telemetry store (daemon runs without "
                        "--state-dir or --telemetry-dir)",
                    )
                payload, bad = tsq_request(
                    self._telemetry_dir, req, store=self.telemetry
                )
                if bad is not None:
                    return err(ERR_DECODE, bad)
                return ok(payload)
            if op == "watch":
                return self._watch(req)
            if op == "trace":
                return ok(self.tracer.export())
            if op == "profiles":
                if self.archive is None:
                    return err(
                        ERR_DECODE,
                        "no profile archive (daemon runs without --state-dir)",
                    )
                filters = {}
                for key in ("shape", "backend", "client"):
                    if req.get(key) is not None:
                        filters[key] = str(req[key])
                for key in ("verdict", "slowest", "limit"):
                    if req.get(key) is not None:
                        try:
                            filters[key] = int(req[key])
                        except (TypeError, ValueError):
                            return err(
                                ERR_DECODE, f"{key} must be an int"
                            )
                if req.get("since") is not None:
                    try:
                        filters["since"] = float(req["since"])
                    except (TypeError, ValueError):
                        return err(ERR_DECODE, "since must be a number")
                # Bound the reply frame unless the caller chose a cut.
                if "limit" not in filters and "slowest" not in filters:
                    filters["limit"] = 100
                return ok(
                    {
                        "records": self.archive.query(**filters),
                        "total": len(self.archive),
                    }
                )
            if op == "shutdown":
                if req.get("drain"):
                    tmo = req.get("timeout")
                    try:
                        tmo = float(tmo) if tmo is not None else None
                    except (TypeError, ValueError):
                        return err(ERR_DECODE, "timeout must be a number")
                    effective = self.request_drain(tmo)
                    return ok(
                        {
                            "stopping": True,
                            "draining": True,
                            "timeout_s": effective,
                        }
                    )
                self.request_stop()
                return ok({"stopping": True})
            if op == "quarantine":
                if self.quarantine is None:
                    return err(
                        ERR_DECODE,
                        "no quarantine store (daemon runs without --state-dir)",
                    )
                action = str(req.get("action") or "list")
                if action == "list":
                    return ok(
                        {
                            "entries": self.quarantine.list(),
                            "threshold": self.quarantine.threshold,
                        }
                    )
                fp = str(req.get("fingerprint") or "")
                if not fp:
                    return err(
                        ERR_DECODE, f"quarantine {action!r} needs a fingerprint"
                    )
                if action == "inspect":
                    info = self.quarantine.get(fp)
                    if info is None:
                        return err(ERR_DECODE, f"{fp!r} is not quarantined")
                    return ok(info)
                if action == "release":
                    return ok(
                        {
                            "released": self.quarantine.release(fp),
                            "fingerprint": fp,
                        }
                    )
                return err(ERR_DECODE, f"unknown quarantine action {action!r}")
            if op == "submit":
                return await self._submit(req, reader)
            if op == "follow":
                return await self._follow(req, reader)
            if op == "grant":
                return self._ds_grant(req)
            if op == "delta":
                return await self._ds_delta(req, reader)
            if op == "partition_done":
                return self._ds_done(req)
            return err(ERR_DECODE, f"unknown op {op!r}")
        except Exception as e:  # protocol handler must never kill the loop
            log.exception("dispatch failed for op %r", op)
            return err(ERR_INTERNAL, repr(e))

    def _emit_progress(self, row: dict) -> None:
        """JobProgress heartbeat hook → the ``search_progress`` event
        (flight ring, metrics, archive all ride the normal emit path)."""
        self.stats.emit(
            "search_progress",
            job=row["job"],
            engine=row["engine"],
            ops_committed=row["ops_committed"],
            total_ops=row["total_ops"],
            frontier_width=row["frontier_width"],
            states_expanded=row["states_expanded"],
            layer_rate=row["layer_rate"],
            progress_ratio=row["progress_ratio"],
            eta_s=row["eta_s"],
            fingerprint=row["fingerprint"],
            trace_id=row["trace_id"],
        )

    def _watch(self, req: dict) -> dict:
        """One-shot progress snapshot of running (or just-done) searches.

        Selectors: ``job`` (one id), ``fingerprint`` (verdict-cache key;
        how a distsearch coordinator polls its ``ppart:`` partition
        jobs), ``search`` (+ optional ``part``: every partition of a
        distributed search running here), or none (all active jobs).  A
        named selector with no match is the definite
        :data:`~.protocol.ERR_UNKNOWN_JOB` — the router forwards it
        rather than failing over."""
        if self.progress is None:
            return err(
                ERR_DECODE,
                "progress heartbeats disabled (progress_interval_s <= 0)",
            )
        if req.get("job") is not None:
            try:
                job = int(req["job"])
            except (TypeError, ValueError):
                return err(ERR_DECODE, "job must be an int")
            row = self.progress.get(job)
            if row is None:
                return err(
                    ERR_UNKNOWN_JOB, f"job {job} is not running here", job=job
                )
            return ok({"progress": [row]})
        if req.get("fingerprint") is not None:
            fp = str(req["fingerprint"])
            rows = self.progress.find(fp)
            if not rows:
                return err(
                    ERR_UNKNOWN_JOB, f"no running job for fingerprint {fp!r}"
                )
            return ok({"progress": rows})
        if req.get("search") is not None:
            search = str(req["search"])
            rows = self.progress.find(f"ppart:{search[:16]}/", prefix=True)
            if req.get("part") is not None:
                part = str(req["part"])
                rows = [
                    r for r in rows if r["fingerprint"].rsplit("/", 1)[-1] == part
                ]
            if not rows:
                return err(
                    ERR_UNKNOWN_JOB,
                    f"no partition of search {search[:16]!r} runs here",
                )
            return ok({"progress": rows})
        return ok({"progress": self.progress.rows()})

    def _decode_history(
        self, text, records, client: str
    ) -> tuple[str | None, list, object] | dict:
        """Shared submit/follow decode: validate and prepare one history
        payload, returning ``(text, events, hist)`` or an error frame.

        Fast admission first: one fused parse+pair+validate+build pass
        (service/fastprep.py).  Fallback-not-fork: anything the fast path
        won't vouch for re-runs through the layered decoder below, which
        produces the canonical error message for every rejection.
        """
        if records is not None:
            # Structured submission: the client ships the event records as
            # a JSON array instead of a JSONL string, skipping one
            # serialize/parse round-trip on the hot path.  The journal and
            # corpus archive still get canonical JSONL (``wire_text``).
            if not isinstance(records, list) or not records:
                self.stats.emit("decode_error", reason="bad records")
                return err(
                    ERR_DECODE, "'records' must be a non-empty list of event objects"
                )
            if text is not None:
                return err(ERR_DECODE, "send 'history' or 'records', not both")
        elif not isinstance(text, str) or not text.strip():
            self.stats.emit("decode_error", reason="missing history")
            return err(ERR_DECODE, "submit needs a non-empty 'history' JSONL string")
        prep = None
        if self.cfg.fast_admission:
            try:
                prep = fast_prepare(text=text, records=records)
            except FastPrepFallback:
                prep = None
        if prep is not None:
            events = prep.events
            hist = prep.hist
            if text is None:
                text = prep.wire_text()
            return text, events, hist
        if text is None:
            try:
                text = "\n".join(
                    json.dumps(r, separators=(",", ":")) for r in records
                )
            except (TypeError, ValueError) as e:
                self.stats.emit(
                    "decode_error", client=client, reason=str(e)[:200]
                )
                return err(
                    ERR_DECODE, f"'records' are not JSON-serializable: {e}"
                )
        try:
            events = list(ev.iter_history(text))
            hist = prepare(events, elide_trivial=True)
        except (ev.DecodeError, ValueError) as e:
            self.stats.emit("decode_error", client=client, reason=str(e)[:200])
            return err(ERR_DECODE, str(e))
        return text, events, hist

    async def _submit(
        self, req: dict, reader: asyncio.StreamReader | None = None
    ) -> dict:
        t_recv = self.tracer.now()
        # Distributed trace context: honor a client-minted id (new
        # clients), mint one otherwise (old clients) — every job traces.
        trace_id, sent_wall = parse_trace_frame(req.get(TRACE_FIELD))
        if trace_id is None:
            trace_id = new_trace_id()
        text = req.get("history")
        records = req.get("records")
        client = str(req.get("client") or "anon")
        try:
            priority = int(req.get("priority", 10))
        except (TypeError, ValueError):
            return err(ERR_DECODE, f"priority must be an int, got {req.get('priority')!r}")
        no_viz = bool(req.get("no_viz", self.cfg.no_viz))
        # Remaining end-to-end budget in seconds.  Optional (old clients
        # never send it), HMAC-covered like every frame field, and already
        # decremented by any router hop the frame crossed.
        deadline = req.get("deadline")
        if deadline is not None:
            try:
                deadline = float(deadline)
            except (TypeError, ValueError):
                return err(
                    ERR_DECODE, f"deadline must be a number, got {deadline!r}"
                )

        t_prep0 = self.tracer.now()
        decoded = self._decode_history(text, records, client)
        if isinstance(decoded, dict):
            return decoded
        text, events, hist = decoded
        t_prep1 = self.tracer.now()

        fingerprint = history_fingerprint(hist)
        cached = self.cache.get(fingerprint)
        if cached is not None:
            self.stats.emit(
                "cache_hit",
                stage="admission",
                client=client,
                fingerprint=fingerprint,
                queue_wait_s=0.0,
            )
            self.tracer.add_span(
                "admit",
                t_recv,
                self.tracer.now(),
                tid=0,
                args={"client": client, "cached": True, "trace_id": trace_id},
            )
            cached.update(cached=True, queue_wait_s=0.0, trace_id=trace_id)
            return ok(cached)

        # Admission gates, in cost order, all BEFORE the journal sees the
        # job (a shed admission owes the client nothing on replay):
        # quarantine (definite — the router must not fail it over), dead
        # deadline, then pressure shedding with an honest retry_after.
        if self.quarantine is not None and self.quarantine.is_quarantined(
            fingerprint
        ):
            info = self.quarantine.get(fingerprint) or {}
            self.stats.emit(
                "quarantine_reject",
                client=client,
                fingerprint=fingerprint,
                crashes=info.get("crashes", 0),
                trace_id=trace_id,
            )
            return err(
                ERR_QUARANTINED,
                f"fingerprint {fingerprint[:12]} is quarantined after "
                f"{info.get('crashes', 0)} crash(es); "
                "`s2-verification-tpu quarantine release` re-admits it",
                fingerprint=fingerprint,
                crashes=info.get("crashes", 0),
            )
        shape = shape_key(hist)
        if deadline is not None and deadline <= 0:
            self.stats.emit(
                "admission_shed",
                reason="deadline",
                client=client,
                trace_id=trace_id,
            )
            return err(
                ERR_DEADLINE,
                "deadline already expired at admission",
                reason="deadline",
            )
        shed = self.admission.decide(
            queue_depth=len(self.queue), deadline_s=deadline, shape=shape
        )
        if shed is not None:
            self.stats.emit(
                "admission_shed",
                reason=shed,
                client=client,
                depth=len(self.queue),
                trace_id=trace_id,
            )
            if shed == "deadline":
                return err(
                    ERR_DEADLINE,
                    "cannot finish inside the deadline at the current "
                    "queue depth (observed per-shape wall time)",
                    reason=shed,
                )
            return err(
                ERR_QUEUE_FULL,
                f"admission shed under {shed} pressure",
                retry_after_s=self.stats.retry_after_hint(len(self.queue)),
                reason=shed,
                depth=len(self.queue),
            )

        # Prefix probe (service/prefixstore.py): fold the chain-hash
        # frontier of the incoming history, ask the store for the deepest
        # cached cut, and plan where the search snapshots next.  Planned
        # jobs run the resumable host-frontier engine in the scheduler.
        plan = None
        if self.prefix is not None:
            plan = plan_for_submit(
                self.prefix,
                hist,
                max_cuts=self.cfg.prefix_cuts,
                min_ops=self.cfg.prefix_min_ops,
            )
            if plan is not None:
                plan.total_events = len(events)
                if plan.carry is not None:
                    self.stats.emit(
                        "prefix_hit",
                        client=client,
                        resume_ops=plan.resume_ops,
                        ops=len(hist.ops),
                        depth_frac=round(
                            plan.resume_ops / max(1, len(hist.ops)), 4
                        ),
                        probed=plan.probed,
                        trace_id=trace_id,
                    )
                    # A resumed search replays no linearization prefix: the
                    # witness would be partial, so the artifact is skipped.
                    no_viz = True
                else:
                    self.stats.emit(
                        "prefix_miss",
                        client=client,
                        ops=len(hist.ops),
                        probed=plan.probed,
                        trace_id=trace_id,
                    )
                if plan.refused:
                    self.stats.emit(
                        "prefix_refused",
                        op="submit",
                        reason=plan.refused,
                        client=client,
                        trace_id=trace_id,
                    )

        cancel = CancelToken(
            time.monotonic() + deadline if deadline is not None else None
        )
        job = Job(
            id=next(self._job_ids),
            client=client,
            priority=priority,
            shape=shape,
            fingerprint=fingerprint,
            events=events,
            hist=hist,
            no_viz=no_viz,
            trace_id=trace_id,
            cancel=cancel,
            prefix=plan,
        )
        fut: asyncio.Future = self._loop.create_future()

        def _resolve(reply: dict) -> None:
            def _finish() -> None:
                if not fut.done():
                    fut.set_result(reply)

            with contextlib.suppress(RuntimeError):  # loop already closed
                self._loop.call_soon_threadsafe(_finish)

        job.resolve = _resolve
        # Write-ahead: the accept record lands before the queue sees the
        # job, so a daemon killed in between owes (and replays) the job
        # rather than silently dropping an admission the client saw.  The
        # append runs through the journal's DegradedWriter: on a full
        # disk the job still runs, but the reply says durable=false.
        durable = False
        if self.journal is not None:
            _, durable = self._journal_writer.run(
                lambda: self.journal.accept(
                    job=job.id,
                    fingerprint=fingerprint,
                    client=client,
                    priority=priority,
                    history=text,
                )
            )
        if self.archive is not None:
            # One corpus entry per fingerprint: the archived workload is
            # replayable even after the stats sink is long gone.
            self.archive.add_history(fingerprint, text)
        try:
            depth = self.queue.put(job)
        except QueueFull as e:
            if self.journal is not None:
                self._journal_writer.run(lambda: self.journal.reject(job.id))
            self.stats.emit(
                "reject",
                client=client,
                priority=priority,
                depth=e.depth,
                retry_after_s=e.retry_after_s,
            )
            return err(
                ERR_QUEUE_FULL,
                str(e),
                retry_after_s=e.retry_after_s,
                depth=e.depth,
            )
        except RuntimeError as e:  # queue closed: daemon is stopping
            if self.journal is not None:
                self._journal_writer.run(lambda: self.journal.reject(job.id))
            return err(ERR_SHUTTING_DOWN, str(e))
        job.enqueued_at = self.tracer.now()
        self.stats.emit(
            "admit",
            job=job.id,
            client=client,
            priority=priority,
            shape=job.shape,
            depth=depth,
            trace_id=trace_id,
        )
        self.stats.set_queue_depth(depth)
        if self.tracer.enabled:
            self.tracer.name_track(job.id, f"job {job.id} ({client})")
            if sent_wall is not None:
                # Client-origin span: network + connect + queueing before
                # the daemon saw the frame.  sent_wall is the client's
                # wall clock, mapped onto our monotonic timeline and
                # clamped to t_recv so skew can't produce negative wait.
                t_sent = min(t_recv, self.tracer.mono_of_wall(sent_wall))
                self.tracer.add_span(
                    "client_wait",
                    t_sent,
                    t_recv,
                    tid=job.id,
                    cat="client",
                    args={"trace_id": trace_id, "origin": "client"},
                )
            self.tracer.add_span(
                "prepare", t_prep0, t_prep1, tid=job.id,
                args={"trace_id": trace_id},
            )
            self.tracer.add_span(
                "admit",
                t_recv,
                job.enqueued_at,
                tid=job.id,
                args={
                    "client": client,
                    "shape": job.shape,
                    "depth": depth,
                    "trace_id": trace_id,
                },
            )
        reply = await self._await_reply(fut, job, reader)
        if self.journal is not None and isinstance(reply.get("ok"), dict):
            # Honest durability: false when the accept record never hit
            # disk OR the journal degraded while the job ran (the done
            # record is then also non-durable).
            reply["ok"]["durable"] = durable and not self._journal_writer.degraded
        return reply

    async def _follow(
        self, req: dict, reader: asyncio.StreamReader | None = None
    ) -> dict:
        """One window of a followed stream: verify the delta against the
        carried frontier and advance the durable frontier on OK.

        The window verdict is **window-scoped** — it answers "is the
        stream still linearizable given the committed prefix", not "is
        this standalone history linearizable" — so it never enters the
        verdict cache, the journal, or any router edge cache; the reply
        carries ``scope="window"`` precisely so caches can refuse it.
        A window with in-flight ops still gets a verdict, but the
        frontier does not advance (``advanced=false``): the client
        resends those events once their finishes arrive.
        """
        t_recv = self.tracer.now()
        trace_id, _ = parse_trace_frame(req.get(TRACE_FIELD))
        if trace_id is None:
            trace_id = new_trace_id()
        if self.prefix is None:
            return err(
                ERR_DECODE,
                "follow needs the prefix store (start verifyd with --prefix)",
            )
        stream = str(req.get("stream") or "")
        if not stream:
            return err(ERR_DECODE, "follow needs a non-empty 'stream' id")
        token = req.get("frontier")
        entry = None
        if token is not None:
            token = str(token)
            entry = self.prefix.get(token)
            if entry is None:
                # Evicted, never durable, or from another fleet member's
                # store: the client resyncs by submitting the full history.
                self.stats.emit(
                    "prefix_refused",
                    op="follow",
                    reason="unknown_frontier",
                    stream=stream,
                    trace_id=trace_id,
                )
                return err(
                    ERR_FRONTIER,
                    f"frontier {token!r} is not in the store (evicted or "
                    "never durable); resubmit the full history",
                    frontier=token,
                )
        client = str(req.get("client") or "anon")
        try:
            priority = int(req.get("priority", 10))
        except (TypeError, ValueError):
            return err(
                ERR_DECODE, f"priority must be an int, got {req.get('priority')!r}"
            )
        deadline = req.get("deadline")
        if deadline is not None:
            try:
                deadline = float(deadline)
            except (TypeError, ValueError):
                return err(
                    ERR_DECODE, f"deadline must be a number, got {deadline!r}"
                )
        decoded = self._decode_history(
            req.get("history"), req.get("records"), client
        )
        if isinstance(decoded, dict):
            return decoded
        _text, events, hist = decoded
        try:
            plan = plan_for_window(hist, token=token, entry=entry, stream=stream)
        except ValueError as e:
            return err(ERR_FRONTIER, str(e), frontier=token)
        plan.total_events = len(events)
        n = len(hist.ops)
        if plan.refused:
            self.stats.emit(
                "prefix_refused",
                op="follow",
                reason=plan.refused,
                stream=stream,
                window=plan.window,
                trace_id=trace_id,
            )
        if n == 0:
            # An all-trivial window: nothing to search (trivial ops cannot
            # change a verdict — checker/entries.py), so it is vacuously
            # OK; the frontier re-keys at the same cut with the event
            # horizon advanced, unless ops were left dangling.
            advanced = False
            if token is not None and not plan.refused:
                new_entry = make_entry(
                    plan.carry,
                    events=plan.base_events + len(events),
                    stream=stream,
                    window=plan.window,
                )
                try:
                    self.prefix.put(token, new_entry)
                    advanced = True
                except ValueError:
                    log.exception("follow re-key refused for %r", token)
            self.stats.emit(
                "window_done",
                stream=stream,
                window=plan.window,
                verdict=0,
                advanced=advanced,
                ops_total=plan.base_ops,
                trace_id=trace_id,
            )
            # Numeric verdict like every searched window (VERDICT_EXIT):
            # clients compare ``verdict == 0``, and a string here would
            # make them treat a vacuously-OK window as inconclusive.
            return ok(
                {
                    "verdict": 0,
                    "outcome": "OK",
                    "backend": "frontier-trivial",
                    "scope": "window",
                    "stream": stream,
                    "window": plan.window,
                    "ops": 0,
                    "ops_total": plan.base_ops,
                    "frontier": token,
                    "advanced": advanced,
                    "trace_id": trace_id,
                }
            )
        shape = shape_key(hist)
        cancel = CancelToken(
            time.monotonic() + deadline if deadline is not None else None
        )
        # The job "fingerprint" is the window's cut key (``pv2:...``) — a
        # namespace the verdict cache never stores, so the scheduler's
        # pre-start cache check always misses for window jobs.
        fingerprint = plan.snap_keys.get(n) or f"pwindow:{stream}/{plan.window}"
        job = Job(
            id=next(self._job_ids),
            client=client,
            priority=priority,
            shape=shape,
            fingerprint=fingerprint,
            events=events,
            hist=hist,
            no_viz=True,  # a window has no standalone witness to draw
            trace_id=trace_id,
            cancel=cancel,
            prefix=plan,
        )
        fut: asyncio.Future = self._loop.create_future()

        def _resolve(reply: dict) -> None:
            def _finish() -> None:
                if not fut.done():
                    fut.set_result(reply)

            with contextlib.suppress(RuntimeError):  # loop already closed
                self._loop.call_soon_threadsafe(_finish)

        job.resolve = _resolve
        try:
            depth = self.queue.put(job)
        except QueueFull as e:
            self.stats.emit(
                "reject",
                client=client,
                priority=priority,
                depth=e.depth,
                retry_after_s=e.retry_after_s,
            )
            return err(
                ERR_QUEUE_FULL,
                str(e),
                retry_after_s=e.retry_after_s,
                depth=e.depth,
            )
        except RuntimeError as e:  # queue closed: daemon is stopping
            return err(ERR_SHUTTING_DOWN, str(e))
        job.enqueued_at = self.tracer.now()
        self.stats.emit(
            "admit",
            job=job.id,
            client=client,
            priority=priority,
            shape=job.shape,
            depth=depth,
            trace_id=trace_id,
        )
        self.stats.set_queue_depth(depth)
        if self.tracer.enabled:
            self.tracer.name_track(
                job.id, f"follow {stream}#{plan.window} ({client})"
            )
            self.tracer.add_span(
                "admit",
                t_recv,
                job.enqueued_at,
                tid=job.id,
                args={
                    "client": client,
                    "stream": stream,
                    "window": plan.window,
                    "trace_id": trace_id,
                },
            )
        reply = await self._await_reply(fut, job, reader)
        body = reply.get("ok")
        if isinstance(body, dict):
            new_key = plan.snap_keys.get(n)
            # The frontier only advances when the worker actually stored
            # the end-of-window snapshot (OK verdict, complete cut).
            advanced = bool(new_key) and new_key in self.prefix
            body.update(
                stream=stream,
                window=plan.window,
                ops=n,
                ops_total=plan.base_ops + n,
                frontier=new_key if advanced else token,
                advanced=advanced,
            )
            self.stats.emit(
                "window_done",
                stream=stream,
                window=plan.window,
                verdict=body.get("verdict"),
                advanced=advanced,
                ops_total=plan.base_ops + n,
                trace_id=trace_id,
            )
        return reply

    # -- distributed search (service/distsearch.py coordinator peer) -------

    _GRANTS_MAX = 1024  # bounded: a dead coordinator must not leak grants

    @staticmethod
    def _ds_fields(req: dict) -> tuple[str, str, str, int] | dict:
        search = str(req.get("search") or "")
        seg = str(req.get("seg") or "")
        part = str(req.get("part") or "")
        if not search or not part:
            return err(ERR_DECODE, "distributed ops need 'search' and 'part'")
        try:
            epoch = int(req.get("epoch"))
        except (TypeError, ValueError):
            return err(
                ERR_DECODE, f"epoch must be an int, got {req.get('epoch')!r}"
            )
        return search, seg, part, epoch

    def _ds_grant(self, req: dict) -> dict:
        """Claim partition ownership.  The fence: a grant older than the
        one already held is a zombie coordinator thread — refused with
        the definite ``EpochFenced`` so it can never double-own."""
        fields = self._ds_fields(req)
        if isinstance(fields, dict):
            return fields
        search, seg, part, epoch = fields
        key = (search, part)
        have = self._grants.get(key)
        if have is not None and have > epoch:
            self.stats.emit(
                "epoch_fence", op="grant", search=search, part=part,
                epoch=epoch, have=have,
            )
            return err(
                ERR_EPOCH,
                f"partition {part} of {search[:12]} is owned at epoch "
                f"{have} > {epoch}",
                epoch=have,
            )
        # Re-insert so the eviction order tracks grant recency.
        self._grants.pop(key, None)
        self._grants[key] = epoch
        while len(self._grants) > self._GRANTS_MAX:
            self._grants.pop(next(iter(self._grants)))
        self.stats.emit(
            "partition_granted", search=search, part=part, epoch=epoch
        )
        return ok({"search": search, "part": part, "epoch": epoch, "seg": seg})

    async def _ds_delta(
        self, req: dict, reader: asyncio.StreamReader | None = None
    ) -> dict:
        """One partition of one segment: search the segment history from
        the carried share of the boundary union and reply with the
        partition's end-of-segment union.

        The epoch is checked twice: at entry (a stale delta never costs a
        search) and again when the verdict is ready — a revocation that
        landed mid-search turns this reply into ``EpochFenced``, so a
        zombie node that missed its own revocation cannot leak a verdict
        back into the merge.  The reply is partition-scoped
        (``scope="partition"``) and never enters any verdict cache.
        """
        t_recv = self.tracer.now()
        trace_id, _ = parse_trace_frame(req.get(TRACE_FIELD))
        if trace_id is None:
            trace_id = new_trace_id()
        fields = self._ds_fields(req)
        if isinstance(fields, dict):
            return fields
        search, seg, part, epoch = fields
        key = (search, part)
        have = self._grants.get(key)
        if have != epoch:
            self.stats.emit(
                "epoch_fence", op="delta", search=search, part=part,
                epoch=epoch, have=have,
            )
            return err(
                ERR_EPOCH,
                f"no live grant for partition {part} of {search[:12]} at "
                f"epoch {epoch} (have {have})",
                epoch=have,
            )
        try:
            carry = PrefixCarry.from_payload(req.get("carry"))
        except (TypeError, ValueError) as e:
            return err(ERR_DECODE, f"bad partition carry: {e}")
        client = str(req.get("client") or "distsearch")
        deadline = req.get("deadline")
        if deadline is not None:
            try:
                deadline = float(deadline)
            except (TypeError, ValueError):
                return err(
                    ERR_DECODE, f"deadline must be a number, got {deadline!r}"
                )
        decoded = self._decode_history(
            req.get("history"), req.get("records"), client
        )
        if isinstance(decoded, dict):
            return decoded
        _text, events, hist = decoded
        n = len(hist.ops)
        if n == 0:
            # All-trivial segment slice: the union passes through unchanged.
            states = pack_states(carry.states)
            self.stats.emit(
                "partition_delta", search=search, part=part, epoch=epoch,
                verdict=0, states=len(states),
                bytes=len(json.dumps(states, separators=(",", ":"))),
            )
            return ok(
                {
                    "verdict": 0,
                    "outcome": "OK",
                    "backend": "frontier-trivial",
                    "scope": "partition",
                    "search": search,
                    "seg": seg,
                    "part": part,
                    "epoch": epoch,
                    "ops": 0,
                    "states": states,
                    "trace_id": trace_id,
                }
            )
        # The final segment's verdict suffices on its own (there is no
        # next boundary to seed), so the coordinator sends union=False
        # and the search may accept early instead of materializing every
        # indefinite-append layer for an unwanted union.
        want_union = req.get("union", True)
        plan = PrefixPlan(
            kind="partition",
            carry=carry,
            snap_keys={n: None} if want_union else {},
        )
        plan.total_events = len(events)
        cancel = CancelToken(
            time.monotonic() + deadline if deadline is not None else None
        )
        self._part_jobs[key] = cancel
        job = Job(
            id=next(self._job_ids),
            client=client,
            priority=0,  # a partition blocks a whole fleet: front of queue
            shape=shape_key(hist),
            fingerprint=f"ppart:{search[:16]}/{part}",
            events=events,
            hist=hist,
            no_viz=True,
            trace_id=trace_id,
            cancel=cancel,
            prefix=plan,
        )
        fut: asyncio.Future = self._loop.create_future()

        def _resolve(reply: dict) -> None:
            def _finish() -> None:
                if not fut.done():
                    fut.set_result(reply)

            with contextlib.suppress(RuntimeError):  # loop already closed
                self._loop.call_soon_threadsafe(_finish)

        job.resolve = _resolve
        try:
            depth = self.queue.put(job)
        except QueueFull as e:
            self._part_jobs.pop(key, None)
            return err(
                ERR_QUEUE_FULL, str(e),
                retry_after_s=e.retry_after_s, depth=e.depth,
            )
        except RuntimeError as e:  # queue closed: daemon is stopping
            self._part_jobs.pop(key, None)
            return err(ERR_SHUTTING_DOWN, str(e))
        job.enqueued_at = self.tracer.now()
        self.stats.emit(
            "admit",
            job=job.id,
            client=client,
            priority=0,
            shape=job.shape,
            depth=depth,
            trace_id=trace_id,
        )
        self.stats.set_queue_depth(depth)
        if self.tracer.enabled:
            self.tracer.name_track(
                job.id, f"partition {part}@{epoch} ({client})"
            )
            self.tracer.add_span(
                "admit", t_recv, job.enqueued_at, tid=job.id,
                args={"client": client, "part": part, "trace_id": trace_id},
            )
        try:
            reply = await self._await_reply(fut, job, reader)
        finally:
            if self._part_jobs.get(key) is cancel:
                self._part_jobs.pop(key, None)
        # Reply-time fence: the grant must STILL be ours.  A steal or
        # revocation that raced the search makes this node a zombie — its
        # verdict must die here, not in the coordinator's merge.
        if self._grants.get(key) != epoch:
            self.stats.emit(
                "epoch_fence", op="delta_reply", search=search, part=part,
                epoch=epoch, have=self._grants.get(key),
            )
            return err(
                ERR_EPOCH,
                f"grant for partition {part} superseded mid-search "
                f"(epoch {epoch})",
                epoch=self._grants.get(key),
            )
        body = reply.get("ok")
        if isinstance(body, dict):
            # Work complete: the grant is spent (the next segment's grant
            # arrives under a fresh epoch).
            self._grants.pop(key, None)
            body.update(
                scope="partition", search=search, seg=seg, part=part,
                epoch=epoch,
            )
            states = body.get("states") or []
            self.stats.emit(
                "partition_delta", search=search, part=part, epoch=epoch,
                verdict=body.get("verdict"), states=len(states),
                bytes=len(json.dumps(states, separators=(",", ":"))),
            )
        return reply

    def _ds_done(self, req: dict) -> dict:
        """Close (or revoke) a partition grant; cancels the in-flight
        partition job so a revoked search stops burning the worker."""
        fields = self._ds_fields(req)
        if isinstance(fields, dict):
            return fields
        search, _seg, part, epoch = fields
        reason = str(req.get("reason") or "done")
        key = (search, part)
        have = self._grants.get(key)
        if have is not None and have > epoch:
            self.stats.emit(
                "epoch_fence", op="done", search=search, part=part,
                epoch=epoch, have=have,
            )
            return err(
                ERR_EPOCH,
                f"partition {part} re-owned at epoch {have} > {epoch}",
                epoch=have,
            )
        closed = self._grants.pop(key, None) is not None
        tok = self._part_jobs.pop(key, None)
        if tok is not None:
            tok.cancel("revoked")
        self.stats.emit(
            "partition_done", search=search, part=part, epoch=epoch,
            reason=reason, closed=closed,
        )
        return ok({"closed": closed, "search": search, "part": part})

    async def _await_reply(
        self,
        fut: asyncio.Future,
        job: Job,
        reader: asyncio.StreamReader | None,
    ) -> dict:
        """Wait for the worker's reply while watching the client socket.

        A peer that disconnects mid-submit (EOF or reset on ``reader``)
        cancels the job with reason ``client_gone`` so no worker stays
        pinned computing an answer nobody will read — the scheduler
        notices at its next cancellation boundary, the lease releases,
        and the (unwritable) reply just fails fast in ``_handle``.  The
        asyncio transport feeds EOF without a pending read, so polling
        ``at_eof()`` here never consumes a pipelined frame.
        """
        while True:
            done, _ = await asyncio.wait({fut}, timeout=0.2)
            if done:
                return fut.result()
            if reader is not None and (
                reader.at_eof() or reader.exception() is not None
            ):
                if job.cancel.cancel("client_gone"):
                    self.stats.emit(
                        "client_gone",
                        job=job.id,
                        client=job.client,
                        trace_id=job.trace_id,
                    )
