"""verifyd — a resident batched verification service.

The one-shot CLI pays process start, history decode, backend selection,
and (for the device engine) XLA compile on every ``check``; the daemon
amortizes all four across requests.  Four cooperating pieces:

- :mod:`.queue`     — bounded admission queue with per-client priority and
                      explicit backpressure (reject-with-retry-after).
- :mod:`.scheduler` — drains the queue in *shape groups* so the device
                      engine's jitted executables (and the persistent
                      compile cache, ``utils/cache.py``) are reused across
                      requests; runs the ``auto`` portfolio per job.
- :mod:`.cache`     — verdict cache keyed by the canonical chain-hash
                      fingerprint of the prepared history: duplicates are
                      answered in O(1).
- :mod:`.supervise` — bounded-child/checkpoint-resume wrapper for device
                      jobs (``checker/resilient.py`` + ``checkpoint.py``):
                      one wedged TPU job degrades to CPU instead of taking
                      the daemon down.

Durability and remote access ride three more:

- :mod:`.journal`    — write-ahead journal of accepted jobs over the
                       CRC-checked segment log (``utils/seglog.py``); a
                       restarted daemon re-runs accepted-but-unanswered
                       jobs instead of silently dropping them.
- :mod:`.protocol`   — adds HMAC frame auth for the TCP transport,
                       bounded frame sizes, and the 69/75/76 exit-code
                       contract.
- :mod:`.chaosproxy` — fault-injecting frame proxy (truncate / garble /
                       delay / duplicate) backing ``scripts/chaos_bench.py``
                       and ``make chaos``.

:mod:`.daemon` ties them together behind a unix-domain socket speaking the
same newline-delimited-JSON framing discipline as ``collector/socket_s2.py``;
:mod:`.client` is the submit side; :mod:`.stats` emits per-job structured
log events (queue wait, backend chosen, cache hit/miss, wall time).

Horizontal scale rides one more: :mod:`.router` fronts N daemons behind
a single address speaking the same protocol — consistent-hash routing on
the verdict-cache fingerprint, bounded work-stealing, circuit-broken
failover, and drain-aware rolling restarts (``route`` CLI subcommand).
"""

from .cache import VerdictCache, history_fingerprint
from .client import (
    VerifydBusy,
    VerifydClient,
    VerifydDeadlineExceeded,
    VerifydError,
)
from .daemon import Verifyd, VerifydConfig
from .queue import AdmissionQueue, Job, QueueFull
from .router import BackendSpec, HashRing, RouterConfig, VerifydRouter
from .scheduler import shape_key
from .stats import ServiceStats

__all__ = [
    "AdmissionQueue",
    "BackendSpec",
    "HashRing",
    "Job",
    "QueueFull",
    "RouterConfig",
    "ServiceStats",
    "Verifyd",
    "VerifydBusy",
    "VerifydClient",
    "VerifydConfig",
    "VerifydDeadlineExceeded",
    "VerifydError",
    "VerifydRouter",
    "VerdictCache",
    "history_fingerprint",
    "shape_key",
]
